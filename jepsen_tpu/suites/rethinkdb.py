"""RethinkDB suite.

Reference: rethinkdb/src/jepsen/rethinkdb.clj + rethinkdb/
document_cas.clj — install rethinkdb from its apt repo (:52-60), write
a config whose ``join=`` lines span the cluster (:67-75), and run
**document-cas**: a table with ``replicas = all nodes``, tunable
``write_acks``/``read_mode``, a register document per key, and CAS as
an atomic conditional update — a row function branching on the current
value, erroring to abort (document_cas.clj:52-110).

The client speaks the ReQL JSON wire protocol via :mod:`.proto.reql`.
"""

from __future__ import annotations

from typing import Optional

from .. import client as client_mod
from .. import independent
from .. import control
from ..control import util as cu
from ..os_setup import debian
from . import common
from .proto import IndeterminateError
from .proto.reql import ReqlClient, ReqlError
from .proto import reql as r

CLIENT_PORT = 28015
CLUSTER_PORT = 29015
DB = "jepsen"
TABLE = "cas"


class RethinkDB(common.DaemonDB):
    logfile = "/var/log/rethinkdb.log"
    pidfile = "/var/run/rethinkdb.pid"
    proc_name = "rethinkdb"

    def __init__(self, opts: Optional[dict] = None):
        super().__init__(opts)
        self.version = (opts or {}).get("version", "2.1.5+2~0jessie")

    def install(self, test, node):
        # (reference: rethinkdb.clj:52-60)
        with control.su():
            cu.write_file(
                "deb http://download.rethinkdb.com/apt jessie main\n",
                "/etc/apt/sources.list.d/rethinkdb.list",
            )
            control.execute(
                "bash", "-c",
                "wget -qO- https://download.rethinkdb.com/apt/pubkey.gpg"
                " | apt-key add -", check=False,
            )
            control.execute("apt-get", "update", check=False)
        debian.install([f"rethinkdb={self.version}"])

    def configure(self, test, node):
        # (reference: rethinkdb.clj:67-85 — join lines per node)
        joins = "\n".join(
            f"join={n}:{CLUSTER_PORT}" for n in test["nodes"] if n != node
        )
        config = "\n".join([
            "bind=all",
            f"server-name={node}",
            f"directory=/var/lib/rethinkdb/jepsen",
            joins,
        ])
        with control.su():
            cu.write_file(config, "/etc/rethinkdb/instances.d/jepsen.conf")

    def start(self, test, node):
        cu.start_daemon(
            {"logfile": self.logfile, "pidfile": self.pidfile,
             "chdir": "/var/lib/rethinkdb"},
            "/usr/bin/rethinkdb",
            "--config-file", "/etc/rethinkdb/instances.d/jepsen.conf",
            "--pid-file", self.pidfile,
        )

    def await_ready(self, test, node):
        cu.await_tcp_port(CLIENT_PORT, timeout_s=300)

    def wipe(self, test, node):
        with control.su():
            control.execute("rm", "-rf", "/var/lib/rethinkdb/jepsen",
                            check=False)


class RethinkCasClient(client_mod.Client):
    """Document CAS (reference: document_cas.clj:52-110).

    Each key is a document {id: k, val: v}; CAS runs as
    ``get(k).update(row -> branch(row.val == old, {val: new},
    error("abort")))`` so the condition and write are one atomic
    operation on the primary."""

    def __init__(self, opts: Optional[dict] = None):
        self.opts = opts or {}
        self.conn: Optional[ReqlClient] = None

    def open(self, test, node):
        c = type(self)(self.opts)
        c.conn = ReqlClient(
            self.opts.get("host", str(node)),
            self.opts.get("port", CLIENT_PORT),
            timeout=self.opts.get("timeout", 10.0),
        )
        return c

    def setup(self, test):
        # replicate to every node with tunable write_acks, the
        # configuration the reference applies (document_cas.clj:30-47
        # set-write-acks! + table-create {:replicas N})
        n = len(test.get("nodes", ["n1"]))
        write_acks = self.opts.get("write-acks", "majority")
        for term in (
            [r.DB_CREATE, [DB]],
            [r.TABLE_CREATE, [r.db(DB), TABLE], {"replicas": n}],
            r.update([r.CONFIG, [self._tbl()]],
                     {"__literal__": {"write_acks": write_acks}}),
        ):
            try:
                self.conn.run(term)
            except (ReqlError, IndeterminateError):
                pass  # already exists / config unsupported on old fakes

    def _tbl(self):
        return r.table(DB, TABLE)

    def invoke(self, test, op):
        k, v = op["value"]
        read_mode = self.opts.get("read-mode", "majority")
        try:
            if op["f"] == "read":
                doc = self.conn.run(
                    [r.GET, [[r.TABLE, [r.db(DB), TABLE],
                              {"read_mode": read_mode}], int(k)]]
                )
                val = doc.get("val") if doc else None
                return {**op, "type": "ok", "value": independent.kv(k, val)}
            if op["f"] == "write":
                self.conn.run(
                    r.insert(self._tbl(), {"id": int(k), "val": int(v)},
                             conflict="update"),
                    {"durability": "hard"},
                )
                return {**op, "type": "ok"}
            if op["f"] == "cas":
                old, new = v
                res = self.conn.run(
                    r.update(
                        r.get(self._tbl(), int(k)),
                        r.func(
                            r.branch(
                                r.eq(r.get_field(r.var(), "val"), int(old)),
                                {"__literal__": {"val": int(new)}},
                                r.error("cas-abort"),
                            )
                        ),
                    ),
                    {"durability": "hard"},
                )
                applied = (res.get("replaced", 0) + res.get("unchanged", 0)) == 1
                if applied and not res.get("errors"):
                    return {**op, "type": "ok"}
                return {**op, "type": "fail",
                        "error": res.get("first_error", "cas-miss")}
            raise ValueError(f"unknown f {op['f']!r}")
        except IndeterminateError as e:
            return {**op, "type": "info", "error": str(e)}
        except ReqlError as e:
            if "cas-abort" in str(e):
                return {**op, "type": "fail", "error": "cas-miss"}
            return {**op, "type": "fail", "error": str(e)}

    def close(self, test):
        if self.conn:
            self.conn.close()


def db(opts: Optional[dict] = None):
    return RethinkDB(opts)


def client(opts: Optional[dict] = None):
    return RethinkCasClient(opts)


def workloads(opts: Optional[dict] = None) -> dict:
    w = common.register_workload(dict(opts or {}))
    # the reference names this probe document-cas (rethinkdb/
    # document_cas.clj:1-185: per-document CAS registers under
    # write_acks/read_mode combinations); both names resolve so
    # reference users find it
    return {"register": w, "document-cas": w}


def test(opts: Optional[dict] = None) -> dict:
    opts = dict(opts or {})
    wname = opts.get("workload", "register")
    w = workloads(opts)[wname]
    return common.build_test(
        f"rethinkdb-{wname}", opts, db=RethinkDB(opts),
        client=RethinkCasClient(opts), workload=w,
    )
