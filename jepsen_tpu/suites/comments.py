"""Comments workload: strict-serializability via write-visibility order.

Writers blindly insert globally-unique ids across sharded tables; a
reader transaction scans every table.  Replaying the history, any write
that completed before another write was *invoked* must be visible
whenever the later write is — seeing w_i without some earlier w_j is
exactly the "comment appeared before the post it replies to" anomaly
(T1 < T2 in real time, T2 visible without T1: a strict-serializability
violation that plain serializability permits).

Reference: cockroachdb/src/jepsen/cockroach/comments.clj:1-177 — the
Client inserts (id, key) rows into ``comment_<hash(id) % n>`` and reads
ids back across all tables in one txn; the checker accumulates the
completed-before set per write invocation and diffs each read against
the union of its seen writes' expectations.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set

from .. import generator as gen
from .. import independent
from ..checker import Checker
from ..history import INVOKE, OK
from . import sql

TABLE_PREFIX = "comment_"
TABLE_COUNT = 10


def table_for(id_: int, table_count: int = TABLE_COUNT) -> str:
    return f"{TABLE_PREFIX}{id_ % table_count}"


class CommentsClient(sql._Base):
    """(reference: comments.clj:42-88)"""

    def __init__(self, opts: Optional[dict] = None):
        super().__init__(opts)
        self.table_count = int(self.opts.get("table-count", TABLE_COUNT))

    def setup(self, test):
        self._exec_ddl(
            *(
                f"CREATE TABLE IF NOT EXISTS {TABLE_PREFIX}{i} "
                "(id INT PRIMARY KEY, key INT)"
                for i in range(self.table_count)
            )
        )

    def invoke(self, test, op):
        k, v = op["value"]
        try:
            if op["f"] == "write":
                v = int(v)
                self.conn.query(
                    f"INSERT INTO {table_for(v, self.table_count)} "
                    f"(id, key) VALUES ({v}, {int(k)})"
                )
                return {**op, "type": "ok"}
            if op["f"] == "read":
                seen: List[int] = []
                self.conn.query("BEGIN")
                try:
                    for i in range(self.table_count):
                        res = self.conn.query(
                            f"SELECT id FROM {TABLE_PREFIX}{i} "
                            f"WHERE key = {int(k)}"
                        )
                        seen.extend(int(r[0]) for r in res.rows)
                    self.conn.query("COMMIT")
                except Exception:
                    try:
                        self.conn.query("ROLLBACK")
                    except Exception:
                        pass
                    raise
                return {**op, "type": "ok",
                        "value": independent.kv(k, sorted(seen))}
            raise ValueError(f"unknown f {op['f']!r}")
        except sql.IndeterminateError as e:
            return self._info(op, e)
        except (sql.PgError, sql.MysqlError) as e:
            return self._fail(op, e)


class CommentsChecker(Checker):
    """Replay: expected[w] = writes completed before w's invocation;
    a read seeing w but missing any of expected[w] is an error.
    (reference: comments.clj:90-141)"""

    def check(self, test, history, opts=None):
        completed: Set[int] = set()
        expected: Dict[int, Set[int]] = {}
        errors = []
        for op in history:
            if op.f == "write":
                if op.type == INVOKE:
                    expected[op.value] = set(completed)
                elif op.type == OK:
                    completed.add(op.value)
            elif op.f == "read" and op.type == OK and op.value is not None:
                seen = set(op.value)
                want: Set[int] = set()
                for w in seen:
                    want |= expected.get(w, set())
                missing = want - seen
                if missing:
                    errors.append(
                        {
                            "index": op.index,
                            "process": op.process,
                            "missing": sorted(missing),
                            "expected-count": len(want),
                        }
                    )
        return {"valid?": not errors, "errors": errors}


def workload(opts: Optional[dict] = None) -> dict:
    """Concurrent blind writes + full-scan reads per independent key.
    (reference: comments.clj:144-177)"""
    opts = dict(opts or {})
    n = max(1, len(opts.get("nodes", ["n1"])))
    ids = {"n": 0}

    def write(test, ctx):
        ids["n"] += 1
        return {"type": "invoke", "f": "write", "value": ids["n"]}

    def read(test, ctx):
        return {"type": "invoke", "f": "read", "value": None}

    def fgen(k):
        return gen.limit(16, gen.mix([write, read]))

    return {
        "generator": independent.concurrent_generator(
            n, range(100_000), fgen
        ),
        "checker": independent.checker(CommentsChecker()),
        "concurrency": 2 * n,
    }
