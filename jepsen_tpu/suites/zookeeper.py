"""ZooKeeper suite.

Reference: zookeeper/src/jepsen/zookeeper.clj — install the zookeeper
debs (:46-49), write ``/etc/zookeeper/conf/myid`` from the node's index
(:50-51) and a zoo.cfg whose ``server.N=node:2888:3888`` lines span the
test nodes (:32-43,52-56), restart the service, and run a linearizable
compare-and-set register over a znode (the reference drives an avout
distributed atom; here the client uses the ZAB wire protocol directly
with version-checked ``setData`` for CAS).
"""

from __future__ import annotations

import json
import uuid
from typing import Any, Optional

from .. import checker as checker_mod
from .. import client as client_mod
from .. import independent
from .. import control
from ..control import util as cu
from ..os_setup import debian
from . import common
from .proto import IndeterminateError
from .proto.zk import ZkClient, ZkError

PORT = 2181


def zk_node_id(test: dict, node: Any) -> int:
    """(reference: zookeeper.clj:26-30)"""
    return test["nodes"].index(node)


def zoo_cfg_servers(test: dict) -> str:
    """(reference: zookeeper.clj:32-43)"""
    return "\n".join(
        f"server.{i}={n}:2888:3888" for i, n in enumerate(test["nodes"])
    )


_ZOO_CFG = """tickTime=2000
initLimit=10
syncLimit=5
dataDir=/var/lib/zookeeper
clientPort=2181
"""


class ZookeeperDB(common.DaemonDB):
    logfile = "/var/log/zookeeper/zookeeper.log"
    proc_name = "java"

    def __init__(self, opts: Optional[dict] = None):
        super().__init__(opts)
        self.version = (opts or {}).get("version")

    def install(self, test, node):
        # (reference: zookeeper.clj:46-49)
        pkgs = (
            [f"zookeeper={self.version}", f"zookeeperd={self.version}"]
            if self.version else ["zookeeper", "zookeeperd"]
        )
        debian.install(pkgs)

    def configure(self, test, node):
        with control.su():
            cu.write_file(str(zk_node_id(test, node)),
                          "/etc/zookeeper/conf/myid")
            cu.write_file(_ZOO_CFG + zoo_cfg_servers(test) + "\n",
                          "/etc/zookeeper/conf/zoo.cfg")

    def start(self, test, node):
        with control.su():
            control.execute("service", "zookeeper", "restart", check=False)

    def kill(self, test, node):
        with control.su():
            control.execute("service", "zookeeper", "stop", check=False)
            cu.grepkill("zookeeper")

    def await_ready(self, test, node):
        cu.await_tcp_port(PORT, timeout_s=120)

    def wipe(self, test, node):
        with control.su():
            control.execute("rm", "-rf", "/var/lib/zookeeper/version-2",
                            check=False)


class ZkRegisterClient(client_mod.Client):
    """CAS register on a znode: read via getData, write via versioned
    create/set, CAS via read-version + conditional setData (the znode
    version is the optimistic lock).  One znode per independent key."""

    def __init__(self, opts: Optional[dict] = None):
        self.opts = opts or {}
        self.conn: Optional[ZkClient] = None

    def open(self, test, node):
        c = type(self)(self.opts)
        c.conn = ZkClient(
            self.opts.get("host", str(node)),
            self.opts.get("port", PORT),
            timeout=self.opts.get("timeout", 10.0),
        )
        return c

    def _path(self, k) -> str:
        return f"/jepsen-{k}"

    def invoke(self, test, op):
        k, v = op["value"]
        path = self._path(k)
        try:
            if op["f"] == "read":
                try:
                    data, _ = self.conn.get_data(path)
                    val = json.loads(data.decode())
                except ZkError as e:
                    if e.code == -101:  # NONODE
                        val = None
                    else:
                        raise
                return {**op, "type": "ok", "value": independent.kv(k, val)}
            if op["f"] == "write":
                data = json.dumps(v).encode()
                try:
                    self.conn.set_data(path, data)
                except ZkError as e:
                    if e.code != -101:
                        raise
                    try:
                        self.conn.create(path, data)
                    except ZkError as e2:
                        if e2.code != -110:  # NODEEXISTS: lost a race
                            raise
                        self.conn.set_data(path, data)
                return {**op, "type": "ok"}
            if op["f"] == "cas":
                old, new = v
                try:
                    data, stat = self.conn.get_data(path)
                except ZkError as e:
                    if e.code == -101:
                        return {**op, "type": "fail", "error": "no-node"}
                    raise
                if json.loads(data.decode()) != old:
                    return {**op, "type": "fail", "error": "value-mismatch"}
                try:
                    self.conn.set_data(path, json.dumps(new).encode(),
                                       version=stat.version)
                except ZkError as e:
                    if e.code == -103:  # BADVERSION: lost the race
                        return {**op, "type": "fail", "error": "bad-version"}
                    raise
                return {**op, "type": "ok"}
            raise ValueError(f"unknown f {op['f']!r}")
        except IndeterminateError as e:
            return {**op, "type": "info", "error": str(e)}
        except ZkError as e:
            return {**op, "type": "fail", "error": str(e)}

    def close(self, test):
        if self.conn:
            self.conn.close()


class ZkLockClient(client_mod.Client):
    """Distributed try-lock over a well-known znode: acquire = create
    (NODE_EXISTS → definite fail), release = delete of our own node —
    the classic ZooKeeper lock recipe, checked against the OWNER-AWARE
    mutex model: completions carry the ZK session id, so the checker
    catches not just double grants but releases by a non-holder
    (reference: hazelcast.clj:340-449 lock clients + the knossos mutex
    model consumed at jepsen/src/jepsen/checker.clj:19-26; the
    owner-aware reduction rides the dense device kernel).

    The client refuses double-acquires and releases-without-holding
    locally (definite fails that never touch the wire).  A connection
    cut mid-acquire is indeterminate: the lock may now be held by a
    node nobody will release — the history stays linearizable (an
    :info acquire may linearize forever), later acquires just fail."""

    PATH = "/jepsen-lock"

    def __init__(self, opts: Optional[dict] = None):
        self.opts = opts or {}
        self.conn: Optional[ZkClient] = None
        self.held = False
        self.uid = uuid.uuid4().hex[:8]

    def open(self, test, node):
        c = type(self)(self.opts)
        c.conn = ZkClient(
            self.opts.get("host", str(node)),
            self.opts.get("port", PORT),
            timeout=self.opts.get("timeout", 10.0),
        )
        return c

    def _me(self) -> dict:
        """A per-opened-client identity for the owner-aware model.
        Deliberately NOT the ZK session id: the connection is lazy, so
        a crash during the handshake would stamp the shared sentinel
        0 and collide distinct clients on one phantom owner — and an
        identity must stay stable across ALL of one client's ops.  One
        client ≈ one session for this recipe, so the per-open id keeps
        the model's owner semantics faithful."""
        return {"client": f"zk-{self.uid}"}

    def invoke(self, test, op):
        try:
            if op["f"] == "acquire":
                if self.held:
                    return {**op, "type": "fail", "error": "already-held"}
                try:
                    self.conn.create(self.PATH, b"held")
                except ZkError as e:
                    if e.code == -110:  # NODEEXISTS: lock taken
                        return {**op, "type": "fail", "error": "taken"}
                    raise
                self.held = True
                return {**op, "type": "ok", "value": self._me()}
            if op["f"] == "release":
                if not self.held:
                    return {**op, "type": "fail", "error": "not-held"}
                try:
                    self.conn.delete(self.PATH)
                except ZkError as e:
                    self.held = False
                    if e.code == -101:
                        # NONODE: the delete DEFINITELY did not execute
                        # — report a definite fail so the checker can
                        # flag the underlying anomaly (our held lock
                        # vanishing is exactly what a lock test exists
                        # to catch: a later acquire-ok with no
                        # intervening release-ok must read as invalid)
                        return {**op, "type": "fail",
                                "error": "lock vanished while held"}
                    raise
                self.held = False
                return {**op, "type": "ok", "value": self._me()}
            raise ValueError(f"unknown f {op['f']!r}")
        except (IndeterminateError, OSError) as e:
            # a cut connection loses track of whether we hold the lock;
            # assume not (never release what we might not own).  OSError
            # covers the lazy handshake dying raw (ConnectionRefused
            # etc.) — without this catch the interpreter's crash path
            # would record an identity-less info op, pushing the WHOLE
            # history off the kernel onto the exponential oracle.  The
            # info op still says WHO may have acted, so the model can
            # linearize it (checker/linear.py info-value propagation)
            self.held = False
            return {**op, "type": "info", "error": str(e),
                    "value": self._me()}
        except ZkError as e:
            return {**op, "type": "fail", "error": str(e)}

    def close(self, test):
        if self.conn:
            self.conn.close()


def lock_workload(opts: Optional[dict] = None) -> dict:
    """Contended try-lock/release cycles checked against the
    owner-aware mutex model — which reduces to cas-register codes at
    encode time (ops/step_kernels.py owner-mutex spec; dense inside
    C ≤ 12, the
    small-frontier generic kernel beyond)."""
    from .. import generator as gen
    from .. import models

    return {
        "generator": gen.each_thread(gen.cycle([
            {"type": "invoke", "f": "acquire", "value": None},
            {"type": "invoke", "f": "release", "value": None},
        ])),
        "checker": checker_mod.linearizable(
            models.owner_mutex(), pure_fs=()
        ),
    }


def db(opts: Optional[dict] = None):
    return ZookeeperDB(opts)


def client(opts: Optional[dict] = None):
    return ZkRegisterClient(opts)


def workloads(opts: Optional[dict] = None) -> dict:
    opts = dict(opts or {})
    return {
        "register": common.register_workload(opts),
        "lock": lock_workload(opts),
    }


def test(opts: Optional[dict] = None) -> dict:
    opts = dict(opts or {})
    wname = opts.get("workload", "register")
    w = workloads(opts)[wname]
    c = {"lock": ZkLockClient}.get(wname, ZkRegisterClient)(opts)
    return common.build_test(
        f"zookeeper-{wname}", opts, db=ZookeeperDB(opts),
        client=c, workload=w,
    )
