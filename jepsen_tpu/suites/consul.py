"""Consul suite.

Reference: consul/src/jepsen/consul/{db,client,register}.clj — install a
consul release zip (db.clj:54-95), run ``consul agent -server`` with the
first node bootstrapping and the rest retry-joining it (db.clj:23-51),
and drive a CAS register over the KV HTTP API: base64-encoded values,
index-based CAS (two-phase: read ModifyIndex, then ``?cas=<index>``;
client.clj:66-85), with reads at configurable consistency
(default/consistent/stale).
"""

from __future__ import annotations

import base64
import json
from typing import Optional

from .. import client as client_mod
from .. import independent
from ..control import util as cu
from ..control import execute, sudo
from . import common
from .proto import IndeterminateError
from .proto.http import HttpError, JsonHttpClient

VERSION = "0.5.2"
DIR = "/opt"                     # (reference: consul/db.clj:14)
BINARY = "consul"
PIDFILE = "/var/run/consul.pid"  # (reference: consul/db.clj:18)
LOGFILE = "/var/log/consul.log"
DATA_DIR = "/var/lib/consul"
HTTP_PORT = 8500
RETRY_INTERVAL = "5s"            # (reference: consul/db.clj:21)


class ConsulDB(common.DaemonDB):
    dir = DIR
    binary = BINARY
    logfile = LOGFILE
    pidfile = PIDFILE

    def __init__(self, opts: Optional[dict] = None):
        super().__init__(opts)
        self.version = (opts or {}).get("version", VERSION)

    def install(self, test, node):
        url = (
            "https://releases.hashicorp.com/consul/"
            f"{self.version}/consul_{self.version}_linux_amd64.zip"
        )
        with sudo():
            cu.install_archive(url, f"{DIR}/{BINARY}")

    def start_args(self, test, node):
        # (reference: consul/db.clj:23-51 start-consul!)
        primary = test["nodes"][0]
        args = [
            "agent", "-server",
            "-log-level", "debug",
            "-client", "0.0.0.0",
            "-bind", str(node),
            "-data-dir", DATA_DIR,
            "-node", str(node),
            "-retry-interval", RETRY_INTERVAL,
        ]
        if node == primary:
            args.append("-bootstrap")
        else:
            args += ["-retry-join", str(primary)]
        return args

    def await_ready(self, test, node):
        cu.await_tcp_port(HTTP_PORT)

    def wipe(self, test, node):
        # (reference: consul/db.clj:80-87)
        with sudo():
            execute("rm", "-rf", PIDFILE, LOGFILE, DATA_DIR, f"{DIR}/{BINARY}")


class ConsulClient(client_mod.Client):
    """CAS register over the consul KV API (reference:
    consul/client.clj).  Values are JSON ints, base64-wrapped by consul;
    CAS reads the current ModifyIndex then writes with ``?cas=``."""

    def __init__(self, opts: Optional[dict] = None):
        self.opts = opts or {}
        self.conn: Optional[JsonHttpClient] = None

    def open(self, test, node):
        c = type(self)(self.opts)
        host = self.opts.get("host", str(node))
        port = self.opts.get("port", HTTP_PORT)
        c.conn = JsonHttpClient(host, port, timeout=5.0)
        return c

    def _read(self, k):
        """→ (value, modify-index) or (None, 0).  (reference:
        consul/client.clj:22-46 parse-body/parse-index)"""
        params = {}
        consistency = self.opts.get("consistency")
        if consistency:
            params[consistency] = ""
        try:
            _, body = self.conn.get(f"/v1/kv/jepsen/{k}", params=params)
        except HttpError as e:
            if e.status == 404:
                return None, 0
            raise
        rec = body[0]
        raw = base64.b64decode(rec["Value"]).decode() if rec.get("Value") else None
        value = json.loads(raw) if raw not in (None, "null") else None
        return value, rec["ModifyIndex"]

    def invoke(self, test, op):
        k, v = op["value"] if isinstance(op["value"], (list, tuple)) else (
            "r", op["value"])
        try:
            if op["f"] == "read":
                value, _ = self._read(k)
                return {**op, "type": "ok", "value": independent.kv(k, value)}
            if op["f"] == "write":
                self.conn.put(f"/v1/kv/jepsen/{k}", json.dumps(v))
                return {**op, "type": "ok"}
            if op["f"] == "cas":
                # (reference: consul/client.clj:66-85 cas!)
                old, new = v
                cur, index = self._read(k)
                if cur != old:
                    return {**op, "type": "fail", "error": "value-mismatch"}
                _, okbody = self.conn.put(
                    f"/v1/kv/jepsen/{k}", json.dumps(new),
                    params={"cas": str(index)},
                )
                if okbody is True or okbody == "true":
                    return {**op, "type": "ok"}
                return {**op, "type": "fail", "error": "index-cas-lost"}
            raise ValueError(f"unknown f {op['f']!r}")
        except IndeterminateError as e:
            return {**op, "type": "info", "error": str(e)}
        except HttpError as e:
            return {**op, "type": "fail", "error": f"{e.status}: {e.body}"}

    def close(self, test):
        if self.conn:
            self.conn.close()


def db(opts: Optional[dict] = None):
    return ConsulDB(opts)


def client(opts: Optional[dict] = None):
    return ConsulClient(opts)


def workloads(opts: Optional[dict] = None) -> dict:
    return {"register": common.register_workload(dict(opts or {}))}


def test(opts: Optional[dict] = None) -> dict:
    opts = dict(opts or {})
    w = workloads(opts)[opts.get("workload", "register")]
    return common.build_test(
        "consul-register", opts, db=ConsulDB(opts), client=ConsulClient(opts),
        workload=w,
    )
