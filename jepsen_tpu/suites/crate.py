"""CrateDB suite.

Reference: crate/src/jepsen/crate/core.clj — tarball install + OpenJDK 8
(core.clj:266-290), crate.yml with unicast discovery over the test
nodes, started via ``bin/crate`` (core.clj:292-320); workloads
dirty-read, lost-updates and version-divergence exercise Crate's
Elasticsearch-derived replication.  The reference talks JDBC; here the
client posts SQL to Crate's HTTP ``_sql`` endpoint.
"""

from __future__ import annotations

from typing import Any, List, Optional

from .. import client as client_mod
from .. import independent
from .. import checker as checker_mod
from .. import generator as gen
from ..control import util as cu
from ..control import execute, sudo
from ..os_setup import debian
from . import common
from .proto import IndeterminateError
from .proto.http import HttpError, JsonHttpClient

DEFAULT_TARBALL = "https://cdn.crate.io/downloads/releases/crate-0.57.4.tar.gz"
DIR = "/opt/crate"
HTTP_PORT = 4200
TRANSPORT_PORT = 4300


class CrateDB(common.DaemonDB):
    dir = DIR
    binary = "bin/crate"
    logfile = f"{DIR}/logs/stdout.log"
    pidfile = f"{DIR}/crate.pid"
    proc_name = "java"  # the server runs under the JVM

    def __init__(self, opts: Optional[dict] = None):
        super().__init__(opts)
        self.tarball = (opts or {}).get("tarball", DEFAULT_TARBALL)

    def install(self, test, node):
        debian.install(["openjdk-8-jre-headless"])
        with sudo():
            cu.install_archive(self.tarball, DIR)

    def configure(self, test, node):
        hosts = ", ".join(f'"{n}:{TRANSPORT_PORT}"' for n in test["nodes"])
        config = "\n".join(
            [
                "cluster.name: jepsen",
                f"node.name: {node}",
                "network.host: 0.0.0.0",
                f"discovery.zen.ping.unicast.hosts: [{hosts}]",
                f"gateway.expected_nodes: {len(test['nodes'])}",
                f"discovery.zen.minimum_master_nodes: "
                f"{len(test['nodes']) // 2 + 1}",
            ]
        )
        with sudo():
            cu.write_file(config, f"{DIR}/config/crate.yml")

    def start_args(self, test, node):
        return ["-d", "-p", self.pidfile]

    def await_ready(self, test, node):
        cu.await_tcp_port(HTTP_PORT, timeout_s=120)

    def wipe(self, test, node):
        with sudo():
            execute("rm", "-rf", f"{DIR}/data", f"{DIR}/logs")


class CrateSqlClient(client_mod.Client):
    """SQL over Crate's HTTP ``_sql`` endpoint.

    Register ops target a ``registers (id, value)`` table with
    ``_version``-guarded CAS — the optimistic-concurrency idiom the
    reference's lost-updates workload relies on
    (crate/src/jepsen/crate/core.clj version-divergence reads
    ``_version``)."""

    def __init__(self, opts: Optional[dict] = None):
        self.opts = opts or {}
        self.conn: Optional[JsonHttpClient] = None

    def open(self, test, node):
        c = type(self)(self.opts)
        c.conn = JsonHttpClient(
            self.opts.get("host", str(node)),
            self.opts.get("port", HTTP_PORT),
            timeout=10.0,
        )
        return c

    def sql(self, stmt: str, args: Optional[List[Any]] = None):
        body = {"stmt": stmt}
        if args:
            body["args"] = args
        _, out = self.conn.post("/_sql", body, ok=(200,))
        return out

    def setup(self, test):
        try:
            self.sql(
                "create table if not exists registers ("
                "id int primary key, value int) "
                "with (number_of_replicas = 'all')"
            )
        except (HttpError, IndeterminateError):
            pass

    def invoke(self, test, op):
        k, v = op["value"] if isinstance(op["value"], (list, tuple)) else (
            0, op["value"])
        try:
            if op["f"] == "read":
                out = self.sql("select value from registers where id = ?", [k])
                rows = out.get("rows") or []
                val = rows[0][0] if rows else None
                return {**op, "type": "ok", "value": independent.kv(k, val)}
            if op["f"] == "write":
                out = self.sql(
                    "insert into registers (id, value) values (?, ?) "
                    "on duplicate key update value = ?",
                    [k, v, v],
                )
                return {**op, "type": "ok"}
            if op["f"] == "cas":
                old, new = v
                out = self.sql(
                    "update registers set value = ? "
                    "where id = ? and value = ?",
                    [new, k, old],
                )
                if out.get("rowcount", 0) == 1:
                    return {**op, "type": "ok"}
                return {**op, "type": "fail", "error": "cas-miss"}
            raise ValueError(f"unknown f {op['f']!r}")
        except IndeterminateError as e:
            return {**op, "type": "info", "error": str(e)}
        except HttpError as e:
            return {**op, "type": "fail", "error": f"{e.status}: {e.body}"}

    def close(self, test):
        if self.conn:
            self.conn.close()


def db(opts: Optional[dict] = None):
    return CrateDB(opts)


def client(opts: Optional[dict] = None):
    return CrateSqlClient(opts)


def workloads(opts: Optional[dict] = None) -> dict:
    return {"register": common.register_workload(dict(opts or {}))}


def test(opts: Optional[dict] = None) -> dict:
    opts = dict(opts or {})
    w = workloads(opts)[opts.get("workload", "register")]
    return common.build_test(
        "crate-register", opts, db=CrateDB(opts), client=CrateSqlClient(opts),
        workload=w,
    )
