"""CrateDB suite.

Reference: crate/src/jepsen/crate/core.clj — tarball install + OpenJDK 8
(core.clj:266-290), crate.yml with unicast discovery over the test
nodes, started via ``bin/crate`` (core.clj:292-320); workloads
dirty-read, lost-updates and version-divergence exercise Crate's
Elasticsearch-derived replication.  The reference talks JDBC; here the
client posts SQL to Crate's HTTP ``_sql`` endpoint.
"""

from __future__ import annotations

import itertools as _itertools
import json as _json

from typing import Any, List, Optional

from .. import client as client_mod
from .. import independent
from .. import checker as checker_mod
from .. import generator as gen
from ..control import util as cu
from ..control import execute, sudo
from ..os_setup import debian
from . import common
from .proto import IndeterminateError
from .proto.http import HttpError, JsonHttpClient

DEFAULT_TARBALL = "https://cdn.crate.io/downloads/releases/crate-0.57.4.tar.gz"
DIR = "/opt/crate"
HTTP_PORT = 4200
TRANSPORT_PORT = 4300


class CrateDB(common.DaemonDB):
    dir = DIR
    binary = "bin/crate"
    logfile = f"{DIR}/logs/stdout.log"
    pidfile = f"{DIR}/crate.pid"
    proc_name = "java"  # the server runs under the JVM

    def __init__(self, opts: Optional[dict] = None):
        super().__init__(opts)
        self.tarball = (opts or {}).get("tarball", DEFAULT_TARBALL)

    def install(self, test, node):
        debian.install(["openjdk-8-jre-headless"])
        with sudo():
            cu.install_archive(self.tarball, DIR)

    def configure(self, test, node):
        hosts = ", ".join(f'"{n}:{TRANSPORT_PORT}"' for n in test["nodes"])
        config = "\n".join(
            [
                "cluster.name: jepsen",
                f"node.name: {node}",
                "network.host: 0.0.0.0",
                f"discovery.zen.ping.unicast.hosts: [{hosts}]",
                f"gateway.expected_nodes: {len(test['nodes'])}",
                f"discovery.zen.minimum_master_nodes: "
                f"{len(test['nodes']) // 2 + 1}",
            ]
        )
        with sudo():
            cu.write_file(config, f"{DIR}/config/crate.yml")

    def start_args(self, test, node):
        return ["-d", "-p", self.pidfile]

    def await_ready(self, test, node):
        cu.await_tcp_port(HTTP_PORT, timeout_s=120)

    def wipe(self, test, node):
        with sudo():
            execute("rm", "-rf", f"{DIR}/data", f"{DIR}/logs")


class CrateSqlClient(client_mod.Client):
    """SQL over Crate's HTTP ``_sql`` endpoint.

    Register ops target a ``registers (id, value)`` table with
    ``_version``-guarded CAS — the optimistic-concurrency idiom the
    reference's lost-updates workload relies on
    (crate/src/jepsen/crate/core.clj version-divergence reads
    ``_version``)."""

    def __init__(self, opts: Optional[dict] = None):
        self.opts = opts or {}
        self.conn: Optional[JsonHttpClient] = None

    def open(self, test, node):
        c = type(self)(self.opts)
        c.conn = JsonHttpClient(
            self.opts.get("host", str(node)),
            self.opts.get("port", HTTP_PORT),
            timeout=10.0,
        )
        return c

    def sql(self, stmt: str, args: Optional[List[Any]] = None):
        body = {"stmt": stmt}
        if args:
            body["args"] = args
        _, out = self.conn.post("/_sql", body, ok=(200,))
        return out

    def setup(self, test):
        try:
            self.sql(
                "create table if not exists registers ("
                "id int primary key, value int) "
                "with (number_of_replicas = 'all')"
            )
        except (HttpError, IndeterminateError):
            pass

    def invoke(self, test, op):
        k, v = op["value"] if isinstance(op["value"], (list, tuple)) else (
            0, op["value"])
        try:
            if op["f"] == "read":
                out = self.sql("select value from registers where id = ?", [k])
                rows = out.get("rows") or []
                val = rows[0][0] if rows else None
                return {**op, "type": "ok", "value": independent.kv(k, val)}
            if op["f"] == "write":
                out = self.sql(
                    "insert into registers (id, value) values (?, ?) "
                    "on duplicate key update value = ?",
                    [k, v, v],
                )
                return {**op, "type": "ok"}
            if op["f"] == "cas":
                old, new = v
                out = self.sql(
                    "update registers set value = ? "
                    "where id = ? and value = ?",
                    [new, k, old],
                )
                if out.get("rowcount", 0) == 1:
                    return {**op, "type": "ok"}
                return {**op, "type": "fail", "error": "cas-miss"}
            raise ValueError(f"unknown f {op['f']!r}")
        except IndeterminateError as e:
            return {**op, "type": "info", "error": str(e)}
        except HttpError as e:
            return {**op, "type": "fail", "error": f"{e.status}: {e.body}"}

    def close(self, test):
        if self.conn:
            self.conn.close()


def db(opts: Optional[dict] = None):
    return CrateDB(opts)


def client(opts: Optional[dict] = None):
    return CrateSqlClient(opts)


def workloads(opts: Optional[dict] = None) -> dict:
    opts = dict(opts or {})
    return {
        "register": common.register_workload(opts),
        # the suite's signature probes (reference: crate/dirty_read.clj,
        # lost_updates.clj, version_divergence.clj)
        "dirty-read": dirty_read_workload(opts),
        "lost-updates": lost_updates_workload(opts),
        "version-divergence": version_divergence_workload(opts),
    }


def test(opts: Optional[dict] = None) -> dict:
    opts = dict(opts or {})
    wname = opts.get("workload", "register")
    w = workloads(opts)[wname]
    c = {
        "dirty-read": CrateDirtyReadClient,
        "lost-updates": CrateLostUpdatesClient,
        "version-divergence": CrateVersionClient,
    }.get(wname, CrateSqlClient)(opts)
    return common.build_test(
        f"crate-{wname}", opts, db=CrateDB(opts), client=c,
        workload=w,
    )


# ---------------------------------------------------------------------
# dirty-read (reference: crate/src/jepsen/crate/dirty_read.clj)
# ---------------------------------------------------------------------


class CrateDirtyReadClient(CrateSqlClient):
    """Sequential-id inserts vs single-id reads vs a final strong read.
    (reference: dirty_read.clj:31-90 — read by id ok/fail, refresh,
    strong-read with a write-count-scaled limit, write)"""

    #: acknowledged-write counter shared across worker clones so the
    #: strong read's LIMIT always covers every insert (the reference's
    #: `limit` atom, dirty_read.clj:31,86)
    _writes = _itertools.count(1)
    _high_water = 0

    def setup(self, test):
        try:
            self.sql(
                "create table if not exists dirty_read (id int primary key) "
                "with (number_of_replicas = 'all')"
            )
        except (HttpError, IndeterminateError):
            pass

    def invoke(self, test, op):
        try:
            if op["f"] == "read":
                out = self.sql(
                    "select id from dirty_read where id = ?", [op["value"]]
                )
                found = bool(out.get("rows"))
                return {**op, "type": "ok" if found else "fail"}
            if op["f"] == "refresh":
                self.sql("refresh table dirty_read")
                return {**op, "type": "ok"}
            if op["f"] == "strong-read":
                out = self.sql(
                    "select id from dirty_read limit ?",
                    [100 + CrateDirtyReadClient._high_water],
                )
                ids = sorted(int(r[0]) for r in (out.get("rows") or []))
                return {**op, "type": "ok", "value": ids}
            if op["f"] == "write":
                n = next(CrateDirtyReadClient._writes)
                CrateDirtyReadClient._high_water = max(
                    CrateDirtyReadClient._high_water, n
                )
                self.sql(
                    "insert into dirty_read (id) values (?)", [op["value"]]
                )
                return {**op, "type": "ok"}
            raise ValueError(f"unknown f {op['f']!r}")
        except IndeterminateError as e:
            return {**op, "type": "info", "error": str(e)}
        except HttpError as e:
            return {**op, "type": "fail", "error": f"{e.status}: {e.body}"}


class DirtyReadChecker(checker_mod.Checker):
    """No successful read of an id that the final strong reads don't
    contain (a dirty read of uncommitted state), and no acknowledged
    write missing from them (a lost write).
    (reference: dirty_read.clj:143-190 checker)"""

    def check(self, test, history, opts=None):
        from ..history import OK

        writes, reads, strong = set(), set(), set()
        saw_strong = False
        for op in history:
            if op.type != OK:
                continue
            if op.f == "write":
                writes.add(op.value)
            elif op.f == "read":
                reads.add(op.value)
            elif op.f == "strong-read":
                saw_strong = True
                strong |= set(op.value or [])
        if not saw_strong:
            return {"valid?": "unknown", "error": "no strong read"}
        dirty = sorted(reads - strong)
        lost = sorted(writes - strong)
        return {
            "valid?": not (dirty or lost),
            "dirty": dirty[:10],
            "lost": lost[:10],
            "read-count": len(reads),
            "write-count": len(writes),
            "strong-count": len(strong),
        }


def dirty_read_workload(opts: Optional[dict] = None) -> dict:
    """Writers insert sequential ids; readers probe recently-written
    ids; a final refresh + strong read per thread settles the verdict.
    (reference: dirty_read.clj:196-250 test)"""
    state = {"next": 0}

    def w(test, ctx):
        v = state["next"]
        state["next"] += 1
        return {"type": "invoke", "f": "write", "value": v}

    def r(test, ctx):
        hi = max(1, state["next"])
        return {"type": "invoke", "f": "read",
                "value": gen.rng.randrange(hi)}

    final = gen.clients(gen.phases(
        gen.each_thread(
            gen.once({"type": "invoke", "f": "refresh", "value": None})
        ),
        gen.each_thread(
            gen.once(
                {"type": "invoke", "f": "strong-read", "value": None}
            )
        ),
    ))
    return {
        "generator": gen.mix([w, r]),
        "final-generator": final,
        "checker": DirtyReadChecker(),
    }


# ---------------------------------------------------------------------
# lost-updates (reference: crate/src/jepsen/crate/lost_updates.clj)
# ---------------------------------------------------------------------


class CrateLostUpdatesClient(CrateSqlClient):
    """Per-key sets grown by read + version-checked write-back (crate's
    _version optimistic concurrency); a losing CAS is a clean :fail.
    (reference: lost_updates.clj:32-104)"""

    def setup(self, test):
        try:
            self.sql(
                "create table if not exists sets "
                "(id int primary key, elements string) "
                "with (number_of_replicas = 'all')"
            )
        except (HttpError, IndeterminateError):
            pass

    def invoke(self, test, op):
        k, v = op["value"]
        try:
            if op["f"] == "read":
                out = self.sql(
                    "select elements from sets where id = ?", [k]
                )
                rows = out.get("rows") or []
                els = sorted(_json.loads(rows[0][0])) if rows else []
                return {**op, "type": "ok",
                        "value": independent.kv(k, els)}
            if op["f"] == "add":
                out = self.sql(
                    "select elements, _version from sets where id = ?", [k]
                )
                rows = out.get("rows") or []
                if rows:
                    els = _json.loads(rows[0][0])
                    version = rows[0][1]
                    els2 = _json.dumps(els + [v])
                    res = self.sql(
                        "update sets set elements = ? "
                        "where id = ? and _version = ?",
                        [els2, k, version],
                    )
                    if res.get("rowcount", 0) == 1:
                        return {**op, "type": "ok"}
                    return {**op, "type": "fail", "error": "version-miss"}
                self.sql(
                    "insert into sets (id, elements) values (?, ?)",
                    [k, _json.dumps([v])],
                )
                return {**op, "type": "ok"}
            raise ValueError(f"unknown f {op['f']!r}")
        except IndeterminateError as e:
            return {**op, "type": "info", "error": str(e)}
        except HttpError as e:
            return {**op, "type": "fail", "error": f"{e.status}: {e.body}"}


def lost_updates_workload(opts: Optional[dict] = None) -> dict:
    """Per-key adds then a final read per key — lost updates show up as
    adds missing from the final read.  Delegates to the shared
    independent-set builder.  (reference: lost_updates.clj:106-160)"""
    return common.independent_set_workload(opts)


# ---------------------------------------------------------------------
# version-divergence
# (reference: crate/src/jepsen/crate/version_divergence.clj)
# ---------------------------------------------------------------------


class CrateVersionClient(CrateSqlClient):
    """Reads return [value, _version]; upsert writes.
    (reference: version_divergence.clj:53-73)"""

    def invoke(self, test, op):
        k, v = op["value"] if isinstance(op["value"], (list, tuple)) else (
            0, op["value"])
        try:
            if op["f"] == "read":
                out = self.sql(
                    "select value, _version from registers where id = ?",
                    [k],
                )
                rows = out.get("rows") or []
                val = list(rows[0]) if rows else None
                return {**op, "type": "ok", "value": independent.kv(k, val)}
            if op["f"] == "write":
                self.sql(
                    "insert into registers (id, value) values (?, ?) "
                    "on duplicate key update value = ?",
                    [k, v, v],
                )
                return {**op, "type": "ok"}
            raise ValueError(f"unknown f {op['f']!r}")
        except IndeterminateError as e:
            return {**op, "type": "info", "error": str(e)}
        except HttpError as e:
            return {**op, "type": "fail", "error": f"{e.status}: {e.body}"}


class MultiversionChecker(checker_mod.Checker):
    """Every read of one _version must observe the same value —
    divergent values under a single version are replica divergence.
    (reference: version_divergence.clj:95-110)"""

    def check(self, test, history, opts=None):
        from ..history import OK

        by_version: dict = {}
        for op in history:
            if op.type == OK and op.f == "read" and op.value is not None:
                if op.value[0] is None:
                    continue
                value, version = op.value
                by_version.setdefault(version, set()).add(value)
        multis = {
            str(ver): sorted(vals)
            for ver, vals in by_version.items()
            if len(vals) > 1
        }
        return {"valid?": not multis, "multis": multis}


def version_divergence_workload(opts: Optional[dict] = None) -> dict:
    """Reads/writes lifted over independent keys; the per-key
    subhistories feed the multiversion checker.
    (reference: version_divergence.clj:112-140 test)"""
    opts = dict(opts or {})
    n = max(1, len(opts.get("nodes", ["n1"])))

    def fgen(k):
        def r(test, ctx):
            return {"type": "invoke", "f": "read", "value": None}

        def w(test, ctx):
            return {"type": "invoke", "f": "write",
                    "value": gen.rng.randrange(5)}

        return gen.limit(
            int(opts.get("per-key-limit", 20)), gen.mix([r, w])
        )

    return {
        "generator": independent.concurrent_generator(
            2 * n, range(100_000), fgen
        ),
        "checker": independent.checker(MultiversionChecker()),
        "concurrency": 2 * n,
    }
