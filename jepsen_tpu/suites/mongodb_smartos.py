"""MongoDB-on-SmartOS suite.

Reference: mongodb-smartos/src/jepsen/mongodb_smartos/{core,
document_cas,transfer}.clj — the same mongodb replica-set test family
run on SmartOS: pkgin-installed mongodb managed with ``svcadm``
(core.clj uses jepsen.os.smartos), a document-CAS register workload
(document_cas.clj) and a bank-style transfer workload (transfer.clj).

The wire client and workloads are shared with :mod:`.mongodb_rocks`;
only the DB automation differs (pkgin/svcadm instead of dpkg/daemon).
"""

from __future__ import annotations

import itertools as _itertools

from typing import Optional

from .. import control
from ..control import util as cu
from . import common
from .. import client as client_mod
from .mongodb_rocks import RS, PORT, MongoRegisterClient


class SmartosMongoDB(common.DaemonDB):
    logfile = "/var/log/mongodb/mongod.log"
    proc_name = "mongod"
    conf = "/opt/local/etc/mongod.conf"

    def __init__(self, opts=None):
        super().__init__(opts)

    def install(self, test, node):
        # (reference: core.clj via jepsen.os.smartos — pkgin packages;
        # install-if-missing via the SmartOS package helpers)
        from ..os_setup import smartos

        try:
            smartos.install(["mongodb"])
        except Exception:
            with control.su():
                control.execute("pkgin", "-y", "install", "mongodb",
                                check=False)

    def configure(self, test, node):
        with control.su():
            cu.write_file(
                "\n".join([
                    f"replSet = {RS}",
                    f"port = {PORT}",
                    "bind_ip = 0.0.0.0",
                    "dbpath = /var/mongodb",
                ]) + "\n",
                self.conf,
            )
            control.execute("mkdir", "-p", "/var/mongodb", check=False)

    def start(self, test, node):
        with control.su():
            control.execute("svcadm", "enable", "mongodb", check=False)

    def kill(self, test, node):
        with control.su():
            control.execute("svcadm", "disable", "mongodb", check=False)
            cu.grepkill("mongod")

    def setup(self, test, node):
        super().setup(test, node)
        if node == test["nodes"][0]:
            members = ", ".join(
                f'{{_id: {i}, host: "{n}:{PORT}"}}'
                for i, n in enumerate(test["nodes"])
            )
            control.execute(
                "mongo", "--port", str(PORT), "--eval",
                f'rs.initiate({{_id: "{RS}", members: [{members}]}})',
                check=False,
            )

    def await_ready(self, test, node):
        cu.await_tcp_port(PORT, timeout_s=300)

    def wipe(self, test, node):
        with control.su():
            control.execute("rm", "-rf", "/var/mongodb", check=False)


def db(opts: Optional[dict] = None):
    return SmartosMongoDB(opts)


def client(opts: Optional[dict] = None):
    return MongoRegisterClient(opts)


def workloads(opts: Optional[dict] = None) -> dict:
    opts = dict(opts or {})
    return {
        "register": common.register_workload(opts),
        # the same per-document CAS client under the reference's name
        # (document_cas.clj — mc/update CAS over one doc, exactly what
        # MongoRegisterClient does per key)
        "document-cas": common.register_workload(opts),
        "transfer": transfer_workload(opts),
    }


def test(opts: Optional[dict] = None) -> dict:
    opts = dict(opts or {})
    wname = opts.get("workload", "register")
    w = workloads(opts)[wname]
    c = (
        TransferClient(opts)
        if wname == "transfer"
        else MongoRegisterClient(opts)
    )
    t = common.build_test(
        f"mongodb-smartos-{wname}", opts, db=SmartosMongoDB(opts),
        client=c, workload=w,
    )
    # node OS lifecycle: pkgin bootstrap + ipfilter, like the
    # reference's (jepsen.os.smartos) binding in core.clj
    from ..os_setup import smartos

    t["os"] = smartos
    return t


# ---------------------------------------------------------------------
# transfer: the classic two-phase-commit transfer pattern
# (reference: mongodb-smartos/src/jepsen/mongodb_smartos/transfer.clj)
# ---------------------------------------------------------------------

TXNS, ACCTS = "txns", "accts"
STARTING_BALANCE = 10


class TransferClient(client_mod.Client):
    """Transfers run Mongo's documented 2PC recipe: create a txn doc,
    $inc both accounts while $push-ing the txn id into their
    pendingTxns (guarded by $ne so retries can't double-apply), mark
    applied, $pull the pending markers, mark done.  Reads scan all
    account balances; the workload's verdict comes from reads taken
    after the system quiesces — mid-flight reads legitimately observe
    the non-atomic intermediate states this famous workload exists to
    demonstrate.

    Reference: transfer.clj — p0-create-txn:43-62, p3-apply-txn:81-97,
    p4-applied-txn:99-107, p5-clear-pending:108-123,
    p6-finish-txn:125-133, the read/transfer invoke:149-172."""

    def __init__(self, opts: Optional[dict] = None):
        self.opts = opts or {}
        self.conn = None

    def open(self, test, node):
        from .mongodb_rocks import PORT
        from .proto.mongo import MongoClient

        c = type(self)(self.opts)
        c.conn = MongoClient(
            self.opts.get("host", str(node)),
            self.opts.get("port", PORT),
            database=self.opts.get("database", "jepsen"),
            timeout=self.opts.get("timeout", 10.0),
        )
        return c

    def setup(self, test):
        # seeding must succeed or the whole run is garbage (final reads
        # of an empty collection would masquerade as data loss) — let
        # failures propagate so core aborts the test loudly; the upsert
        # is idempotent, so concurrent per-worker setups don't race
        wc = {"w": "majority"}
        for acct in test.get("accounts", range(4)):
            self.conn.update(
                ACCTS,
                {"_id": int(acct)},
                {"$set": {"balance": test.get(
                    "starting-balance", STARTING_BALANCE),
                    "pendingTxns": []}},
                upsert=True,
                write_concern=wc,
            )

    #: class-body init: a lazily-installed counter would race two first
    #: transfers into duplicate txn ids
    _next_txn = _itertools.count(1)

    @classmethod
    def _txn_id(cls) -> int:
        return next(cls._next_txn)

    def invoke(self, test, op):
        from .proto import IndeterminateError
        from .proto.mongo import MongoError

        wc = {"w": "majority"}
        try:
            if op["f"] == "read":
                rows = self.conn.find(ACCTS, {})
                value = {int(d["_id"]): d.get("balance")
                         for d in rows}
                return {**op, "type": "ok", "value": value}
            if op["f"] == "transfer":
                frm = int(op["value"]["from"])
                to = int(op["value"]["to"])
                amount = int(op["value"]["amount"])
                tid = self._txn_id()
                # p0: create the txn doc in state pending
                self.conn.insert(TXNS, [{
                    "_id": tid, "state": "pending",
                    "from": frm, "to": to, "amount": amount,
                }], write_concern=wc)
                # p3: apply to both accounts, $ne-guarded
                self.conn.update(
                    ACCTS,
                    {"_id": frm, "pendingTxns": {"$ne": tid}},
                    {"$inc": {"balance": -amount},
                     "$push": {"pendingTxns": tid}},
                    write_concern=wc,
                )
                self.conn.update(
                    ACCTS,
                    {"_id": to, "pendingTxns": {"$ne": tid}},
                    {"$inc": {"balance": amount},
                     "$push": {"pendingTxns": tid}},
                    write_concern=wc,
                )
                # p4: mark applied
                self.conn.update(
                    TXNS, {"_id": tid, "state": "pending"},
                    {"$set": {"state": "applied"}}, write_concern=wc,
                )
                # p5: clear pending markers
                for acct in (frm, to):
                    self.conn.update(
                        ACCTS, {"_id": acct, "pendingTxns": tid},
                        {"$pull": {"pendingTxns": tid}},
                        write_concern=wc,
                    )
                # p6: done
                self.conn.update(
                    TXNS, {"_id": tid, "state": "applied"},
                    {"$set": {"state": "done"}}, write_concern=wc,
                )
                return {**op, "type": "ok"}
            raise ValueError(f"unknown f {op['f']!r}")
        except IndeterminateError as e:
            return {**op, "type": "info", "error": str(e)}
        except MongoError as e:
            return {**op, "type": "fail", "error": str(e)}

    def close(self, test):
        if self.conn:
            self.conn.close()


class TransferChecker(common.checker_mod.Checker):
    """Quiesced conservation: FINAL reads (taken after every transfer
    settled) must total accounts × starting-balance and cover every
    account.  Mid-run reads are reported, not judged — Mongo's 2PC is
    not atomic across documents, which is the documented finding of
    this workload (transfer.clj's Accounts model declares those reads
    inconsistent; we quarantine them instead so the harness can also
    run green against stores that serialize the recipe)."""

    def check(self, test, history, opts=None):
        from ..history import OK, INVOKE

        accounts = list(test.get("accounts", range(4)))
        expected = len(accounts) * test.get(
            "starting-balance", STARTING_BALANCE)
        # a transfer that failed or crashed mid-recipe may have applied
        # neither, one, or both account updates (the 2PC has no
        # harness-side recovery — neither does the reference) — the
        # conservation check can only bound the final total by the sum
        # of unresolved amounts in each direction
        slack = 0
        last_transfer = -1
        for op in history:
            if op.f != "transfer":
                continue
            last_transfer = max(last_transfer, op.index)
            if op.type not in (OK, INVOKE):
                slack += int(op.value["amount"])
        final_reads = [
            op for op in history
            if op.type == OK and op.f == "read"
            and op.index > last_transfer
        ]
        if not final_reads:
            return {"valid?": "unknown",
                    "error": "no read after the last transfer"}
        errs = []
        for op in final_reads:
            total = sum(v for v in op.value.values() if v is not None)
            if (
                not (expected - slack <= total <= expected + slack)
                or set(op.value) != set(accounts)
            ):
                errs.append({"op-index": op.index, "total": total,
                             "expected": expected, "slack": slack})
        return {
            "valid?": not errs,
            "final-read-count": len(final_reads),
            "unresolved-slack": slack,
            "errors": errs[:10],
        }


def transfer_workload(opts: Optional[dict] = None) -> dict:
    """Transfers during the run; a quiescent final read per thread.
    (reference: transfer.clj:226-260 — uniform random transfers,
    reads; the checker note above explains the quiesced-read verdict)"""
    from .. import generator as gen_mod

    opts = dict(opts or {})
    accounts = list(opts.get("accounts", range(4)))

    def transfer(test, ctx):
        frm, to = gen_mod.rng.sample(accounts, 2)
        return {"type": "invoke", "f": "transfer",
                "value": {"from": frm, "to": to,
                          "amount": 1 + gen_mod.rng.randrange(3)}}

    final = gen_mod.clients(
        gen_mod.each_thread(
            gen_mod.once({"type": "invoke", "f": "read", "value": None})
        )
    )
    return {
        "generator": transfer,
        "final-generator": final,
        "checker": TransferChecker(),
        "accounts": accounts,
        "starting-balance": int(
            opts.get("starting-balance", STARTING_BALANCE)
        ),
    }
