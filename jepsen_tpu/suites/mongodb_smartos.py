"""MongoDB-on-SmartOS suite.

Reference: mongodb-smartos/src/jepsen/mongodb_smartos/{core,
document_cas,transfer}.clj — the same mongodb replica-set test family
run on SmartOS: pkgin-installed mongodb managed with ``svcadm``
(core.clj uses jepsen.os.smartos), a document-CAS register workload
(document_cas.clj) and a bank-style transfer workload (transfer.clj).

The wire client and workloads are shared with :mod:`.mongodb_rocks`;
only the DB automation differs (pkgin/svcadm instead of dpkg/daemon).
"""

from __future__ import annotations

from typing import Optional

from .. import control
from ..control import util as cu
from . import common
from .mongodb_rocks import RS, PORT, MongoRegisterClient


class SmartosMongoDB(common.DaemonDB):
    logfile = "/var/log/mongodb/mongod.log"
    proc_name = "mongod"
    conf = "/opt/local/etc/mongod.conf"

    def __init__(self, opts=None):
        super().__init__(opts)

    def install(self, test, node):
        # (reference: core.clj via jepsen.os.smartos — pkgin packages;
        # install-if-missing via the SmartOS package helpers)
        from ..os_setup import smartos

        try:
            smartos.install(["mongodb"])
        except Exception:
            with control.su():
                control.execute("pkgin", "-y", "install", "mongodb",
                                check=False)

    def configure(self, test, node):
        with control.su():
            cu.write_file(
                "\n".join([
                    f"replSet = {RS}",
                    f"port = {PORT}",
                    "bind_ip = 0.0.0.0",
                    "dbpath = /var/mongodb",
                ]) + "\n",
                self.conf,
            )
            control.execute("mkdir", "-p", "/var/mongodb", check=False)

    def start(self, test, node):
        with control.su():
            control.execute("svcadm", "enable", "mongodb", check=False)

    def kill(self, test, node):
        with control.su():
            control.execute("svcadm", "disable", "mongodb", check=False)
            cu.grepkill("mongod")

    def setup(self, test, node):
        super().setup(test, node)
        if node == test["nodes"][0]:
            members = ", ".join(
                f'{{_id: {i}, host: "{n}:{PORT}"}}'
                for i, n in enumerate(test["nodes"])
            )
            control.execute(
                "mongo", "--port", str(PORT), "--eval",
                f'rs.initiate({{_id: "{RS}", members: [{members}]}})',
                check=False,
            )

    def await_ready(self, test, node):
        cu.await_tcp_port(PORT, timeout_s=300)

    def wipe(self, test, node):
        with control.su():
            control.execute("rm", "-rf", "/var/mongodb", check=False)


def db(opts: Optional[dict] = None):
    return SmartosMongoDB(opts)


def client(opts: Optional[dict] = None):
    return MongoRegisterClient(opts)


def workloads(opts: Optional[dict] = None) -> dict:
    return {"register": common.register_workload(dict(opts or {}))}


def test(opts: Optional[dict] = None) -> dict:
    opts = dict(opts or {})
    w = workloads(opts)["register"]
    t = common.build_test(
        "mongodb-smartos-register", opts, db=SmartosMongoDB(opts),
        client=MongoRegisterClient(opts), workload=w,
    )
    # node OS lifecycle: pkgin bootstrap + ipfilter, like the
    # reference's (jepsen.os.smartos) binding in core.clj
    from ..os_setup import smartos

    t["os"] = smartos
    return t
