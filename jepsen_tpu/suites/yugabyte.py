"""YugabyteDB suite.

Reference: yugabyte/src/yugabyte/* — the largest reference suite
(~3.6k LoC): a tarball install with ``yb-master`` processes on the
first ``replication-factor`` nodes and ``yb-tserver`` everywhere
(auto.clj:49-140), and two API families for every workload:

- **YCQL** (Cassandra protocol, port 9042): bank, counter, set,
  single/multi-key-acid, long-fork (yugabyte/ycql/*.clj)
- **YSQL** (PostgreSQL protocol, port 5433): bank, append, long-fork,
  default-value (yugabyte/ysql/*.clj)

YCQL clients here ride :mod:`.proto.cql` (LWT ``IF`` conditions give
CAS); YSQL clients reuse the shared :mod:`.sql` pgwire clients.
"""

from __future__ import annotations

from typing import Optional

from .. import client as client_mod
from .. import independent
from ..control import util as cu
from ..control import execute, sudo
from . import common, sql, yb_nemesis
from .proto import IndeterminateError
from .proto.cql import CqlClient, CqlError

DIR = "/opt/yugabyte"  # (reference: auto.clj dir)
MASTER_RPC_PORT = 7100
TSERVER_RPC_PORT = 9100
YCQL_PORT = 9042
YSQL_PORT = 5433
DEFAULT_TARBALL = (
    "https://downloads.yugabyte.com/yugabyte-2.1.2.0-linux.tar.gz"
)
KEYSPACE = "jepsen"


class YugabyteDB(common.DaemonDB):
    """yb-master on the first RF nodes, yb-tserver everywhere.
    (reference: auto.clj:57-76 master-nodes, 90-140 start!)"""

    dir = DIR
    binary = "bin/yb-tserver"
    logfile = f"{DIR}/tserver.log"
    pidfile = f"{DIR}/tserver.pid"
    master_logfile = f"{DIR}/master.log"
    master_pidfile = f"{DIR}/master.pid"

    def __init__(self, opts: Optional[dict] = None):
        super().__init__(opts)
        self.tarball = (opts or {}).get("tarball", DEFAULT_TARBALL)
        self.rf = (opts or {}).get("replication-factor", 3)

    def master_nodes(self, test):
        return test["nodes"][: min(self.rf, len(test["nodes"]))]

    def master_addresses(self, test) -> str:
        return ",".join(
            f"{n}:{MASTER_RPC_PORT}" for n in self.master_nodes(test)
        )

    def install(self, test, node):
        with sudo():
            cu.install_archive(self.tarball, DIR)
            execute(f"{DIR}/bin/post_install.sh", check=False)

    def start(self, test, node):
        if node in self.master_nodes(test):
            self.start_master(test, node)
            cu.await_tcp_port(MASTER_RPC_PORT, timeout_s=120)
        self.start_tserver(test, node)

    # granular component control — the per-suite nemesis targets
    # masters and tservers separately (reference: auto.clj
    # start-master!/start-tserver!/stop-*/kill-*, consumed by
    # yugabyte/nemesis.clj:12-46 process-nemesis)

    def start_master(self, test, node):
        if node not in self.master_nodes(test):
            return "not a master node"
        masters = self.master_addresses(test)
        cu.start_daemon(
            {"logfile": self.master_logfile,
             "pidfile": self.master_pidfile, "chdir": DIR},
            f"{DIR}/bin/yb-master",
            "--master_addresses", masters,
            "--rpc_bind_addresses", f"{node}:{MASTER_RPC_PORT}",
            "--fs_data_dirs", f"{DIR}/data/master",
            "--replication_factor", str(self.rf),
        )
        return "started"

    def start_tserver(self, test, node):
        masters = self.master_addresses(test)
        cu.start_daemon(
            {"logfile": self.logfile, "pidfile": self.pidfile, "chdir": DIR},
            f"{DIR}/bin/yb-tserver",
            "--tserver_master_addrs", masters,
            "--rpc_bind_addresses", f"{node}:{TSERVER_RPC_PORT}",
            "--fs_data_dirs", f"{DIR}/data/tserver",
            "--start_pgsql_proxy",
            "--pgsql_proxy_bind_address", f"0.0.0.0:{YSQL_PORT}",
            "--cql_proxy_bind_address", f"0.0.0.0:{YCQL_PORT}",
        )
        return "started"

    def stop_master(self, test, node):
        cu.stop_daemon(pidfile=self.master_pidfile, cmd="yb-master")
        return "stopped"

    def stop_tserver(self, test, node):
        cu.stop_daemon(pidfile=self.pidfile, cmd="yb-tserver")
        return "stopped"

    def kill_master(self, test, node):
        cu.grepkill("yb-master", 9)
        return "killed"

    def kill_tserver(self, test, node):
        cu.grepkill("yb-tserver", 9)
        return "killed"

    def kill(self, test, node):
        cu.stop_daemon(pidfile=self.pidfile, cmd="yb-tserver")
        cu.stop_daemon(pidfile=self.master_pidfile, cmd="yb-master")

    def await_ready(self, test, node):
        cu.await_tcp_port(YCQL_PORT, timeout_s=300)

    def wipe(self, test, node):
        with sudo():
            execute("rm", "-rf", f"{DIR}/data")

    def log_files(self, test, node):
        return [self.logfile, self.master_logfile]


# ---------------------------------------------------------------------
# YCQL clients (reference: yugabyte/ycql/*.clj)
# ---------------------------------------------------------------------


class YcqlRegisterClient(client_mod.Client):
    """Per-key CAS registers with LWT: writes unconditional, CAS via
    ``IF val = old`` (reference: ycql/single_key_acid.clj)."""

    def __init__(self, opts: Optional[dict] = None):
        self.opts = opts or {}
        self.conn: Optional[CqlClient] = None

    def open(self, test, node):
        c = type(self)(self.opts)
        c.conn = CqlClient(
            self.opts.get("host", str(node)),
            self.opts.get("port", YCQL_PORT),
            timeout=self.opts.get("timeout", 10.0),
        )
        return c

    def setup(self, test):
        for stmt in (
            f"CREATE KEYSPACE IF NOT EXISTS {KEYSPACE}",
            f"CREATE TABLE IF NOT EXISTS {KEYSPACE}.registers "
            "(id int PRIMARY KEY, val int)",
        ):
            try:
                self.conn.query(stmt)
            except (CqlError, IndeterminateError):
                pass

    def invoke(self, test, op):
        k, v = op["value"]
        t = f"{KEYSPACE}.registers"
        try:
            if op["f"] == "read":
                res = self.conn.query(
                    f"SELECT val FROM {t} WHERE id = {int(k)}",
                    consistency="quorum",
                )
                val = res.cell_int(res.rows[0], 0) if res.rows else None
                return {**op, "type": "ok", "value": independent.kv(k, val)}
            if op["f"] == "write":
                self.conn.query(
                    f"INSERT INTO {t} (id, val) VALUES ({int(k)}, {int(v)})"
                )
                return {**op, "type": "ok"}
            if op["f"] == "cas":
                old, new = v
                res = self.conn.query(
                    f"UPDATE {t} SET val = {int(new)} WHERE id = {int(k)} "
                    f"IF val = {int(old)}"
                )
                applied = bool(res.rows) and res.cell_bool(res.rows[0], 0)
                if applied:
                    return {**op, "type": "ok"}
                return {**op, "type": "fail", "error": "cas-miss"}
            raise ValueError(f"unknown f {op['f']!r}")
        except IndeterminateError as e:
            return {**op, "type": "info", "error": str(e)}
        except CqlError as e:
            if e.timeout:
                return {**op, "type": "info", "error": str(e)}
            return {**op, "type": "fail", "error": str(e)}

    def close(self, test):
        if self.conn:
            self.conn.close()


class YcqlSetClient(client_mod.Client):
    """Set workload: one row per element (reference: ycql/set.clj)."""

    def __init__(self, opts: Optional[dict] = None):
        self.opts = opts or {}
        self.conn: Optional[CqlClient] = None

    def open(self, test, node):
        c = type(self)(self.opts)
        c.conn = CqlClient(
            self.opts.get("host", str(node)),
            self.opts.get("port", YCQL_PORT),
            timeout=self.opts.get("timeout", 10.0),
        )
        return c

    def setup(self, test):
        for stmt in (
            f"CREATE KEYSPACE IF NOT EXISTS {KEYSPACE}",
            f"CREATE TABLE IF NOT EXISTS {KEYSPACE}.elements "
            "(val int PRIMARY KEY)",
        ):
            try:
                self.conn.query(stmt)
            except (CqlError, IndeterminateError):
                pass

    def invoke(self, test, op):
        t = f"{KEYSPACE}.elements"
        try:
            if op["f"] == "add":
                self.conn.query(
                    f"INSERT INTO {t} (val) VALUES ({int(op['value'])})"
                )
                return {**op, "type": "ok"}
            if op["f"] == "read":
                res = self.conn.query(f"SELECT val FROM {t}",
                                      consistency="quorum")
                return {**op, "type": "ok",
                        "value": sorted(res.cell_int(r, 0) for r in res.rows)}
            raise ValueError(f"unknown f {op['f']!r}")
        except IndeterminateError as e:
            return {**op, "type": "info", "error": str(e)}
        except CqlError as e:
            if e.timeout:
                return {**op, "type": "info", "error": str(e)}
            return {**op, "type": "fail", "error": str(e)}

    def close(self, test):
        if self.conn:
            self.conn.close()


# ---------------------------------------------------------------------


def _ysql_opts(opts: Optional[dict]) -> dict:
    o = dict(opts or {})
    o.setdefault("dialect", "pg")
    o.setdefault("port", YSQL_PORT)
    o.setdefault("user", "postgres")
    return o


def db(opts: Optional[dict] = None):
    return YugabyteDB(opts)


def client(opts: Optional[dict] = None):
    return YcqlRegisterClient(opts)


class YcqlCounterClient(client_mod.Client):
    """Counter column increments (reference: ycql/counter.clj)."""

    def __init__(self, opts: Optional[dict] = None):
        self.opts = opts or {}
        self.conn: Optional[CqlClient] = None

    def open(self, test, node):
        c = type(self)(self.opts)
        c.conn = CqlClient(
            self.opts.get("host", str(node)),
            self.opts.get("port", YCQL_PORT),
            timeout=self.opts.get("timeout", 10.0),
        )
        return c

    def setup(self, test):
        for stmt in (
            f"CREATE KEYSPACE IF NOT EXISTS {KEYSPACE}",
            f"CREATE TABLE IF NOT EXISTS {KEYSPACE}.counters "
            "(id int PRIMARY KEY, val counter)",
        ):
            try:
                self.conn.query(stmt)
            except (CqlError, IndeterminateError):
                pass

    def invoke(self, test, op):
        t = f"{KEYSPACE}.counters"
        try:
            if op["f"] == "add":
                self.conn.query(
                    f"UPDATE {t} SET val = val + {int(op['value'])} "
                    "WHERE id = 0"
                )
                return {**op, "type": "ok"}
            if op["f"] == "read":
                res = self.conn.query(
                    f"SELECT val FROM {t} WHERE id = 0",
                    consistency="quorum",
                )
                val = res.cell_int(res.rows[0], 0) if res.rows else 0
                return {**op, "type": "ok", "value": val or 0}
            raise ValueError(f"unknown f {op['f']!r}")
        except IndeterminateError as e:
            return {**op, "type": "info", "error": str(e)}
        except CqlError as e:
            if e.timeout:
                return {**op, "type": "info", "error": str(e)}
            return {**op, "type": "fail", "error": str(e)}

    def close(self, test):
        if self.conn:
            self.conn.close()


def workloads(opts: Optional[dict] = None) -> dict:
    """ycql.* and ysql.* workload names, like the reference's
    workload-per-API naming (runner.clj).  single-key-acid is the
    reference's name for the per-key linearizable CAS-register probe
    (single_key_acid.clj:1-45: 2n-thread key groups, half writers/CAS,
    half readers) — the same shape as the register workload, exposed
    under both names so reference users find it."""
    opts = dict(opts or {})
    out = {}
    for w in ("register", "set", "counter"):
        out[f"ycql.{w}"] = common.generic_workload(w, opts)
    for w in ("register", "bank", "set", "counter", "list-append",
              "long-fork"):
        out[f"ysql.{w}"] = common.generic_workload(w, _ysql_opts(opts))
    out["ycql.single-key-acid"] = common.generic_workload("register", opts)
    out["ysql.single-key-acid"] = common.generic_workload(
        "register", _ysql_opts(opts)
    )
    # the CQL transfer is unconditional balance arithmetic (no read
    # inside the txn), so balances legitimately go negative — the
    # reference pairs it with the allow-negative bank workload
    # (yugabyte/bank.clj:13-14 workload-allow-neg, bank.clj:183)
    out["ycql.bank"] = common.generic_workload(
        "bank", {**opts, "negative-balances?": True}
    )
    out["ycql.long-fork"] = common.generic_workload("long-fork", opts)
    # list-append with one table per key (reference: ysql/append_table
    # .clj); the txn checker is the shared elle list-append checker
    out["ysql.append-table"] = common.generic_workload(
        "list-append", _ysql_opts(opts)
    )
    out["ysql.multi-key-acid"] = multi_key_acid_workload(opts)
    out["ycql.multi-key-acid"] = multi_key_acid_workload(opts)
    out["ysql.default-value"] = default_value_workload(opts)
    return out


_YCQL_CLIENTS = {
    "register": YcqlRegisterClient,
    "single-key-acid": YcqlRegisterClient,
    "set": YcqlSetClient,
    "counter": YcqlCounterClient,
}


def _client_for(wname: str, opts: dict) -> client_mod.Client:
    api, _, w = wname.partition(".")
    if api == "ycql":
        if w == "multi-key-acid":
            return YcqlMultiKeyAcidClient(opts)
        if w == "bank":
            return YcqlBankClient(opts)
        if w == "long-fork":
            return YcqlLongForkClient(opts)
        return _YCQL_CLIENTS[w](opts)
    if w == "multi-key-acid":
        return MultiKeyAcidClient(_ysql_opts(opts))
    if w == "default-value":
        return DefaultValueClient(_ysql_opts(opts))
    if w == "append-table":
        return AppendTableClient(_ysql_opts(opts))
    if w == "single-key-acid":
        w = "register"
    return sql.client_for(w, _ysql_opts(opts))


def test(opts: Optional[dict] = None) -> dict:
    opts = dict(opts or {})
    wname = opts.get("workload", "ycql.register")
    w = workloads(opts)[wname]
    db_ = YugabyteDB(opts)
    # the suite fault menu (master/tserver targeting, partition
    # geometries, clock skew) takes over when any of its fault names is
    # requested (reference: yugabyte/nemesis.clj:240-247)
    pkg = None
    if set(opts.get("faults", ())) & yb_nemesis.KNOWN_FAULTS:
        pkg = common.suite_nemesis_package(
            opts, db_, yb_nemesis.package(opts, db_),
            yb_nemesis.KNOWN_FAULTS,
        )
    return common.build_test(
        f"yugabyte-{wname}", opts, db=db_,
        client=_client_for(wname, opts), workload=w,
        nemesis_package=pkg,
    )


# ---------------------------------------------------------------------
# multi-key ACID (YSQL)
# ---------------------------------------------------------------------

MKA_TABLE = "multi_key_acid"
MKA_KEYS = (0, 1, 2)


class MultiKeyAcidClient(sql._Base):
    """Transactional writes over a composite-key table, checked as a
    linearizable multi-register per independent key.

    Reference: yugabyte/src/yugabyte/ysql/multi_key_acid.clj:14-52 — one
    table (k1, k2, val, PK (k1, k2)); :write runs every [w k1 v] mop as
    an upsert inside one transaction, :read selects the k1s of its mops
    and rewrites them with the observed values.
    """

    dialect = "pg"

    def setup(self, test):
        self._exec_ddl(
            f"CREATE TABLE IF NOT EXISTS {MKA_TABLE} "
            "(k1 INT, k2 INT, val INT, PRIMARY KEY (k1, k2))"
        )

    def _upsert(self, k1: int, k2: int, v: int) -> str:
        if self.dialect == "cockroach":
            return (
                f"UPSERT INTO {MKA_TABLE} (k1, k2, val) "
                f"VALUES ({k1}, {k2}, {v})"
            )
        if self.dialect == "mysql":
            return (
                f"INSERT INTO {MKA_TABLE} (k1, k2, val) "
                f"VALUES ({k1}, {k2}, {v}) "
                f"ON DUPLICATE KEY UPDATE val = {v}"
            )
        return (
            f"INSERT INTO {MKA_TABLE} (k1, k2, val) "
            f"VALUES ({k1}, {k2}, {v}) "
            f"ON CONFLICT (k1, k2) DO UPDATE SET val = {v}"
        )

    def invoke(self, test, op):
        k2, mops = op["value"]
        try:
            if op["f"] == "read":
                k1s = sorted({k for _f, k, _v in mops})
                in_list = ", ".join(str(k) for k in k1s)
                res = self.conn.query(
                    f"SELECT k1, val FROM {MKA_TABLE} "
                    f"WHERE k2 = {k2} AND k1 IN ({in_list})"
                )
                got = {int(r[0]): (None if r[1] is None else int(r[1]))
                       for r in res.rows}
                out = [[f, k, got.get(k)] for f, k, _v in mops]
                return {**op, "type": "ok",
                        "value": independent.kv(k2, out)}
            if op["f"] == "write":
                self.conn.query("BEGIN")
                try:
                    for f, k1, v in mops:
                        assert f == "w", f
                        self.conn.query(self._upsert(k1, k2, v))
                    self.conn.query("COMMIT")
                except Exception:
                    try:
                        self.conn.query("ROLLBACK")
                    except Exception:
                        pass
                    raise
                return {**op, "type": "ok"}
            raise ValueError(f"unknown f {op['f']!r}")
        except IndeterminateError as e:
            return self._info(op, e)
        except (sql.PgError, sql.MysqlError) as e:
            return self._fail(op, e)


def multi_key_acid_workload(opts: Optional[dict] = None) -> dict:
    """Random read/write transactions over 3 sub-keys per independent
    key, checked linearizable against the multi-register model.
    (reference: yugabyte/src/yugabyte/multi_key_acid.clj:40-72)

    Reads of absent rows surface as None mops, which the model treats
    as always-legal — the same semantics as the reference's
    MultiRegister ("Nil reads are always legal",
    multi_key_acid.clj:22-27), so a vanished row is only caught once a
    non-None read of that key disagrees with the model state."""
    import random as _random

    from .. import checker as checker_mod
    from .. import models
    from .. import util as util_mod

    opts = dict(opts or {})
    n = max(1, len(opts.get("nodes", ["n1"])))

    def r(test, ctx):
        ks = util_mod.random_nonempty_subset(MKA_KEYS)
        return {"type": "invoke", "f": "read",
                "value": [["r", k, None] for k in sorted(ks)]}

    def w(test, ctx):
        ks = util_mod.random_nonempty_subset(MKA_KEYS)
        return {"type": "invoke", "f": "write",
                "value": [["w", k, _random.randint(0, 4)] for k in sorted(ks)]}

    from .. import generator as gen_mod

    def fgen(k):
        return gen_mod.process_limit(
            20, gen_mod.stagger(1 / 20, gen_mod.reserve(n, r, w))
        )

    return {
        "generator": independent.concurrent_generator(
            2 * n, range(100_000), fgen
        ),
        "checker": independent.checker(
            checker_mod.linearizable(models.multi_register({}), pure_fs=())
        ),
        "concurrency": 4 * n,
    }


class YcqlMultiKeyAcidClient(client_mod.Client):
    """The CQL flavor of multi-key ACID: writes ride one
    ``BEGIN TRANSACTION … END TRANSACTION`` statement (YCQL's
    distributed-transaction syntax), reads select the sub-keys with an
    ``IN`` predicate.  Checked by the same linearizable multi-register
    workload as the YSQL flavor.

    Reference: yugabyte/src/yugabyte/ycql/multi_key_acid.clj:13-61 —
    a transactional table (id, ik, val, PK (id, ik)); :write stitches
    its inserts into a single transaction statement, :read rewrites its
    mops with the observed values.
    """

    def __init__(self, opts: Optional[dict] = None):
        self.opts = opts or {}
        self.conn: Optional[CqlClient] = None

    def open(self, test, node):
        c = type(self)(self.opts)
        c.conn = CqlClient(
            self.opts.get("host", str(node)),
            self.opts.get("port", YCQL_PORT),
            timeout=self.opts.get("timeout", 10.0),
        )
        return c

    def setup(self, test):
        for stmt in (
            f"CREATE KEYSPACE IF NOT EXISTS {KEYSPACE}",
            f"CREATE TABLE IF NOT EXISTS {KEYSPACE}.multi_key_acid "
            "(id int, ik int, val int, PRIMARY KEY (id, ik)) "
            "WITH transactions = {'enabled': 'true'}",
        ):
            try:
                self.conn.query(stmt)
            except (CqlError, IndeterminateError):
                pass

    def invoke(self, test, op):
        ik, mops = op["value"]
        t = f"{KEYSPACE}.multi_key_acid"
        try:
            if op["f"] == "read":
                ids = sorted({k for _f, k, _v in mops})
                in_list = ", ".join(str(i) for i in ids)
                res = self.conn.query(
                    f"SELECT id, val FROM {t} "
                    f"WHERE ik = {int(ik)} AND id IN ({in_list})",
                    consistency="quorum",
                )
                got = {
                    res.cell_int(r, 0): res.cell_int(r, 1) for r in res.rows
                }
                out = [[f, k, got.get(k)] for f, k, _v in mops]
                return {**op, "type": "ok", "value": independent.kv(ik, out)}
            if op["f"] == "write":
                inserts = "".join(
                    f"INSERT INTO {t} (id, ik, val) "
                    f"VALUES ({int(k)}, {int(ik)}, {int(v)}); "
                    for f, k, v in mops
                )
                self.conn.query(
                    f"BEGIN TRANSACTION {inserts}END TRANSACTION"
                )
                return {**op, "type": "ok"}
            raise ValueError(f"unknown f {op['f']!r}")
        except IndeterminateError as e:
            return {**op, "type": "info", "error": str(e)}
        except CqlError as e:
            if e.timeout:
                return {**op, "type": "info", "error": str(e)}
            return {**op, "type": "fail", "error": str(e)}

    def close(self, test):
        if self.conn:
            self.conn.close()


# ---------------------------------------------------------------------
# YCQL bank (reference: yugabyte/src/yugabyte/ycql/bank.clj:20-58)
# ---------------------------------------------------------------------


class _YcqlBase(client_mod.Client):
    """Shared CQL connection plumbing for the YCQL workload clients."""

    def __init__(self, opts: Optional[dict] = None):
        self.opts = opts or {}
        self.conn: Optional[CqlClient] = None

    def open(self, test, node):
        c = type(self)(self.opts)
        c.conn = CqlClient(
            self.opts.get("host", str(node)),
            self.opts.get("port", YCQL_PORT),
            timeout=self.opts.get("timeout", 10.0),
        )
        return c

    def close(self, test):
        if self.conn:
            self.conn.close()

    def _ddl(self, *stmts: str) -> None:
        for stmt in stmts:
            try:
                self.conn.query(stmt)
            except (CqlError, IndeterminateError):
                pass


class YcqlBankClient(_YcqlBase):
    """Bank transfers as one YCQL distributed transaction: two
    balance-arithmetic UPDATEs inside BEGIN/END TRANSACTION; reads are a
    full-table scan.  (reference: ycql/bank.clj:20-58 CQLBank — the
    transfer statement shape at :46-56)"""

    def setup(self, test):
        self._ddl(
            f"CREATE KEYSPACE IF NOT EXISTS {KEYSPACE}",
            f"CREATE TABLE IF NOT EXISTS {KEYSPACE}.accounts "
            "(id int PRIMARY KEY, balance bigint) "
            "WITH transactions = {'enabled': 'true'}",
        )
        accounts = list(test.get("accounts", range(8)))
        total = test.get("total-amount", 100)
        t = f"{KEYSPACE}.accounts"
        for i, acct in enumerate(accounts):
            bal = total if i == 0 else 0
            self._ddl(
                f"INSERT INTO {t} (id, balance) "
                f"VALUES ({int(acct)}, {int(bal)})"
            )

    def invoke(self, test, op):
        t = f"{KEYSPACE}.accounts"
        try:
            if op["f"] == "read":
                res = self.conn.query(
                    f"SELECT id, balance FROM {t}", consistency="quorum"
                )
                value = {
                    res.cell_int(r, 0): res.cell_int(r, 1) for r in res.rows
                }
                return {**op, "type": "ok", "value": value}
            if op["f"] == "transfer":
                frm = int(op["value"]["from"])
                to = int(op["value"]["to"])
                amt = int(op["value"]["amount"])
                self.conn.query(
                    "BEGIN TRANSACTION "
                    f"UPDATE {t} SET balance = balance - {amt} "
                    f"WHERE id = {frm}; "
                    f"UPDATE {t} SET balance = balance + {amt} "
                    f"WHERE id = {to}; "
                    "END TRANSACTION"
                )
                return {**op, "type": "ok"}
            raise ValueError(f"unknown f {op['f']!r}")
        except IndeterminateError as e:
            return {**op, "type": "info", "error": str(e)}
        except CqlError as e:
            if e.timeout:
                return {**op, "type": "info", "error": str(e)}
            return {**op, "type": "fail", "error": str(e)}


class YcqlLongForkClient(_YcqlBase):
    """Long-fork txns over an indexed table: single-row writes, group
    reads through the key2 value index rewritten into the txn mops.
    (reference: ycql/long_fork.clj:13-55 — the index-backed read at
    :31-44, the insert at :46-50)"""

    def setup(self, test):
        self._ddl(
            f"CREATE KEYSPACE IF NOT EXISTS {KEYSPACE}",
            f"CREATE TABLE IF NOT EXISTS {KEYSPACE}.long_fork "
            "(key int PRIMARY KEY, key2 int, val int) "
            "WITH transactions = {'enabled': 'true'}",
            f"CREATE INDEX IF NOT EXISTS long_forks "
            f"ON {KEYSPACE}.long_fork (key2) INCLUDE (val)",
        )

    def invoke(self, test, op):
        t = f"{KEYSPACE}.long_fork"
        txn = op["value"]
        try:
            if op["f"] == "read":
                ks = sorted({k for _f, k, _v in txn})
                in_list = ", ".join(str(k) for k in ks)
                res = self.conn.query(
                    f"SELECT key2, val FROM {t} WHERE key2 IN ({in_list})",
                    consistency="quorum",
                )
                got = {
                    res.cell_int(r, 0): res.cell_int(r, 1) for r in res.rows
                }
                out = [[f, k, got.get(k)] for f, k, _v in txn]
                return {**op, "type": "ok", "value": out}
            if op["f"] == "write":
                [[_f, k, v]] = txn
                self.conn.query(
                    f"INSERT INTO {t} (key, key2, val) "
                    f"VALUES ({int(k)}, {int(k)}, {int(v)})"
                )
                return {**op, "type": "ok"}
            raise ValueError(f"unknown f {op['f']!r}")
        except IndeterminateError as e:
            return {**op, "type": "info", "error": str(e)}
        except CqlError as e:
            if e.timeout:
                return {**op, "type": "info", "error": str(e)}
            return {**op, "type": "fail", "error": str(e)}


# ---------------------------------------------------------------------
# YSQL default-value (reference: yugabyte/src/yugabyte/default_value.clj
# and ysql/default_value.clj)
# ---------------------------------------------------------------------

DV_TABLE = "foo"


class DefaultValueClient(sql._Base):
    """Concurrent create/drop-table churn against inserts and reads of a
    table whose second column carries DEFAULT 0; any read observing a
    NULL there is the anomaly.  (reference: ysql/default_value.clj:
    create-table!:41-52, insert!:25-28, read-natural:36-39,
    invoke-op!:104-117 — missing-relation errors fail the op rather
    than crash, like the reference's with-table/catch-dne handling)"""

    dialect = "pg"

    def setup(self, test):
        # the probe simulates a migration against an *existing* table
        # (default_value.clj:1-11); seeding it also keeps short runs
        # from recording zero ok reads/inserts when the generator's
        # 1-in-26 create-table draw comes late
        self._exec_ddl(
            f"CREATE TABLE IF NOT EXISTS {DV_TABLE} "
            "(dummy INT, v INT DEFAULT 0)"
        )

    def invoke(self, test, op):
        try:
            if op["f"] == "create-table":
                self.conn.query(
                    f"CREATE TABLE IF NOT EXISTS {DV_TABLE} "
                    "(dummy INT, v INT DEFAULT 0)"
                )
                return {**op, "type": "ok"}
            if op["f"] == "drop-table":
                self.conn.query(f"DROP TABLE IF EXISTS {DV_TABLE}")
                return {**op, "type": "ok"}
            if op["f"] == "insert":
                self.conn.query(f"INSERT INTO {DV_TABLE} (dummy) VALUES (1)")
                return {**op, "type": "ok"}
            if op["f"] == "read":
                res = self.conn.query(f"SELECT v FROM {DV_TABLE}")
                rows = [
                    None if r[0] is None else int(r[0]) for r in res.rows
                ]
                return {**op, "type": "ok", "value": rows}
            raise ValueError(f"unknown f {op['f']!r}")
        except sql.IndeterminateError as e:
            return self._info(op, e)
        except (sql.PgError, sql.MysqlError) as e:
            # a read/insert racing a drop-table legitimately fails with
            # "does not exist" — an op failure, not a harness crash
            return self._fail(op, e)


class DefaultValueChecker(common.checker_mod.Checker):
    """valid? iff no ok read observed a NULL in the defaulted column.
    (reference: default_value.clj:70-103 bad-row/bad-read/checker)"""

    def check(self, test, history, opts=None):
        from ..history import OK

        reads = [
            op for op in history if op.type == OK and op.f == "read"
        ]
        bad = [
            {"op-index": op.index, "bad-rows": [v for v in (op.value or []) if v is None]}
            for op in reads
            if any(v is None for v in (op.value or []))
        ]
        return {
            "valid?": not bad,
            "read-count": len(reads),
            "bad-read-count": len(bad),
            "bad-reads": bad[:10],
        }


def default_value_workload(opts: Optional[dict] = None) -> dict:
    """DDL churn (create/drop) mixed 1:25 with reads and inserts,
    staggered tightly.  (reference: default_value.clj:generator:60-68)"""
    from .. import generator as gen_mod

    def r(test, ctx):
        return {"type": "invoke", "f": "read", "value": None}

    def i(test, ctx):
        return {"type": "invoke", "f": "insert", "value": None}

    def create(test, ctx):
        return {"type": "invoke", "f": "create-table", "value": None}

    def drop(test, ctx):
        return {"type": "invoke", "f": "drop-table", "value": None}

    mix = [create, drop] + [r, i] * 25
    return {
        "generator": gen_mod.stagger(1 / 100, gen_mod.mix(mix)),
        "checker": DefaultValueChecker(),
    }


# ---------------------------------------------------------------------
# ysql.append-table: one TABLE per list key (reference:
# yugabyte/src/yugabyte/ysql/append_table.clj)
# ---------------------------------------------------------------------


class AppendTableClient(sql._Base):
    """List-append where each key is its own table and rows are the
    list elements: append = INSERT, read = SELECT ordered by the key
    column.  Tables are created lazily when an op hits
    "relation does not exist" — YB can't CREATE IF NOT EXISTS safely,
    so the reference swallows already-exists races the same way
    (append_table.clj:76-120 create-table!/with-table).

    The reference documents that YB offers no safe transactional row
    order (append_table.clj:10-16) and ships NOW()-keyed inserts
    (insert!, :44-60) plus a COUNT(*)-keyed variant
    (insert-using-count!, :34-42); ``append-table-key`` picks
    ("now"/"count", default "now")."""

    dialect = "pg"

    def __init__(self, opts: Optional[dict] = None):
        super().__init__(opts)
        self.key_mode = self.opts.get("append-table-key", "now")

    @staticmethod
    def _table(k) -> str:
        return f"append{int(k)}"

    def _create(self, table: str):
        # straight through conn.query, NOT _exec_ddl: that helper
        # swallows every DDL error, which would hide real CREATE
        # failures; only the already-exists race is benign here
        ddl = (
            f"CREATE TABLE IF NOT EXISTS {table} "
            "(k TIMESTAMP DEFAULT CURRENT_TIMESTAMP, v INT)"
            if self.key_mode == "now" else
            f"CREATE TABLE IF NOT EXISTS {table} (k INT, v INT)"
        )
        try:
            self.conn.query(ddl)
        except (sql.PgError, sql.MysqlError) as e:
            if "already exists" not in str(e):
                raise

    def _mop(self, f, k, v):
        table = self._table(k)
        if f == "r":
            res = self.conn.query(
                f"SELECT k, v FROM {table} ORDER BY k"
            )
            return ["r", k, [int(row[-1]) for row in res.rows]]
        if f == "append":
            if self.key_mode == "count":
                n = int(self.conn.query(
                    f"SELECT count(*) FROM {table}").rows[0][0])
                self.conn.query(
                    f"INSERT INTO {table} (k, v) "
                    f"VALUES ({n}, {int(v)})"
                )
            else:
                self.conn.query(
                    f"INSERT INTO {table} (v) VALUES ({int(v)})"
                )
            return ["append", k, v]
        raise ValueError(f"unknown micro-op {f!r}")

    @staticmethod
    def _missing_table(e) -> bool:
        msg = str(e)
        return ("does not exist" in msg or "no such table" in msg
                or "doesn't exist" in msg)

    def _run_txn(self, txn):
        """One attempt: BEGIN/COMMIT around multi-statement work like
        the reference's with-txn (append_table.clj:131-140; count-mode
        appends are two statements even alone)."""
        use_txn = len(txn) > 1 or (
            self.key_mode == "count"
            and any(f == "append" for f, _k, _v in txn)
        )
        if not use_txn:
            return [self._mop(f, k, v) for f, k, v in txn]
        self.conn.query("BEGIN")
        try:
            out = [self._mop(f, k, v) for f, k, v in txn]
            self.conn.query("COMMIT")
            return out
        except Exception:
            try:
                self.conn.query("ROLLBACK")
            except Exception:  # noqa: BLE001
                pass
            raise

    def invoke(self, test, op):
        txn = op["value"]
        try:
            try:
                out = self._run_txn(txn)
            except (sql.PgError, sql.MysqlError) as e:
                if not self._missing_table(e):
                    raise
                # lazily create every table the txn touches (outside
                # the aborted txn), then retry once
                for _f, k, _v in txn:
                    self._create(self._table(k))
                out = self._run_txn(txn)
            return {**op, "type": "ok", "value": out}
        except sql.IndeterminateError as e:
            return self._info(op, e)
        except (sql.PgError, sql.MysqlError) as e:
            return self._fail(op, e)
