"""Stolon (HA PostgreSQL) suite.

Reference: stolon/src/jepsen/stolon/{db,client,append,set,bank}.clj —
install PostgreSQL from the pgdg apt repo (db.clj:44-60) plus the
stolon release tarball; each node runs a ``stolon-keeper`` (manages the
local postgres), a ``stolon-sentinel`` (leader election via the store),
and a ``stolon-proxy`` (routes clients to the master, port 25432);
cluster state lives in an etcd/consul store (db.clj:27-43,62-150).
Clients speak pgwire through the proxy via :mod:`.sql` (dialect
``pg``).
"""

from __future__ import annotations

import itertools as _itertools
import threading as _threading

from typing import Optional

from ..control import util as cu
from ..control import execute, sudo
from ..os_setup import debian
from . import common, sql

DIR = "/opt/stolon"
PROXY_PORT = 25432
CLUSTER = "jepsen"
STORE_PORT = 2379  # etcd store endpoints (reference: db.clj:62-70)
DEFAULT_TARBALL = (
    "https://github.com/sorintlab/stolon/releases/download/v0.16.0/"
    "stolon-v0.16.0-linux-amd64.tar.gz"
)


class StolonDB(common.DaemonDB):
    dir = DIR
    binary = "bin/stolon-keeper"
    logfile = f"{DIR}/keeper.log"    # (reference: db.clj:31-33)
    pidfile = f"{DIR}/keeper.pid"
    sentinel_logfile = f"{DIR}/sentinel.log"
    sentinel_pidfile = f"{DIR}/sentinel.pid"
    proxy_logfile = f"{DIR}/proxy.log"
    proxy_pidfile = f"{DIR}/proxy.pid"

    def __init__(self, opts: Optional[dict] = None):
        super().__init__(opts)
        self.tarball = (opts or {}).get("tarball", DEFAULT_TARBALL)

    def install(self, test, node):
        # (reference: db.clj:44-60 install-pg! — pgdg apt repo)
        debian.install(["postgresql-12", "postgresql-client-12"])
        with sudo():
            execute("systemctl", "stop", "postgresql", check=False)
            cu.install_archive(self.tarball, DIR)

    def _store_endpoints(self, test) -> str:
        return ",".join(
            f"http://{n}:{STORE_PORT}" for n in test["nodes"]
        )

    def start(self, test, node):
        store = [
            "--store-backend", "etcdv3",
            "--store-endpoints", self._store_endpoints(test),
        ]
        if node == test["nodes"][0]:
            execute(
                f"{DIR}/bin/stolonctl", "init", "--cluster-name", CLUSTER,
                *store, "-y", check=False,
            )
        cu.start_daemon(
            {"logfile": self.sentinel_logfile,
             "pidfile": self.sentinel_pidfile, "chdir": DIR},
            f"{DIR}/bin/stolon-sentinel",
            "--cluster-name", CLUSTER, *store,
        )
        cu.start_daemon(
            {"logfile": self.logfile, "pidfile": self.pidfile, "chdir": DIR},
            f"{DIR}/bin/stolon-keeper",
            "--cluster-name", CLUSTER, *store,
            "--uid", f"keeper{test['nodes'].index(node)}",
            "--data-dir", f"{DIR}/data",
            "--pg-listen-address", str(node),
            "--pg-su-password", "pw",
            "--pg-repl-username", "repl",
            "--pg-repl-password", "pw",
            "--pg-bin-path", "/usr/lib/postgresql/12/bin",
        )
        cu.start_daemon(
            {"logfile": self.proxy_logfile, "pidfile": self.proxy_pidfile,
             "chdir": DIR},
            f"{DIR}/bin/stolon-proxy",
            "--cluster-name", CLUSTER, *store,
            "--listen-address", "0.0.0.0",
            "--port", str(PROXY_PORT),
        )

    def kill(self, test, node):
        for pidfile, name in [
            (self.proxy_pidfile, "stolon-proxy"),
            (self.pidfile, "stolon-keeper"),
            (self.sentinel_pidfile, "stolon-sentinel"),
        ]:
            cu.stop_daemon(pidfile=pidfile, cmd=name)
        cu.grepkill("postgres")

    def await_ready(self, test, node):
        cu.await_tcp_port(PROXY_PORT, timeout_s=300)

    def wipe(self, test, node):
        with sudo():
            execute("rm", "-rf", f"{DIR}/data")

    def log_files(self, test, node):
        return [self.logfile, self.sentinel_logfile, self.proxy_logfile]


def _opts(opts: Optional[dict]) -> dict:
    o = dict(opts or {})
    o.setdefault("dialect", "pg")
    o.setdefault("port", PROXY_PORT)
    o.setdefault("user", "postgres")
    o.setdefault("password", "pw")
    return o


def db(opts: Optional[dict] = None):
    return StolonDB(opts)


def client(opts: Optional[dict] = None):
    return sql.RegisterClient(_opts(opts))


WORKLOADS = ("register", "bank", "set", "list-append")


def workloads(opts: Optional[dict] = None) -> dict:
    opts = _opts(opts)
    out = {w: common.generic_workload(w, opts) for w in WORKLOADS}
    # the double-spend probe (reference: stolon/ledger.clj)
    out["ledger"] = ledger_workload(opts)
    return out


def test(opts: Optional[dict] = None) -> dict:
    opts = _opts(opts)
    wname = opts.get("workload", "list-append")
    w = workloads(opts)[wname]
    c = (
        LedgerClient(opts)
        if wname == "ledger"
        else sql.client_for(wname, opts)
    )
    return common.build_test(
        f"stolon-{wname}", opts, db=StolonDB(opts),
        client=c, workload=w,
    )


# ---------------------------------------------------------------------
# ledger: the double-spend probe
# (reference: stolon/src/jepsen/stolon/ledger.clj)
# ---------------------------------------------------------------------

LEDGER_TABLE = "ledger"


class LedgerClient(sql._Base):
    """A bank ledger where each transfer is a row; withdrawals insert
    only if the account's balance (summed from the other rows, inside
    the same transaction) stays non-negative — so a double-spend race
    is exactly a G2-item anomaly made concrete.

    Reference: ledger.clj — add-entry!/balance-select (:27-52),
    transfer!'s read-then-conditionally-insert with a jitter sleep
    between (:54-69), per-client unique row ids (:74-131)."""

    dialect = "pg"

    #: row-id counter shared across worker clones (class-level so every
    #: open()ed copy draws from one sequence; CPython's itertools.count
    #: is safe under the GIL but the lock keeps that explicit)
    _ids = _itertools.count(1)
    _ids_lock = _threading.Lock()

    ISOLATION_LEVELS = (
        "SERIALIZABLE", "REPEATABLE READ",
        "READ COMMITTED", "READ UNCOMMITTED",
    )

    def __init__(self, opts: Optional[dict] = None):
        super().__init__(opts)
        # validate ONCE, where a raise aborts test construction — a
        # per-invoke raise would be downgraded to info ops and the
        # misconfigured run would pass vacuously
        self.isolation = (
            str(self.opts.get("isolation", "serializable"))
            .upper()
            .replace("-", " ")
        )
        if self.isolation not in self.ISOLATION_LEVELS:
            raise ValueError(
                f"unknown isolation {self.isolation!r}; "
                f"expected one of {self.ISOLATION_LEVELS}"
            )

    def _next_id(self) -> int:
        with LedgerClient._ids_lock:
            return next(LedgerClient._ids)

    def setup(self, test):
        self._exec_ddl(
            f"CREATE TABLE IF NOT EXISTS {LEDGER_TABLE} "
            "(id INT PRIMARY KEY, account INT NOT NULL, "
            "amount INT NOT NULL)"
        )

    def invoke(self, test, op):
        import random as _random
        import time as _time

        account, amount = op["value"]
        rid = self._next_id()
        # the double-spend is only an anomaly under serializability —
        # at read committed two concurrent balance checks passing is
        # LEGAL, so without this the checker would flag healthy
        # clusters (reference: ledger.clj:117-121 sets the test's
        # isolation on every connection; validated in __init__)
        try:
            try:
                self.conn.query(
                    f"BEGIN ISOLATION LEVEL {self.isolation}"
                )
            except (sql.PgError, sql.MysqlError) as e:
                # a refused BEGIN is a definite failure, like every
                # other sql client's error path
                return self._fail(op, e)
            try:
                if amount > 0:
                    self.conn.query(
                        f"INSERT INTO {LEDGER_TABLE} (id, account, amount) "
                        f"VALUES ({rid}, {int(account)}, {int(amount)})"
                    )
                    ok = True
                else:
                    res = self.conn.query(
                        f"SELECT amount FROM {LEDGER_TABLE} "
                        f"WHERE account = {int(account)} AND id != {rid}"
                    )
                    balance = sum(int(r[0]) for r in res.rows)
                    if balance + amount < 0:
                        ok = False
                    else:
                        # the jitter widens the double-spend window
                        # (reference: ledger.clj:66)
                        _time.sleep(_random.random() * 0.01)
                        self.conn.query(
                            f"INSERT INTO {LEDGER_TABLE} "
                            "(id, account, amount) "
                            f"VALUES ({rid}, {int(account)}, {int(amount)})"
                        )
                        ok = True
                self.conn.query("COMMIT")
                return {**op, "type": "ok" if ok else "fail"}
            except (sql.PgError, sql.MysqlError) as e:
                try:
                    self.conn.query("ROLLBACK")
                except Exception:
                    pass
                return self._fail(op, e)
        except sql.IndeterminateError as e:
            return self._info(op, e)


class LedgerChecker(common.checker_mod.Checker):
    """Every account's most-charitable balance (deposits count even
    when indeterminate; withdrawals only when acknowledged) must be
    exactly zero-or-positive... the reference flags ANY nonzero
    balance, since its generator funds then fully drains each account.
    We flag only negative balances — a double-spend's signature — so
    the checker also serves the random-transfer generator.
    (reference: ledger.clj:139-163 check-account/checker)"""

    def check(self, test, history, opts=None):
        from ..history import OK, INFO

        per_account: dict = {}
        for op in history:
            if op.f != "transfer" or op.type not in (OK, INFO):
                continue
            account, amount = op.value
            if amount > 0 or op.type == OK:
                per_account[account] = per_account.get(account, 0) + amount
        errs = [
            {"account": a, "balance": b}
            for a, b in sorted(per_account.items())
            if b < 0
        ]
        return {"valid?": not errs, "errors": errs[:10]}


class _LedgerGen(common.gen.Generator):
    """Fund each account, then attempt a burst of double-spends.
    (reference: ledger.clj:165-173 fund-then-double-spend-gen)"""

    def __init__(self, account: int = 0, queue: tuple = ()):
        self.account = account
        self.queue = queue

    def op(self, test, ctx):
        queue = self.queue
        account = self.account
        if not queue:
            burst = 2 ** common.gen.rng.randrange(5)
            queue = ((account, 10),) + ((account, -9),) * burst
            account += 1
        filled = common.gen.fill_in_op(
            {"f": "transfer", "value": list(queue[0])}, ctx
        )
        if filled == common.gen.PENDING:
            return (common.gen.PENDING, self)
        return (filled, _LedgerGen(account, queue[1:]))

    def update(self, test, ctx, event):
        return self


def ledger_workload(opts: Optional[dict] = None) -> dict:
    """(reference: ledger.clj:184-189 workload)"""
    return {"generator": _LedgerGen(), "checker": LedgerChecker()}
