"""Stolon (HA PostgreSQL) suite.

Reference: stolon/src/jepsen/stolon/{db,client,append,set,bank}.clj —
install PostgreSQL from the pgdg apt repo (db.clj:44-60) plus the
stolon release tarball; each node runs a ``stolon-keeper`` (manages the
local postgres), a ``stolon-sentinel`` (leader election via the store),
and a ``stolon-proxy`` (routes clients to the master, port 25432);
cluster state lives in an etcd/consul store (db.clj:27-43,62-150).
Clients speak pgwire through the proxy via :mod:`.sql` (dialect
``pg``).
"""

from __future__ import annotations

from typing import Optional

from ..control import util as cu
from ..control import execute, sudo
from ..os_setup import debian
from . import common, sql

DIR = "/opt/stolon"
PROXY_PORT = 25432
CLUSTER = "jepsen"
STORE_PORT = 2379  # etcd store endpoints (reference: db.clj:62-70)
DEFAULT_TARBALL = (
    "https://github.com/sorintlab/stolon/releases/download/v0.16.0/"
    "stolon-v0.16.0-linux-amd64.tar.gz"
)


class StolonDB(common.DaemonDB):
    dir = DIR
    binary = "bin/stolon-keeper"
    logfile = f"{DIR}/keeper.log"    # (reference: db.clj:31-33)
    pidfile = f"{DIR}/keeper.pid"
    sentinel_logfile = f"{DIR}/sentinel.log"
    sentinel_pidfile = f"{DIR}/sentinel.pid"
    proxy_logfile = f"{DIR}/proxy.log"
    proxy_pidfile = f"{DIR}/proxy.pid"

    def __init__(self, opts: Optional[dict] = None):
        super().__init__(opts)
        self.tarball = (opts or {}).get("tarball", DEFAULT_TARBALL)

    def install(self, test, node):
        # (reference: db.clj:44-60 install-pg! — pgdg apt repo)
        debian.install(["postgresql-12", "postgresql-client-12"])
        with sudo():
            execute("systemctl", "stop", "postgresql", check=False)
            cu.install_archive(self.tarball, DIR)

    def _store_endpoints(self, test) -> str:
        return ",".join(
            f"http://{n}:{STORE_PORT}" for n in test["nodes"]
        )

    def start(self, test, node):
        store = [
            "--store-backend", "etcdv3",
            "--store-endpoints", self._store_endpoints(test),
        ]
        if node == test["nodes"][0]:
            execute(
                f"{DIR}/bin/stolonctl", "init", "--cluster-name", CLUSTER,
                *store, "-y", check=False,
            )
        cu.start_daemon(
            {"logfile": self.sentinel_logfile,
             "pidfile": self.sentinel_pidfile, "chdir": DIR},
            f"{DIR}/bin/stolon-sentinel",
            "--cluster-name", CLUSTER, *store,
        )
        cu.start_daemon(
            {"logfile": self.logfile, "pidfile": self.pidfile, "chdir": DIR},
            f"{DIR}/bin/stolon-keeper",
            "--cluster-name", CLUSTER, *store,
            "--uid", f"keeper{test['nodes'].index(node)}",
            "--data-dir", f"{DIR}/data",
            "--pg-listen-address", str(node),
            "--pg-su-password", "pw",
            "--pg-repl-username", "repl",
            "--pg-repl-password", "pw",
            "--pg-bin-path", "/usr/lib/postgresql/12/bin",
        )
        cu.start_daemon(
            {"logfile": self.proxy_logfile, "pidfile": self.proxy_pidfile,
             "chdir": DIR},
            f"{DIR}/bin/stolon-proxy",
            "--cluster-name", CLUSTER, *store,
            "--listen-address", "0.0.0.0",
            "--port", str(PROXY_PORT),
        )

    def kill(self, test, node):
        for pidfile, name in [
            (self.proxy_pidfile, "stolon-proxy"),
            (self.pidfile, "stolon-keeper"),
            (self.sentinel_pidfile, "stolon-sentinel"),
        ]:
            cu.stop_daemon(pidfile=pidfile, cmd=name)
        cu.grepkill("postgres")

    def await_ready(self, test, node):
        cu.await_tcp_port(PROXY_PORT, timeout_s=300)

    def wipe(self, test, node):
        with sudo():
            execute("rm", "-rf", f"{DIR}/data")

    def log_files(self, test, node):
        return [self.logfile, self.sentinel_logfile, self.proxy_logfile]


def _opts(opts: Optional[dict]) -> dict:
    o = dict(opts or {})
    o.setdefault("dialect", "pg")
    o.setdefault("port", PROXY_PORT)
    o.setdefault("user", "postgres")
    o.setdefault("password", "pw")
    return o


def db(opts: Optional[dict] = None):
    return StolonDB(opts)


def client(opts: Optional[dict] = None):
    return sql.RegisterClient(_opts(opts))


WORKLOADS = ("register", "bank", "set", "list-append")


def workloads(opts: Optional[dict] = None) -> dict:
    opts = _opts(opts)
    return {w: common.generic_workload(w, opts) for w in WORKLOADS}


def test(opts: Optional[dict] = None) -> dict:
    opts = _opts(opts)
    wname = opts.get("workload", "list-append")
    w = workloads(opts)[wname]
    return common.build_test(
        f"stolon-{wname}", opts, db=StolonDB(opts),
        client=sql.client_for(wname, opts), workload=w,
    )
