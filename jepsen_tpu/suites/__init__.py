"""Per-database test suites.

The reference ships 29 sibling leiningen projects, each bundling DB
automation (install/start/stop over the control DSL), a client over the
database's wire protocol, workload wiring, and a CLI runner (reference:
SURVEY §2.5; e.g. consul/src/jepsen/consul.clj, consul/db.clj,
consul/client.clj).  Here each suite is a module (or package, for the
larger ones) under ``jepsen_tpu.suites``, and all wire protocols are
implemented from scratch on the Python stdlib in
``jepsen_tpu.suites.proto`` — no DB driver dependencies.

``suite(name)`` returns the suite module; each suite exposes:

- ``db(opts)``        → a jepsen_tpu.db.DB automating install/teardown
- ``client(opts)``    → a jepsen_tpu.client.Client over the wire protocol
- ``workloads(opts)`` → {name: partial test map}
- ``test(opts)``      → a full runnable test map
- ``cli()``           → argparse-ready command table (optional)
"""

from __future__ import annotations

import importlib

SUITES = (
    "aerospike",
    "chronos",
    "cockroachdb",
    "consul",
    "crate",
    "dgraph",
    "disque",
    "elasticsearch",
    "etcd",
    "faunadb",
    "galera",
    "hazelcast",
    "ignite",
    "localkv",
    "logcabin",
    "mongodb_rocks",
    "mongodb_smartos",
    "mysql_cluster",
    "percona",
    "postgres_rds",
    "rabbitmq",
    "raftis",
    "rethinkdb",
    "robustirc",
    "stolon",
    "tidb",
    "yugabyte",
    "zookeeper",
)


def suite(name: str):
    """Import and return the suite module for ``name`` (dashes ok)."""
    name = name.replace("-", "_")
    if name not in SUITES:
        raise KeyError(f"unknown suite {name!r}; known: {sorted(SUITES)}")
    return importlib.import_module(f"jepsen_tpu.suites.{name}")
