"""Raftis (Redis over Raft) suite.

Reference: raftis/src/jepsen/raftis.clj — clone + build raftis on each
node, start it with the cluster's member list, and run a single
read/write register over the Redis protocol (:20-48; note the
reference's client has no CAS — raftis only exposes GET/SET — so the
register model is write/read only).
"""

from __future__ import annotations

from typing import Optional

from .. import client as client_mod
from .. import independent
from .. import control
from ..control import util as cu
from ..os_setup import debian
from . import common
from .proto import IndeterminateError, ProtocolError
from .proto.resp import RespClient

DIR = "/opt/raftis"
PORT = 6379


class RaftisDB(common.DaemonDB):
    dir = DIR
    binary = "raftis"
    logfile = f"{DIR}/raftis.log"
    pidfile = f"{DIR}/raftis.pid"

    def install(self, test, node):
        # (reference: raftis.clj — git clone + build)
        debian.install(["git-core", "build-essential", "golang"])
        with control.su():
            control.execute(
                "bash", "-c",
                f"test -d {DIR} || git clone --depth 1 "
                f"https://github.com/goraft/raftis {DIR}",
                check=False,
            )
            with control.cd(DIR):
                control.execute("go", "build", "-o", "raftis", check=False)

    def start_args(self, test, node):
        peers = ",".join(f"{n}:{PORT}" for n in test["nodes"])
        return ["-bind", f"0.0.0.0:{PORT}", "-peers", peers]

    def await_ready(self, test, node):
        cu.await_tcp_port(PORT, timeout_s=120)


class RaftisClient(client_mod.Client):
    """GET/SET register (reference: raftis.clj:34-48)."""

    def __init__(self, opts: Optional[dict] = None):
        self.opts = opts or {}
        self.conn: Optional[RespClient] = None

    def open(self, test, node):
        c = type(self)(self.opts)
        c.conn = RespClient(
            self.opts.get("host", str(node)),
            self.opts.get("port", PORT),
            timeout=self.opts.get("timeout", 5.0),
        )
        return c

    def invoke(self, test, op):
        k, v = op["value"]
        try:
            if op["f"] == "read":
                raw = self.conn.call("GET", f"r{k}")
                val = int(raw) if raw is not None else None
                return {**op, "type": "ok", "value": independent.kv(k, val)}
            if op["f"] == "write":
                self.conn.call("SET", f"r{k}", str(v))
                return {**op, "type": "ok"}
            raise ValueError(f"unknown f {op['f']!r}")
        except IndeterminateError as e:
            return {**op, "type": "info", "error": str(e)}
        except ProtocolError as e:
            return {**op, "type": "fail", "error": str(e)}

    def close(self, test):
        if self.conn:
            self.conn.close()


def db(opts: Optional[dict] = None):
    return RaftisDB(opts)


def client(opts: Optional[dict] = None):
    return RaftisClient(opts)


def workloads(opts: Optional[dict] = None) -> dict:
    # write/read only — raftis exposes no CAS (reference: raftis.clj:20-22)
    opts = dict(opts or {})
    opts["cas?"] = False
    return {"register": common.register_workload(opts)}


def test(opts: Optional[dict] = None) -> dict:
    opts = dict(opts or {})
    w = workloads(opts)["register"]
    return common.build_test(
        "raftis-register", opts, db=RaftisDB(opts),
        client=RaftisClient(opts), workload=w,
    )
