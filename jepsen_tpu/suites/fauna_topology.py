"""FaunaDB topology churn as a membership state machine: nodes join and
leave replicas while the workload runs, with the invariant that no
replica is ever emptied.

Reference: faunadb/src/jepsen/faunadb/topology.clj — initial-topology
(:12-28: nodes round-robined over ``replica-<i>`` names), add-ops
(:103-113: any test node not in the active topology may join at a random
active node), remove-ops (:115-137: only nodes whose replica keeps ≥1
other node are removable), rand-op's even add/remove mixing (:165-180),
and apply-op's best-effort state transitions (:182-207).  The cluster
actions ride faunadb-admin the way the reference's topology nemesis does
(faunadb/nemesis.clj join!/remove!).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from .. import control
from .. import generator as gen
from ..control import execute, sudo
from ..nemesis.membership import MembershipGenerator, MembershipNemesis, State


def replica_name(i: int) -> str:
    return f"replica-{i}"


class FaunaTopology(State):
    """The membership State implementation.  ``topo`` is
    {replica_count, nodes: [{node, state, replica}]}; invoke applies
    join/leave via faunadb-admin and evolves the model."""

    def __init__(self, replicas: int = 2):
        self.replicas = replicas
        self.topo: Dict[str, Any] = {}

    # -- model helpers (reference: topology.clj:30-101) ----------------

    def active_nodes(self) -> List[Any]:
        return [
            n["node"] for n in self.topo["nodes"] if n["state"] == "active"
        ]

    def nodes_by_replica(self) -> Dict[str, List[Any]]:
        out: Dict[str, List[Any]] = {}
        for n in self.topo["nodes"]:
            if n["state"] == "active":
                out.setdefault(n["replica"], []).append(n["node"])
        return out

    # -- State protocol ------------------------------------------------

    def setup(self, test):
        self.topo = {
            "replica_count": self.replicas,
            "nodes": [
                {"node": node, "state": "active",
                 "replica": replica_name(i % self.replicas)}
                for i, node in enumerate(test["nodes"])
            ],
        }
        return self

    def fs(self):
        return {"add-node", "remove-node"}

    def node_view(self, test, node):
        # best-effort: ask the node for its cluster status; unreachable
        # or dummy nodes report None (unknown), like the reference's
        # status parsing (faunadb/auto.clj status)
        try:
            out = execute("faunadb-admin", "status", check=False)
            return str(out) or None
        except Exception:  # noqa: BLE001 — view refresh must not crash
            return None

    def merge_views(self, test):
        return self.topo

    def op(self, test):
        """An add or remove op, mixed evenly by *type* like rand-op
        (topology.clj:165-180); "pending" when neither is possible."""
        if not self.topo:
            return "pending"  # setup() hasn't populated the model yet
        adds = self._add_ops(test)
        removes = self._remove_ops()
        choices = [ops for ops in (adds, removes) if ops]
        if not choices:
            return "pending"
        ops = gen.rng.choice(choices)
        return gen.rng.choice(ops)

    def _add_ops(self, test):
        active = set(self.active_nodes())
        if not active:
            return []
        joinable = sorted(set(test["nodes"]) - {
            n["node"] for n in self.topo["nodes"]
        })
        return [
            {"type": "info", "f": "add-node",
             "value": {"node": node,
                       "join": gen.rng.choice(sorted(active))}}
            for node in joinable
        ]

    def _remove_ops(self):
        removable = [
            node
            for nodes in self.nodes_by_replica().values()
            if len(nodes) > 1
            for node in nodes
        ]
        return [
            {"type": "info", "f": "remove-node", "value": node}
            for node in sorted(removable)
        ]

    def invoke(self, test, op):
        f = op["f"]
        if f == "add-node":
            node = op["value"]["node"]
            join_target = op["value"]["join"]

            def join(test, n):
                with sudo():
                    return execute(
                        "faunadb-admin", "join", str(join_target),
                        check=False,
                    )

            res = control.on_nodes(test, [node], join)
            topo = dict(self.topo)
            topo["nodes"] = list(topo["nodes"]) + [{
                "node": node, "state": "active",
                "replica": replica_name(
                    gen.rng.randrange(topo["replica_count"])
                ),
            }]
            self.topo = topo
            return {**op, "type": "info",
                    "value": {**op["value"],
                              "result": str(res.get(node))}}
        if f == "remove-node":
            node = op["value"]
            # issue the removal from a surviving active node
            others = [n for n in self.active_nodes() if n != node]
            if not others:
                return {**op, "type": "fail", "error": "no active peer"}

            def remove(test, n):
                with sudo():
                    return execute(
                        "faunadb-admin", "remove", str(node), check=False
                    )

            res = control.on_nodes(test, [others[0]], remove)
            topo = dict(self.topo)
            topo["nodes"] = [
                n for n in topo["nodes"] if n["node"] != node
            ]
            self.topo = topo
            return {**op, "type": "info",
                    "value": {"node": node,
                              "result": str(res.get(others[0]))}}
        raise ValueError(f"unknown f {f!r}")

    def resolve(self, test):
        return self

    def resolve_op(self, test, op_pair):
        # transitions apply optimistically in invoke(); ops resolve
        # immediately (the reference calls this whole dance
        # "best-effort", topology.clj:188-196)
        return self

    def teardown(self, test):
        pass


def package(opts: dict, replicas: Optional[int] = None) -> dict:
    """A {nemesis, generator} bundle for build_test.
    (reference: faunadb topology nemesis wiring in faunadb/runner.clj)"""
    state = FaunaTopology(replicas or opts.get("replicas", 2))
    nem = MembershipNemesis(state, opts)
    return {
        "nemesis": nem,
        "generator": gen.stagger(
            opts.get("interval", 10), MembershipGenerator(nem)
        ),
        "final_generator": None,
        "perf": set(),
    }
