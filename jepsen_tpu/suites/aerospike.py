"""Aerospike suite.

Reference: aerospike/src/aerospike/{support,cas_register,counter,set,
pause,nemesis,core}.clj — install the aerospike server deb
(support.clj:50-120), configure a mesh-heartbeat cluster over the test
nodes with a strong-consistency namespace, manage the roster with
``asinfo`` (support.clj:143-200), and run three workloads:
**cas-register** (generation-checked CAS, cas_register.clj:53-76),
**counter** (increments + reads, counter.clj), and **set** (list
append read-modify-write, set.clj).

The client speaks the AS_MSG binary protocol via
:mod:`.proto.aerospike`; CAS uses the record generation exactly like
the reference's ``gen-policy EXPECT_GEN_EQUAL``.
"""

from __future__ import annotations

from typing import Optional

from .. import client as client_mod
from .. import independent
from .. import control
from ..control import util as cu
from ..os_setup import debian
from . import common
from .proto import IndeterminateError
from .proto.aerospike import AerospikeClient, AerospikeError

PORT = 3000
FABRIC_PORT = 3001
MESH_PORT = 3002
NAMESPACE = "jepsen"  # (reference: support.clj:50)
SET = "registers"

_CONF = """service {{
  proto-fd-max 15000
}}
logging {{
  file /var/log/aerospike/aerospike.log {{ context any info }}
}}
network {{
  service {{ address any
            port {port} }}
  heartbeat {{ mode mesh
              address any
              port {mesh_port}
{mesh_seeds}
              interval 150
              timeout 10 }}
  fabric {{ port {fabric_port} }}
}}
namespace {namespace} {{
  replication-factor {rf}
  memory-size 512M
  storage-engine memory
}}
"""


class AerospikeDB(common.DaemonDB):
    logfile = "/var/log/aerospike/aerospike.log"
    proc_name = "asd"

    def __init__(self, opts: Optional[dict] = None):
        super().__init__(opts)
        self.version = (opts or {}).get("version")

    def install(self, test, node):
        # (reference: support.clj install! — aerospike server + tools debs)
        pkgs = ["aerospike-server-community", "aerospike-tools"]
        if self.version:
            pkgs = [f"{p}={self.version}" for p in pkgs]
        debian.install(pkgs)
        with control.su():
            control.execute("mkdir", "-p", "/var/log/aerospike",
                            check=False)

    def configure(self, test, node):
        mesh_seeds = "\n".join(
            f"              mesh-seed-address-port {n} {MESH_PORT}"
            for n in test["nodes"]
        )
        conf = _CONF.format(
            port=PORT, mesh_port=MESH_PORT, fabric_port=FABRIC_PORT,
            namespace=NAMESPACE, mesh_seeds=mesh_seeds,
            rf=min(3, len(test["nodes"])),
        )
        with control.su():
            cu.write_file(conf, "/etc/aerospike/aerospike.conf")

    def start(self, test, node):
        with control.su():
            control.execute("service", "aerospike", "start", check=False)

    def kill(self, test, node):
        with control.su():
            control.execute("service", "aerospike", "stop", check=False)
            cu.grepkill("asd")

    def await_ready(self, test, node):
        cu.await_tcp_port(PORT, timeout_s=300)

    def wipe(self, test, node):
        with control.su():
            control.execute("rm", "-rf", "/opt/aerospike/data", check=False)


class _AsBase(client_mod.Client):
    def __init__(self, opts: Optional[dict] = None):
        self.opts = opts or {}
        self.conn: Optional[AerospikeClient] = None

    def open(self, test, node):
        c = type(self)(self.opts)
        c.conn = AerospikeClient(
            self.opts.get("host", str(node)),
            self.opts.get("port", PORT),
            namespace=self.opts.get("namespace", NAMESPACE),
            timeout=self.opts.get("timeout", 5.0),
        )
        return c

    def close(self, test):
        if self.conn:
            self.conn.close()


class CasRegisterClient(_AsBase):
    """Generation-checked CAS (reference: cas_register.clj:40-76)."""

    def invoke(self, test, op):
        k, v = op["value"]
        try:
            if op["f"] == "read":
                bins, _gen = self.conn.get(SET, int(k))
                val = bins.get("value") if bins else None
                return {**op, "type": "ok", "value": independent.kv(k, val)}
            if op["f"] == "write":
                self.conn.put(SET, int(k), {"value": int(v)})
                return {**op, "type": "ok"}
            if op["f"] == "cas":
                old, new = v
                bins, gen = self.conn.get(SET, int(k))
                if bins is None or bins.get("value") != old:
                    return {**op, "type": "fail", "error": "value-mismatch"}
                try:
                    self.conn.put(SET, int(k), {"value": int(new)},
                                  generation=gen)
                except AerospikeError as e:
                    if e.generation_mismatch:
                        return {**op, "type": "fail",
                                "error": "generation-mismatch"}
                    raise
                return {**op, "type": "ok"}
            raise ValueError(f"unknown f {op['f']!r}")
        except IndeterminateError as e:
            return {**op, "type": "info", "error": str(e)}
        except AerospikeError as e:
            return {**op, "type": "fail", "error": str(e)}


class CounterClient(_AsBase):
    """Increment-only counter (reference: counter.clj)."""

    KEY = 0

    RETRIES = 5

    def invoke(self, test, op):
        try:
            if op["f"] == "add":
                # read-modify-write, guarded both ways: generation check
                # on existing records, create-only on first increment —
                # otherwise two concurrent first adds both write {count:1}
                # and one increment is silently lost
                for _ in range(self.RETRIES):
                    bins, gen = self.conn.get(SET, self.KEY)
                    cur = bins.get("count", 0) if bins else 0
                    try:
                        self.conn.put(
                            SET, self.KEY,
                            {"count": cur + int(op["value"])},
                            generation=gen if bins is not None else None,
                            create_only=bins is None,
                        )
                        return {**op, "type": "ok"}
                    except AerospikeError as e:
                        if e.generation_mismatch or e.key_exists:
                            continue  # lost a race; re-read and retry
                        raise
                return {**op, "type": "fail", "error": "rmw-retries-exhausted"}
            if op["f"] == "read":
                bins, _gen = self.conn.get(SET, self.KEY)
                return {**op, "type": "ok",
                        "value": bins.get("count", 0) if bins else 0}
            raise ValueError(f"unknown f {op['f']!r}")
        except IndeterminateError as e:
            return {**op, "type": "info", "error": str(e)}
        except AerospikeError as e:
            return {**op, "type": "fail", "error": str(e)}


def db(opts: Optional[dict] = None):
    return AerospikeDB(opts)


def client(opts: Optional[dict] = None):
    return CasRegisterClient(opts)


class SetClient(_AsBase):
    """A set as CAS-appends to one record's string bin: add appends
    " v", read splits the bin back into integers.
    (reference: aerospike/set.clj:12-41 — single key "cats", append!,
    space-split parse)"""

    BIN = "value"

    def invoke(self, test, op):
        k, v = op["value"]
        try:
            if op["f"] == "read":
                bins, _gen = self.conn.get(SET, int(k))
                raw = str((bins or {}).get(self.BIN, ""))
                vals = sorted(
                    int(x) for x in raw.split(" ") if x.strip()
                )
                return {**op, "type": "ok", "value": independent.kv(k, vals)}
            if op["f"] == "add":
                self.conn.append_str(SET, int(k), self.BIN, f" {int(v)}")
                return {**op, "type": "ok"}
            raise ValueError(f"unknown f {op['f']!r}")
        except IndeterminateError as e:
            return {**op, "type": "info", "error": str(e)}
        except AerospikeError as e:
            return {**op, "type": "fail", "error": str(e)}


def set_workload(opts: Optional[dict] = None) -> dict:
    """(reference: set.clj:43-66 workload — shared independent-set
    shape)"""
    return common.independent_set_workload(opts)


def workloads(opts: Optional[dict] = None) -> dict:
    from . import aerospike_pause

    opts = dict(opts or {})
    return {
        "cas-register": common.register_workload(opts),
        "counter": common.counter_workload(opts),
        "set": set_workload(opts),
        # pause-to-lose-writes state machine (reference:
        # aerospike/pause.clj; test() assembles the full shared-state
        # client+nemesis wiring via pause_test)
        "pause": aerospike_pause.pause_workload(opts),
    }


def test(opts: Optional[dict] = None) -> dict:
    from . import aerospike_pause

    opts = dict(opts or {})
    wname = opts.get("workload", "cas-register")
    if wname == "pause":
        # the pause workload wires client+nemesis+generators through a
        # shared state machine; it assembles its own test map
        return aerospike_pause.pause_test(opts)
    w = workloads(opts)[wname]
    c = {
        "counter": CounterClient,
        "set": SetClient,
    }.get(wname, CasRegisterClient)(opts)
    # the suite fault menu (capped kills + revive/recluster recovery)
    # takes over when its fault names are requested
    pkg = None
    faults = set(opts.get("faults", ()))
    if faults & KNOWN_FAULTS:
        pkg = common.suite_nemesis_package(
            opts, AerospikeDB(opts),
            nemesis_package({
                **opts,
                "no-clocks": "clock-skew" not in faults,
                "no-kills": not (faults & {"kill", "revive-recluster"}),
                "no-partitions": "partition" not in faults,
            }),
            KNOWN_FAULTS,
        )
    return common.build_test(
        f"aerospike-{wname}", opts, db=AerospikeDB(opts), client=c,
        workload=w, nemesis_package=pkg,
    )


# ---------------------------------------------------------------------
# Suite nemesis: kills capped at max-dead, revive + recluster recovery
# (reference: aerospike/src/aerospike/nemesis.clj:1-145)
# ---------------------------------------------------------------------

import threading as _threading

from .. import generator as gen_mod
from ..nemesis import Nemesis, compose, partition_random_halves
from ..nemesis import time as nt
from ..util import random_nonempty_subset


class AsKillNemesis(Nemesis):
    """kill (capped at ``max_dead`` simultaneously-dead nodes),
    restart, and the asinfo revive/recluster recovery pair.
    (reference: nemesis.clj:17-57 kill-nemesis; revive!/recluster! from
    support.clj:142-152)"""

    def __init__(self, signal: int = 9, max_dead: int = 2):
        self.signal = signal
        self.max_dead = max_dead
        self.dead: set = set()
        self._lock = _threading.Lock()

    def setup(self, test):
        return self

    def invoke(self, test, op):
        from .. import control
        from ..control import execute, sudo

        f = op["f"]
        targets = op.get("value") or list(test["nodes"])

        def act(test, node):
            if f == "kill":
                with self._lock:
                    # the cap keeps a quorum alive (capped-conj,
                    # nemesis.clj:11-15)
                    if node not in self.dead and len(self.dead) >= self.max_dead:
                        return "still-alive"
                    self.dead.add(node)
                with sudo():
                    execute("killall", f"-{self.signal}", "asd", check=False)
                return "killed"
            if f == "restart":
                with sudo():
                    execute("service", "aerospike", "restart", check=False)
                with self._lock:
                    self.dead.discard(node)
                return "started"
            if f == "revive":
                with sudo():
                    return execute(
                        "asinfo", "-v", f"revive:namespace={NAMESPACE}",
                        check=False,
                    )
            if f == "recluster":
                with sudo():
                    return execute("asinfo", "-v", "recluster:", check=False)
            raise ValueError(f"unknown f {f!r}")

        res = control.on_nodes(test, targets, act)
        return {**op, "type": "info",
                "value": {str(k): str(v) for k, v in res.items()}}

    def teardown(self, test):
        pass

    def fs(self):
        return {"kill", "restart", "revive", "recluster"}


def full_nemesis(opts: dict) -> Nemesis:
    """(reference: nemesis.clj:97-111 full-nemesis)"""
    return compose([
        ({"partition-start": "start", "partition-stop": "stop"},
         partition_random_halves()),
        ({"kill", "restart", "revive", "recluster"},
         AsKillNemesis(
             signal=15 if opts.get("clean-kill") else 9,
             max_dead=opts.get("max-dead-nodes", 2),
         )),
        ({"clock-reset": "reset", "clock-bump": "bump",
          "clock-strobe": "strobe",
          "clock-check-offsets": "check-offsets"},
         nt.clock_nemesis()),
    ])


def _killer_gen(test, ctx):
    """One random step of the kill / restart / revive+recluster dance.
    (reference: nemesis.clj:59-94 killer-gen)"""
    r = gen_mod.rng.random()
    nodes = list(test["nodes"])
    if r < 1 / 3:
        return {"type": "info", "f": "kill",
                "value": random_nonempty_subset(nodes, gen_mod.rng)}
    if r < 2 / 3:
        return {"type": "info", "f": "restart",
                "value": random_nonempty_subset(nodes, gen_mod.rng)}
    return {"type": "info", "f": "revive", "value": nodes}


def full_gen(opts: dict):
    """(reference: nemesis.clj:113-126 full-gen)"""
    mix = []
    if not opts.get("no-clocks"):
        mix.append(gen_mod.f_map(
            {"strobe": "clock-strobe", "reset": "clock-reset",
             "bump": "clock-bump",
             "check-offsets": "clock-check-offsets"},
            nt.clock_gen(),
        ))
    if not opts.get("no-kills"):
        # revive is followed by recluster via flip-flop so the pair
        # lands together like the reference's [revive-gen recluster-gen]
        mix.append(gen_mod.flip_flop(
            _killer_gen,
            gen_mod.repeat({"type": "info", "f": "recluster", "value": None}),
        ))
    if not opts.get("no-partitions"):
        mix.append(gen_mod.cycle([
            {"type": "info", "f": "partition-start", "value": None},
            {"type": "info", "f": "partition-stop", "value": None},
        ]))
    if not mix:
        return None
    return gen_mod.stagger(
        opts.get("interval", 10), gen_mod.mix(mix)
    )


def nemesis_package(opts: dict) -> dict:
    """(reference: nemesis.clj:128-145 full)"""
    return {
        "nemesis": full_nemesis(opts),
        "generator": full_gen(opts),
        "final_generator": [
            {"type": "info", "f": "partition-stop", "value": None},
            {"type": "info", "f": "clock-reset", "value": None},
            {"type": "info", "f": "restart", "value": None},
            {"type": "info", "f": "revive", "value": None},
            {"type": "info", "f": "recluster", "value": None},
        ],
        "perf": {
            ("kill", frozenset({"kill"}), frozenset({"restart"}),
             "#E9A4A0"),
            ("partition", frozenset({"partition-start"}),
             frozenset({"partition-stop"}), "#A0E9DB"),
        },
    }


#: fault names routing test() to the suite package
KNOWN_FAULTS = {"kill", "partition", "clock-skew", "revive-recluster"}
