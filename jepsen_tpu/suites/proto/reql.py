"""RethinkDB ReQL wire protocol (V0_4, JSON serialization).

The reference drives RethinkDB through the official Clojure driver
(rethinkdb/src/jepsen/rethinkdb.clj + rethinkdb/document_cas.clj).
This implements the driver's wire format from scratch: the V0_4
handshake (magic + auth key + JSON protocol marker), then
length-prefixed JSON queries ``[START, term, optargs]`` with 8-byte
tokens, and enough ReQL term constructors for the document-CAS
workload: db/table create, get, insert, update with branch/eq row
functions.
"""

from __future__ import annotations

import json
import socket
import struct
from typing import Any, Dict, Optional

from . import IndeterminateError, ProtocolError

V0_4 = 0x400C2D20
PROTOCOL_JSON = 0x7E6970C7

START = 1

# response types
SUCCESS_ATOM, SUCCESS_SEQUENCE, SUCCESS_PARTIAL = 1, 2, 3
WAIT_COMPLETE = 4
CLIENT_ERROR, COMPILE_ERROR, RUNTIME_ERROR = 16, 17, 18

# term ids (ql2.proto)
DATUM, MAKE_ARRAY, VAR, ERROR = 1, 2, 10, 12
DB, TABLE, GET, EQ = 14, 15, 16, 17
GET_FIELD = 31
UPDATE = 53
INSERT = 56
DB_CREATE, TABLE_CREATE = 57, 60
BRANCH = 65
FUNC = 69
CONFIG = 174  # table.config() → single-selection over table_config


class ReqlError(ProtocolError):
    pass


# -- term constructors -------------------------------------------------


def db(name: str) -> list:
    return [DB, [name]]


def table(dbname: str, name: str) -> list:
    return [TABLE, [db(dbname), name]]


def get(tbl: list, key: Any) -> list:
    return [GET, [tbl, key]]


def insert(tbl: list, doc: dict, conflict: str = "error") -> list:
    return [INSERT, [tbl, {"__literal__": doc}], {"conflict": conflict}]


def update(sel: list, value: Any) -> list:
    return [UPDATE, [sel, value]]


def func(body: list) -> list:
    """One-arg row function; the row is VAR 1."""
    return [FUNC, [[MAKE_ARRAY, [1]], body]]


def var() -> list:
    return [VAR, [1]]


def get_field(row: list, name: str) -> list:
    return [GET_FIELD, [row, name]]


def eq(a: Any, b: Any) -> list:
    return [EQ, [a, b]]


def branch(cond: list, then: Any, otherwise: Any) -> list:
    return [BRANCH, [cond, then, otherwise]]


def error(msg: str) -> list:
    return [ERROR, [msg]]


def _serialize(term: Any) -> Any:
    """Plain dicts inside terms are object literals; mark insert docs
    with __literal__ so nested dicts aren't mistaken for optargs."""
    if isinstance(term, dict):
        if "__literal__" in term:
            return {k: _serialize(v) for k, v in term["__literal__"].items()}
        return {k: _serialize(v) for k, v in term.items()}
    if isinstance(term, list):
        return [_serialize(t) for t in term]
    return term


class ReqlClient:
    def __init__(self, host: str, port: int = 28015, auth_key: str = "",
                 timeout: float = 10.0):
        self.host = host
        self.port = port
        self.auth_key = auth_key
        self.timeout = timeout
        self.sock: Optional[socket.socket] = None
        self._buf = b""
        self._token = 0

    def connect(self) -> "ReqlClient":
        self.sock = socket.create_connection(
            (self.host, self.port), timeout=self.timeout
        )
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        key = self.auth_key.encode()
        self.sock.sendall(
            struct.pack("<I", V0_4)
            + struct.pack("<I", len(key)) + key
            + struct.pack("<I", PROTOCOL_JSON)
        )
        # null-terminated handshake reply
        reply = b""
        while not reply.endswith(b"\x00"):
            chunk = self.sock.recv(64)
            if not chunk:
                raise IndeterminateError("handshake: connection closed")
            reply += chunk
        if not reply.startswith(b"SUCCESS"):
            raise ReqlError(f"handshake failed: {reply[:-1].decode(errors='replace')}")
        return self

    def close(self) -> None:
        if self.sock is not None:
            try:
                self.sock.close()
            finally:
                self.sock = None

    def _recv_exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            try:
                chunk = self.sock.recv(65536)
            except OSError as e:
                self.close()
                raise IndeterminateError(f"recv failed: {e}") from e
            if not chunk:
                self.close()
                raise IndeterminateError("connection closed")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def run(self, term: list, optargs: Optional[Dict[str, Any]] = None) -> Any:
        """START a query, return the decoded result payload."""
        if self.sock is None:
            self.connect()
        self._token += 1
        token = self._token
        q = json.dumps([START, _serialize(term), optargs or {}]).encode()
        try:
            self.sock.sendall(
                struct.pack("<q", token) + struct.pack("<I", len(q)) + q
            )
        except OSError as e:
            self.close()
            raise IndeterminateError(f"send failed: {e}") from e
        rtoken = struct.unpack("<q", self._recv_exact(8))[0]
        if rtoken != token:
            raise ReqlError(f"token mismatch: sent {token}, got {rtoken}")
        (ln,) = struct.unpack("<I", self._recv_exact(4))
        payload = json.loads(self._recv_exact(ln))
        t = payload.get("t")
        if t in (SUCCESS_ATOM, SUCCESS_SEQUENCE, SUCCESS_PARTIAL):
            r = payload.get("r", [])
            return r[0] if t == SUCCESS_ATOM else r
        if t == RUNTIME_ERROR:
            raise ReqlError(str(payload.get("r", ["runtime error"])[0]),
                            code=t)
        raise ReqlError(f"response type {t}: {payload.get('r')!r}", code=t)
