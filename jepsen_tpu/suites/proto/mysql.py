"""MySQL client/server protocol (handshake v10 + COM_QUERY).

Backs the tidb, galera, percona, and mysql-cluster suites (the
reference uses clojure.java.jdbc + the MariaDB/MySQL JDBC driver:
tidb/src/tidb/sql.clj, galera/src/jepsen/galera.clj).

Implements packet framing, HandshakeResponse41 with
mysql_native_password (plus auth-switch handling), text-protocol
COM_QUERY result sets, and ERR packets surfaced with their server error
codes (1213 deadlock, 1205 lock wait timeout, …).
"""

from __future__ import annotations

import hashlib
import socket
import struct
from typing import List, Optional, Tuple

from . import IndeterminateError, ProtocolError

CLIENT_LONG_PASSWORD = 0x1
CLIENT_FOUND_ROWS = 0x2  # affected_rows counts matched, not changed, rows
CLIENT_PROTOCOL_41 = 0x200
CLIENT_TRANSACTIONS = 0x2000
CLIENT_SECURE_CONNECTION = 0x8000
CLIENT_PLUGIN_AUTH = 0x80000
CLIENT_CONNECT_WITH_DB = 0x8


class MysqlError(ProtocolError):
    """ERR packet; ``code`` is the server error number."""

    @property
    def retriable(self) -> bool:
        # 1213 ER_LOCK_DEADLOCK, 1205 ER_LOCK_WAIT_TIMEOUT,
        # 8002/8022/9007 TiDB txn retry errors
        return self.code in (1213, 1205, 8002, 8022, 9007)


class MysqlResult:
    def __init__(self):
        self.columns: List[str] = []
        self.rows: List[List[Optional[str]]] = []
        self.affected_rows = 0
        self.last_insert_id = 0


def _native_password(password: str, scramble: bytes) -> bytes:
    """SHA1(pw) XOR SHA1(scramble + SHA1(SHA1(pw)))."""
    if not password:
        return b""
    h1 = hashlib.sha1(password.encode()).digest()
    h2 = hashlib.sha1(h1).digest()
    h3 = hashlib.sha1(scramble + h2).digest()
    return bytes(a ^ b for a, b in zip(h1, h3))


def _lenenc(data: bytes, off: int) -> Tuple[Optional[int], int]:
    """Parse a length-encoded integer → (value-or-None-for-NULL, new off)."""
    first = data[off]
    if first < 0xFB:
        return first, off + 1
    if first == 0xFB:
        return None, off + 1
    if first == 0xFC:
        return struct.unpack("<H", data[off + 1 : off + 3])[0], off + 3
    if first == 0xFD:
        return int.from_bytes(data[off + 1 : off + 4], "little"), off + 4
    return struct.unpack("<Q", data[off + 1 : off + 9])[0], off + 9


class MysqlClient:
    def __init__(
        self,
        host: str,
        port: int = 3306,
        user: str = "root",
        password: str = "",
        database: str = "",
        timeout: float = 10.0,
    ):
        self.host = host
        self.port = port
        self.user = user
        self.password = password
        self.database = database
        self.timeout = timeout
        self.sock: Optional[socket.socket] = None
        self._buf = b""
        self._seq = 0

    # -- framing -----------------------------------------------------------

    def _recv_exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            try:
                chunk = self.sock.recv(65536)
            except (OSError, socket.timeout) as e:
                raise IndeterminateError(f"recv failed: {e}") from e
            if not chunk:
                raise IndeterminateError("connection closed by server")
            self._buf += chunk
        data, self._buf = self._buf[:n], self._buf[n:]
        return data

    def _read_packet(self) -> bytes:
        head = self._recv_exact(4)
        ln = int.from_bytes(head[:3], "little")
        self._seq = (head[3] + 1) & 0xFF
        return self._recv_exact(ln)

    def _send_packet(self, payload: bytes) -> None:
        head = len(payload).to_bytes(3, "little") + bytes([self._seq])
        self._seq = (self._seq + 1) & 0xFF
        try:
            self.sock.sendall(head + payload)
        except OSError as e:
            raise IndeterminateError(f"send failed: {e}") from e

    @staticmethod
    def _err(payload: bytes) -> MysqlError:
        code = struct.unpack("<H", payload[1:3])[0]
        msg = payload[3:]
        if msg[:1] == b"#":  # SQL state marker
            msg = msg[6:]
        return MysqlError(msg.decode(errors="replace"), code=code)

    # -- connection --------------------------------------------------------

    def connect(self) -> "MysqlClient":
        self.sock = socket.create_connection(
            (self.host, self.port), timeout=self.timeout
        )
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._seq = 0
        greeting = self._read_packet()
        if greeting[:1] == b"\xff":
            raise self._err(greeting)
        # protocol version byte, server version (nul string)
        off = greeting.index(b"\0", 1) + 1
        off += 4  # thread id
        scramble = greeting[off : off + 8]
        off += 8 + 1  # auth data part 1 + filler
        off += 2 + 1 + 2 + 2  # caps low, charset, status, caps high
        auth_len = greeting[off]
        off += 1 + 10  # auth data len + reserved
        scramble += greeting[off : off + max(13, auth_len - 8)].rstrip(b"\0")
        scramble = scramble[:20]

        # FOUND_ROWS makes `UPDATE … WHERE val = old` report matched
        # rows, so a CAS to the same value still counts as applied —
        # without it the SQL register clients would report false
        # linearizability violations when old == new.
        caps = (
            CLIENT_LONG_PASSWORD
            | CLIENT_FOUND_ROWS
            | CLIENT_PROTOCOL_41
            | CLIENT_TRANSACTIONS
            | CLIENT_SECURE_CONNECTION
            | CLIENT_PLUGIN_AUTH
        )
        if self.database:
            caps |= CLIENT_CONNECT_WITH_DB
        auth = _native_password(self.password, scramble)
        payload = struct.pack("<IIB23x", caps, 1 << 24, 33)  # utf8_general_ci
        payload += self.user.encode() + b"\0"
        payload += bytes([len(auth)]) + auth
        if self.database:
            payload += self.database.encode() + b"\0"
        payload += b"mysql_native_password\0"
        self._send_packet(payload)

        reply = self._read_packet()
        if reply[:1] == b"\xfe":  # AuthSwitchRequest
            plugin_end = reply.index(b"\0", 1)
            plugin = reply[1:plugin_end].decode()
            new_scramble = reply[plugin_end + 1 :].rstrip(b"\0")[:20]
            if plugin == "mysql_native_password":
                self._send_packet(_native_password(self.password, new_scramble))
            elif plugin == "mysql_clear_password":
                self._send_packet(self.password.encode() + b"\0")
            else:
                raise ProtocolError(f"unsupported auth plugin {plugin}")
            reply = self._read_packet()
        if reply[:1] == b"\xff":
            raise self._err(reply)
        return self

    def close(self) -> None:
        if self.sock is not None:
            try:
                self._seq = 0
                self._send_packet(b"\x01")  # COM_QUIT
            except Exception:
                pass
            try:
                self.sock.close()
            finally:
                self.sock = None

    # -- queries -----------------------------------------------------------

    def query(self, sql: str) -> MysqlResult:
        """COM_QUERY with the text protocol."""
        if self.sock is None:
            self.connect()
        self._seq = 0
        self._send_packet(b"\x03" + sql.encode())
        first = self._read_packet()
        res = MysqlResult()
        if first[:1] == b"\xff":
            raise self._err(first)
        if first[:1] == b"\x00":  # OK packet
            res.affected_rows, off = _lenenc(first, 1)
            res.last_insert_id, _ = _lenenc(first, off)
            return res
        ncols, _ = _lenenc(first, 0)
        for _ in range(ncols):
            coldef = self._read_packet()
            # catalog, schema, table, org_table, name — all lenenc strings
            off = 0
            for i in range(5):
                ln, off = _lenenc(coldef, off)
                if i == 4:
                    res.columns.append(coldef[off : off + ln].decode())
                off += ln
        pkt = self._read_packet()
        if pkt[:1] == b"\xfe" and len(pkt) < 9:  # EOF before rows
            pkt = self._read_packet()
        while True:
            if pkt[:1] == b"\xff":
                raise self._err(pkt)
            if pkt[:1] == b"\xfe" and len(pkt) < 9:  # EOF/OK: done
                return res
            off, row = 0, []
            while off < len(pkt):
                ln, off = _lenenc(pkt, off)
                if ln is None:
                    row.append(None)
                else:
                    row.append(pkt[off : off + ln].decode(errors="replace"))
                    off += ln
            res.rows.append(row)
            pkt = self._read_packet()
