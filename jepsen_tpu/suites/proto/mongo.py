"""MongoDB wire protocol (OP_MSG) with a from-scratch BSON codec.

Backs the mongodb-rocks and mongodb-smartos suites (the reference uses
the Monger/Java driver: mongodb-rocks/src/jepsen/mongodb/core.clj).
Implements the BSON subset the workloads need (double, string, doc,
array, bool, null, int32, int64) and the modern OP_MSG request cycle:
one kind-0 body section per message, commands insert/find/update/
delete/findAndModify addressed via ``$db``.

Write/read concerns ride in the command documents, so
majority-read/majority-write semantics are expressible exactly like the
reference's ``:write-concern :majority`` options.
"""

from __future__ import annotations

import socket
import struct
import threading
from typing import Any, Dict, List, Optional, Tuple

from . import IndeterminateError, ProtocolError

# ---------------------------------------------------------------------------
# BSON
# ---------------------------------------------------------------------------


def _encode_value(name: str, v: Any) -> bytes:
    key = name.encode() + b"\0"
    if isinstance(v, bool):  # before int: bool is an int subclass
        return b"\x08" + key + (b"\x01" if v else b"\x00")
    if isinstance(v, float):
        return b"\x01" + key + struct.pack("<d", v)
    if isinstance(v, str):
        b = v.encode()
        return b"\x02" + key + struct.pack("<i", len(b) + 1) + b + b"\0"
    if isinstance(v, dict):
        return b"\x03" + key + bson_encode(v)
    if isinstance(v, (list, tuple)):
        return b"\x04" + key + bson_encode(
            {str(i): x for i, x in enumerate(v)}
        )
    if v is None:
        return b"\x0a" + key
    if isinstance(v, int):
        if -(2**31) <= v < 2**31:
            return b"\x10" + key + struct.pack("<i", v)
        return b"\x12" + key + struct.pack("<q", v)
    raise TypeError(f"cannot BSON-encode {type(v)}: {v!r}")


def bson_encode(doc: Dict[str, Any]) -> bytes:
    body = b"".join(_encode_value(k, v) for k, v in doc.items())
    return struct.pack("<i", len(body) + 5) + body + b"\0"


def _decode_value(t: int, data: bytes, off: int) -> Tuple[Any, int]:
    if t == 0x01:
        return struct.unpack("<d", data[off : off + 8])[0], off + 8
    if t == 0x02:
        (n,) = struct.unpack("<i", data[off : off + 4])
        return data[off + 4 : off + 3 + n].decode(), off + 4 + n
    if t in (0x03, 0x04):
        (n,) = struct.unpack("<i", data[off : off + 4])
        sub = bson_decode(data[off : off + n])
        if t == 0x04:
            sub = [sub[k] for k in sorted(sub, key=int)]
        return sub, off + n
    if t == 0x08:
        return data[off] != 0, off + 1
    if t == 0x0A:
        return None, off
    if t == 0x10:
        return struct.unpack("<i", data[off : off + 4])[0], off + 4
    if t == 0x12:
        return struct.unpack("<q", data[off : off + 8])[0], off + 8
    if t == 0x11:  # timestamp
        return struct.unpack("<Q", data[off : off + 8])[0], off + 8
    if t == 0x07:  # ObjectId
        return data[off : off + 12].hex(), off + 12
    raise ProtocolError(f"cannot BSON-decode element type {t:#x}")


def bson_decode(data: bytes) -> Dict[str, Any]:
    (total,) = struct.unpack("<i", data[:4])
    off, out = 4, {}
    while off < total - 1:
        t = data[off]
        off += 1
        end = data.index(b"\0", off)
        name = data[off:end].decode()
        off = end + 1
        out[name], off = _decode_value(t, data, off)
    return out


# ---------------------------------------------------------------------------
# OP_MSG
# ---------------------------------------------------------------------------

OP_MSG = 2013


class MongoError(ProtocolError):
    """Command returned ok: 0 (or a writeErrors array)."""


class MongoClient:
    def __init__(
        self,
        host: str,
        port: int = 27017,
        database: str = "test",
        timeout: float = 10.0,
    ):
        self.host = host
        self.port = port
        self.database = database
        self.timeout = timeout
        self.sock: Optional[socket.socket] = None
        self._buf = b""
        self._request_id = 0
        self._lock = threading.Lock()

    def connect(self) -> "MongoClient":
        self.sock = socket.create_connection(
            (self.host, self.port), timeout=self.timeout
        )
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return self

    def close(self) -> None:
        if self.sock is not None:
            try:
                self.sock.close()
            finally:
                self.sock = None

    def _recv_exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            try:
                chunk = self.sock.recv(65536)
            except (OSError, socket.timeout) as e:
                raise IndeterminateError(f"recv failed: {e}") from e
            if not chunk:
                raise IndeterminateError("connection closed by server")
            self._buf += chunk
        data, self._buf = self._buf[:n], self._buf[n:]
        return data

    def command(self, doc: Dict[str, Any]) -> Dict[str, Any]:
        """Run one command document; raises MongoError on ok: 0."""
        if self.sock is None:
            self.connect()
        with self._lock:
            self._request_id += 1
            doc = {**doc, "$db": self.database}
            body = struct.pack("<I", 0) + b"\x00" + bson_encode(doc)
            header = struct.pack(
                "<iiii", 16 + len(body), self._request_id, 0, OP_MSG
            )
            try:
                self.sock.sendall(header + body)
            except OSError as e:
                raise IndeterminateError(f"send failed: {e}") from e
            ln, _rid, _rto, opcode = struct.unpack("<iiii", self._recv_exact(16))
            payload = self._recv_exact(ln - 16)
        if opcode != OP_MSG:
            raise ProtocolError(f"unexpected reply opcode {opcode}")
        # flagBits(4) + kind byte(1) + doc
        reply = bson_decode(payload[5:])
        if not reply.get("ok"):
            raise MongoError(
                reply.get("errmsg", str(reply)), code=reply.get("code")
            )
        if reply.get("writeErrors"):
            we = reply["writeErrors"][0]
            raise MongoError(we.get("errmsg", str(we)), code=we.get("code"))
        return reply

    # -- convenience CRUD --------------------------------------------------

    def insert(self, coll: str, docs: List[dict], write_concern=None) -> dict:
        cmd = {"insert": coll, "documents": docs}
        if write_concern:
            cmd["writeConcern"] = write_concern
        return self.command(cmd)

    def find(self, coll: str, filter: dict, read_concern=None) -> List[dict]:
        cmd: Dict[str, Any] = {"find": coll, "filter": filter}
        if read_concern:
            cmd["readConcern"] = read_concern
        reply = self.command(cmd)
        cursor = reply["cursor"]
        out = list(cursor["firstBatch"])
        # drain the cursor: firstBatch caps at ~101 docs on a real mongod
        while cursor.get("id"):
            reply = self.command({"getMore": cursor["id"], "collection": coll})
            cursor = reply["cursor"]
            out.extend(cursor["nextBatch"])
        return out

    def update(
        self, coll: str, filter: dict, update: dict, upsert=False, write_concern=None
    ) -> dict:
        cmd: Dict[str, Any] = {
            "update": coll,
            "updates": [{"q": filter, "u": update, "upsert": upsert}],
        }
        if write_concern:
            cmd["writeConcern"] = write_concern
        return self.command(cmd)

    def find_and_modify(
        self, coll: str, query: dict, update: dict, new=True, upsert=False,
        write_concern=None,
    ) -> Optional[dict]:
        cmd = {
            "findAndModify": coll,
            "query": query,
            "update": update,
            "new": new,
            "upsert": upsert,
        }
        if write_concern:
            cmd["writeConcern"] = write_concern
        reply = self.command(cmd)
        return reply.get("value")
