"""Aerospike wire protocol (AS_MSG), from scratch.

The reference drives Aerospike through the official Java client
(aerospike/src/aerospike/support.clj); its workloads need get/put with
generation-checked writes (optimistic CAS), integer bins, and list
append emulated via read-modify-write.  This implements that slice of
the protocol:

- 8-byte proto header: version=2, type=3 (AS_MSG), 48-bit length
- 22-byte message header: header_sz, info1/2/3, result_code,
  generation, record_ttl, transaction_ttl, n_fields, n_ops
- fields: namespace (0), set (1), user key (2, with 1-byte type
  prefix: 1=int, 3=string); a RIPEMD-160 digest field (4) computed
  from set+key, which the server uses for partition routing
- ops: size, op (1=read, 2=write), bin type (1=int, 3=string),
  version, name-len, name, value
"""

from __future__ import annotations

import hashlib
import socket
import struct
from typing import Any, Dict, List, Optional, Tuple

from . import IndeterminateError, ProtocolError

AS_MSG_TYPE = 3

INFO1_READ = 0x01
INFO1_GET_ALL = 0x02
INFO2_WRITE = 0x01
INFO2_GENERATION = 0x04   # write only if generation matches
INFO2_CREATE_ONLY = 0x20  # write only if the record does not exist

OP_READ, OP_WRITE, OP_APPEND = 1, 2, 9

PARTICLE_INT, PARTICLE_STR = 1, 3

FIELD_NAMESPACE, FIELD_SET, FIELD_KEY, FIELD_DIGEST = 0, 1, 2, 4

RESULT_OK = 0
RESULT_KEY_NOT_FOUND = 2
RESULT_GENERATION = 3
RESULT_KEY_EXISTS = 5
RESULT_TIMEOUT = 9


class AerospikeError(ProtocolError):
    @property
    def not_found(self) -> bool:
        return self.code == RESULT_KEY_NOT_FOUND

    @property
    def generation_mismatch(self) -> bool:
        return self.code == RESULT_GENERATION

    @property
    def key_exists(self) -> bool:
        return self.code == RESULT_KEY_EXISTS


def _digest(set_name: str, key: Any) -> bytes:
    """RIPEMD-160 over set + key-with-type, per the Aerospike client."""
    h = hashlib.new("ripemd160")
    h.update(set_name.encode())
    if isinstance(key, int):
        h.update(bytes([PARTICLE_INT]) + struct.pack(">q", key))
    else:
        h.update(bytes([PARTICLE_STR]) + str(key).encode())
    return h.digest()


def _field(ftype: int, data: bytes) -> bytes:
    return struct.pack(">IB", len(data) + 1, ftype) + data


def _op(op: int, bin_name: str, value: Optional[bytes],
        particle: int = 0) -> bytes:
    name = bin_name.encode()
    vlen = len(value) if value else 0
    return (
        struct.pack(">IBBBB", 4 + len(name) + vlen, op, particle, 0,
                    len(name))
        + name + (value or b"")
    )


def _int_particle(v: int) -> bytes:
    return struct.pack(">q", v)


class AerospikeClient:
    def __init__(self, host: str, port: int = 3000,
                 namespace: str = "jepsen", timeout: float = 5.0):
        self.host = host
        self.port = port
        self.namespace = namespace
        self.timeout = timeout
        self.sock: Optional[socket.socket] = None
        self._buf = b""

    def connect(self) -> "AerospikeClient":
        self.sock = socket.create_connection(
            (self.host, self.port), timeout=self.timeout
        )
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return self

    def close(self) -> None:
        if self.sock is not None:
            try:
                self.sock.close()
            finally:
                self.sock = None

    def _recv_exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            try:
                chunk = self.sock.recv(65536)
            except OSError as e:
                self.close()
                raise IndeterminateError(f"recv failed: {e}") from e
            if not chunk:
                self.close()
                raise IndeterminateError("connection closed")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def _call(self, info1: int, info2: int, generation: int,
              set_name: str, key: Any, ops: List[bytes]
              ) -> Tuple[int, int, Dict[str, Any]]:
        """→ (result_code, generation, bins)."""
        if self.sock is None:
            self.connect()
        fields = [
            _field(FIELD_NAMESPACE, self.namespace.encode()),
            _field(FIELD_SET, set_name.encode()),
            _field(FIELD_DIGEST, _digest(set_name, key)),
        ]
        body = struct.pack(
            ">BBBBBBIIIHH",
            22, info1, info2, 0, 0, 0,
            generation, 0, 1000,  # record_ttl=0, transaction_ttl
            len(fields), len(ops),
        ) + b"".join(fields) + b"".join(ops)
        header = struct.pack(">Q", (2 << 56) | (AS_MSG_TYPE << 48) | len(body))
        try:
            self.sock.sendall(header + body)
        except OSError as e:
            self.close()
            raise IndeterminateError(f"send failed: {e}") from e

        (proto,) = struct.unpack(">Q", self._recv_exact(8))
        length = proto & 0xFFFFFFFFFFFF
        payload = self._recv_exact(length)
        result_code = payload[5]
        (gen,) = struct.unpack_from(">I", payload, 6)
        n_fields, n_ops = struct.unpack_from(">HH", payload, 18)
        off = payload[0]  # header_sz
        for _ in range(n_fields):
            (sz,) = struct.unpack_from(">I", payload, off)
            off += 4 + sz
        bins: Dict[str, Any] = {}
        for _ in range(n_ops):
            (sz,) = struct.unpack_from(">I", payload, off)
            _opid, particle, _ver, nlen = struct.unpack_from(
                ">BBBB", payload, off + 4)
            name = payload[off + 8 : off + 8 + nlen].decode()
            val_raw = payload[off + 8 + nlen : off + 4 + sz]
            if particle == PARTICLE_INT and len(val_raw) == 8:
                bins[name] = struct.unpack(">q", val_raw)[0]
            else:
                bins[name] = val_raw.decode(errors="replace")
            off += 4 + sz
        return result_code, gen, bins

    # -- public ops ----------------------------------------------------
    def get(self, set_name: str, key: Any) -> Tuple[Optional[dict], int]:
        """→ (bins or None, generation)."""
        code, gen, bins = self._call(
            INFO1_READ | INFO1_GET_ALL, 0, 0, set_name, key, [])
        if code == RESULT_KEY_NOT_FOUND:
            return None, 0
        if code != RESULT_OK:
            raise AerospikeError(f"get failed: code {code}", code=code)
        return bins, gen

    def put(self, set_name: str, key: Any, bins: Dict[str, int],
            generation: Optional[int] = None,
            create_only: bool = False) -> None:
        """Write integer bins; with generation, the write applies only
        if the record's generation matches (CAS); with create_only, the
        write fails with KEY_EXISTS if the record is already there."""
        info2 = INFO2_WRITE
        gen = 0
        if generation is not None:
            info2 |= INFO2_GENERATION
            gen = generation
        if create_only:
            info2 |= INFO2_CREATE_ONLY
        ops = [
            _op(OP_WRITE, name, _int_particle(v), PARTICLE_INT)
            for name, v in bins.items()
        ]
        code, _g, _b = self._call(0, info2, gen, set_name, key, ops)
        if code == RESULT_TIMEOUT:
            raise IndeterminateError("server-side timeout")
        if code != RESULT_OK:
            raise AerospikeError(f"put failed: code {code}", code=code)

    def append_str(self, set_name: str, key: Any, bin_name: str,
                   s: str) -> None:
        """Append a string to a string bin (creating the record if
        absent) — the primitive the reference's set workload rides
        (aerospike/set.clj:35 s/append!)."""
        ops = [_op(OP_APPEND, bin_name, s.encode(), PARTICLE_STR)]
        code, _g, _b = self._call(0, INFO2_WRITE, 0, set_name, key, ops)
        if code == RESULT_TIMEOUT:
            raise IndeterminateError("server-side timeout")
        if code != RESULT_OK:
            raise AerospikeError(f"append failed: code {code}", code=code)
