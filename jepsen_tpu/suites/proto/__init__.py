"""Wire-protocol clients, from scratch on the Python stdlib.

The reference suites pull in a JVM driver per database (JDBC, the
Aerospike Java client, carmine for Redis, …).  Since this framework's
clients are Python and the environment forbids new dependencies, each
protocol the suites need is implemented here directly:

- :mod:`resp`    — Redis serialization protocol (disque, raftis)
- :mod:`http`    — JSON-over-HTTP helper (etcd, consul, elasticsearch,
                   crate, dgraph, faunadb, chronos, hazelcast, ignite)
- :mod:`pgwire`  — PostgreSQL wire protocol v3 (postgres-rds, stolon,
                   cockroachdb, yugabyte YSQL)
- :mod:`mysql`   — MySQL client/server protocol (tidb, galera, percona,
                   mysql-cluster)
- :mod:`zk`      — ZooKeeper jute protocol (zookeeper)
- :mod:`mongo`   — MongoDB OP_MSG + a minimal BSON codec (mongodb-*)
- :mod:`cql`     — Cassandra CQL binary protocol v4 (yugabyte YCQL)
- :mod:`irc`     — line-oriented IRC (robustirc)

Each client is deliberately small: connect, authenticate, issue the
handful of statements the workloads need, and surface errors as
:class:`ProtocolError` with enough detail for clients to classify
ok/fail/info.
"""

from __future__ import annotations


class ProtocolError(Exception):
    """A database-reported error (definite failure)."""

    def __init__(self, message: str, code=None):
        super().__init__(message)
        self.code = code


class IndeterminateError(Exception):
    """The connection died mid-request: the op may or may not have
    applied (maps to a :type :info completion)."""
