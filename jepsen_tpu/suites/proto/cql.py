"""Cassandra CQL binary protocol v4.

Backs the yugabyte YCQL workloads (the reference uses the cassaforte /
DataStax Java driver: yugabyte/src/yugabyte/ycql/client.clj).
Implements STARTUP/READY, QUERY with text-format values, RESULT
decoding (void / rows / set_keyspace), and ERROR frames surfaced with
their CQL error codes so callers can separate definite failures
(invalid query, already-exists) from timeouts (write_timeout 0x1100,
read_timeout 0x1200 → indeterminate).
"""

from __future__ import annotations

import socket
import struct
from typing import List, Optional, Tuple

from . import IndeterminateError, ProtocolError

VERSION_REQ = 0x04
VERSION_RESP = 0x84

OP_ERROR, OP_STARTUP, OP_READY, OP_QUERY, OP_RESULT = 0x00, 0x01, 0x02, 0x07, 0x08

CONSISTENCY = {
    "one": 0x0001,
    "quorum": 0x0004,
    "all": 0x0005,
    "serial": 0x0008,
    "local-one": 0x000A,
}

WRITE_TIMEOUT, READ_TIMEOUT = 0x1100, 0x1200


class CqlError(ProtocolError):
    @property
    def timeout(self) -> bool:
        return self.code in (WRITE_TIMEOUT, READ_TIMEOUT)


class CqlResult:
    def __init__(self):
        self.columns: List[str] = []
        self.col_types: List[int] = []  # CQL option ids per column
        self.rows: List[List[Optional[bytes]]] = []
        self.kind: str = "void"

    def cell_int(self, row: List[Optional[bytes]], i: int) -> Optional[int]:
        """Decode column i of a row as an integer, honouring the
        column's wire type (fixed-width ints vs text)."""
        cell = row[i]
        if cell is None:
            return None
        t = self.col_types[i] if i < len(self.col_types) else 0x000D
        if t in (0x0002, 0x0009, 0x0013, 0x0014):  # bigint/int/small/tiny
            return int.from_bytes(cell, "big", signed=True)
        return int(cell.decode())

    def cell_bool(self, row: List[Optional[bytes]], i: int) -> Optional[bool]:
        cell = row[i]
        if cell is None:
            return None
        t = self.col_types[i] if i < len(self.col_types) else 0x000D
        if t == 0x0004:  # boolean
            return cell != b"\x00"
        return cell.decode().lower() in ("true", "1")


class CqlClient:
    def __init__(self, host: str, port: int = 9042, timeout: float = 10.0):
        self.host = host
        self.port = port
        self.timeout = timeout
        self.sock: Optional[socket.socket] = None
        self._buf = b""
        self._stream = 0

    # -- framing -----------------------------------------------------------

    def _recv_exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            try:
                chunk = self.sock.recv(65536)
            except (OSError, socket.timeout) as e:
                raise IndeterminateError(f"recv failed: {e}") from e
            if not chunk:
                raise IndeterminateError("connection closed by server")
            self._buf += chunk
        data, self._buf = self._buf[:n], self._buf[n:]
        return data

    def _send_frame(self, opcode: int, body: bytes) -> None:
        self._stream = (self._stream + 1) % 0x7FFF
        header = struct.pack(
            "!BBhBI", VERSION_REQ, 0, self._stream, opcode, len(body)
        )
        try:
            self.sock.sendall(header + body)
        except OSError as e:
            raise IndeterminateError(f"send failed: {e}") from e

    def _read_frame(self) -> Tuple[int, bytes]:
        header = self._recv_exact(9)
        _v, _flags, _stream, opcode, ln = struct.unpack("!BBhBI", header)
        return opcode, self._recv_exact(ln)

    # -- connection --------------------------------------------------------

    def connect(self) -> "CqlClient":
        self.sock = socket.create_connection(
            (self.host, self.port), timeout=self.timeout
        )
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        # STARTUP: string map {"CQL_VERSION": "3.0.0"}
        k, v = b"CQL_VERSION", b"3.0.0"
        body = struct.pack("!H", 1)
        body += struct.pack("!H", len(k)) + k + struct.pack("!H", len(v)) + v
        self._send_frame(OP_STARTUP, body)
        opcode, payload = self._read_frame()
        if opcode == OP_ERROR:
            raise self._error(payload)
        if opcode != OP_READY:
            raise ProtocolError(f"expected READY, got opcode {opcode:#x}")
        return self

    def close(self) -> None:
        if self.sock is not None:
            try:
                self.sock.close()
            finally:
                self.sock = None

    @staticmethod
    def _error(payload: bytes) -> CqlError:
        (code,) = struct.unpack("!I", payload[:4])
        (n,) = struct.unpack("!H", payload[4:6])
        return CqlError(payload[6 : 6 + n].decode(errors="replace"), code=code)

    # -- queries -----------------------------------------------------------

    def query(self, cql: str, consistency: str = "quorum") -> CqlResult:
        if self.sock is None:
            self.connect()
        q = cql.encode()
        body = struct.pack("!I", len(q)) + q
        body += struct.pack("!HB", CONSISTENCY[consistency], 0)
        self._send_frame(OP_QUERY, body)
        opcode, payload = self._read_frame()
        if opcode == OP_ERROR:
            raise self._error(payload)
        if opcode != OP_RESULT:
            raise ProtocolError(f"expected RESULT, got opcode {opcode:#x}")
        return self._decode_result(payload)

    def _decode_result(self, payload: bytes) -> CqlResult:
        res = CqlResult()
        (kind,) = struct.unpack("!I", payload[:4])
        if kind == 1:
            res.kind = "void"
            return res
        if kind == 3:
            res.kind = "set_keyspace"
            return res
        if kind != 2:
            res.kind = f"kind-{kind}"
            return res
        res.kind = "rows"
        flags, ncols = struct.unpack("!II", payload[4:12])
        off = 12
        if flags & 0x0001:  # global tables spec: ks + table
            for _ in range(2):
                (n,) = struct.unpack("!H", payload[off : off + 2])
                off += 2 + n
        for _ in range(ncols):
            if not flags & 0x0001:
                for _ in range(2):
                    (n,) = struct.unpack("!H", payload[off : off + 2])
                    off += 2 + n
            (n,) = struct.unpack("!H", payload[off : off + 2])
            res.columns.append(payload[off + 2 : off + 2 + n].decode())
            off += 2 + n
            (t,) = struct.unpack("!H", payload[off : off + 2])
            off += 2
            res.col_types.append(t)
            if t == 0x0000:  # custom: string class name
                (n,) = struct.unpack("!H", payload[off : off + 2])
                off += 2 + n
            elif t in (0x0020, 0x0022):  # list/set: one inner type
                off += 2
            elif t == 0x0021:  # map: two inner types
                off += 4
        (nrows,) = struct.unpack("!I", payload[off : off + 4])
        off += 4
        for _ in range(nrows):
            row = []
            for _ in range(ncols):
                (n,) = struct.unpack("!i", payload[off : off + 4])
                off += 4
                if n < 0:
                    row.append(None)
                else:
                    row.append(payload[off : off + n])
                    off += n
            res.rows.append(row)
        return res


def int_value(cell: Optional[bytes]) -> Optional[int]:
    """Decode a bigint/int cell."""
    if cell is None:
        return None
    return int.from_bytes(cell, "big", signed=True)


def text_value(cell: Optional[bytes]) -> Optional[str]:
    if cell is None:
        return None
    return cell.decode()
