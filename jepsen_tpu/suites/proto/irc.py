"""Minimal IRC client (RFC 1459 line protocol).

Backs the robustirc suite (the reference drives RobustIRC through a
Java IRC client: robustirc/src/jepsen/robustirc.clj).  The workload
needs only: register (NICK/USER), JOIN a channel, PRIVMSG, and read
delivered messages — message delivery/ordering is what the checker
verifies.
"""

from __future__ import annotations

import socket
from typing import List, Optional, Tuple

from . import IndeterminateError


class IrcTimeout(IndeterminateError):
    """A read deadline elapsed with no data (distinct from EOF/partition
    so drain loops can end cleanly without masking dead connections)."""


class IrcClient:
    def __init__(self, host: str, port: int = 6667, nick: str = "jepsen", timeout: float = 10.0):
        self.host = host
        self.port = port
        self.nick = nick
        self.timeout = timeout
        self.sock: Optional[socket.socket] = None
        self._buf = b""

    def connect(self) -> "IrcClient":
        self.sock = socket.create_connection(
            (self.host, self.port), timeout=self.timeout
        )
        self._send(f"NICK {self.nick}")
        self._send(f"USER {self.nick} 0 * :{self.nick}")
        # wait for 001 welcome
        self._await(lambda p, c, a: c == "001")
        return self

    def close(self) -> None:
        if self.sock is not None:
            try:
                self._send("QUIT :bye")
            except Exception:
                pass
            try:
                self.sock.close()
            finally:
                self.sock = None

    def _send(self, line: str) -> None:
        try:
            self.sock.sendall(line.encode() + b"\r\n")
        except OSError as e:
            raise IndeterminateError(f"send failed: {e}") from e

    def _read_line(self) -> str:
        while b"\r\n" not in self._buf:
            try:
                chunk = self.sock.recv(65536)
            except socket.timeout as e:
                raise IrcTimeout(f"read timed out: {e}") from e
            except OSError as e:
                raise IndeterminateError(f"recv failed: {e}") from e
            if not chunk:
                raise IndeterminateError("connection closed by server")
            self._buf += chunk
        line, self._buf = self._buf.split(b"\r\n", 1)
        return line.decode(errors="replace")

    @staticmethod
    def parse(line: str) -> Tuple[Optional[str], str, List[str]]:
        """':prefix CMD a b :trailing' → (prefix, CMD, [a, b, trailing])."""
        prefix = None
        if line.startswith(":"):
            prefix, line = line[1:].split(" ", 1)
        if " :" in line:
            line, trailing = line.split(" :", 1)
            args = line.split() + [trailing]
        else:
            args = line.split()
        return prefix, args[0], args[1:]

    def _await(self, pred) -> Tuple[Optional[str], str, List[str]]:
        """Read (answering PINGs) until pred(prefix, cmd, args) is true."""
        while True:
            prefix, cmd, args = self.parse(self._read_line())
            if cmd == "PING":
                self._send(f"PONG {args[0] if args else ''}")
                continue
            if pred(prefix, cmd, args):
                return prefix, cmd, args

    def join(self, channel: str) -> None:
        self._send(f"JOIN {channel}")
        self._await(lambda p, c, a: c == "JOIN" and a and a[-1] == channel)

    def privmsg(self, target: str, message: str) -> None:
        self._send(f"PRIVMSG {target} :{message}")

    def topic(self, channel: str, text: str) -> None:
        """Set the channel topic (robustirc's set workload writes
        elements as topic changes)."""
        self._send(f"TOPIC {channel} :{text}")

    def read_messages(self, max_lines: int = 100) -> List[Tuple[str, str, str]]:
        """Drain pending PRIVMSGs/TOPICs → [(sender-nick, target, text)].
        Returns when the drain deadline passes or after max_lines; a
        severed connection still raises IndeterminateError so callers
        never mistake a dead link for an empty mailbox."""
        out = []
        if self.sock is None:
            return out
        self.sock.settimeout(0.2)
        try:
            for _ in range(max_lines):
                prefix, cmd, args = self.parse(self._read_line())
                if cmd == "PING":
                    self._send(f"PONG {args[0] if args else ''}")
                elif cmd in ("PRIVMSG", "TOPIC") and len(args) >= 2:
                    nick = (prefix or "").split("!", 1)[0]
                    out.append((nick, args[0], args[1]))
        except IrcTimeout:
            pass
        finally:
            self.sock.settimeout(self.timeout)
        return out
