"""Hazelcast open binary client protocol (1.x framing, IMDG 3.12).

Backs the hazelcast suite's lock / semaphore / atomic / id-gen / map /
queue workloads (the reference drives them through the official JVM
client: hazelcast/src/jepsen/hazelcast.clj:117-445).  This implements
the client side from scratch:

- **Framing** (little-endian): frameLength:int32 (self-inclusive),
  version:uint8, flags:uint8 (0xC0 = unfragmented), type:uint16,
  correlationId:int64, partitionId:int32 (-1 = any), dataOffset:uint16
  (= header size, 22), then the parameter payload.
- **Parameters**: str = int32 length + utf8; bool = 1 byte; int/long
  little-endian fixed width; nullable values carry a 1-byte is-null
  flag first.
- **Data** (map/queue keys and values) wraps Hazelcast's default
  serialization: big-endian int32 type id then the value bytes
  (CONSTANT_TYPE_LONG = -7 → 8-byte BE long; CONSTANT_TYPE_STRING =
  -11 → int32 length + utf8).

Message-type ids follow the published hazelcast-client-protocol 1.x
tables (service byte ‖ method byte).  The ids this module actually
exercises are pinned by the differential fake server in
tests/fake_servers.py, which speaks the same spec; drive a live 3.12
cluster to cross-verify before trusting a new id.
"""

from __future__ import annotations

import socket
import struct
import threading
from typing import Any, List, Optional

from . import IndeterminateError, ProtocolError

PROTOCOL_PREFIX = b"CB2"  # client-binary protocol, version 2 handshake

VERSION = 1
FLAGS_UNFRAGMENTED = 0xC0
HEADER = struct.Struct("<iBBHqih")  # len, ver, flags, type, corr, part, off
HEADER_SIZE = HEADER.size  # 22

# -- message types ----------------------------------------------------------

AUTH = 0x0002

# generic response types
RESP_VOID = 100
RESP_BOOL = 101
RESP_INT = 102
RESP_LONG = 103
RESP_STRING = 104
RESP_DATA = 105
RESP_AUTH = 107
RESP_ERROR = 109

# map service 0x01
MAP_PUT = 0x0101
MAP_GET = 0x0102
MAP_REMOVE = 0x0103
MAP_REPLACE = 0x0104
MAP_REPLACE_IF_SAME = 0x0105
MAP_PUT_IF_ABSENT = 0x010D

# queue service 0x03
QUEUE_OFFER = 0x0301
QUEUE_POLL = 0x0305
QUEUE_SIZE = 0x0303

# lock service 0x07
LOCK_LOCK = 0x0705
LOCK_UNLOCK = 0x0706
LOCK_TRY_LOCK = 0x0708

# atomic long service 0x0A
ATOMIC_LONG_ADD_AND_GET = 0x0A05
ATOMIC_LONG_COMPARE_AND_SET = 0x0A06
ATOMIC_LONG_GET = 0x0A08
ATOMIC_LONG_INCREMENT_AND_GET = 0x0A0B
ATOMIC_LONG_SET = 0x0A0D

# atomic reference service 0x0B
ATOMIC_REF_COMPARE_AND_SET = 0x0B04
ATOMIC_REF_GET = 0x0B06
ATOMIC_REF_SET = 0x0B07

# semaphore service 0x0D
SEMAPHORE_INIT = 0x0D01
SEMAPHORE_ACQUIRE = 0x0D02
SEMAPHORE_RELEASE = 0x0D06
SEMAPHORE_TRY_ACQUIRE = 0x0D07

# flake id generator service 0x1C
FLAKE_ID_NEW_BATCH = 0x1C01

# CP-subsystem fenced lock (4.x CP FencedLock semantics: a successful
# acquire returns a monotonically increasing fencing token; re-acquires
# by the holder return the hold's existing token)
FENCED_LOCK_TRY_LOCK = 0x2603
FENCED_LOCK_UNLOCK = 0x2604

#: the "acquire failed" fence (CP FencedLock.INVALID_FENCE)
INVALID_FENCE = 0

# serialization constant type ids (big-endian int32 before the body)
TYPE_LONG = -7
TYPE_STRING = -11


class HzError(ProtocolError):
    def __init__(self, msg: str, code: int = 0):
        super().__init__(f"hazelcast error: {msg}", code=code)


# -- parameter encoding -----------------------------------------------------


def _str(s: str) -> bytes:
    b = s.encode()
    return struct.pack("<i", len(b)) + b


def _nullable_str(s: Optional[str]) -> bytes:
    if s is None:
        return b"\x01"
    return b"\x00" + _str(s)


def _bool(v: bool) -> bytes:
    return b"\x01" if v else b"\x00"


def _long(v: int) -> bytes:
    return struct.pack("<q", v)


def _int(v: int) -> bytes:
    return struct.pack("<i", v)


def data_long(v: int) -> bytes:
    """A java.lang.Long as Hazelcast Data."""
    return struct.pack(">iq", TYPE_LONG, v)


def data_string(s: str) -> bytes:
    b = s.encode()
    return struct.pack(">ii", TYPE_STRING, len(b)) + b


def _data(d: bytes) -> bytes:
    return struct.pack("<i", len(d)) + d


def parse_data(d: bytes) -> Any:
    """Decode a Data blob back to a python value."""
    (tid,) = struct.unpack_from(">i", d, 0)
    if tid == TYPE_LONG:
        return struct.unpack_from(">q", d, 4)[0]
    if tid == TYPE_STRING:
        (n,) = struct.unpack_from(">i", d, 4)
        return d[8 : 8 + n].decode()
    raise HzError(f"unsupported data type id {tid}")


class _Reader:
    __slots__ = ("buf", "off")

    def __init__(self, buf: bytes, off: int = 0):
        self.buf = buf
        self.off = off

    def u8(self) -> int:
        v = self.buf[self.off]
        self.off += 1
        return v

    def i32(self) -> int:
        (v,) = struct.unpack_from("<i", self.buf, self.off)
        self.off += 4
        return v

    def i64(self) -> int:
        (v,) = struct.unpack_from("<q", self.buf, self.off)
        self.off += 8
        return v

    def string(self) -> str:
        n = self.i32()
        s = self.buf[self.off : self.off + n].decode()
        self.off += n
        return s

    def nullable_string(self) -> Optional[str]:
        return None if self.u8() else self.string()

    def data(self) -> bytes:
        n = self.i32()
        d = self.buf[self.off : self.off + n]
        self.off += n
        return d

    def nullable_data(self) -> Optional[bytes]:
        return None if self.u8() else self.data()


class HzClient:
    """One authenticated client connection.  Logically single-threaded
    (one outstanding request), like the suite's worker processes."""

    def __init__(
        self,
        host: str,
        port: int = 5701,
        group: str = "jepsen",
        password: str = "jepsen-pass",
        timeout: float = 5.0,
    ):
        self.host = host
        self.port = port
        self.group = group
        self.password = password
        self.timeout = timeout
        self.sock: Optional[socket.socket] = None
        self.uuid: Optional[str] = None
        self.owner_uuid: Optional[str] = None
        self._corr = 0
        self._lock = threading.Lock()
        #: per-connection thread id for lock/semaphore ownership; the
        #: JVM client uses the calling thread's id — one id per client
        #: models our logically single-threaded processes
        self.thread_id = 1

    # -- transport --

    def connect(self) -> "HzClient":
        s = socket.create_connection(
            (self.host, self.port), timeout=self.timeout
        )
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.sock = s
        s.sendall(PROTOCOL_PREFIX)
        r = self._invoke(
            AUTH,
            _str(self.group)
            + _str(self.password)
            + _nullable_str(None)
            + _nullable_str(None)
            + _bool(True)
            + _str("PYH")  # client type
            + bytes([1])  # serialization version
            + _str("3.12"),
        )
        status = r.u8()
        if status != 0:
            raise HzError(f"authentication failed (status {status})")
        # address: nullable (host str, port int)
        if not r.u8():
            r.string()
            r.i32()
        self.uuid = r.nullable_string()
        self.owner_uuid = r.nullable_string()
        return self

    def close(self) -> None:
        if self.sock is not None:
            try:
                self.sock.close()
            finally:
                self.sock = None

    def _recv_exact(self, n: int) -> bytes:
        assert self.sock is not None
        chunks = b""
        while len(chunks) < n:
            try:
                c = self.sock.recv(n - len(chunks))
            except socket.timeout as e:
                raise IndeterminateError(f"hazelcast timeout: {e}") from e
            except OSError as e:
                raise IndeterminateError(f"hazelcast conn lost: {e}") from e
            if not c:
                raise IndeterminateError("hazelcast connection closed")
            chunks += c
        return chunks

    def _invoke(
        self, msg_type: int, payload: bytes, partition: int = -1
    ) -> _Reader:
        if self.sock is None:
            raise IndeterminateError("hazelcast client not connected")
        with self._lock:
            self._corr += 1
            corr = self._corr
            frame = HEADER.pack(
                HEADER_SIZE + len(payload),
                VERSION,
                FLAGS_UNFRAGMENTED,
                msg_type,
                corr,
                partition,
                HEADER_SIZE,
            ) + payload
            try:
                self.sock.sendall(frame)
            except OSError as e:
                raise IndeterminateError(f"hazelcast send failed: {e}") from e
            head = self._recv_exact(HEADER_SIZE)
            ln, _ver, _flags, rtype, rcorr, _part, off = HEADER.unpack(head)
            body = self._recv_exact(ln - HEADER_SIZE)
        if rcorr != corr:
            raise HzError(f"correlation mismatch ({rcorr} != {corr})")
        r = _Reader(head + body, off)
        if rtype == RESP_ERROR:
            code = r.i32()
            cls = r.nullable_string() or "?"
            msg = r.nullable_string() or ""
            raise HzError(f"{cls}: {msg}", code=code)
        return r

    # -- map --

    def map_get(self, name: str, key: bytes) -> Optional[bytes]:
        r = self._invoke(
            MAP_GET, _str(name) + _data(key) + _long(self.thread_id)
        )
        return r.nullable_data()

    def map_put(self, name: str, key: bytes, value: bytes) -> Optional[bytes]:
        r = self._invoke(
            MAP_PUT,
            _str(name) + _data(key) + _data(value) + _long(self.thread_id)
            + _long(-1),  # ttl
        )
        return r.nullable_data()

    def map_put_if_absent(
        self, name: str, key: bytes, value: bytes
    ) -> Optional[bytes]:
        """Returns the previous value (None = the put won)."""
        r = self._invoke(
            MAP_PUT_IF_ABSENT,
            _str(name) + _data(key) + _data(value) + _long(self.thread_id)
            + _long(-1),
        )
        return r.nullable_data()

    def map_replace_if_same(
        self, name: str, key: bytes, old: bytes, new: bytes
    ) -> bool:
        r = self._invoke(
            MAP_REPLACE_IF_SAME,
            _str(name) + _data(key) + _data(old) + _data(new)
            + _long(self.thread_id),
        )
        return bool(r.u8())

    # -- queue --

    def queue_offer(self, name: str, value: bytes, timeout_ms: int = 0) -> bool:
        r = self._invoke(
            QUEUE_OFFER, _str(name) + _data(value) + _long(timeout_ms)
        )
        return bool(r.u8())

    def queue_poll(self, name: str, timeout_ms: int = 0) -> Optional[bytes]:
        r = self._invoke(QUEUE_POLL, _str(name) + _long(timeout_ms))
        return r.nullable_data()

    # -- lock --

    def lock(self, name: str, lease_ms: int = -1) -> None:
        self._invoke(
            LOCK_LOCK,
            _str(name) + _long(lease_ms) + _long(self.thread_id) + _long(0),
        )

    def try_lock(
        self, name: str, timeout_ms: int = 0, lease_ms: int = -1
    ) -> bool:
        r = self._invoke(
            LOCK_TRY_LOCK,
            _str(name) + _long(self.thread_id) + _long(lease_ms)
            + _long(timeout_ms) + _long(0),
        )
        return bool(r.u8())

    def unlock(self, name: str) -> None:
        self._invoke(
            LOCK_UNLOCK, _str(name) + _long(self.thread_id) + _long(0)
        )

    def try_lock_fenced(
        self, name: str, timeout_ms: int = 0
    ) -> int:
        """CP fenced lock: returns the fencing token on success,
        INVALID_FENCE (0) on timeout.  A holder's re-acquire returns
        the hold's existing token."""
        r = self._invoke(
            FENCED_LOCK_TRY_LOCK,
            _str(name) + _long(self.thread_id) + _long(timeout_ms),
        )
        return r.i64()

    def unlock_fenced(self, name: str) -> None:
        self._invoke(
            FENCED_LOCK_UNLOCK, _str(name) + _long(self.thread_id)
        )

    # -- semaphore --

    def semaphore_init(self, name: str, permits: int) -> bool:
        r = self._invoke(SEMAPHORE_INIT, _str(name) + _int(permits))
        return bool(r.u8())

    def semaphore_try_acquire(
        self, name: str, permits: int = 1, timeout_ms: int = 0
    ) -> bool:
        r = self._invoke(
            SEMAPHORE_TRY_ACQUIRE,
            _str(name) + _int(permits) + _long(timeout_ms),
        )
        return bool(r.u8())

    def semaphore_release(self, name: str, permits: int = 1) -> None:
        self._invoke(SEMAPHORE_RELEASE, _str(name) + _int(permits))

    # -- atomic long --

    def atomic_add_and_get(self, name: str, delta: int) -> int:
        r = self._invoke(ATOMIC_LONG_ADD_AND_GET, _str(name) + _long(delta))
        return r.i64()

    def atomic_get(self, name: str) -> int:
        r = self._invoke(ATOMIC_LONG_GET, _str(name))
        return r.i64()

    def atomic_set(self, name: str, value: int) -> None:
        self._invoke(ATOMIC_LONG_SET, _str(name) + _long(value))

    def atomic_compare_and_set(self, name: str, old: int, new: int) -> bool:
        r = self._invoke(
            ATOMIC_LONG_COMPARE_AND_SET, _str(name) + _long(old) + _long(new)
        )
        return bool(r.u8())

    def atomic_increment_and_get(self, name: str) -> int:
        r = self._invoke(ATOMIC_LONG_INCREMENT_AND_GET, _str(name))
        return r.i64()

    # -- atomic reference --

    def ref_get(self, name: str) -> Optional[bytes]:
        r = self._invoke(ATOMIC_REF_GET, _str(name))
        return r.nullable_data()

    def ref_set(self, name: str, value: Optional[bytes]) -> None:
        payload = _str(name)
        payload += b"\x01" if value is None else b"\x00" + _data(value)
        self._invoke(ATOMIC_REF_SET, payload)

    def ref_compare_and_set(
        self, name: str, old: Optional[bytes], new: Optional[bytes]
    ) -> bool:
        payload = _str(name)
        for v in (old, new):
            payload += b"\x01" if v is None else b"\x00" + _data(v)
        r = self._invoke(ATOMIC_REF_COMPARE_AND_SET, payload)
        return bool(r.u8())

    # -- flake id generator --

    def new_id_batch(self, name: str, batch_size: int = 1) -> List[int]:
        """Returns batch_size unique ids (base + i*increment)."""
        r = self._invoke(FLAKE_ID_NEW_BATCH, _str(name) + _int(batch_size))
        base = r.i64()
        increment = r.i64()
        n = r.i32()
        return [base + i * increment for i in range(n)]
