"""AMQP 0-9-1 client (the subset the rabbitmq suite needs).

The reference drives RabbitMQ through Langohr (rabbitmq/src/jepsen/
rabbitmq.clj:18-24): queue.declare with durability args, basic.publish
with persistent delivery mode, basic.get + basic.ack for dequeues.
This module implements exactly that slice of AMQP 0-9-1 from scratch:
PLAIN auth handshake, one channel, queue.declare/purge,
basic.publish/get/ack.

Framing: frame = type(1) channel(2) size(4) payload frame-end(0xCE).
Method payload = class-id(2) method-id(2) arguments.
"""

from __future__ import annotations

import socket
import struct
from typing import Any, Dict, Optional, Tuple

from . import IndeterminateError, ProtocolError

FRAME_METHOD, FRAME_HEADER, FRAME_BODY, FRAME_HEARTBEAT = 1, 2, 3, 8
FRAME_END = 0xCE


class AmqpError(ProtocolError):
    pass


def _short_str(s: str) -> bytes:
    b = s.encode()
    return bytes([len(b)]) + b


def _long_str(b: bytes) -> bytes:
    return struct.pack("!I", len(b)) + b


def _field_table(d: Dict[str, Any]) -> bytes:
    out = b""
    for k, v in d.items():
        out += _short_str(k)
        if isinstance(v, bool):
            out += b"t" + (b"\x01" if v else b"\x00")
        elif isinstance(v, int):
            out += b"I" + struct.pack("!i", v)
        elif isinstance(v, str):
            out += b"S" + _long_str(v.encode())
        else:
            raise ValueError(f"unsupported table value {v!r}")
    return _long_str(out)


def _parse_field_table(data: bytes, off: int) -> Tuple[dict, int]:
    (n,) = struct.unpack_from("!I", data, off)
    off += 4
    end = off + n
    out = {}
    while off < end:
        ln = data[off]
        key = data[off + 1 : off + 1 + ln].decode()
        off += 1 + ln
        t = data[off : off + 1]
        off += 1
        if t == b"t":
            out[key] = bool(data[off]); off += 1
        elif t == b"I":
            (out[key],) = struct.unpack_from("!i", data, off); off += 4
        elif t == b"S":
            (sl,) = struct.unpack_from("!I", data, off)
            out[key] = data[off + 4 : off + 4 + sl].decode(errors="replace")
            off += 4 + sl
        elif t == b"F":
            out[key], off = _parse_field_table(data, off)
        elif t == b"l":
            (out[key],) = struct.unpack_from("!q", data, off); off += 8
        else:
            raise AmqpError(f"unsupported field type {t!r}")
    return out, end


class AmqpClient:
    def __init__(
        self,
        host: str,
        port: int = 5672,
        user: str = "guest",
        password: str = "guest",
        vhost: str = "/",
        timeout: float = 10.0,
    ):
        self.host = host
        self.port = port
        self.user = user
        self.password = password
        self.vhost = vhost
        self.timeout = timeout
        self.sock: Optional[socket.socket] = None
        self._buf = b""

    # -- framing -------------------------------------------------------
    def _send(self, data: bytes) -> None:
        try:
            self.sock.sendall(data)
        except OSError as e:
            self.close()
            raise IndeterminateError(f"send failed: {e}") from e

    def _send_method(self, channel: int, class_id: int, method_id: int,
                     args: bytes) -> None:
        payload = struct.pack("!HH", class_id, method_id) + args
        self._send(
            struct.pack("!BHI", FRAME_METHOD, channel, len(payload))
            + payload + bytes([FRAME_END])
        )

    def _recv_exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            try:
                chunk = self.sock.recv(65536)
            except OSError as e:
                self.close()
                raise IndeterminateError(f"recv failed: {e}") from e
            if not chunk:
                self.close()
                raise IndeterminateError("connection closed")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def _read_frame(self) -> Tuple[int, int, bytes]:
        t, ch, size = struct.unpack("!BHI", self._recv_exact(7))
        payload = self._recv_exact(size)
        end = self._recv_exact(1)
        if end[0] != FRAME_END:
            raise AmqpError(f"bad frame end {end!r}")
        return t, ch, payload

    def _read_method(self) -> Tuple[int, int, int, bytes]:
        """Skip heartbeats → (channel, class, method, args)."""
        while True:
            t, ch, payload = self._read_frame()
            if t == FRAME_HEARTBEAT:
                continue
            if t != FRAME_METHOD:
                raise AmqpError(f"expected method frame, got type {t}")
            cid, mid = struct.unpack_from("!HH", payload, 0)
            if cid == 10 and mid == 50:  # connection.close
                self._reply_close_ok(0)
                raise self._close_error(payload[4:])
            if cid == 20 and mid == 40:  # channel.close
                self._send_method(ch, 20, 41, b"")
                raise self._close_error(payload[4:])
            return ch, cid, mid, payload[4:]

    def _close_error(self, args: bytes) -> AmqpError:
        (code,) = struct.unpack_from("!H", args, 0)
        ln = args[2]
        text = args[3 : 3 + ln].decode(errors="replace")
        return AmqpError(f"{code}: {text}", code=code)

    def _reply_close_ok(self, ch: int) -> None:
        try:
            self._send_method(ch, 10, 51, b"")
        except IndeterminateError:
            pass

    # -- connection ----------------------------------------------------
    def connect(self) -> "AmqpClient":
        self.sock = socket.create_connection(
            (self.host, self.port), timeout=self.timeout
        )
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._buf = b""
        self._send(b"AMQP\x00\x00\x09\x01")
        _, cid, mid, _args = self._read_method()
        if (cid, mid) != (10, 10):  # connection.start
            raise AmqpError(f"expected connection.start, got {cid}.{mid}")
        response = b"\x00" + self.user.encode() + b"\x00" + self.password.encode()
        self._send_method(
            0, 10, 11,  # connection.start-ok
            _field_table({"product": "jepsen-tpu"})
            + _short_str("PLAIN")
            + _long_str(response)
            + _short_str("en_US"),
        )
        _, cid, mid, args = self._read_method()
        if (cid, mid) == (10, 30):  # connection.tune
            channel_max, frame_max, heartbeat = struct.unpack_from(
                "!HIH", args, 0)
            frame_max = frame_max or 131072
            self._send_method(
                0, 10, 31, struct.pack("!HIH", channel_max, frame_max, 0)
            )
        self._send_method(
            0, 10, 40, _short_str(self.vhost) + b"\x00\x00"
        )  # connection.open
        _, cid, mid, _args = self._read_method()
        if (cid, mid) != (10, 41):
            raise AmqpError(f"expected connection.open-ok, got {cid}.{mid}")
        # channel.open
        self._send_method(1, 20, 10, b"\x00")
        ch, cid, mid, _args = self._read_method()
        if (cid, mid) != (20, 11):
            raise AmqpError(f"expected channel.open-ok, got {cid}.{mid}")
        return self

    def close(self) -> None:
        if self.sock is not None:
            try:
                self.sock.close()
            finally:
                self.sock = None

    # -- queue ops -----------------------------------------------------
    def queue_declare(self, queue: str, durable: bool = True,
                      args: Optional[dict] = None) -> Tuple[str, int, int]:
        """→ (queue, message-count, consumer-count)."""
        bits = 0b00010 if durable else 0  # durable flag is bit 1
        self._send_method(
            1, 50, 10,
            b"\x00\x00" + _short_str(queue) + bytes([bits])
            + _field_table(args or {}),
        )
        _, cid, mid, rargs = self._read_method()
        if (cid, mid) != (50, 11):
            raise AmqpError(f"expected queue.declare-ok, got {cid}.{mid}")
        ln = rargs[0]
        name = rargs[1 : 1 + ln].decode()
        msgs, consumers = struct.unpack_from("!II", rargs, 1 + ln)
        return name, msgs, consumers

    def queue_purge(self, queue: str) -> int:
        self._send_method(1, 50, 30, b"\x00\x00" + _short_str(queue) + b"\x00")
        _, cid, mid, rargs = self._read_method()
        if (cid, mid) != (50, 31):
            raise AmqpError(f"expected queue.purge-ok, got {cid}.{mid}")
        (count,) = struct.unpack_from("!I", rargs, 0)
        return count

    # -- basic ops -----------------------------------------------------
    def basic_publish(self, body: bytes, routing_key: str,
                      exchange: str = "", persistent: bool = True) -> None:
        self._send_method(
            1, 60, 40,
            b"\x00\x00" + _short_str(exchange) + _short_str(routing_key)
            + b"\x00",
        )
        # content header: class 60, weight 0, body size, flags, props
        flags = 0x1000  # delivery-mode present
        props = bytes([2 if persistent else 1])
        header = struct.pack("!HHQH", 60, 0, len(body), flags) + props
        self._send(
            struct.pack("!BHI", FRAME_HEADER, 1, len(header))
            + header + bytes([FRAME_END])
        )
        self._send(
            struct.pack("!BHI", FRAME_BODY, 1, len(body))
            + body + bytes([FRAME_END])
        )

    def basic_get(self, queue: str, no_ack: bool = False
                  ) -> Optional[Tuple[int, bytes]]:
        """→ (delivery-tag, body) or None if the queue is empty."""
        self._send_method(
            1, 60, 70,
            b"\x00\x00" + _short_str(queue) + (b"\x01" if no_ack else b"\x00"),
        )
        _, cid, mid, rargs = self._read_method()
        if (cid, mid) == (60, 72):  # get-empty
            return None
        if (cid, mid) != (60, 71):  # get-ok
            raise AmqpError(f"expected basic.get-ok, got {cid}.{mid}")
        (tag,) = struct.unpack_from("!Q", rargs, 0)
        # content header + body frames follow
        t, _ch, payload = self._read_frame()
        if t != FRAME_HEADER:
            raise AmqpError("expected content header")
        (body_size,) = struct.unpack_from("!Q", payload, 4)
        body = b""
        while len(body) < body_size:
            t, _ch, chunk = self._read_frame()
            if t != FRAME_BODY:
                raise AmqpError("expected content body")
            body += chunk
        return tag, body

    def basic_ack(self, delivery_tag: int) -> None:
        self._send_method(
            1, 60, 80, struct.pack("!Q", delivery_tag) + b"\x00"
        )
