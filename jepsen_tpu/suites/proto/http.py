"""JSON-over-HTTP helper on http.client.

Backs every suite whose database speaks REST: etcd (v3 gRPC-gateway +
v2 keys API), consul KV, elasticsearch, crate (_sql), dgraph, faunadb,
chronos, hazelcast, ignite.  (The reference uses clj-http / verschlimmbesserung
/ per-DB JVM clients for these.)

One persistent connection per client; requests and replies are JSON
unless raw bytes are requested.
"""

from __future__ import annotations

import http.client
import json
import socket
from typing import Any, Dict, Optional, Tuple
from urllib.parse import urlencode

from . import IndeterminateError, ProtocolError


class HttpError(ProtocolError):
    def __init__(self, status: int, body: Any):
        super().__init__(f"HTTP {status}: {body!r}", code=status)
        self.status = status
        self.body = body


class JsonHttpClient:
    def __init__(self, host: str, port: int, timeout: float = 5.0):
        self.host = host
        self.port = port
        self.timeout = timeout
        self.conn: Optional[http.client.HTTPConnection] = None

    def connect(self) -> "JsonHttpClient":
        self.conn = http.client.HTTPConnection(
            self.host, self.port, timeout=self.timeout
        )
        self.conn.connect()
        return self

    def close(self) -> None:
        if self.conn is not None:
            try:
                self.conn.close()
            finally:
                self.conn = None

    def request(
        self,
        method: str,
        path: str,
        body: Any = None,
        params: Optional[Dict[str, Any]] = None,
        headers: Optional[Dict[str, str]] = None,
        form: bool = False,
        ok: Tuple[int, ...] = (200, 201, 204),
        raise_on_error: bool = True,
    ) -> Tuple[int, Any]:
        """One request → (status, parsed-JSON-or-text body).

        A transport failure *after* the request may have applied server
        side, so it raises IndeterminateError; a clean non-2xx status is
        a definite HttpError (unless raise_on_error=False).
        """
        if self.conn is None:
            self.connect()
        if params:
            path = f"{path}?{urlencode(params)}"
        hdrs = dict(headers or {})
        payload = None
        if body is not None:
            if form:
                payload = urlencode(body)
                hdrs.setdefault("Content-Type", "application/x-www-form-urlencoded")
            elif isinstance(body, (bytes, str)):
                payload = body
            else:
                payload = json.dumps(body)
                hdrs.setdefault("Content-Type", "application/json")
        try:
            self.conn.request(method, path, body=payload, headers=hdrs)
            resp = self.conn.getresponse()
            raw = resp.read()
            status = resp.status
        except (OSError, socket.timeout, http.client.HTTPException) as e:
            # connection state unknown; drop it so the next call redials
            self.close()
            raise IndeterminateError(f"http {method} {path} failed: {e}") from e
        try:
            parsed = json.loads(raw) if raw else None
        except ValueError:
            parsed = raw.decode(errors="replace")
        if raise_on_error and status not in ok:
            raise HttpError(status, parsed)
        return status, parsed

    # convenience verbs
    def get(self, path: str, **kw) -> Tuple[int, Any]:
        return self.request("GET", path, **kw)

    def put(self, path: str, body: Any = None, **kw) -> Tuple[int, Any]:
        return self.request("PUT", path, body=body, **kw)

    def post(self, path: str, body: Any = None, **kw) -> Tuple[int, Any]:
        return self.request("POST", path, body=body, **kw)

    def delete(self, path: str, **kw) -> Tuple[int, Any]:
        return self.request("DELETE", path, **kw)
