"""PostgreSQL frontend/backend wire protocol v3, simple-query flavor.

Backs the postgres-rds, stolon, cockroachdb, and yugabyte-YSQL suites
(the reference drives all four through JDBC: e.g.
cockroachdb/src/jepsen/cockroach/client.clj, stolon/src/jepsen/stolon/db.clj).

Implements: StartupMessage, auth (trust / cleartext / MD5 /
SCRAM-SHA-256), the simple Query cycle, and error surfacing with
SQLSTATE codes so callers can classify definite vs indeterminate
failures (serialization failures, unique violations, …).
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import os
import socket
import struct
from typing import Any, List, Optional, Tuple

from . import IndeterminateError, ProtocolError


class PgError(ProtocolError):
    """ErrorResponse from the backend; ``code`` is the SQLSTATE."""

    def __init__(self, fields: dict):
        self.fields = fields
        super().__init__(
            f"{fields.get('S', 'ERROR')} {fields.get('C', '?????')}: "
            f"{fields.get('M', '')}",
            code=fields.get("C"),
        )

    @property
    def serialization_failure(self) -> bool:
        # 40001 serialization_failure, 40P01 deadlock_detected
        return self.code in ("40001", "40P01")


class QueryResult:
    def __init__(self):
        self.columns: List[str] = []
        self.rows: List[List[Optional[str]]] = []
        self.command: Optional[str] = None

    def __repr__(self):
        return f"QueryResult(cols={self.columns}, rows={len(self.rows)}, {self.command!r})"


class PgClient:
    def __init__(
        self,
        host: str,
        port: int = 5432,
        user: str = "postgres",
        password: str = "",
        database: str = "postgres",
        timeout: float = 10.0,
        options: Optional[dict] = None,
    ):
        self.host = host
        self.port = port
        self.user = user
        self.password = password
        self.database = database
        self.timeout = timeout
        self.options = options or {}
        self.sock: Optional[socket.socket] = None
        self._buf = b""
        self.parameters: dict = {}
        self.in_txn = False

    # -- low-level framing -------------------------------------------------

    def _send(self, data: bytes) -> None:
        try:
            self.sock.sendall(data)
        except OSError as e:
            raise IndeterminateError(f"send failed: {e}") from e

    def _recv_exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            try:
                chunk = self.sock.recv(65536)
            except (OSError, socket.timeout) as e:
                raise IndeterminateError(f"recv failed: {e}") from e
            if not chunk:
                raise IndeterminateError("connection closed by server")
            self._buf += chunk
        data, self._buf = self._buf[:n], self._buf[n:]
        return data

    def _read_message(self) -> Tuple[bytes, bytes]:
        """→ (type byte, payload)."""
        head = self._recv_exact(5)
        t, ln = head[:1], struct.unpack("!I", head[1:])[0]
        return t, self._recv_exact(ln - 4)

    @staticmethod
    def _msg(t: bytes, payload: bytes) -> bytes:
        return t + struct.pack("!I", len(payload) + 4) + payload

    # -- startup & auth ----------------------------------------------------

    def connect(self) -> "PgClient":
        self.sock = socket.create_connection(
            (self.host, self.port), timeout=self.timeout
        )
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        params = {"user": self.user, "database": self.database, **self.options}
        body = struct.pack("!I", 196608)  # protocol 3.0
        for k, v in params.items():
            body += k.encode() + b"\0" + str(v).encode() + b"\0"
        body += b"\0"
        self._send(struct.pack("!I", len(body) + 4) + body)
        self._auth()
        # drain until ReadyForQuery
        while True:
            t, payload = self._read_message()
            if t == b"Z":
                break
            if t == b"E":
                raise PgError(self._parse_error(payload))
            if t == b"S":
                k, v = payload.split(b"\0")[:2]
                self.parameters[k.decode()] = v.decode()
        return self

    def _auth(self) -> None:
        while True:
            t, payload = self._read_message()
            if t == b"E":
                raise PgError(self._parse_error(payload))
            if t != b"R":
                # ParameterStatus etc. may arrive after auth ok; push back
                self._buf = (
                    t + struct.pack("!I", len(payload) + 4) + payload + self._buf
                )
                return
            (kind,) = struct.unpack("!I", payload[:4])
            if kind == 0:  # AuthenticationOk
                return
            if kind == 3:  # CleartextPassword
                self._send(self._msg(b"p", self.password.encode() + b"\0"))
            elif kind == 5:  # MD5Password
                salt = payload[4:8]
                inner = hashlib.md5(
                    self.password.encode() + self.user.encode()
                ).hexdigest()
                digest = (
                    "md5" + hashlib.md5(inner.encode() + salt).hexdigest()
                )
                self._send(self._msg(b"p", digest.encode() + b"\0"))
            elif kind == 10:  # SASL: pick SCRAM-SHA-256
                mechs = payload[4:].split(b"\0")
                if b"SCRAM-SHA-256" not in mechs:
                    raise ProtocolError(f"unsupported SASL mechanisms: {mechs}")
                self._scram()
            else:
                raise ProtocolError(f"unsupported auth request {kind}")

    def _scram(self) -> None:
        """SCRAM-SHA-256 exchange (RFC 5802/7677)."""
        nonce = base64.b64encode(os.urandom(18)).decode()
        first_bare = f"n={self.user},r={nonce}"
        msg = b"SCRAM-SHA-256\0" + struct.pack(
            "!I", len(first_bare) + 3
        ) + b"n,," + first_bare.encode()
        self._send(self._msg(b"p", msg))
        t, payload = self._read_message()
        if t == b"E":
            raise PgError(self._parse_error(payload))
        assert t == b"R" and struct.unpack("!I", payload[:4])[0] == 11
        server_first = payload[4:].decode()
        fields = dict(f.split("=", 1) for f in server_first.split(","))
        r, s, i = fields["r"], fields["s"], int(fields["i"])
        if not r.startswith(nonce):
            raise ProtocolError("SCRAM server nonce mismatch")
        salted = hashlib.pbkdf2_hmac(
            "sha256", self.password.encode(), base64.b64decode(s), i
        )
        client_key = hmac.new(salted, b"Client Key", hashlib.sha256).digest()
        stored_key = hashlib.sha256(client_key).digest()
        final_wo_proof = f"c={base64.b64encode(b'n,,').decode()},r={r}"
        auth_msg = f"{first_bare},{server_first},{final_wo_proof}".encode()
        sig = hmac.new(stored_key, auth_msg, hashlib.sha256).digest()
        proof = base64.b64encode(
            bytes(a ^ b for a, b in zip(client_key, sig))
        ).decode()
        self._send(self._msg(b"p", f"{final_wo_proof},p={proof}".encode()))
        t, payload = self._read_message()
        if t == b"E":
            raise PgError(self._parse_error(payload))
        assert t == b"R" and struct.unpack("!I", payload[:4])[0] == 12
        server_key = hmac.new(salted, b"Server Key", hashlib.sha256).digest()
        expect = hmac.new(server_key, auth_msg, hashlib.sha256).digest()
        got = dict(
            f.split("=", 1) for f in payload[4:].decode().split(",")
        ).get("v", "")
        if base64.b64decode(got) != expect:
            raise ProtocolError("SCRAM server signature mismatch")
        # next R message is AuthenticationOk; handled by _auth loop

    @staticmethod
    def _parse_error(payload: bytes) -> dict:
        fields = {}
        for part in payload.split(b"\0"):
            if part:
                fields[chr(part[0])] = part[1:].decode(errors="replace")
        return fields

    # -- queries -----------------------------------------------------------

    def query(self, sql: str) -> QueryResult:
        """Run one simple query; returns rows as text columns.

        Raises PgError for backend errors (definite — the statement did
        not commit, though an explicit COMMIT that errors is still
        definite abort) and IndeterminateError for transport failures.
        """
        if self.sock is None:
            self.connect()
        self._send(self._msg(b"Q", sql.encode() + b"\0"))
        res = QueryResult()
        err: Optional[PgError] = None
        while True:
            t, payload = self._read_message()
            if t == b"T":  # RowDescription
                (ncols,) = struct.unpack("!H", payload[:2])
                off, cols = 2, []
                for _ in range(ncols):
                    end = payload.index(b"\0", off)
                    cols.append(payload[off:end].decode())
                    off = end + 1 + 18
                res.columns = cols
            elif t == b"D":  # DataRow
                (ncols,) = struct.unpack("!H", payload[:2])
                off, row = 2, []
                for _ in range(ncols):
                    (ln,) = struct.unpack("!i", payload[off : off + 4])
                    off += 4
                    if ln < 0:
                        row.append(None)
                    else:
                        row.append(payload[off : off + ln].decode())
                        off += ln
                res.rows.append(row)
            elif t == b"C":  # CommandComplete
                res.command = payload.rstrip(b"\0").decode()
            elif t == b"E":
                err = PgError(self._parse_error(payload))
            elif t == b"Z":  # ReadyForQuery: txn status I/T/E
                self.in_txn = payload[:1] in (b"T", b"E")
                break
            # ignore N (notice), S (parameter), I (empty), K (key data)
        if err is not None:
            raise err
        return res

    def close(self) -> None:
        if self.sock is not None:
            try:
                self.sock.sendall(self._msg(b"X", b""))
            except OSError:
                pass
            try:
                self.sock.close()
            finally:
                self.sock = None


def quote_literal(s: Any) -> str:
    """Escape a value as a SQL string literal."""
    return "'" + str(s).replace("'", "''") + "'"
