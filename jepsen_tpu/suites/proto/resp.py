"""Redis serialization protocol (RESP2) client.

Used by the disque and raftis suites (the reference drives both through
carmine, a Clojure Redis client: disque/src/jepsen/disque.clj,
raftis/src/jepsen/raftis.clj).  RESP2 is symmetric and tiny: commands go
out as arrays of bulk strings; replies are simple strings (+), errors
(-), integers (:), bulk strings ($), or arrays (*).
"""

from __future__ import annotations

import socket
from typing import Any, List, Optional, Union

from . import IndeterminateError, ProtocolError

Reply = Union[None, int, str, bytes, List[Any]]


class RespClient:
    def __init__(self, host: str, port: int = 6379, timeout: float = 5.0):
        self.host = host
        self.port = port
        self.timeout = timeout
        self.sock: Optional[socket.socket] = None
        self._buf = b""

    # -- connection --------------------------------------------------------

    def connect(self) -> "RespClient":
        self.sock = socket.create_connection(
            (self.host, self.port), timeout=self.timeout
        )
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return self

    def close(self) -> None:
        if self.sock is not None:
            try:
                self.sock.close()
            finally:
                self.sock = None

    # -- wire --------------------------------------------------------------

    def _encode(self, args: tuple) -> bytes:
        out = [b"*%d\r\n" % len(args)]
        for a in args:
            b = a if isinstance(a, bytes) else str(a).encode()
            out.append(b"$%d\r\n%s\r\n" % (len(b), b))
        return b"".join(out)

    def _read_line(self) -> bytes:
        while b"\r\n" not in self._buf:
            try:
                chunk = self.sock.recv(65536)
            except (OSError, socket.timeout) as e:
                raise IndeterminateError(f"recv failed: {e}") from e
            if not chunk:
                raise IndeterminateError("connection closed mid-reply")
            self._buf += chunk
        line, self._buf = self._buf.split(b"\r\n", 1)
        return line

    def _read_exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            try:
                chunk = self.sock.recv(65536)
            except (OSError, socket.timeout) as e:
                raise IndeterminateError(f"recv failed: {e}") from e
            if not chunk:
                raise IndeterminateError("connection closed mid-reply")
            self._buf += chunk
        data, self._buf = self._buf[:n], self._buf[n:]
        return data

    def _read_reply(self) -> Reply:
        line = self._read_line()
        kind, rest = line[:1], line[1:]
        if kind == b"+":
            return rest.decode()
        if kind == b"-":
            msg = rest.decode()
            raise ProtocolError(msg, code=msg.split(" ", 1)[0])
        if kind == b":":
            return int(rest)
        if kind == b"$":
            n = int(rest)
            if n < 0:
                return None
            data = self._read_exact(n + 2)[:-2]
            return data.decode(errors="replace")
        if kind == b"*":
            n = int(rest)
            if n < 0:
                return None
            return [self._read_reply() for _ in range(n)]
        raise ProtocolError(f"unparseable RESP reply: {line!r}")

    # -- public ------------------------------------------------------------

    def call(self, *args: Any) -> Reply:
        """Issue one command and return its decoded reply."""
        if self.sock is None:
            self.connect()
        try:
            self.sock.sendall(self._encode(args))
        except OSError as e:
            raise IndeterminateError(f"send failed: {e}") from e
        return self._read_reply()
