"""ZooKeeper client protocol (jute serialization over TCP).

Backs the zookeeper suite (the reference uses the Curator/avout JVM
stack: zookeeper/src/jepsen/zookeeper.clj).  Implements the session
handshake (ConnectRequest/Response), the length-prefixed jute framing,
and the request types the workloads need: create, getData, setData
(with compare-and-set via version), delete, exists, getChildren.

Jute primitives: int/long are big-endian; ustring and buffer are
4-byte-length-prefixed (length -1 = null); vectors are count-prefixed.
"""

from __future__ import annotations

import socket
import struct
from typing import List, Optional, Tuple

from . import IndeterminateError, ProtocolError

# request types (org.apache.zookeeper.ZooDefs.OpCode)
CREATE, DELETE, EXISTS, GET_DATA, SET_DATA, GET_CHILDREN = 1, 2, 3, 4, 5, 8
PING, CLOSE = 11, -11

# error codes (KeeperException.Code)
OK = 0
NO_NODE = -101
BAD_VERSION = -103
NODE_EXISTS = -110
CONNECTION_LOSS = -4

ERR_NAMES = {
    NO_NODE: "NoNode",
    BAD_VERSION: "BadVersion",
    NODE_EXISTS: "NodeExists",
    CONNECTION_LOSS: "ConnectionLoss",
}

# world-readable-writable ACL: perms=31 (ALL), scheme "world", id "anyone"
OPEN_ACL = [(31, "world", "anyone")]


class ZkError(ProtocolError):
    def __init__(self, code: int):
        super().__init__(
            f"zookeeper error {ERR_NAMES.get(code, code)}", code=code
        )


class Stat:
    """The subset of jute Stat the workloads use."""

    __slots__ = ("czxid", "mzxid", "version")

    def __init__(self, czxid: int, mzxid: int, version: int):
        self.czxid = czxid
        self.mzxid = mzxid
        self.version = version

    def __repr__(self):
        return f"Stat(version={self.version})"


def _buffer(b: Optional[bytes]) -> bytes:
    if b is None:
        return struct.pack("!i", -1)
    return struct.pack("!i", len(b)) + b


def _ustring(s: str) -> bytes:
    return _buffer(s.encode())


def _read_buffer(data: bytes, off: int) -> Tuple[Optional[bytes], int]:
    (n,) = struct.unpack("!i", data[off : off + 4])
    off += 4
    if n < 0:
        return None, off
    return data[off : off + n], off + n


def _read_stat(data: bytes, off: int) -> Tuple[Stat, int]:
    # czxid mzxid ctime mtime version cversion aversion ephemeralOwner
    # dataLength numChildren pzxid
    czxid, mzxid, _ct, _mt, version = struct.unpack(
        "!qqqqi", data[off : off + 36]
    )
    return Stat(czxid, mzxid, version), off + 36 + 4 + 4 + 8 + 4 + 4 + 8


class ZkClient:
    def __init__(
        self,
        host: str,
        port: int = 2181,
        timeout: float = 10.0,
        session_timeout_ms: int = 10000,
    ):
        self.host = host
        self.port = port
        self.timeout = timeout
        self.session_timeout_ms = session_timeout_ms
        self.sock: Optional[socket.socket] = None
        self._buf = b""
        self._xid = 0
        self.session_id = 0

    # -- framing -----------------------------------------------------------

    def _recv_exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            try:
                chunk = self.sock.recv(65536)
            except (OSError, socket.timeout) as e:
                raise IndeterminateError(f"recv failed: {e}") from e
            if not chunk:
                raise IndeterminateError("connection closed by server")
            self._buf += chunk
        data, self._buf = self._buf[:n], self._buf[n:]
        return data

    def _send_frame(self, payload: bytes) -> None:
        try:
            self.sock.sendall(struct.pack("!i", len(payload)) + payload)
        except OSError as e:
            raise IndeterminateError(f"send failed: {e}") from e

    def _read_frame(self) -> bytes:
        (n,) = struct.unpack("!i", self._recv_exact(4))
        return self._recv_exact(n)

    # -- session -----------------------------------------------------------

    def connect(self) -> "ZkClient":
        self.sock = socket.create_connection(
            (self.host, self.port), timeout=self.timeout
        )
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        req = struct.pack("!iqiq", 0, 0, self.session_timeout_ms, 0) + _buffer(
            b"\0" * 16
        )
        self._send_frame(req)
        resp = self._read_frame()
        _proto, _timeout, self.session_id = struct.unpack("!iiq", resp[:16])
        return self

    def close(self) -> None:
        if self.sock is not None:
            try:
                self._xid += 1
                self._send_frame(struct.pack("!ii", self._xid, CLOSE))
            except Exception:
                pass
            try:
                self.sock.close()
            finally:
                self.sock = None

    # -- request cycle -----------------------------------------------------

    def _call(self, op_type: int, payload: bytes) -> bytes:
        if self.sock is None:
            self.connect()
        self._xid += 1
        self._send_frame(struct.pack("!ii", self._xid, op_type) + payload)
        frame = self._read_frame()
        xid, _zxid, err = struct.unpack("!iqi", frame[:16])
        if err != OK:
            raise ZkError(err)
        return frame[16:]

    # -- operations --------------------------------------------------------

    def create(
        self,
        path: str,
        data: bytes = b"",
        flags: int = 0,
        acl=OPEN_ACL,
    ) -> str:
        body = _ustring(path) + _buffer(data)
        body += struct.pack("!i", len(acl))
        for perms, scheme, ident in acl:
            body += struct.pack("!i", perms) + _ustring(scheme) + _ustring(ident)
        body += struct.pack("!i", flags)
        resp = self._call(CREATE, body)
        out, _ = _read_buffer(resp, 0)
        return out.decode()

    def get_data(self, path: str) -> Tuple[bytes, Stat]:
        resp = self._call(GET_DATA, _ustring(path) + b"\0")
        data, off = _read_buffer(resp, 0)
        stat, _ = _read_stat(resp, off)
        return (data or b""), stat

    def set_data(self, path: str, data: bytes, version: int = -1) -> Stat:
        """version -1 = unconditional; otherwise compare-and-set."""
        resp = self._call(
            SET_DATA, _ustring(path) + _buffer(data) + struct.pack("!i", version)
        )
        stat, _ = _read_stat(resp, 0)
        return stat

    def delete(self, path: str, version: int = -1) -> None:
        self._call(DELETE, _ustring(path) + struct.pack("!i", version))

    def exists(self, path: str) -> Optional[Stat]:
        try:
            resp = self._call(EXISTS, _ustring(path) + b"\0")
        except ZkError as e:
            if e.code == NO_NODE:
                return None
            raise
        stat, _ = _read_stat(resp, 0)
        return stat

    def get_children(self, path: str) -> List[str]:
        resp = self._call(GET_CHILDREN, _ustring(path) + b"\0")
        (n,) = struct.unpack("!i", resp[:4])
        off, out = 4, []
        for _ in range(n):
            s, off = _read_buffer(resp, off)
            out.append(s.decode())
        return sorted(out)
