"""Shared SQL clients for the relational suites.

The reference repeats the same JDBC client patterns across cockroachdb,
tidb, yugabyte(ysql), stolon, galera, percona and mysql-cluster:
open a connection, create a table, then run register/bank/set/append
workload ops inside transactions with retry/indeterminacy handling
(e.g. tidb/src/tidb/sql.clj, cockroachdb/src/jepsen/cockroach/client.clj,
galera/src/jepsen/galera/dirty_reads.clj).  This module implements those
clients once over the from-scratch wire protocols
(:mod:`.proto.pgwire`, :mod:`.proto.mysql`), parameterized by dialect.

Dialects: ``pg`` (postgres, stolon, RDS), ``cockroach`` (pgwire +
UPSERT), ``mysql`` (tidb, galera, percona, ndb).
"""

from __future__ import annotations

from typing import Any, Optional

from .. import client as client_mod
from .. import independent
from .proto import IndeterminateError
from .proto.mysql import MysqlClient, MysqlError
from .proto.pgwire import PgClient, PgError


class Conn:
    """One SQL connection + dialect-specific statement shapes."""

    def __init__(self, dialect: str, host: str, opts: dict):
        self.dialect = dialect
        self.opts = opts
        if dialect in ("pg", "cockroach"):
            self.c = PgClient(
                host,
                port=opts.get("port", 26257 if dialect == "cockroach" else 5432),
                user=opts.get("user", "root" if dialect == "cockroach"
                              else "postgres"),
                password=opts.get("password", ""),
                database=opts.get("database", "postgres"),
                timeout=opts.get("timeout", 10.0),
            )
        elif dialect == "mysql":
            self.c = MysqlClient(
                host,
                port=opts.get("port", 3306),
                user=opts.get("user", "root"),
                password=opts.get("password", ""),
                database=opts.get("database", ""),
                timeout=opts.get("timeout", 10.0),
            )
        else:
            raise ValueError(f"unknown dialect {dialect!r}")

    # -- statement shapes ----------------------------------------------
    def upsert(self, table: str, key: int, col: str, val: Any) -> str:
        if self.dialect == "cockroach":
            return f"UPSERT INTO {table} (id, {col}) VALUES ({key}, {val})"
        if self.dialect == "pg":
            return (
                f"INSERT INTO {table} (id, {col}) VALUES ({key}, {val}) "
                f"ON CONFLICT (id) DO UPDATE SET {col} = {val}"
            )
        return (
            f"INSERT INTO {table} (id, {col}) VALUES ({key}, {val}) "
            f"ON DUPLICATE KEY UPDATE {col} = {val}"
        )

    def concat_append(self, table: str, key: int, elem: Any) -> str:
        v = str(elem)
        if self.dialect == "cockroach":
            return (
                f"INSERT INTO {table} (id, vals) VALUES ({key}, '{v}') "
                f"ON CONFLICT (id) DO UPDATE "
                f"SET vals = concat({table}.vals, ',', '{v}')"
            )
        if self.dialect == "pg":
            return (
                f"INSERT INTO {table} (id, vals) VALUES ({key}, '{v}') "
                f"ON CONFLICT (id) DO UPDATE "
                f"SET vals = {table}.vals || ',' || '{v}'"
            )
        return (
            f"INSERT INTO {table} (id, vals) VALUES ({key}, '{v}') "
            f"ON DUPLICATE KEY UPDATE vals = concat(vals, ',', '{v}')"
        )

    def query(self, sql: str):
        return self.c.query(sql)

    def close(self):
        self.c.close()


class _Base(client_mod.Client):
    dialect = "pg"

    def __init__(self, opts: Optional[dict] = None):
        self.opts = dict(opts or {})
        self.dialect = self.opts.get("dialect", type(self).dialect)
        self.conn: Optional[Conn] = None

    def open(self, test, node):
        c = type(self)(self.opts)
        c.conn = Conn(
            self.dialect, self.opts.get("host", str(node)), self.opts
        )
        return c

    def _fail(self, op, e):
        return {**op, "type": "fail", "error": str(e)}

    def _info(self, op, e):
        return {**op, "type": "info", "error": str(e)}

    def close(self, test):
        if self.conn:
            self.conn.close()

    def _exec_ddl(self, *stmts: str) -> None:
        for s in stmts:
            try:
                self.conn.query(s)
            except (PgError, MysqlError):
                pass  # already exists
            except IndeterminateError:
                pass


class RegisterClient(_Base):
    """Per-key CAS registers: ``registers (id primary key, val)``.
    (reference: cockroachdb register.clj, tidb register.clj)"""

    TABLE = "registers"

    def setup(self, test):
        self._exec_ddl(
            f"CREATE TABLE IF NOT EXISTS {self.TABLE} "
            "(id INT PRIMARY KEY, val INT)"
        )

    def invoke(self, test, op):
        k, v = op["value"]
        try:
            if op["f"] == "read":
                res = self.conn.query(
                    f"SELECT val FROM {self.TABLE} WHERE id = {int(k)}"
                )
                val = int(res.rows[0][0]) if res.rows and res.rows[0][0] is not None else None
                return {**op, "type": "ok", "value": independent.kv(k, val)}
            if op["f"] == "write":
                self.conn.query(
                    self.conn.upsert(self.TABLE, int(k), "val", int(v))
                )
                return {**op, "type": "ok"}
            if op["f"] == "cas":
                old, new = v
                res = self.conn.query(
                    f"UPDATE {self.TABLE} SET val = {int(new)} "
                    f"WHERE id = {int(k)} AND val = {int(old)}"
                )
                affected = getattr(res, "affected_rows", None)
                if affected is None:
                    # pgwire: command tag "UPDATE n"
                    affected = int((res.command or "UPDATE 0").split()[-1])
                if affected == 1:
                    return {**op, "type": "ok"}
                return {**op, "type": "fail", "error": "cas-miss"}
            raise ValueError(f"unknown f {op['f']!r}")
        except IndeterminateError as e:
            return self._info(op, e)
        except (PgError, MysqlError) as e:
            return self._fail(op, e)


class BankClient(_Base):
    """Bank transfers in explicit transactions.
    (reference: tests/bank.clj clients in cockroach/tidb suites)"""

    TABLE = "accounts"

    def setup(self, test):
        self._exec_ddl(
            f"CREATE TABLE IF NOT EXISTS {self.TABLE} "
            "(id INT PRIMARY KEY, balance INT)"
        )
        n = len(test.get("accounts", range(8)))
        total = test.get("total-amount", 100)
        per = total // n
        first = total - per * (n - 1)
        for i, acct in enumerate(test.get("accounts", range(8))):
            try:
                self.conn.query(
                    self.conn.upsert(
                        self.TABLE, int(acct), "balance",
                        first if i == 0 else per,
                    )
                )
            except (PgError, MysqlError, IndeterminateError):
                pass

    def invoke(self, test, op):
        try:
            if op["f"] == "read":
                res = self.conn.query(
                    f"SELECT id, balance FROM {self.TABLE}"
                )
                value = {int(r[0]): int(r[1]) for r in res.rows}
                return {**op, "type": "ok", "value": value}
            if op["f"] == "transfer":
                frm, to = int(op["value"]["from"]), int(op["value"]["to"])
                amt = int(op["value"]["amount"])
                self.conn.query("BEGIN")
                try:
                    res = self.conn.query(
                        f"SELECT balance FROM {self.TABLE} WHERE id = {frm}"
                    )
                    bal = int(res.rows[0][0]) if res.rows else None
                    if bal is None or (
                        bal < amt and not test.get("negative-balances?")
                    ):
                        self.conn.query("ROLLBACK")
                        return {**op, "type": "fail",
                                "error": "insufficient funds"}
                    self.conn.query(
                        f"UPDATE {self.TABLE} SET balance = balance - {amt} "
                        f"WHERE id = {frm}"
                    )
                    self.conn.query(
                        f"UPDATE {self.TABLE} SET balance = balance + {amt} "
                        f"WHERE id = {to}"
                    )
                    self.conn.query("COMMIT")
                    return {**op, "type": "ok"}
                except (PgError, MysqlError) as e:
                    try:
                        self.conn.query("ROLLBACK")
                    except Exception:
                        pass
                    return self._fail(op, e)
            raise ValueError(f"unknown f {op['f']!r}")
        except IndeterminateError as e:
            return self._info(op, e)
        except (PgError, MysqlError) as e:
            return self._fail(op, e)


class SetClient(_Base):
    """Unique-element set: ``sets (val int)``.
    (reference: tidb sets.clj, cockroach sets.clj)"""

    TABLE = "sets"

    def setup(self, test):
        self._exec_ddl(
            f"CREATE TABLE IF NOT EXISTS {self.TABLE} (val INT)"
        )

    def invoke(self, test, op):
        try:
            if op["f"] == "add":
                self.conn.query(
                    f"INSERT INTO {self.TABLE} (val) VALUES "
                    f"({int(op['value'])})"
                )
                return {**op, "type": "ok"}
            if op["f"] == "read":
                res = self.conn.query(f"SELECT val FROM {self.TABLE}")
                return {**op, "type": "ok",
                        "value": sorted(int(r[0]) for r in res.rows)}
            raise ValueError(f"unknown f {op['f']!r}")
        except IndeterminateError as e:
            return self._info(op, e)
        except (PgError, MysqlError) as e:
            return self._fail(op, e)


class CounterClient(_Base):
    """Plain-int counter: SQL has no counter column type, so a single
    row's int is bumped with column arithmetic and reads return the
    current value.  (reference: yugabyte ysql/counter.clj:12-28 —
    ``UPDATE counter SET count = count + ? WHERE id = 0``)"""

    TABLE = "counters"

    def setup(self, test):
        self._exec_ddl(
            f"CREATE TABLE IF NOT EXISTS {self.TABLE} "
            "(id INT PRIMARY KEY, count INT)"
        )
        try:
            self.conn.query(
                f"INSERT INTO {self.TABLE} (id, count) VALUES (0, 0)"
            )
        except (PgError, MysqlError):
            pass  # row already seeded by another worker

    def invoke(self, test, op):
        try:
            if op["f"] == "add":
                self.conn.query(
                    f"UPDATE {self.TABLE} SET count = count + "
                    f"{int(op['value'])} WHERE id = 0"
                )
                return {**op, "type": "ok"}
            if op["f"] == "read":
                res = self.conn.query(
                    f"SELECT count FROM {self.TABLE} WHERE id = 0"
                )
                v = int(res.rows[0][0]) if res.rows else 0
                return {**op, "type": "ok", "value": v}
            raise ValueError(f"unknown f {op['f']!r}")
        except IndeterminateError as e:
            return self._info(op, e)
        except (PgError, MysqlError) as e:
            return self._fail(op, e)


class AppendClient(_Base):
    """Elle list-append txns over ``lists (id, vals text)``: each micro-op
    batch runs in one transaction; reads parse the comma-joined list.
    (reference: tests/cycle/append.clj clients in tidb txn.clj,
    yugabyte ysql append.clj)"""

    TABLE = "lists"

    def setup(self, test):
        self._exec_ddl(
            f"CREATE TABLE IF NOT EXISTS {self.TABLE} "
            "(id INT PRIMARY KEY, vals TEXT)"
        )

    def invoke(self, test, op):
        txn = op["value"]
        out = []
        try:
            self.conn.query("BEGIN")
            try:
                for f, k, v in txn:
                    if f == "r":
                        res = self.conn.query(
                            f"SELECT vals FROM {self.TABLE} "
                            f"WHERE id = {int(k)}"
                        )
                        raw = res.rows[0][0] if res.rows else None
                        vals = ([int(x) for x in raw.split(",") if x != ""]
                                if raw else [])
                        out.append(["r", k, vals])
                    elif f == "append":
                        self.conn.query(
                            self.conn.concat_append(self.TABLE, int(k), v)
                        )
                        out.append(["append", k, v])
                    else:
                        raise ValueError(f"unknown micro-op {f!r}")
                self.conn.query("COMMIT")
                return {**op, "type": "ok", "value": out}
            except (PgError, MysqlError) as e:
                try:
                    self.conn.query("ROLLBACK")
                except Exception:
                    pass
                return self._fail(op, e)
        except IndeterminateError as e:
            return self._info(op, e)


class TxnClient(_Base):
    """Read/write micro-op transactions over ``txns (id, val int)`` —
    serves the long-fork and rw-register (Elle) workloads, whose ops
    carry ``[["r", k, None], ["w", k, v], …]`` micro-op lists under f
    "txn"/"read"/"write".  (reference: tidb txn.clj, dgraph wr.clj,
    tests/long_fork.clj:38-48)"""

    TABLE = "txns"

    def setup(self, test):
        self._exec_ddl(
            f"CREATE TABLE IF NOT EXISTS {self.TABLE} "
            "(id INT PRIMARY KEY, val INT)"
        )

    def invoke(self, test, op):
        txn = op["value"]
        out = []
        try:
            self.conn.query("BEGIN")
            try:
                for f, k, v in txn:
                    if f == "r":
                        res = self.conn.query(
                            f"SELECT val FROM {self.TABLE} "
                            f"WHERE id = {int(k)}"
                        )
                        val = (int(res.rows[0][0])
                               if res.rows and res.rows[0][0] is not None
                               else None)
                        out.append(["r", k, val])
                    elif f == "w":
                        self.conn.query(
                            self.conn.upsert(self.TABLE, int(k), "val",
                                             int(v))
                        )
                        out.append(["w", k, v])
                    else:
                        raise ValueError(f"unknown micro-op {f!r}")
                self.conn.query("COMMIT")
                return {**op, "type": "ok", "value": out}
            except (PgError, MysqlError) as e:
                try:
                    self.conn.query("ROLLBACK")
                except Exception:
                    pass
                return self._fail(op, e)
        except IndeterminateError as e:
            return self._info(op, e)


CLIENTS = {
    "register": RegisterClient,
    "bank": BankClient,
    "set": SetClient,
    "counter": CounterClient,
    "list-append": AppendClient,
    "long-fork": TxnClient,
    "rw-register": TxnClient,
}


def client_for(workload: str, opts: dict) -> client_mod.Client:
    try:
        cls = CLIENTS[workload]
    except KeyError:
        raise KeyError(
            f"no SQL client for workload {workload!r}; have {sorted(CLIENTS)}"
        )
    return cls(opts)
