"""etcd suite — the canonical walkthrough database.

The reference builds this test across doc/tutorial/01-…08-*.md: install
an etcd release tarball on every node (02-db.md), cluster them with
``--initial-cluster``, drive reads/writes/CAS over the v2 keys HTTP API
via the verschlimmbesserung client (03-client.md), check with a
CAS-register model (04-checker.md), partition with a nemesis
(05-nemesis.md), and finish with a set workload (08-set.md).

Here the client speaks the v2 keys API directly over
:mod:`jepsen_tpu.suites.proto.http` — quorum reads, ``prevValue`` CAS —
and the register workload feeds the TPU-batched linearizability
checker.
"""

from __future__ import annotations

import json
from typing import Any, Optional

from .. import client as client_mod
from .. import independent
from ..control import util as cu
from ..control import execute, sudo
from . import common
from .proto import IndeterminateError
from .proto.http import HttpError, JsonHttpClient

VERSION = "v3.1.5"  # (reference: doc/tutorial/02-db.md — etcd-test v3.1.5)
DIR = "/opt/etcd"  # (reference: doc/tutorial/02-db.md `(def dir "/opt/etcd")`)
CLIENT_PORT = 2379
PEER_PORT = 2380


def node_url(node: Any, port: int) -> str:
    return f"http://{node}:{port}"


def initial_cluster(test: dict) -> str:
    """node1=http://node1:2380,… (reference: doc/tutorial/02-db.md
    initial-cluster)."""
    return ",".join(f"{n}={node_url(n, PEER_PORT)}" for n in test["nodes"])


class EtcdDB(common.DaemonDB):
    dir = DIR
    binary = "etcd"
    logfile = f"{DIR}/etcd.log"
    pidfile = f"{DIR}/etcd.pid"

    def __init__(self, opts: Optional[dict] = None):
        super().__init__(opts)
        self.version = (opts or {}).get("version", VERSION)

    def install(self, test, node):
        url = (
            "https://storage.googleapis.com/etcd/"
            f"{self.version}/etcd-{self.version}-linux-amd64.tar.gz"
        )
        with sudo():
            cu.install_archive(url, self.dir)

    def start_args(self, test, node):
        return [
            "--log-output", "stderr",
            "--name", str(node),
            "--listen-peer-urls", node_url(node, PEER_PORT),
            "--initial-advertise-peer-urls", node_url(node, PEER_PORT),
            "--listen-client-urls", f"http://0.0.0.0:{CLIENT_PORT}",
            "--advertise-client-urls", node_url(node, CLIENT_PORT),
            "--initial-cluster-state", "new",
            "--initial-cluster", initial_cluster(test),
        ]

    def start_env(self, test, node):
        return {"ETCD_API": "2"}

    def await_ready(self, test, node):
        cu.await_tcp_port(CLIENT_PORT)

    def wipe(self, test, node):
        with sudo():
            execute("rm", "-rf", self.dir)


class EtcdClient(client_mod.Client):
    """CAS register over the etcd v2 keys API.

    read → quorum GET /v2/keys/<k>; write → PUT value=v; cas → PUT
    value=v' prevValue=v (reference: doc/tutorial/03-client.md; the
    verschlimmbesserung calls etcd/get :quorum?, etcd/reset!,
    etcd/cas!).  Values travel as JSON ints.  Ops use the
    independent-key convention value=[k, v].
    """

    def __init__(self, opts: Optional[dict] = None):
        self.opts = opts or {}
        self.conn: Optional[JsonHttpClient] = None

    def open(self, test, node):
        c = type(self)(self.opts)
        host = self.opts.get("host", str(node))
        port = self.opts.get("port", CLIENT_PORT)
        c.conn = JsonHttpClient(host, port, timeout=5.0)
        return c

    def _key(self, k) -> str:
        return f"/v2/keys/jepsen/{k}"

    def invoke(self, test, op):
        k, v = op["value"] if isinstance(op["value"], (list, tuple)) else (
            "r", op["value"])
        try:
            if op["f"] == "read":
                try:
                    _, body = self.conn.get(self._key(k), params={"quorum": "true"})
                    val = json.loads(body["node"]["value"])
                except HttpError as e:
                    if e.status == 404:
                        val = None
                    else:
                        raise
                return {**op, "type": "ok", "value": independent.kv(k, val)}
            elif op["f"] == "write":
                self.conn.put(self._key(k), {"value": json.dumps(v)}, form=True)
                return {**op, "type": "ok"}
            elif op["f"] == "cas":
                old, new = v
                try:
                    self.conn.put(
                        self._key(k),
                        {"value": json.dumps(new), "prevValue": json.dumps(old)},
                        form=True,
                    )
                    return {**op, "type": "ok"}
                except HttpError as e:
                    # 412 precondition failed / 404 missing key = clean fail
                    if e.status in (404, 412):
                        return {**op, "type": "fail", "error": e.body}
                    raise
            elif op["f"] == "add":
                # set workload: append to a single set key via CAS loop
                return self._add(test, op)
            raise ValueError(f"unknown f {op['f']!r}")
        except IndeterminateError as e:
            return {**op, "type": "info", "error": str(e)}
        except HttpError as e:
            return {**op, "type": "fail", "error": f"{e.status}: {e.body}"}

    def _add(self, test, op):
        """Set workload add: read-modify-CAS a JSON list (reference:
        doc/tutorial/08-set.md uses a single set key)."""
        for _ in range(5):
            try:
                _, body = self.conn.get("/v2/keys/jepsen/set",
                                        params={"quorum": "true"})
                cur = json.loads(body["node"]["value"])
                idx = body["node"]["modifiedIndex"]
                new = cur + [op["value"]]
                self.conn.put(
                    "/v2/keys/jepsen/set",
                    {"value": json.dumps(new), "prevIndex": str(idx)},
                    form=True,
                )
                return {**op, "type": "ok"}
            except HttpError as e:
                if e.status == 404:
                    try:
                        self.conn.put(
                            "/v2/keys/jepsen/set",
                            {"value": json.dumps([op["value"]]),
                             "prevExist": "false"},
                            form=True,
                        )
                        return {**op, "type": "ok"}
                    except HttpError as e2:
                        if e2.status == 412:
                            continue
                        return {**op, "type": "fail", "error": str(e2.body)}
                elif e.status == 412:
                    continue
                else:
                    return {**op, "type": "fail", "error": f"{e.status}"}
        return {**op, "type": "fail", "error": "cas-retries-exhausted"}

    def close(self, test):
        if self.conn:
            self.conn.close()


class _SetReadClient(EtcdClient):
    """Reads the whole set key for the final read."""

    def invoke(self, test, op):
        if op["f"] == "read":
            try:
                _, body = self.conn.get("/v2/keys/jepsen/set",
                                        params={"quorum": "true"})
                return {**op, "type": "ok",
                        "value": json.loads(body["node"]["value"])}
            except IndeterminateError as e:
                return {**op, "type": "info", "error": str(e)}
            except HttpError as e:
                if e.status == 404:
                    return {**op, "type": "ok", "value": []}
                return {**op, "type": "fail", "error": f"{e.status}"}
        return super().invoke(test, op)


def db(opts: Optional[dict] = None):
    return EtcdDB(opts)


def client(opts: Optional[dict] = None):
    return EtcdClient(opts)


def workloads(opts: Optional[dict] = None) -> dict:
    opts = dict(opts or {})
    return {
        "register": common.register_workload(opts),
        "set": common.set_workload(opts),
    }


def test(opts: Optional[dict] = None) -> dict:
    """Full etcd test map (reference: doc/tutorial/06-refining.md
    etcd-test)."""
    opts = dict(opts or {})
    wname = opts.get("workload", "register")
    w = workloads(opts)[wname]
    c = _SetReadClient(opts) if wname == "set" else EtcdClient(opts)
    return common.build_test(
        f"etcd-{wname}", opts, db=EtcdDB(opts), client=c, workload=w
    )
