"""Cockroach-style ``sets`` workload: sequential unique adds into one
table, a single final read, and a checker accounting for every element
class — ok / lost / unexpected / duplicates / revived (failed adds that
appear anyway) / recovered (indeterminate adds that appear).

Reference: cockroachdb/src/jepsen/cockroach/sets.clj — check-sets
(:20-94: the six element classes and their interval-set/fraction
reporting), SetsClient (:96-131: ``set (val int)`` table, insert per
add, full-table final read), test (:133-150: sequential staggered adds
+ one final read).  The generic set workload (suites/common.py) keeps
the richer per-element set-full timeline; this one mirrors cockroach's
exact report shape.
"""

from __future__ import annotations

from collections import Counter
from typing import Optional

from .. import generator as gen
from ..checker import Checker
from ..history import INVOKE, OK, FAIL, INFO
from ..util import fraction, integer_interval_set_str


class SetsChecker(Checker):
    """(reference: cockroach/sets.clj:20-94 check-sets)"""

    def check(self, test, history, opts=None):
        attempts, adds, fails, unsure = set(), set(), set(), set()
        final = None
        for op in history:
            if op.f == "add":
                if op.type == INVOKE:
                    attempts.add(op.value)
                elif op.type == OK:
                    adds.add(op.value)
                elif op.type == FAIL:
                    fails.add(op.value)
                elif op.type == INFO:
                    unsure.add(op.value)
            elif op.f == "read" and op.type == OK:
                final = op.value
        if final is None:
            return {"valid?": "unknown", "error": "Set was never read"}

        final_set = set(final)
        dups = sorted(v for v, n in Counter(final).items() if n > 1)
        ok = final_set & adds
        unexpected = final_set - attempts
        revived = final_set & fails
        lost = adds - final_set
        recovered = final_set & unsure
        return {
            "valid?": not (lost or unexpected or dups or revived),
            "duplicates": dups,
            "ok": integer_interval_set_str(ok),
            "lost": integer_interval_set_str(lost),
            "unexpected": integer_interval_set_str(unexpected),
            "recovered": integer_interval_set_str(recovered),
            "revived": integer_interval_set_str(revived),
            "ok-frac": fraction(len(ok), len(attempts)),
            "revived-frac": fraction(len(revived), len(fails)),
            "unexpected-frac": fraction(len(unexpected), len(attempts)),
            "lost-frac": fraction(len(lost), len(attempts)),
            "recovered-frac": fraction(len(recovered), len(attempts)),
        }


def workload(opts: Optional[dict] = None) -> dict:
    """Sequential adds staggered during the run; one final read.
    (reference: cockroach/sets.clj:133-150 test)"""
    counter = {"n": 0}

    def add(test, ctx):
        v = counter["n"]
        counter["n"] += 1
        return {"type": "invoke", "f": "add", "value": v}

    final = gen.clients(
        gen.each_thread(
            gen.once({"type": "invoke", "f": "read", "value": None})
        )
    )
    return {
        "generator": add,
        "final-generator": final,
        "checker": SetsChecker(),
    }
