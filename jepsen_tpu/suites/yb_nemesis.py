"""YugabyteDB fault menu: master/tserver-targeted process faults,
partitions, and clock skew, with flip-flop fault/recovery scheduling.

Reference: yugabyte/src/yugabyte/nemesis.clj — process-nemesis
(:12-46: kill/stop/pause/resume target random node subsets, with master
ops restricted to the master nodes), clock-nemesis-wrapper (:48-67:
also stops the ntp service), full-nemesis composition (:69-84),
partition generators (:86-116), mixed-generator's
flip-flop-per-fault-family shape (:155-191), final-generator recovery
(:193-209), long-recovery alternation (:211-223), and the
:kill/:stop/:pause/:partition shorthand expansion (:225-238).
"""

from __future__ import annotations

from .. import control
from .. import generator as gen
from ..control import util as cu
from ..nemesis import (
    Nemesis,
    bisect,
    complete_grudge,
    compose,
    majorities_ring,
    partitioner,
    split_one,
)
from ..nemesis import time as nt
from ..util import random_nonempty_subset

#: every f the process nemesis owns
PROCESS_FS = frozenset({
    "start-master", "start-tserver",
    "stop-master", "stop-tserver",
    "kill-master", "kill-tserver",
    "pause-master", "pause-tserver",
    "resume-master", "resume-tserver",
})


class YbProcessNemesis(Nemesis):
    """start/stop/kill/pause/resume masters and tservers independently.
    (reference: nemesis.clj:12-46 process-nemesis)"""

    def __init__(self, db):
        self.db = db

    def setup(self, test):
        return self

    def invoke(self, test, op):
        f = op["f"]
        nodes = list(test["nodes"])
        masters = self.db.master_nodes(test)
        if f in ("resume-tserver", "start-tserver"):
            targets = nodes
        elif f in ("resume-master", "start-master"):
            targets = masters
        elif f.endswith("-tserver"):
            targets = random_nonempty_subset(nodes, gen.rng)
        else:
            targets = random_nonempty_subset(masters, gen.rng)

        db = self.db

        def act(test, node):
            return {
                "start-master": db.start_master,
                "start-tserver": db.start_tserver,
                "stop-master": db.stop_master,
                "stop-tserver": db.stop_tserver,
                "kill-master": db.kill_master,
                "kill-tserver": db.kill_tserver,
                "pause-master": lambda t, n: cu.signal(
                    "yb-master", "STOP"),
                "pause-tserver": lambda t, n: cu.signal(
                    "yb-tserver", "STOP"),
                "resume-master": lambda t, n: cu.signal(
                    "yb-master", "CONT"),
                "resume-tserver": lambda t, n: cu.signal(
                    "yb-tserver", "CONT"),
            }[f](test, node)

        res = control.on_nodes(test, targets, act)
        return {**op, "type": "info",
                "value": {str(k): str(v) for k, v in res.items()}}

    def teardown(self, test):
        pass

    def fs(self):
        return PROCESS_FS


def full_nemesis(db) -> Nemesis:
    """(reference: nemesis.clj:69-84 full-nemesis — its
    clock-nemesis-wrapper existed only to stop the ntp service, which
    this framework's ClockNemesis.setup already does for
    ntp/ntpd/systemd-timesyncd, nemesis/time.py)"""
    return compose([
        (PROCESS_FS, YbProcessNemesis(db)),
        ({"start-partition": "start", "stop-partition": "stop"},
         partitioner()),
        ({"reset-clock": "reset", "strobe-clock": "strobe",
          "check-clock-offsets": "check-offsets", "bump-clock": "bump"},
         nt.clock_nemesis()),
    ])


def _op(f, value=None, **extra):
    return {"type": "info", "f": f, "value": value, **extra}


def partition_one_gen(test, ctx):
    """(reference: nemesis.clj:96-101)"""
    return _op("start-partition",
               complete_grudge(split_one(list(test["nodes"]))),
               partition_type="single-node")


def partition_half_gen(test, ctx):
    """(reference: nemesis.clj:103-108)"""
    nodes = list(test["nodes"])
    gen.rng.shuffle(nodes)
    return _op("start-partition", complete_grudge(bisect(nodes)),
               partition_type="half")


def partition_ring_gen(test, ctx):
    """(reference: nemesis.clj:110-115)"""
    return _op("start-partition", majorities_ring(list(test["nodes"])),
               partition_type="ring")


def clock_gen():
    """The standard clock mix with yugabyte's f names.
    (reference: nemesis.clj:127-134)"""
    return gen.f_map(
        {"check-offsets": "check-clock-offsets", "reset": "reset-clock",
         "strobe": "strobe-clock", "bump": "bump-clock"},
        nt.clock_gen(),
    )


def expand_options(n: dict) -> dict:
    """:kill → kill both components, etc.
    (reference: nemesis.clj:225-238 expand-options)"""
    n = dict(n)
    if n.get("kill"):
        n["kill-tserver"] = n["kill-master"] = True
    if n.get("stop"):
        n["stop-tserver"] = n["stop-master"] = True
    if n.get("pause"):
        n["pause-tserver"] = n["pause-master"] = True
    if n.get("partition"):
        n["partition-one"] = n["partition-half"] = n["partition-ring"] = True
    return n


def _opt_mix(n: dict, possible: dict):
    gens = [g for opt, g in possible.items() if n.get(opt)]
    return gen.mix(gens) if gens else None


def mixed_generator(n: dict):
    """Flip-flops between each enabled fault family and its recovery,
    staggered by the interval.  (reference: nemesis.clj:155-191)"""
    def o(possible, recovery):
        m = _opt_mix(n, possible)
        return gen.flip_flop(m, gen.repeat(recovery)) if m else None

    modes = [
        o({"kill-tserver": lambda t, c: _op("kill-tserver"),
           "stop-tserver": lambda t, c: _op("stop-tserver")},
          _op("start-tserver")),
        o({"kill-master": lambda t, c: _op("kill-master"),
           "stop-master": lambda t, c: _op("stop-master")},
          _op("start-master")),
        o({"pause-tserver": lambda t, c: _op("pause-tserver")},
          _op("resume-tserver")),
        o({"pause-master": lambda t, c: _op("pause-master")},
          _op("resume-master")),
        o({"partition-one": partition_one_gen,
           "partition-half": partition_half_gen,
           "partition-ring": partition_ring_gen},
          _op("stop-partition")),
        _opt_mix(n, {"clock-skew": clock_gen()}),
    ]
    modes = [m for m in modes if m is not None]
    if not modes:
        return None
    return gen.stagger(n.get("interval", 10), gen.mix(modes))


def final_generator(n: dict):
    """Recover everything the enabled faults may have broken.
    (reference: nemesis.clj:193-209)"""
    fs = []
    if n.get("clock-skew"):
        fs.append("reset-clock")
    if n.get("pause-master"):
        fs.append("resume-master")
    if n.get("pause-tserver"):
        fs.append("resume-tserver")
    if n.get("kill-tserver") or n.get("stop-tserver"):
        fs.append("start-tserver")
    if n.get("kill-master") or n.get("stop-master"):
        fs.append("start-master")
    if any(n.get(k) for k in
           ("partition-one", "partition-half", "partition-ring")):
        fs.append("stop-partition")
    return [_op(f) for f in fs] or None


def full_generator(n: dict):
    """With :long-recovery, alternate 120 s fault windows with recovery
    + 60 s calm; else just the mixed faults.
    (reference: nemesis.clj:211-223 full-generator)"""
    mixed = mixed_generator(n)
    if mixed is None:
        return None
    if n.get("long-recovery"):
        final = final_generator(n) or []
        window = gen.phases(
            gen.time_limit(120, mixed),
            list(final),
            gen.sleep(60),
        )
        return gen.cycle(window)
    return mixed


def package(opts: dict, db) -> dict:
    """The {nemesis, generator, final_generator} bundle build_test
    consumes, from a fault-name list (e.g. ["kill-master",
    "partition-ring", "clock-skew"]) or shorthands ("kill", "stop",
    "pause", "partition").  (reference: nemesis.clj:240-247 nemesis)"""
    n = expand_options(
        {f: True for f in opts.get("faults", ())}
        | {"interval": opts.get("interval", 10),
           "long-recovery": bool(opts.get("long-recovery"))}
    )
    return {
        "nemesis": full_nemesis(db),
        "generator": full_generator(n),
        "final_generator": final_generator(n),
        "perf": {
            ("kill", frozenset({"kill-master", "kill-tserver",
                                "stop-master", "stop-tserver"}),
             frozenset({"start-master", "start-tserver"}), "#E9A4A0"),
            ("pause", frozenset({"pause-master", "pause-tserver"}),
             frozenset({"resume-master", "resume-tserver"}), "#A0B1E9"),
            ("partition", frozenset({"start-partition"}),
             frozenset({"stop-partition"}), "#A0E9DB"),
        },
    }


#: fault names this module understands; test() routes to this package
#: when any appears in opts["faults"] (recovery ops are not faults a
#: user requests, so they're excluded)
KNOWN_FAULTS = (
    PROCESS_FS
    | {
        "kill", "stop", "pause", "partition",
        "partition-one", "partition-half", "partition-ring", "clock-skew",
    }
) - {"start-master", "start-tserver", "resume-master", "resume-tserver"}
