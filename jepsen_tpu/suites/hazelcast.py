"""Hazelcast suite.

Reference: hazelcast/src/jepsen/hazelcast.clj — the reference builds a
small server uberjar (hazelcast.clj:34-48), uploads it to every node,
starts it with the member list, and drives Java-client workloads:
distributed lock, unique IDs, atomic-ref CAS, crdt-ish maps, and
queues.

The full reference workload matrix (hazelcast.clj:652-768) runs over
a from-scratch open-binary-client-protocol implementation
(proto/hazelcast.py): map/crdt-map CAS sets, the six lock flavors
checked against owner-aware/reentrant/fenced mutex models
(models/locks.py), the 2-permit cp-semaphore, cas over
AtomicLong/AtomicReference, four unique-id generators, and queues.
The server is the stock Hazelcast distribution zip, member list
templated into hazelcast.xml.
"""

from __future__ import annotations

from typing import Optional

from .. import checker as checker_mod
from .. import client as client_mod
from .. import generator as gen
from .. import independent
from ..control import util as cu
from ..control import execute, sudo
from ..os_setup import debian
from . import common
from .proto import IndeterminateError
from .proto import hazelcast as hzp

VERSION = "3.12.12"
DIR = "/opt/hazelcast"
PORT = 5701

_XML = """<?xml version="1.0" encoding="UTF-8"?>
<hazelcast xmlns="http://www.hazelcast.com/schema/config">
  <group><name>jepsen</name></group>
  <network>
    <port auto-increment="false">{port}</port>
    <join>
      <multicast enabled="false"/>
      <tcp-ip enabled="true">
{members}
      </tcp-ip>
    </join>
  </network>
</hazelcast>
"""


class HazelcastDB(common.DaemonDB):
    dir = DIR
    binary = "bin/start.sh"
    logfile = f"{DIR}/hazelcast.log"
    pidfile = f"{DIR}/hazelcast.pid"
    proc_name = "java"  # the server runs under the JVM

    def __init__(self, opts: Optional[dict] = None):
        super().__init__(opts)
        self.version = (opts or {}).get("version", VERSION)

    def install(self, test, node):
        debian.install(["openjdk-8-jre-headless"])
        url = (
            "https://github.com/hazelcast/hazelcast/releases/download/"
            f"v{self.version}/hazelcast-{self.version}.zip"
        )
        with sudo():
            cu.install_archive(url, DIR)

    def configure(self, test, node):
        members = "\n".join(
            f"        <member>{n}:{PORT}</member>" for n in test["nodes"]
        )
        with sudo():
            cu.write_file(
                _XML.format(port=PORT, members=members),
                f"{DIR}/bin/hazelcast.xml",
            )

    def await_ready(self, test, node):
        cu.await_tcp_port(PORT, timeout_s=120)

    def wipe(self, test, node):
        with sudo():
            execute("rm", "-f", self.logfile)


def unique_ids_workload(opts: Optional[dict] = None) -> dict:
    def generate(test, ctx):
        return {"type": "invoke", "f": "generate", "value": None}

    return {
        "generator": generate,
        "checker": checker_mod.unique_ids(),
    }


# ---------------------------------------------------------------------
# binary-protocol clients (proto/hazelcast.py — the reference drives
# these structures through the official JVM client, hazelcast.clj)
# ---------------------------------------------------------------------


class _HzBinClient(client_mod.Client):
    """Base for clients over the from-scratch binary protocol."""

    def __init__(self, opts: Optional[dict] = None):
        self.opts = opts or {}
        self.conn: Optional[hzp.HzClient] = None

    def open(self, test, node):
        c = type(self)(self.opts)
        c.conn = hzp.HzClient(
            self.opts.get("host", str(node)),
            self.opts.get("client-port", PORT),
        ).connect()
        return c

    def close(self, test):
        if self.conn:
            self.conn.close()

    def _me(self) -> dict:
        """Client identity for the owner-aware lock models.  The fence
        here is INVALID (0) — classic lock/semaphore ops carry no
        token; the fenced workloads' acquires override it with the live
        CP fencing token (HzLockClient with ``fenced?``)."""
        return {"client": self.conn.uuid, "fence": 0}

    def _guard(self, op, body, info_value=None):
        """``info_value``: payload to stamp on indeterminate results —
        the lock/semaphore clients pass their identity so an info op
        (which stays open forever in the checker) still tells the
        owner-aware models WHO may have acted; without it the op could
        never linearize and would poison every later legitimate step."""
        try:
            return body()
        except IndeterminateError as e:
            out = {**op, "type": "info", "error": str(e)}
            if info_value is not None:
                out["value"] = info_value
            return out
        except hzp.HzError as e:
            return {**op, "type": "fail", "error": str(e)}


class HzMapClient(_HzBinClient):
    """Single-key set-in-a-map with CAS updates (reference:
    hazelcast.clj:453-491 map-client: get → conj → replace, or
    putIfAbsent when fresh; one attempt per invoke, :cas-failed on a
    lost race).  Values serialize as a comma-joined sorted string."""

    KEY = hzp.data_string("hi")

    @property
    def map_name(self) -> str:
        return (
            "jepsen.crdt-map" if self.opts.get("crdt?") else "jepsen.map"
        )

    @staticmethod
    def _enc(vals) -> bytes:
        return hzp.data_string(",".join(str(v) for v in sorted(vals)))

    @staticmethod
    def _dec(data) -> list:
        s = hzp.parse_data(data)
        return [int(x) for x in s.split(",")] if s else []

    def invoke(self, test, op):
        def body():
            name = self.map_name
            if op["f"] == "add":
                cur = self.conn.map_get(name, self.KEY)
                if cur is None:
                    prev = self.conn.map_put_if_absent(
                        name, self.KEY, self._enc({op["value"]})
                    )
                    if prev is None:
                        return {**op, "type": "ok"}
                    return {**op, "type": "fail", "error": "cas-failed"}
                new = sorted(set(self._dec(cur)) | {int(op["value"])})
                if self.conn.map_replace_if_same(
                    name, self.KEY, cur, self._enc(new)
                ):
                    return {**op, "type": "ok"}
                return {**op, "type": "fail", "error": "cas-failed"}
            if op["f"] == "read":
                cur = self.conn.map_get(name, self.KEY)
                vals = self._dec(cur) if cur is not None else []
                return {**op, "type": "ok", "value": sorted(vals)}
            raise ValueError(f"unknown f {op['f']!r}")

        return self._guard(op, body)


class HzLockClient(_HzBinClient):
    """acquire/release over a distributed lock; completions carry the
    session identity so the owner-aware/reentrant/fenced models know
    WHO acted (reference: hazelcast.clj:117-163 lock-client and
    :305-371 fenced-lock-client).  With ``fenced?`` the CP fenced-lock
    calls are used instead and completions carry the REAL fencing
    token, so the fence-monotonicity models check live tokens, not the
    INVALID placeholder."""

    @property
    def lock_name(self) -> str:
        return self.opts.get("lock-name", "jepsen.lock")

    @property
    def fenced(self) -> bool:
        return bool(self.opts.get("fenced?"))

    def invoke(self, test, op):
        def body():
            if op["f"] == "acquire":
                if self.fenced:
                    fence = self.conn.try_lock_fenced(
                        self.lock_name, timeout_ms=5000
                    )
                    if fence != hzp.INVALID_FENCE:
                        return {
                            **op, "type": "ok",
                            "value": {**self._me(), "fence": fence},
                        }
                    return {**op, "type": "fail", "error": "timeout"}
                if self.conn.try_lock(self.lock_name, timeout_ms=5000):
                    return {**op, "type": "ok", "value": self._me()}
                return {**op, "type": "fail", "error": "timeout"}
            if op["f"] == "release":
                if self.fenced:
                    self.conn.unlock_fenced(self.lock_name)
                else:
                    self.conn.unlock(self.lock_name)  # HzError → fail
                return {**op, "type": "ok", "value": self._me()}
            raise ValueError(f"unknown f {op['f']!r}")

        return self._guard(op, body, info_value=self._me())


class HzSemaphoreClient(_HzBinClient):
    """Permit acquire/release against a 2-permit semaphore (reference:
    hazelcast.clj:373-400 cp-semaphore-client).  Releases are guarded
    by a local held-count so a client never hands back a permit it
    doesn't hold — the server-side over-issue is what the
    acquired-permits model checks."""

    NAME = "jepsen.semaphore"

    def __init__(self, opts: Optional[dict] = None):
        super().__init__(opts)
        self.held = 0

    def setup(self, test):
        self.conn.semaphore_init(
            self.NAME, int(self.opts.get("permits", 2))
        )

    def invoke(self, test, op):
        def body():
            if op["f"] == "acquire":
                if self.conn.semaphore_try_acquire(
                    self.NAME, timeout_ms=5000
                ):
                    self.held += 1
                    return {**op, "type": "ok", "value": self._me()}
                return {**op, "type": "fail", "error": "timeout"}
            if op["f"] == "release":
                if self.held <= 0:
                    return {**op, "type": "fail", "error": "no-permit"}
                self.conn.semaphore_release(self.NAME)
                self.held -= 1
                return {**op, "type": "ok", "value": self._me()}
            raise ValueError(f"unknown f {op['f']!r}")

        return self._guard(op, body, info_value=self._me())


class HzCasLongClient(_HzBinClient):
    """Keyed cas-register over AtomicLongs (reference: hazelcast.clj
    cp-cas-long-client; lifted over keys so the independent checker
    feeds the device batch axis)."""

    def _name(self, k) -> str:
        return f"jepsen.cas-long-{k}"

    def invoke(self, test, op):
        def body():
            k, v = op["value"]
            name = self._name(k)
            if op["f"] == "read":
                return {
                    **op, "type": "ok",
                    "value": independent.kv(k, self.conn.atomic_get(name)),
                }
            if op["f"] == "write":
                self.conn.atomic_set(name, int(v))
                return {**op, "type": "ok"}
            if op["f"] == "cas":
                old, new = v
                if self.conn.atomic_compare_and_set(
                    name, int(old), int(new)
                ):
                    return {**op, "type": "ok"}
                return {**op, "type": "fail", "error": "cas-miss"}
            raise ValueError(f"unknown f {op['f']!r}")

        return self._guard(op, body)


class HzCasRefClient(_HzBinClient):
    """Keyed cas-register over AtomicReferences holding boxed longs
    (reference: hazelcast.clj cp-cas-reference-client).  An unset
    reference reads as 0, matching the AtomicLong default so the same
    register model covers both."""

    def _name(self, k) -> str:
        return f"jepsen.cas-ref-{k}"

    @staticmethod
    def _box(v) -> Optional[bytes]:
        return None if int(v) == 0 else hzp.data_long(int(v))

    def invoke(self, test, op):
        def body():
            k, v = op["value"]
            name = self._name(k)
            if op["f"] == "read":
                cur = self.conn.ref_get(name)
                val = hzp.parse_data(cur) if cur is not None else 0
                return {**op, "type": "ok",
                        "value": independent.kv(k, val)}
            if op["f"] == "write":
                self.conn.ref_set(name, self._box(v))
                return {**op, "type": "ok"}
            if op["f"] == "cas":
                old, new = v
                if self.conn.ref_compare_and_set(
                    name, self._box(old), self._box(new)
                ):
                    return {**op, "type": "ok"}
                return {**op, "type": "fail", "error": "cas-miss"}
            raise ValueError(f"unknown f {op['f']!r}")

        return self._guard(op, body)


class HzAtomicLongIdClient(_HzBinClient):
    """Unique ids from an AtomicLong (reference: hazelcast.clj
    atomic-long-id-client / cp-id-gen-long)."""

    NAME = "jepsen.id.atomic-long"

    def invoke(self, test, op):
        def body():
            return {
                **op, "type": "ok",
                "value": self.conn.atomic_increment_and_get(self.NAME),
            }

        return self._guard(op, body)


class HzRefIdClient(_HzBinClient):
    """Unique ids via CAS loop on an AtomicReference (reference:
    hazelcast.clj atomic-ref-id-client)."""

    NAME = "jepsen.id.atomic-ref"
    RETRIES = 16

    def invoke(self, test, op):
        def body():
            for _ in range(self.RETRIES):
                cur = self.conn.ref_get(self.NAME)
                nxt = (hzp.parse_data(cur) if cur is not None else 0) + 1
                if self.conn.ref_compare_and_set(
                    self.NAME, cur, hzp.data_long(nxt)
                ):
                    return {**op, "type": "ok", "value": nxt}
            return {**op, "type": "fail", "error": "cas-contention"}

        return self._guard(op, body)


class HzFlakeIdClient(_HzBinClient):
    """Unique ids from a FlakeIdGenerator batch (reference:
    hazelcast.clj id-gen-client)."""

    NAME = "jepsen.id.flake"

    def invoke(self, test, op):
        def body():
            return {
                **op, "type": "ok",
                "value": self.conn.new_id_batch(self.NAME, 1)[0],
            }

        return self._guard(op, body)


class HzQueueClient(_HzBinClient):
    """Queue ops over the binary protocol (reference: hazelcast.clj
    queue-client: take/offer with drain at the end)."""

    NAME = "jepsen.queue"

    def invoke(self, test, op):
        def body():
            if op["f"] == "enqueue":
                self.conn.queue_offer(self.NAME, hzp.data_long(op["value"]))
                return {**op, "type": "ok"}
            if op["f"] == "dequeue":
                v = self.conn.queue_poll(self.NAME)
                if v is None:
                    return {**op, "type": "fail", "error": "empty"}
                return {**op, "type": "ok", "value": hzp.parse_data(v)}
            if op["f"] == "drain":
                got = []
                while True:
                    v = self.conn.queue_poll(self.NAME)
                    if v is None:
                        break
                    got.append(hzp.parse_data(v))
                return {**op, "type": "ok", "value": got}
            raise ValueError(f"unknown f {op['f']!r}")

        return self._guard(op, body)


# ---------------------------------------------------------------------
# workloads
# ---------------------------------------------------------------------


def map_workload(opts: Optional[dict] = None) -> dict:
    """CAS-maintained set in a map entry, read at the end (reference:
    hazelcast.clj:493-507 map-workload; checker/set)."""
    counter = {"n": 0}

    def add(test, ctx):
        counter["n"] += 1
        return {"type": "invoke", "f": "add", "value": counter["n"]}

    final = gen.clients(
        gen.each_thread(
            gen.once({"type": "invoke", "f": "read", "value": None})
        )
    )
    return {
        "generator": gen.stagger(0.05, add),
        "final-generator": final,
        "checker": checker_mod.set_checker(),
    }


def lock_workload(
    model, reentrant: bool = False, opts: Optional[dict] = None
) -> dict:
    """acquire/release cycles per thread against a linearizability
    model (reference: hazelcast.clj:667-725 lock/cp-lock/fenced-lock
    workload family: per-client cycles of acquire/release — doubled
    acquires for the reentrant flavors — checker/linearizable)."""
    opts = opts or {}
    steps = [{"type": "invoke", "f": "acquire", "value": None}]
    if reentrant:
        steps = steps * 2
    steps += [{"type": "invoke", "f": "release", "value": None}] * (
        2 if reentrant else 1
    )
    g = gen.each_thread(gen.stagger(0.05, gen.cycle(list(steps))))
    limit = int(opts.get("op-limit", 60))
    if limit:
        g = gen.limit(limit, g)
    return {
        "generator": g,
        # the fenced models are oracle-only (permits ride the dense
        # table automaton since round 4); a contended INVALID history
        # is the exponential blowup class for the oracle, so its search
        # gets a wall-time budget (verdict "unknown" past it) instead
        # of hanging the whole analysis
        "checker": checker_mod.linearizable(
            model, pure_fs=(),
            # "oracle-budget": seconds, or None for an unbounded search
            oracle_budget_s=(
                float(opts["oracle-budget"])
                if opts.get("oracle-budget", 300) is not None
                else None
            ) if "oracle-budget" in opts else 300.0,
        ),
    }


def cas_register_workload(opts: Optional[dict] = None) -> dict:
    """Keyed cas-register generator + independent linearizable checker
    (the same probe shape as the generic register workload, backed by
    hazelcast atomics).  AtomicLongs (and the boxed-long references)
    initialize to 0, so the model starts at 0, not None — the
    reference's model/cas-register 0 (hazelcast.clj:745,755)."""
    from .. import models
    from ..workloads import linearizable_register as linreg

    o = dict(opts or {})
    o.setdefault("model", models.cas_register(0))
    return linreg.test(o)


def db(opts: Optional[dict] = None):
    return HazelcastDB(opts)


def client(opts: Optional[dict] = None):
    return HzQueueClient(opts)


def workloads(opts: Optional[dict] = None) -> dict:
    """The full reference matrix (hazelcast.clj:652-768 workloads):
    map/crdt-map, the six lock flavors, cp-semaphore, cas over
    AtomicLong/AtomicReference, four unique-id generators, and the
    queue pair."""
    from .. import models

    opts = dict(opts or {})
    return {
        "map": map_workload(opts),
        "crdt-map": map_workload(opts),
        "lock": lock_workload(models.mutex(), opts=opts),
        "lock-no-quorum": lock_workload(models.mutex(), opts=opts),
        "non-reentrant-cp-lock": lock_workload(
            models.owner_mutex(), opts=opts
        ),
        "reentrant-cp-lock": lock_workload(
            models.reentrant_mutex(), reentrant=True, opts=opts
        ),
        "non-reentrant-fenced-lock": lock_workload(
            models.fenced_mutex(), opts=opts
        ),
        "reentrant-fenced-lock": lock_workload(
            models.reentrant_fenced_mutex(), reentrant=True, opts=opts
        ),
        "cp-semaphore": lock_workload(
            models.acquired_permits(int(opts.get("permits", 2))),
            opts=opts,
        ),
        "cp-cas-long": cas_register_workload(opts),
        "cp-cas-reference": cas_register_workload(opts),
        "cp-id-gen-long": unique_ids_workload(opts),
        "atomic-long-ids": unique_ids_workload(opts),
        "atomic-ref-ids": unique_ids_workload(opts),
        "id-gen-ids": unique_ids_workload(opts),
        "queue": common.queue_workload(opts),
        "linearizable-queue": common.linearizable_queue_workload(opts),
        "unique-ids": unique_ids_workload(opts),
    }


_CLIENTS = {
    "map": HzMapClient,
    "crdt-map": HzMapClient,
    "lock": HzLockClient,
    "lock-no-quorum": HzLockClient,
    "non-reentrant-cp-lock": HzLockClient,
    "reentrant-cp-lock": HzLockClient,
    "non-reentrant-fenced-lock": HzLockClient,
    "reentrant-fenced-lock": HzLockClient,
    "cp-semaphore": HzSemaphoreClient,
    "cp-cas-long": HzCasLongClient,
    "cp-cas-reference": HzCasRefClient,
    "cp-id-gen-long": HzAtomicLongIdClient,
    "atomic-long-ids": HzAtomicLongIdClient,
    "atomic-ref-ids": HzRefIdClient,
    "id-gen-ids": HzFlakeIdClient,
    "queue": HzQueueClient,
    "linearizable-queue": HzQueueClient,
    "unique-ids": HzFlakeIdClient,
}

#: per-workload client opt tweaks (distinct lock names mirror the
#: reference's jepsen.lock / jepsen.lock.no-quorum / cpLock1 / cpLock2)
_CLIENT_OPTS = {
    "crdt-map": {"crdt?": True},
    "lock-no-quorum": {"lock-name": "jepsen.lock.no-quorum"},
    "non-reentrant-cp-lock": {"lock-name": "jepsen.cpLock1"},
    "reentrant-cp-lock": {"lock-name": "jepsen.cpLock2"},
    "non-reentrant-fenced-lock": {"lock-name": "jepsen.cpLock1",
                                  "fenced?": True},
    "reentrant-fenced-lock": {"lock-name": "jepsen.cpLock2",
                              "fenced?": True},
}


def test(opts: Optional[dict] = None) -> dict:
    opts = dict(opts or {})
    wname = opts.get("workload", "queue")
    w = workloads(opts)[wname]
    copts = {**opts, **_CLIENT_OPTS.get(wname, {})}
    c = _CLIENTS.get(wname, HzQueueClient)(copts)
    return common.build_test(
        f"hazelcast-{wname}", opts, db=HazelcastDB(opts), client=c, workload=w,
    )
