"""Hazelcast suite.

Reference: hazelcast/src/jepsen/hazelcast.clj — the reference builds a
small server uberjar (hazelcast.clj:34-48), uploads it to every node,
starts it with the member list, and drives Java-client workloads:
distributed lock, unique IDs, atomic-ref CAS, crdt-ish maps, and
queues.

Without a JVM client, this suite drives Hazelcast's REST endpoints
(maps + queues), which cover the queue and unique-ids workloads; the
lock/atomic-ref workloads need the binary client protocol and are
exposed as a documented gap (`workloads()` omits them).  The server
here is the stock Hazelcast distribution zip with REST enabled, member
list templated into hazelcast.xml.
"""

from __future__ import annotations

import json
import uuid
from typing import Any, Optional

from .. import checker as checker_mod
from .. import client as client_mod
from ..control import util as cu
from ..control import execute, sudo
from ..os_setup import debian
from . import common
from .proto import IndeterminateError
from .proto.http import HttpError, JsonHttpClient

VERSION = "3.12.12"
DIR = "/opt/hazelcast"
PORT = 5701

_XML = """<?xml version="1.0" encoding="UTF-8"?>
<hazelcast xmlns="http://www.hazelcast.com/schema/config">
  <group><name>jepsen</name></group>
  <properties>
    <property name="hazelcast.rest.enabled">true</property>
  </properties>
  <network>
    <port auto-increment="false">{port}</port>
    <join>
      <multicast enabled="false"/>
      <tcp-ip enabled="true">
{members}
      </tcp-ip>
    </join>
  </network>
</hazelcast>
"""


class HazelcastDB(common.DaemonDB):
    dir = DIR
    binary = "bin/start.sh"
    logfile = f"{DIR}/hazelcast.log"
    pidfile = f"{DIR}/hazelcast.pid"
    proc_name = "java"  # the server runs under the JVM

    def __init__(self, opts: Optional[dict] = None):
        super().__init__(opts)
        self.version = (opts or {}).get("version", VERSION)

    def install(self, test, node):
        debian.install(["openjdk-8-jre-headless"])
        url = (
            "https://github.com/hazelcast/hazelcast/releases/download/"
            f"v{self.version}/hazelcast-{self.version}.zip"
        )
        with sudo():
            cu.install_archive(url, DIR)

    def configure(self, test, node):
        members = "\n".join(
            f"        <member>{n}:{PORT}</member>" for n in test["nodes"]
        )
        with sudo():
            cu.write_file(
                _XML.format(port=PORT, members=members),
                f"{DIR}/bin/hazelcast.xml",
            )

    def await_ready(self, test, node):
        cu.await_tcp_port(PORT, timeout_s=120)

    def wipe(self, test, node):
        with sudo():
            execute("rm", "-f", self.logfile)


class HazelcastQueueClient(client_mod.Client):
    """Queue workload over REST: POST offers, DELETE polls.
    (reference: hazelcast.clj queue-client — enqueue/dequeue/drain)"""

    QUEUE = "jepsen.queue"

    def __init__(self, opts: Optional[dict] = None):
        self.opts = opts or {}
        self.conn: Optional[JsonHttpClient] = None

    def open(self, test, node):
        c = type(self)(self.opts)
        c.conn = JsonHttpClient(
            self.opts.get("host", str(node)),
            self.opts.get("port", PORT),
            timeout=10.0,
        )
        return c

    def invoke(self, test, op):
        base = f"/hazelcast/rest/queues/{self.QUEUE}"
        try:
            if op["f"] == "enqueue":
                self.conn.post(base, str(op["value"]), ok=(200, 201, 204))
                return {**op, "type": "ok"}
            if op["f"] == "dequeue":
                status, body = self.conn.request(
                    "DELETE", f"{base}/2", raise_on_error=False
                )
                if status == 204 or body in (None, ""):
                    return {**op, "type": "fail", "error": "empty"}
                if status != 200:
                    raise HttpError(status, body)
                return {**op, "type": "ok", "value": int(body)}
            if op["f"] == "drain":
                got = []
                while True:
                    status, body = self.conn.request(
                        "DELETE", f"{base}/2", raise_on_error=False
                    )
                    if status != 200 or body in (None, ""):
                        break
                    got.append(int(body))
                return {**op, "type": "ok", "value": got}
            raise ValueError(f"unknown f {op['f']!r}")
        except IndeterminateError as e:
            return {**op, "type": "info", "error": str(e)}
        except HttpError as e:
            return {**op, "type": "fail", "error": f"{e.status}: {e.body}"}

    def close(self, test):
        if self.conn:
            self.conn.close()


class HazelcastIdClient(client_mod.Client):
    """unique-ids via a REST map used as an atomic counter per node —
    each client reserves blocks by writing node-scoped keys.
    (reference: hazelcast.clj id-gen-client)"""

    def __init__(self, opts: Optional[dict] = None):
        self.opts = opts or {}
        self.conn: Optional[JsonHttpClient] = None
        self.node = None
        self.uid = uuid.uuid4().hex[:12]  # survives client churn
        self.n = 0

    def open(self, test, node):
        c = type(self)(self.opts)
        c.node = str(node)
        c.conn = JsonHttpClient(
            self.opts.get("host", str(node)),
            self.opts.get("port", PORT),
            timeout=10.0,
        )
        return c

    def invoke(self, test, op):
        try:
            if op["f"] == "generate":
                self.n += 1
                val = f"{self.node}-{self.uid}-{self.n}"
                self.conn.post(
                    f"/hazelcast/rest/maps/jepsen.ids/{val}", "1",
                    ok=(200, 201, 204),
                )
                return {**op, "type": "ok", "value": val}
            raise ValueError(f"unknown f {op['f']!r}")
        except IndeterminateError as e:
            return {**op, "type": "info", "error": str(e)}
        except HttpError as e:
            return {**op, "type": "fail", "error": f"{e.status}: {e.body}"}

    def close(self, test):
        if self.conn:
            self.conn.close()


def unique_ids_workload(opts: Optional[dict] = None) -> dict:
    def generate(test, ctx):
        return {"type": "invoke", "f": "generate", "value": None}

    return {
        "generator": generate,
        "checker": checker_mod.unique_ids(),
    }


def db(opts: Optional[dict] = None):
    return HazelcastDB(opts)


def client(opts: Optional[dict] = None):
    return HazelcastQueueClient(opts)


def workloads(opts: Optional[dict] = None) -> dict:
    opts = dict(opts or {})
    return {
        "queue": common.queue_workload(opts),
        "linearizable-queue": common.linearizable_queue_workload(opts),
        "unique-ids": unique_ids_workload(opts),
    }


def test(opts: Optional[dict] = None) -> dict:
    opts = dict(opts or {})
    wname = opts.get("workload", "queue")
    w = workloads(opts)[wname]
    c = (HazelcastIdClient(opts) if wname == "unique-ids"
         else HazelcastQueueClient(opts))
    return common.build_test(
        f"hazelcast-{wname}", opts, db=HazelcastDB(opts), client=c, workload=w,
    )
