"""localkv suite — a real native database, installed and torn apart
in-container.

Every other suite's DB automation targets a server this image cannot
run; this one closes the loop with zero external dependencies: the
"database" is ``native/repregd.cc``, a single-binary replicated
linearizable register (multi-writer ABD over majority quorums, fsync'd
state).  The suite's DB **compiles the source on the node with g++
through the control layer** — the same deploy-and-build mechanism the
reference uses for its clock-fault helpers
(jepsen/src/jepsen/nemesis/time.clj:20-50) and for CharybdeFS
(charybdefs/src/jepsen/charybdefs.clj:40-65) — then runs one replica
per node under ``start-stop-daemon``, with every directed peer link
routed through a partitionable loopback forwarder
(:class:`jepsen_tpu.net.LoopbackProxyNet`).

That makes this the full reference test shape — install → run →
partition/kill → snarf logs → check — against REAL processes with real
replication state, executable in any container with g++
(reference shape: the etcd tutorial, doc/tutorial/01-…05-*.md, and
core_test.clj:122-177's integration tests).  ``doc/example-local-cluster``
holds a committed artifact of a full run.
"""

from __future__ import annotations

import os
import socket
import threading
from typing import Any, Dict, List, Optional

from .. import client as client_mod, util
from .. import db as db_mod
from .. import net as net_mod
from ..checker import linearizable
from ..control import execute, upload
from ..control import util as cu
from ..models import cas_register
from . import common

#: the daemon source, vendored in-repo; uploaded to each node and
#: compiled there
SOURCE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
    "repregd.cc",
)


def _free_port() -> int:
    return util.free_port()


def _node_id(node: Any, nodes: List[Any]) -> int:
    try:
        return int(str(node).lstrip("n"))
    except ValueError:
        return nodes.index(node) + 1


class LocalKVDB(db_mod.DB, db_mod.Process, db_mod.Pause, db_mod.LogFiles):
    """Compiles and runs one repregd replica per node.

    All replicas share this host (the localkv deployment model), so
    each gets a per-node directory, port, and state file; peer links
    ride per-edge loopback forwarders so the standard partitioner
    genuinely severs replication traffic.  Wiring (ports + proxy
    routes) is built lazily on first setup — test assembly stays free
    of side effects.
    """

    def __init__(self, opts: Optional[dict] = None):
        self.opts = dict(opts or {})
        self.base = self.opts.get("dir", "/tmp/jepsen-localkv")
        self.net = net_mod.LoopbackProxyNet()
        self.ports: Dict[Any, int] = {}
        self._peer_specs: Dict[Any, str] = {}
        self._lock = threading.Lock()
        self._wired = False
        self._torn_down: set = set()

    # -- wiring --------------------------------------------------------

    def _ensure_wiring(self, test: dict) -> None:
        with self._lock:
            if self._wired:
                return
            nodes = list(test["nodes"])
            self.ports = {n: _free_port() for n in nodes}
            for a in nodes:
                spec = []
                for b in nodes:
                    if a == b:
                        continue
                    p = self.net.add_route(a, b, "127.0.0.1", self.ports[b])
                    spec.append(f"{_node_id(b, nodes)}=127.0.0.1:{p}")
                self._peer_specs[a] = ",".join(spec)
            self._wired = True
            # teardowns recorded before wiring (db.cycle tears down
            # first, in parallel across nodes) must not count toward
            # the live cluster's shutdown
            self._torn_down = set()

    def _dir(self, node: Any) -> str:
        return f"{self.base}/{node}"

    # -- DB ------------------------------------------------------------

    def setup(self, test: dict, node: Any) -> None:
        self._ensure_wiring(test)
        d = self._dir(node)
        execute("mkdir", "-p", d)
        upload(SOURCE, f"{d}/repregd.cc")
        # build on the node, exactly like the reference gcc's its clock
        # helpers on DB nodes (nemesis/time.clj:20-50)
        execute(
            "g++", "-O2", "-pthread", "-o", f"{d}/repregd", f"{d}/repregd.cc"
        )
        self.start(test, node)
        cu.await_tcp_port(self.ports[node], host="127.0.0.1", timeout_s=60)

    def teardown(self, test: dict, node: Any) -> None:
        cu.meh(lambda: self.kill(test, node))
        execute("rm", "-rf", self._dir(node), check=False)
        with self._lock:
            if not self._wired:
                return  # pre-wiring teardown of a cycle: nothing to free
            self._torn_down.add(node)
            if self._torn_down >= set(test["nodes"]):
                # all replicas down: release the forwarders, and arm a
                # fresh wiring pass — core.run CYCLES the db (teardown
                # before setup, db.py cycle), so the next setup must
                # rebuild routes on this same Net instance
                self.net.reset()
                self._wired = False
                self._torn_down = set()

    # -- Process -------------------------------------------------------

    def start(self, test: dict, node: Any) -> None:
        d = self._dir(node)
        nodes = list(test["nodes"])
        cu.start_daemon(
            {
                "logfile": f"{d}/server.log",
                "pidfile": f"{d}/server.pid",
                "chdir": d,
                "match-executable?": False,
            },
            f"{d}/repregd",
            str(_node_id(node, nodes)),
            str(self.ports[node]),
            f"{d}/state",
            self._peer_specs[node],
        )

    def kill(self, test: dict, node: Any) -> None:
        # match this node's unique binary path, not a generic name, so
        # other replicas (and other runs) survive
        cu.grepkill(f"{self._dir(node)}/repregd", 9)
        cu.stop_daemon(pidfile=f"{self._dir(node)}/server.pid")

    # -- Pause ---------------------------------------------------------

    def pause(self, test: dict, node: Any) -> None:
        cu.grepkill(f"{self._dir(node)}/repregd", "STOP")

    def resume(self, test: dict, node: Any) -> None:
        cu.grepkill(f"{self._dir(node)}/repregd", "CONT")

    # -- LogFiles ------------------------------------------------------

    def log_files(self, test: dict, node: Any):
        return [f"{self._dir(node)}/server.log"]


class LocalKVClient(client_mod.Client):
    """Line-protocol client: each worker talks to its own node's
    replica, which coordinates the quorum op.  ERR-EARLY → :fail
    (nothing stored), ERR-MAYBE → :info (indeterminate)."""

    def __init__(self, opts: Optional[dict] = None, node: Any = None):
        self.opts = dict(opts or {})
        self.node = node
        self.sock: Optional[socket.socket] = None
        self.f = None

    def open(self, test, node):
        c = LocalKVClient(self.opts, node)
        c._connect(test)
        return c

    def _connect(self, test):
        port = test["db"].ports[self.node]
        self.sock = socket.create_connection(("127.0.0.1", port), timeout=5)
        self.f = self.sock.makefile("rw")

    def _ask(self, line: str) -> str:
        self.f.write(line + "\n")
        self.f.flush()
        out = self.f.readline().strip()
        if not out:
            raise ConnectionError("server went away")
        return out

    def invoke(self, test, op):
        if op["f"] not in ("read", "write"):
            # a programming error must fail loudly, not soak into the
            # history as indeterminate ops
            raise ValueError(f"unsupported op f={op['f']!r}")
        try:
            if self.sock is None:
                self._connect(test)
        except OSError as e:
            # connect refused: the request never reached any server —
            # definite failure for every op type
            self.sock = None
            return {**op, "type": "fail", "error": f"connect: {e!r}"}
        try:
            if op["f"] == "read":
                out = self._ask("R")
                if out.startswith("ERR"):
                    return {**op, "type": "fail", "error": out}
                return {**op, "type": "ok", "value": int(out)}
            out = self._ask(f"W {op['value']}")
            if out == "OK":
                return {**op, "type": "ok"}
            if out.startswith("ERR-EARLY"):
                return {**op, "type": "fail", "error": out}
            return {**op, "type": "info", "error": out}
        except (OSError, ConnectionError, ValueError) as e:
            # ValueError here = a mangled wire reply (int parse), the
            # same indeterminacy class as a cut connection
            self.sock = None
            t = "fail" if op["f"] == "read" else "info"
            return {**op, "type": t, "error": repr(e)}

    def close(self, test):
        if self.sock is not None:
            self.sock.close()
            self.sock = None


def db(opts: Optional[dict] = None) -> LocalKVDB:
    return LocalKVDB(opts)


def client(opts: Optional[dict] = None) -> LocalKVClient:
    return LocalKVClient(opts)


def register_workload(opts: Optional[dict] = None) -> dict:
    """Single replicated register: concurrent reads and unique-valued
    writes (unique values keep the linearizability search sharp — a
    read's value pins exactly which write it observed)."""
    import random

    counter = {"n": 0}

    def rw(test, ctx):
        if random.random() < 0.5:
            return {"type": "invoke", "f": "read", "value": None}
        counter["n"] += 1
        return {"type": "invoke", "f": "write", "value": counter["n"]}

    return {
        "generator": rw,
        "checker": linearizable(cas_register(0)),
    }


def workloads(opts: Optional[dict] = None) -> dict:
    return {"register": register_workload(opts or {})}


def test(opts: Optional[dict] = None) -> dict:
    """Full runnable test map.  opts: nodes, faults (partition/kill/
    pause), time-limit, concurrency, rate, dir."""
    opts = dict(opts or {})
    opts.setdefault("nodes", ["n1", "n2", "n3"])
    d = db(opts)
    wname = opts.get("workload", "register")
    # only_active: an idle clock sub-nemesis would still gcc clock
    # helpers into /opt/jepsen at setup — pointless (and sudo-dependent)
    # for a loopback cluster that never requests clock faults
    from ..nemesis import combined

    pkg = combined.nemesis_package(
        {
            "db": d,
            "faults": opts.get("faults", ["partition", "kill"]),
            "interval": opts.get("interval", combined.DEFAULT_INTERVAL),
        },
        only_active=True,
    )
    t = common.build_test(
        "localkv",
        opts,
        db=d,
        client=client(opts),
        workload=workloads(opts)[wname],
        nemesis_package=pkg,
    )
    # partitions act on the DB's own peer forwarders
    t["net"] = d.net
    from ..control.local import LocalRemote

    t.setdefault("remote", LocalRemote())
    return t
