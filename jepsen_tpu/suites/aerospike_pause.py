"""Aerospike pause workload: pause a master to trap in-flight writes,
promote a new master, then resume the old one so it commits the trapped
writes with a stale view — lost updates a set read exposes.

Reference: aerospike/src/aerospike/pause.clj — a state machine SHARED
by client, nemesis, and generator cycling healthy → pausing → paused →
wait (:165-208 docstring), with healthy-delay 5 s / pause-delay 30 s /
masters-limit 1 (:17-26), three pause modes (:40-82): ``process``
(SIGSTOP/SIGCONT asd), ``net`` (a self-healing netem delay daemon —
raising latency would sever our own SSH, so a nohup'd shell undoes it),
and ``clock`` (bump the clock far ahead and isolate the node so it
commits locally with future timestamps; resume resets clocks, heals,
and restarts the others); blind string-append writes per key block
checked as independent sets (:104-160, :209-233).

Deviation: the reference's generator blocks in Thread/sleep while
deciding (:145-171).  This framework's scheduler is single-threaded
and generators must never block, so state deadlines are virtual-time
timestamps and the generator returns PENDING until they pass — same
schedule, no blocked scheduler.
"""

from __future__ import annotations

import threading
from typing import Optional

from .. import checker as checker_mod
from .. import client as client_mod
from .. import control
from .. import generator as gen
from .. import independent
from .. import net as net_mod
from ..control import execute, lit, su
from ..generator import PENDING, Generator
from ..nemesis import Nemesis
from ..nemesis import time as nt

HEALTHY_DELAY_MS = 5_000   # (reference: pause.clj:17-19)
PAUSE_DELAY_MS = 30_000    # (reference: pause.clj:21-23)
MASTERS_LIMIT = 1          # (reference: pause.clj:25-26)


class PauseState:
    """The shared machine.  The nemesis moves healthy→pausing→paused
    and wait→healthy; the first successful client add during paused
    moves paused→wait (the write that proves a new master got
    promoted).  Deadlines are owned by the generator (virtual time)."""

    def __init__(self, test: dict, opts: Optional[dict] = None,
                 rng=None):
        opts = opts or {}
        self.lock = threading.Lock()
        self.rng = rng if rng is not None else gen.rng
        self.mode = opts.get("pause-mode", "process")
        self.healthy_delay_ms = opts.get(
            "healthy-delay", HEALTHY_DELAY_MS)
        self.pause_delay_ms = opts.get("pause-delay", PAUSE_DELAY_MS)
        self.masters_limit = opts.get("masters-limit", MASTERS_LIMIT)
        self.state = "wait"
        self.masters: list = []
        self.keys: list = []
        self.next_key = 0
        self.deadline_ns: Optional[int] = None
        self.next_healthy(test)

    def next_healthy(self, test):
        """Pick a new master set and a fresh key block
        (reference: pause.clj:28-37 next-healthy)."""
        with self.lock:
            nodes = list(test["nodes"])
            self.rng.shuffle(nodes)
            self.state = "healthy"
            self.masters = nodes[: self.masters_limit]
            n = len(nodes) or 1
            per = max(1, test.get("concurrency", n) // n)
            self.keys = list(range(self.next_key, self.next_key + per))
            self.next_key += per
            self.deadline_ns = None

    def note(self, state: str):
        with self.lock:
            self.state = state
            self.deadline_ns = None

    def add_succeeded(self):
        """paused → wait on the first post-pause ack
        (reference: pause.clj:128-133)."""
        with self.lock:
            if self.state == "paused":
                self.state = "wait"
                self.deadline_ns = None


def pause_node(state: PauseState, test, node):
    """(reference: pause.clj:39-69 pause!)"""
    mode = state.mode
    if mode == "process":
        with su():
            execute("killall", "-19", "asd")
    elif mode == "net":
        # self-healing: raising latency would sever our own control
        # connection, so a detached shell restores it after the delay
        secs = int(state.pause_delay_ms / 1000) + 1
        with su():
            execute(
                "nohup", "bash", "-c",
                f"tc qdisc add dev eth0 root netem delay "
                f"{state.pause_delay_ms}ms 1ms distribution normal; "
                f"sleep {secs}; tc qdisc del dev eth0 root",
                lit("&"),
            )
    elif mode == "clock":
        nt.bump_time(1000 * state.pause_delay_ms)
    else:
        raise ValueError(f"unknown pause-mode {mode!r}")
    return "paused"


def resume_node(state: PauseState, test, node):
    """(reference: pause.clj:71-82 resume!)"""
    mode = state.mode
    if mode == "process":
        with su():
            execute("killall", "-18", "asd")
    elif mode == "clock":
        nt.reset_time()
    return "resumed"


class PauseNemesis(Nemesis):
    """Applies pause/resume to the op's nodes and advances the state
    machine (reference: pause.clj:84-102).  Clock mode adds the
    isolation partition on pause and heal + restart-the-others on
    resume (pause.clj:58-69,76-82)."""

    def __init__(self, state: PauseState, db=None):
        self.state = state
        self.db = db

    def setup(self, test):
        if self.state.mode == "clock":
            # compile the bump/strobe tools on every node first —
            # pause_node's nt.bump_time executes them (the reference
            # runs nt/install! in its nemesis setup, pause.clj:86-89)
            def prep(t, n):
                nt.install()
                nt.reset_time()

            control.on_nodes(test, list(test["nodes"]), prep)
        return self

    def invoke(self, test, op):
        state = self.state
        targets = list(op.get("value") or state.masters)
        others = [n for n in test["nodes"] if n not in targets]
        if op["f"] == "pause":
            res = control.on_nodes(
                test, targets,
                lambda t, n: pause_node(state, t, n))
            if state.mode == "clock":
                # snub both directions so far-future commits stay local
                grudge = {t: set(others) for t in targets}
                for o in others:
                    grudge[o] = set(targets)
                net_mod.drop_all(test, grudge)
            state.note("paused")
        elif op["f"] == "resume":
            res = control.on_nodes(
                test, targets,
                lambda t, n: resume_node(state, t, n))
            if state.mode == "clock":
                net_mod.heal(test)
                if self.db is not None:
                    control.on_nodes(
                        test, others,
                        lambda t, n: self.db.start(t, n))
            state.next_healthy(test)
        else:
            raise ValueError(f"unknown f {op['f']!r}")
        return {**op, "type": "info",
                "value": {str(k): str(v) for k, v in res.items()}}

    def teardown(self, test):
        pass

    def fs(self):
        return frozenset({"pause", "resume"})


class PauseNemGen(Generator):
    """Nemesis schedule from the state machine: healthy → (after
    healthy-delay) pause the masters; wait → (after pause-delay, or
    immediately in clock mode) resume them (reference: pause.clj
    :144-163, nemesis branch)."""

    def __init__(self, state: PauseState):
        self.state = state

    def op(self, test, ctx):
        s = self.state
        now = ctx["time"]
        with s.lock:
            if s.state == "healthy":
                if s.deadline_ns is None:
                    s.deadline_ns = now + s.healthy_delay_ms * 1_000_000
                if now < s.deadline_ns:
                    return (PENDING, self)
                return (
                    gen.fill_in_op(
                        {"type": "info", "f": "pause",
                         "value": list(s.masters)}, ctx),
                    self,
                )
            if s.state == "wait":
                if s.deadline_ns is None:
                    delay = 0 if s.mode == "clock" else s.pause_delay_ms
                    s.deadline_ns = now + delay * 1_000_000
                if now < s.deadline_ns:
                    return (PENDING, self)
                return (
                    gen.fill_in_op(
                        {"type": "info", "f": "resume",
                         "value": list(s.masters)}, ctx),
                    self,
                )
            # pausing/paused: the nemesis op is in flight or clients
            # are racing toward the first post-pause ack
            return (PENDING, self)

    def update(self, test, ctx, event):
        return self


class PauseClientGen(Generator):
    """Client schedule: blind adds against the current key block,
    ceasing entirely during wait (reference: pause.clj:158-163)."""

    def __init__(self, state: PauseState):
        self.state = state
        self.counter = 0
        self.rr = 0

    def op(self, test, ctx):
        s = self.state
        with s.lock:
            if s.state == "wait" or not s.keys:
                return (PENDING, self)
            self.rr += 1
            k = s.keys[self.rr % len(s.keys)]
        v = self.counter
        self.counter += 1
        return (
            gen.fill_in_op(
                {"type": "invoke", "f": "add",
                 "value": independent.kv(k, v)}, ctx),
            self,
        )

    def update(self, test, ctx, event):
        return self


class FinalReadGen(Generator):
    """One read per key ever written, built lazily at final-phase time
    (the key range isn't known until the run ends — the reference
    defers this with gen/derefer + delay, pause.clj:215-223)."""

    def __init__(self, state: PauseState):
        self.state = state
        self.inner = None
        self.built = False

    def _build(self):
        with self.state.lock:
            n_keys = self.state.next_key
        return [
            {"type": "invoke", "f": "read",
             "value": independent.kv(k, None)}
            for k in range(n_keys)
        ]

    def op(self, test, ctx):
        if not self.built:
            self.inner = self._build()
            self.built = True
        if self.inner is None:
            return None
        res = gen.op(self.inner, test, ctx)
        if res is None:
            return None
        o, g2 = res
        self.inner = g2
        return (o, self)

    def update(self, test, ctx, event):
        return self


class PauseClient(client_mod.Client):
    """Blind string-appends + set reads on the "pause" set, flipping
    the machine paused→wait on the first successful add (reference:
    pause.clj:104-141)."""

    SET = "pause"
    BIN = "value"

    def __init__(self, state: PauseState, opts: Optional[dict] = None):
        self.state = state
        self.opts = opts or {}
        self.conn = None

    def open(self, test, node):
        from .aerospike import PORT, NAMESPACE
        from .proto.aerospike import AerospikeClient

        c = type(self)(self.state, self.opts)
        c.conn = AerospikeClient(
            self.opts.get("host", str(node)),
            self.opts.get("port", PORT),
            namespace=self.opts.get("namespace", NAMESPACE),
            timeout=self.opts.get("timeout", 5.0),
        )
        return c

    def invoke(self, test, op):
        from .proto import IndeterminateError
        from .proto.aerospike import AerospikeError

        k, v = op["value"]
        try:
            if op["f"] == "read":
                bins, _gen = self.conn.get(self.SET, int(k))
                raw = str((bins or {}).get(self.BIN, ""))
                vals = sorted(
                    int(x) for x in raw.split(" ") if x.strip())
                return {**op, "type": "ok",
                        "value": independent.kv(k, vals)}
            if op["f"] == "add":
                self.conn.append_str(self.SET, int(k), self.BIN,
                                     f" {int(v)}")
                self.state.add_succeeded()
                return {**op, "type": "ok"}
            raise ValueError(f"unknown f {op['f']!r}")
        except IndeterminateError as e:
            return {**op, "type": "info", "error": str(e)}
        except AerospikeError as e:
            return {**op, "type": "fail", "error": str(e)}

    def close(self, test):
        if self.conn:
            self.conn.close()


def pause_workload(opts: Optional[dict] = None) -> dict:
    """The client-side workload pieces over a fresh, PRIVATE state
    machine — real runs need pause_test, which wires one SHARED
    machine through client + nemesis + generators; this entry only
    satisfies the workloads() registry shape (a private rng keeps
    registry enumeration from perturbing the seeded module rng other
    workloads reproduce from)."""
    import random as _random

    opts = dict(opts or {})
    state = PauseState(
        {"nodes": list(opts.get("nodes", [])),
         "concurrency": opts.get("concurrency", 5)}, opts,
        rng=_random.Random(0))
    return {
        "generator": PauseClientGen(state),
        "final-generator": gen.clients(FinalReadGen(state)),
        "checker": independent.checker(checker_mod.set_checker()),
    }


def pause_test(opts: Optional[dict] = None) -> dict:
    """The assembled test: shared state machine wiring client gen,
    nemesis gen, final resume, and per-key set checking (reference:
    pause.clj:162-233 workload+nemesis)."""
    from . import common
    from .aerospike import AerospikeDB

    opts = dict(opts or {})
    seed_test = {"nodes": list(opts.get("nodes", [])),
                 "concurrency": opts.get("concurrency", 5)}
    state = PauseState(seed_test, opts)
    database = opts.get("db") or AerospikeDB(opts)

    pkg = {
        "nemesis": PauseNemesis(state, database),
        "generator": PauseNemGen(state),
        # resume everyone, then let the cluster settle (reference
        # :225-233)
        "final_generator": [
            gen.once(lambda test, ctx: {
                "type": "info", "f": "resume",
                "value": list(test["nodes"])}),
            gen.sleep(opts.get("final-settle", 10)),
        ],
        "perf": {("pause", frozenset({"pause"}),
                  frozenset({"resume"}), "#A0B1E9")},
    }
    workload = {
        "generator": PauseClientGen(state),
        "final-generator": gen.clients(FinalReadGen(state)),
        "checker": independent.checker(checker_mod.set_checker()),
    }
    return common.build_test(
        "aerospike-pause", opts, db=database,
        client=PauseClient(state, opts),
        workload=workload, nemesis_package=pkg,
    )
