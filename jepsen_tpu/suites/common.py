"""Shared scaffolding for the per-database test suites.

Every reference suite repeats the same skeleton: a ``db/DB`` reification
that installs a tarball/deb, writes a config, and runs the server under
``start-daemon!``; a client over the DB's wire protocol; a workload
table; and a runner merging CLI opts into a test map (e.g.
consul/src/jepsen/consul/db.clj:23-95, tidb/src/tidb/db.clj,
doc/tutorial/02-db.md).  This module factors that skeleton once.

Suites provide:

- a :class:`DaemonDB` subclass (install/config/start hooks), and
- workload builders composed from :mod:`jepsen_tpu.workloads` plus the
  generic set/counter/sets builders below, and
- :func:`build_test` merges it all into a runnable test map with the
  standard nemesis packages.
"""

from __future__ import annotations

import logging
import os
from typing import Any, Callable, Dict, Iterable, List, Optional

from .. import checker as checker_mod
from .. import client as client_mod
from .. import db as db_mod
from .. import generator as gen
from ..control import util as cu
from ..nemesis import combined
from ..workloads import noop_test

log = logging.getLogger("jepsen_tpu.suites")


class DaemonDB(db_mod.DB, db_mod.Process, db_mod.Pause, db_mod.LogFiles):
    """A DB whose server runs as a single daemon per node.

    Subclasses set ``dir``, ``binary``, ``logfile``, ``pidfile`` and
    implement :meth:`install` and :meth:`start_args`; the base class
    wires setup/teardown/start/kill/pause/resume through the control
    DSL's daemon helpers, exactly as reference suites do with
    ``cu/start-daemon!``/``stop-daemon!``/``grepkill!``
    (jepsen/src/jepsen/control/util.clj:286-399).
    """

    dir: str = "/opt/db"
    binary: str = "db"
    logfile: str = "/opt/db/db.log"
    pidfile: str = "/opt/db/db.pid"

    def __init__(self, opts: Optional[dict] = None):
        self.opts = dict(opts or {})

    # -- hooks ---------------------------------------------------------
    def install(self, test: dict, node: Any) -> None:
        """Fetch + unpack the server onto the node."""
        raise NotImplementedError

    def configure(self, test: dict, node: Any) -> None:
        """Write config files (optional hook)."""

    def start_args(self, test: dict, node: Any) -> List[Any]:
        """argv tail after the binary."""
        return []

    def start_env(self, test: dict, node: Any) -> Dict[str, str]:
        return {}

    def await_ready(self, test: dict, node: Any) -> None:
        """Block until the server answers (optional hook)."""

    def wipe(self, test: dict, node: Any) -> None:
        """Remove data directories on teardown (optional hook)."""

    # -- DB ------------------------------------------------------------
    def setup(self, test: dict, node: Any) -> None:
        self.install(test, node)
        self.configure(test, node)
        self.start(test, node)
        self.await_ready(test, node)

    def teardown(self, test: dict, node: Any) -> None:
        cu.meh(lambda: self.kill(test, node))
        self.wipe(test, node)

    @property
    def proc_name(self) -> str:
        """Process comm name for killall/pkill — the binary's basename
        (``binary`` may be a path like ``bin/crate``)."""
        return os.path.basename(self.binary)

    # -- Process -------------------------------------------------------
    def start(self, test: dict, node: Any) -> None:
        cu.start_daemon(
            {
                "logfile": self.logfile,
                "pidfile": self.pidfile,
                "chdir": self.dir,
                "env": self.start_env(test, node),
            },
            f"{self.dir}/{self.binary}",
            *self.start_args(test, node),
        )

    def kill(self, test: dict, node: Any) -> None:
        cu.stop_daemon(pidfile=self.pidfile, cmd=self.proc_name)

    # -- Pause ---------------------------------------------------------
    def pause(self, test: dict, node: Any) -> None:
        cu.signal(self.proc_name, "STOP")

    def resume(self, test: dict, node: Any) -> None:
        cu.signal(self.proc_name, "CONT")

    # -- LogFiles ------------------------------------------------------
    def log_files(self, test: dict, node: Any) -> Iterable[str]:
        return [self.logfile]


# ---------------------------------------------------------------------
# Generic workloads shared by many suites
# ---------------------------------------------------------------------


def set_workload(opts: Optional[dict] = None) -> dict:
    """Unique-element set: clients add distinct integers, then a final
    read checks for lost/duplicated elements.  The shape every suite's
    "set"/"sets" workload follows (e.g. elasticsearch/src/jepsen/
    elasticsearch/sets.clj, yugabyte set.clj, tidb sets.clj).
    """
    opts = opts or {}
    counter = {"n": 0}

    def add(test, ctx):
        v = counter["n"]
        counter["n"] += 1
        return {"type": "invoke", "f": "add", "value": v}

    final = gen.clients(
        gen.each_thread(gen.once({"type": "invoke", "f": "read", "value": None}))
    )
    return {
        "generator": add,
        "final-generator": final,
        "checker": checker_mod.set_full(
            linearizable=bool(opts.get("linearizable?", False))
        ),
    }


def counter_workload(opts: Optional[dict] = None) -> dict:
    """Eventually-consistent counter: increments (and optionally
    decrements) mixed with reads, verified by the bounds-interval
    counter checker (reference: checker.clj:737-795; e.g.
    aerospike/src/aerospike/counter.clj, yugabyte counter.clj)."""
    opts = opts or {}

    def inc(test, ctx):
        return {"type": "invoke", "f": "add", "value": 1}

    def dec(test, ctx):
        return {"type": "invoke", "f": "add", "value": -1}

    def read(test, ctx):
        return {"type": "invoke", "f": "read", "value": None}

    mixed = [inc, read] if not opts.get("decrements?") else [inc, dec, read]
    return {
        "generator": gen.mix(mixed),
        "checker": checker_mod.counter(),
    }


def _queue_ops():
    """Unique-value enqueue + unknown-value dequeue op fns — the op
    shape both queue workloads share."""
    counter = {"n": 0}

    def enq(test, ctx):
        counter["n"] += 1
        return {"type": "invoke", "f": "enqueue", "value": counter["n"]}

    def deq(test, ctx):
        return {"type": "invoke", "f": "dequeue", "value": None}

    return enq, deq


def queue_workload(opts: Optional[dict] = None) -> dict:
    """Total-queue: enqueues/dequeues raced with faults, then every
    thread drains (reference: e.g. rabbitmq.clj queue workload +
    checker.clj:628 total-queue).  Shared by the rabbitmq, disque, and
    hazelcast suites."""
    enq, deq = _queue_ops()
    final = gen.clients(
        gen.each_thread(gen.once({"type": "invoke", "f": "drain",
                                  "value": None}))
    )
    return {
        "generator": gen.mix([enq, deq]),
        "final-generator": final,
        "checker": checker_mod.total_queue(),
    }


def linearizable_queue_workload(opts: Optional[dict] = None) -> dict:
    """Queue ops checked for full linearizability against the
    unordered-queue model (the knossos model the reference's checker
    consumes, jepsen/src/jepsen/checker.clj:19-26,218-239).  Unique
    elements keep the history inside the device bitset kernel's
    envelope (ops/step_kernels.py unordered_queue_step); total-queue
    (queue_workload) remains the O(n) default for unbounded runs."""
    from .. import models

    opts = opts or {}
    enq, deq = _queue_ops()
    g = gen.mix([enq, deq])
    limit = opts.get("op-limit", opts.get("per-key-limit", 40))
    if limit:
        g = gen.limit(int(limit), g)
    return {
        "generator": g,
        "checker": checker_mod.linearizable(
            models.unordered_queue(), pure_fs=()
        ),
    }


class UnreadOkSetChecker(checker_mod.Checker):
    """The per-key set checker, except a key whose final read was never
    even *invoked* (the time limit cut the key's schedule before its
    read phase) is vacuously valid with a marker instead of poisoning
    the whole run with "unknown".  A key whose reads were invoked but
    all FAILED keeps its unknown verdict — that's real evidence of an
    unreachable key, not a scheduling artifact."""

    def __init__(self):
        self.inner = checker_mod.set_checker()

    def check(self, test, history, opts=None):
        out = self.inner.check(test, history, opts)
        if out.get("valid?") == "unknown":
            read_invoked = any(op.f == "read" for op in history)
            if not read_invoked:
                return {"valid?": True, "unread?": True}
        return out


def unread_ok_set_checker() -> checker_mod.Checker:
    return UnreadOkSetChecker()


def independent_set_workload(opts: Optional[dict] = None) -> dict:
    """Per-key unique adds then a final read per thread, lifted over
    independent keys with the unread-tolerant set checker — the shape
    crate's lost-updates and aerospike's set share (reference:
    crate/lost_updates.clj:106-160, aerospike/set.clj:43-66)."""
    opts = dict(opts or {})
    n = max(1, len(opts.get("nodes", ["n1"])))
    counter = {"n": 0}

    def fgen(k):
        def add(test, ctx):
            counter["n"] += 1
            return {"type": "invoke", "f": "add", "value": counter["n"]}

        return gen.phases(
            gen.limit(
                int(opts.get("per-key-limit", 20)),
                gen.stagger(1 / 50, add),
            ),
            gen.each_thread(
                gen.once({"type": "invoke", "f": "read", "value": None})
            ),
        )

    from .. import independent

    return {
        "generator": independent.concurrent_generator(
            2 * n, range(100_000), fgen
        ),
        "checker": independent.checker(unread_ok_set_checker()),
        "concurrency": 2 * n,
    }


def register_workload(opts: Optional[dict] = None) -> dict:
    """Per-key linearizable CAS registers (the flagship workload);
    delegates to workloads.linearizable_register.  Declares the 2n
    concurrency its per-key thread groups need (reference:
    linearizable_register.clj:40-43)."""
    from ..workloads import linearizable_register

    return linearizable_register.test(opts or {})


WORKLOAD_BUILDERS: Dict[str, Callable[[dict], dict]] = {}


def generic_workload(name: str, opts: Optional[dict] = None) -> dict:
    """Look up a workload by name across the generic + core tables."""
    from .. import workloads as w

    opts = opts or {}
    table = {
        "set": set_workload,
        "counter": counter_workload,
        "register": register_workload,
        "linearizable-register": register_workload,
    }
    if name in table:
        return table[name](opts)
    return w.workload(name, opts)


# ---------------------------------------------------------------------
# Test assembly
# ---------------------------------------------------------------------


def suite_nemesis_package(
    opts: dict, db, suite_pkg: dict, known: set
) -> dict:
    """Combine a suite's own fault menu with the generic packages for
    any requested faults the menu doesn't cover.  Silently dropping the
    leftovers would report results for fault scenarios never exercised;
    if the two packages' op namespaces collide, this raises instead.
    """
    faults = set(opts.get("faults", ()))
    claimed = faults & known
    if opts.get("partition-targets") and claimed & {
        "partition", "partition-one", "partition-half", "partition-ring"
    }:
        raise ValueError(
            "partition-targets is not supported by this suite's fault "
            "menu; use the generic partition fault without the suite's "
            "partition names"
        )
    leftover = sorted(faults - known)
    if not leftover:
        return suite_pkg
    rest_opts = {
        **{k: v for k, v in opts.items() if k != "faults"},
        "db": db,
        "faults": leftover,
        "interval": opts.get("interval", combined.DEFAULT_INTERVAL),
    }
    if opts.get("partition-targets"):
        # same translation build_test's default path performs —
        # combined.partition_package reads opts["partition"]["targets"]
        rest_opts["partition"] = {"targets": opts["partition-targets"]}
    rest = combined.nemesis_package(rest_opts, only_active=True)
    try:
        return combined.compose_packages([suite_pkg, rest])
    except ValueError as e:
        raise ValueError(
            f"faults {leftover} cannot run alongside this suite's fault "
            f"menu ({sorted(claimed)}): {e}"
        ) from e


def build_test(
    name: str,
    opts: Optional[dict],
    *,
    db: db_mod.DB,
    client: client_mod.Client,
    workload: dict,
    nemesis_package: Optional[dict] = None,
) -> dict:
    """Merge a suite's db + client + workload (+ standard nemesis
    packages from opts["faults"]) into a full runnable test map — the
    per-suite runner every reference suite ends with (e.g.
    cockroachdb/src/jepsen/cockroach/runner.clj,
    yugabyte/src/yugabyte/runner.clj).

    opts keys honoured: nodes, time-limit, concurrency, faults (list of
    fault keywords for nemesis/combined), interval, rate.
    """
    opts = dict(opts or {})
    test = noop_test()
    test.update(
        {
            "name": name,
            "db": db,
            "client": client,
            "store?": opts.get("store?", False),
        }
    )
    # standard harness opts must flow through, or suite runs lose their
    # store location / logging flags (the CLI merges these into opts;
    # reference: cli.clj test-opt-fn feeding every suite's test map)
    for k in ("store-base", "leave-db-running?", "logging-json?", "ssh",
              "remote", "time-limit", "mesh", "mesh-fn",
              # persisted so `analyze` can rebuild THIS suite's checker
              # from the stored map (without them a resumed analysis
              # would silently run the default workload's checker over
              # a foreign history; reference: cli.clj:402-431 analyze
              # re-invokes the same test-fn with the stored opts)
              "suite", "workload"):
        if k in opts:
            test[k] = opts[k]
    if "nodes" in opts:
        test["nodes"] = list(opts["nodes"])
    test.update({k: v for k, v in workload.items() if k not in ("generator", "final-generator", "checker")})
    if opts.get("concurrency") is not None:
        test["concurrency"] = opts["concurrency"]

    checker = workload.get("checker") or checker_mod.unbridled_optimism()
    test["checker"] = checker_mod.compose(
        {
            "workload": checker,
            "stats": checker_mod.stats(),
            "exceptions": checker_mod.unhandled_exceptions(),
            # latency/rate SVGs with fault-window shading — the
            # reference's runners compose (checker/perf) into every
            # run (e.g. cockroach/runner.clj, galera dirty_reads.clj
            # :117-120)
            "perf": checker_mod.perf_checker(),
        }
    )

    # Nemesis package from fault spec (reference: nemesis/combined.clj:328);
    # suites with their own fault menus (e.g. yugabyte's master/tserver
    # targeting) pass a pre-built package instead
    if nemesis_package is not None:
        pkg = nemesis_package
    else:
        pkg_opts = {
            "db": db,
            "faults": opts.get("faults", []),
            "interval": opts.get("interval", combined.DEFAULT_INTERVAL),
        }
        if opts.get("partition-targets"):
            pkg_opts["partition"] = {"targets": opts["partition-targets"]}
        pkg = combined.nemesis_package(pkg_opts)
    test["nemesis"] = pkg.get("nemesis") or test["nemesis"]

    # Fault-window shading for the latency/rate plots: the package's
    # perf entries (name, start-fs, stop-fs, color) become the plot
    # specs checker.perf.nemesis_regions consumes (reference:
    # nemesis/combined.clj perf sets feeding checker/perf.clj:240-283)
    perf_specs = [
        {"name": n, "start": tuple(starts), "stop": tuple(stops),
         "color": color}
        for (n, starts, stops, color) in sorted(
            pkg.get("perf") or (), key=lambda e: str(e[0])
        )
    ]
    if perf_specs:
        test.setdefault("plot", {})["nemeses"] = perf_specs

    # Generator: rate-staggered client ops raced with the nemesis
    # schedule, bounded by time-limit, then nemesis final + workload
    # final reads (reference runner shape: e.g. tidb/src/tidb/run.clj).
    body = gen.clients(workload.get("generator"))
    rate = opts.get("rate")
    if rate:
        body = gen.stagger(1.0 / rate, body)
    if pkg.get("generator") is not None:
        body = gen.any(body, gen.nemesis(pkg["generator"]))
    body = gen.time_limit(opts.get("time-limit", 60), body)

    parts: List[Any] = [body]
    if pkg.get("final_generator"):
        parts.append(gen.nemesis(pkg["final_generator"]))
    if workload.get("final-generator") is not None:
        parts.append(workload["final-generator"])
    test["generator"] = gen.phases(*parts) if len(parts) > 1 else body

    # --tracing ENDPOINT: span every client call, exported to the
    # endpoint (a JSONL spans file; reference: dgraph/core.clj:118,175
    # builds its tracer from the --tracing URL and client.clj wraps
    # each client call in a span)
    from .. import trace

    return trace.wire(test, opts.get("tracing"))
