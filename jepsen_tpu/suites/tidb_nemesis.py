"""TiDB fault menu: per-component (pd / tikv / tidb) process faults,
PD scheduler stress, slow isolated PD primaries, partitions, and clock
skew, with flip-flop fault/recovery scheduling.

Reference: tidb/src/tidb/nemesis.clj — process-nemesis (:19-53:
kill/start/pause/resume each of pd, tikv, and tidb independently;
resume/start target every node, faults a random nonempty subset, and an
op :value overrides the targets), schedule-nemesis (:55-87: pd-ctl
shuffle-leader / shuffle-region / random-merge schedulers added and
removed on one node), slow-primary-nemesis (:89-147: run the PD
leader's clock slow via faketime, transfer leadership to it, then
isolate it in a minority), full-nemesis composition (:149-166),
partition generators for single-node / pd-leader / half / ring grudges
(:170-207), the clock mix with tidb's f names (:209-216), opt-mix +
flip-flop mixed-generator (:218-283), final-generator recovery
(:285-306), the restart-kv-without-pd and slow-primary special
schedules (:308-340), full-generator dispatch (:342-359), and the
:kill/:stop/:pause/:schedules/:partition shorthand expansion (:361-380).
"""

from __future__ import annotations

import time as _time

from .. import control
from .. import faketime
from .. import generator as gen
from .. import net as net_mod
from ..nemesis import (
    Nemesis,
    bisect,
    complete_grudge,
    compose,
    majorities_ring,
    partitioner,
    split_one,
)
from ..nemesis import time as nt
from ..util import random_nonempty_subset

#: every f the process nemesis owns
PROCESS_FS = frozenset({
    "start-pd", "start-kv", "start-db",
    "kill-pd", "kill-kv", "kill-db",
    "pause-pd", "pause-kv", "pause-db",
    "resume-pd", "resume-kv", "resume-db",
})

#: fs that recover rather than break — these target every node
RECOVERY_FS = frozenset({
    "start-pd", "start-kv", "start-db",
    "resume-pd", "resume-kv", "resume-db",
})

SCHEDULE_FS = frozenset({
    "shuffle-leader", "del-shuffle-leader",
    "shuffle-region", "del-shuffle-region",
    "random-merge", "del-random-merge",
})

#: pd-ctl scheduler commands per f (reference: nemesis.clj:74-85 —
#: the reference pipes `sched add …`, but pd-ctl's actual command
#: table spells it `scheduler`; `sched` is rejected, which the
#: reference's own swallow-the-error handler hides)
_SCHEDULERS = {
    "shuffle-leader": ("scheduler", "add", "shuffle-leader-scheduler"),
    "del-shuffle-leader":
        ("scheduler", "remove", "shuffle-leader-scheduler"),
    "shuffle-region": ("scheduler", "add", "shuffle-region-scheduler"),
    "del-shuffle-region":
        ("scheduler", "remove", "shuffle-region-scheduler"),
    "random-merge": ("scheduler", "add", "random-merge-scheduler"),
    "del-random-merge": ("scheduler", "remove", "random-merge-scheduler"),
}


class TidbProcessNemesis(Nemesis):
    """Kill, start, pause, and resume pd-server, tikv-server, and
    tidb-server independently (reference: nemesis.clj:19-53
    process-nemesis)."""

    def __init__(self, db):
        self.db = db

    def setup(self, test):
        return self

    def invoke(self, test, op):
        f = op["f"]
        nodes = list(test["nodes"])
        if f not in RECOVERY_FS:
            nodes = random_nonempty_subset(nodes, gen.rng)
        # "If the op wants to give us nodes, that's great"
        nodes = op.get("value") or nodes
        db = self.db
        actions = {
            "start-pd": db.start_pd, "start-kv": db.start_kv,
            "start-db": db.start_db,
            "kill-pd": db.stop_pd, "kill-kv": db.stop_kv,
            "kill-db": db.stop_db,
            "pause-pd": db.pause_pd, "pause-kv": db.pause_kv,
            "pause-db": db.pause_db,
            "resume-pd": db.resume_pd, "resume-kv": db.resume_kv,
            "resume-db": db.resume_db,
        }
        res = control.on_nodes(test, nodes, actions[f])
        return {**op, "type": "info",
                "value": {str(k): str(v) for k, v in res.items()}}

    def teardown(self, test):
        pass

    def fs(self):
        return PROCESS_FS


class ScheduleNemesis(Nemesis):
    """Add/remove PD stress-test schedulers (shuffle-leader,
    shuffle-region, random-merge) through pd-ctl on one node
    (reference: nemesis.clj:55-87 schedule-nemesis; a failed pd-ctl is
    recorded, not raised — :66-68 swallows it too)."""

    def __init__(self, db):
        self.db = db

    def setup(self, test):
        return self

    def invoke(self, test, op):
        node = gen.rng.choice(list(test["nodes"]))

        def act(test, node):
            try:
                self.db.pd_ctl(test, node, *_SCHEDULERS[op["f"]])
                return "ok"
            except Exception as e:  # noqa: BLE001
                return f"failed: {e!r}"[:120]

        res = control.on_nodes(test, [node], act)
        return {**op, "type": "info",
                "value": {str(k): str(v) for k, v in res.items()}}

    def teardown(self, test):
        pass

    def fs(self):
        return SCHEDULE_FS


class SlowPrimaryNemesis(Nemesis):
    """Create a slow, isolated PD primary: pick a random PD member,
    restart every pd-server under faketime (rate 0.1 on the victim,
    1.0 elsewhere), transfer PD leadership onto the slow node, then cut
    it off in a minority partition.  Because its clock runs slow it may
    fail to step down before the majority elects a faster leader —
    two primaries issuing timestamps concurrently (reference:
    nemesis.clj:89-147 slow-primary-nemesis; the partition is healed by
    the shared partitioner's :stop-partition, as the reference's
    slow-primary-generator does)."""

    def __init__(self, db):
        self.db = db

    def setup(self, test):
        return self

    def invoke(self, test, op):
        db = self.db
        nodes = list(test["nodes"])
        contact = nodes[0]
        members = db.pd_members(test, contact)
        if not isinstance(members, dict) or not members.get("members"):
            return {**op, "type": "info", "value": "failed",
                    "error": "pd-members-unreachable"}
        slow_leader = gen.rng.choice(members["members"])
        name = slow_leader.get("name")
        slow_node = next(
            (n for n in nodes if db._pd_name(test, n) == name), None
        )
        if slow_node is None:
            return {**op, "type": "info", "value": "failed",
                    "error": f"member {name!r} not in node list"}

        def reclock(test, node):
            rate = 0.1 if node == slow_node else 1.0
            try:
                faketime.wrap(f"{db.dir}/bin/pd-server", rate=rate)
            except Exception as e:  # noqa: BLE001
                return f"faketime-failed: {e!r}"[:120]
            db.stop_pd(test, node)
            db.start_pd(test, node)
            return f"rate={rate}"

        reclocked = control.on_nodes(test, nodes, reclock)
        # a full PD restart has no leader for a while — transferring
        # into the void silently degrades the scenario to partitioning
        # a random member (reference awaits db/pd-leader first,
        # nemesis.clj:119-121)
        deadline = _time.monotonic() + 60
        while (
            not isinstance(db.pd_leader(test, contact), dict)
            and _time.monotonic() < deadline
        ):
            _time.sleep(1)
        transfer = db.pd_transfer_leader(test, contact, name)

        # isolate the slow leader in a minority, through the same net
        # selection the shared partitioner uses (test["net"] when a
        # test supplies one, iptables otherwise)
        fast = [n for n in nodes if n != slow_node]
        gen.rng.shuffle(fast)
        grudge = complete_grudge(bisect([slow_node] + fast))
        net_mod.drop_all(test, grudge)
        return {**op, "type": "info",
                "value": {
                    "slow-node": str(slow_node),
                    "reclocked": {str(k): str(v)
                                  for k, v in reclocked.items()},
                    "transfer-status": transfer[0],
                    "isolated": True,
                }}

    def teardown(self, test):
        pass

    def fs(self):
        return frozenset({"slow-primary"})


def full_nemesis(db) -> Nemesis:
    """(reference: nemesis.clj:149-166 full-nemesis)"""
    return compose([
        (PROCESS_FS, TidbProcessNemesis(db)),
        (SCHEDULE_FS, ScheduleNemesis(db)),
        (frozenset({"slow-primary"}), SlowPrimaryNemesis(db)),
        ({"start-partition": "start", "stop-partition": "stop"},
         partitioner()),
        ({"reset-clock": "reset", "strobe-clock": "strobe",
          "check-clock-offsets": "check-offsets", "bump-clock": "bump"},
         nt.clock_nemesis()),
    ])


def _op(f, value=None, **extra):
    return {"type": "info", "f": f, "value": value, **extra}


def partition_one_gen(test, ctx):
    """Isolate one random node (reference: nemesis.clj:170-176)."""
    return _op("start-partition",
               complete_grudge(split_one(list(test["nodes"]))),
               partition_type="single-node")


def partition_pd_leader_gen(test, ctx):
    """Isolate the current PD leader in a minority (reference:
    nemesis.clj:178-188).  Falls back to a random loner when PD is
    unreachable — a dead PD mustn't park the fault schedule."""
    nodes = list(test["nodes"])
    db = test.get("db")
    leader = None
    if db is not None and hasattr(db, "pd_leader_node"):
        leader = db.pd_leader_node(test, gen.rng.choice(nodes))
    if leader is None:
        leader = gen.rng.choice(nodes)
    followers = [n for n in nodes if n != leader]
    gen.rng.shuffle(followers)
    grudge = complete_grudge([[leader], followers])
    return _op("start-partition", grudge, partition_type="pd-leader")


def partition_half_gen(test, ctx):
    """(reference: nemesis.clj:190-195)"""
    nodes = list(test["nodes"])
    gen.rng.shuffle(nodes)
    return _op("start-partition", complete_grudge(bisect(nodes)),
               partition_type="half")


def partition_ring_gen(test, ctx):
    """(reference: nemesis.clj:197-202)"""
    return _op("start-partition", majorities_ring(list(test["nodes"])),
               partition_type="ring")


def clock_gen():
    """The standard clock mix with tidb's f names (reference:
    nemesis.clj:209-216 clock-gen)."""
    return gen.f_map(
        {"check-offsets": "check-clock-offsets", "reset": "reset-clock",
         "strobe": "strobe-clock", "bump": "bump-clock"},
        nt.clock_gen(),
    )


def expand_options(n: dict) -> dict:
    """:kill → all three components, etc. (reference: nemesis.clj
    :361-380 expand-options)."""
    n = dict(n)
    if n.get("kill"):
        n["kill-pd"] = n["kill-kv"] = n["kill-db"] = True
    if n.get("pause"):
        n["pause-pd"] = n["pause-kv"] = n["pause-db"] = True
    if n.get("schedules"):
        n["shuffle-leader"] = n["shuffle-region"] = True
        n["random-merge"] = True
    if n.get("partition"):
        n["partition-one"] = n["partition-pd-leader"] = True
        n["partition-half"] = n["partition-ring"] = True
    return n


def _opt_mix(n: dict, possible: dict):
    gens = [g for opt, g in possible.items() if n.get(opt)]
    return gen.mix(gens) if gens else None


def mixed_generator(n: dict):
    """Flip-flops between each enabled fault family and its single
    recovery, staggered by the interval (reference: nemesis.clj
    :218-283 mixed-generator)."""
    def o(possible, recovery):
        m = _opt_mix(n, possible)
        return gen.flip_flop(m, gen.repeat(recovery)) if m else None

    modes = [
        o({"kill-pd": lambda t, c: _op("kill-pd")}, _op("start-pd")),
        o({"kill-kv": lambda t, c: _op("kill-kv")}, _op("start-kv")),
        o({"kill-db": lambda t, c: _op("kill-db")}, _op("start-db")),
        o({"pause-pd": lambda t, c: _op("pause-pd")}, _op("resume-pd")),
        o({"pause-kv": lambda t, c: _op("pause-kv")}, _op("resume-kv")),
        o({"pause-db": lambda t, c: _op("pause-db")}, _op("resume-db")),
        o({"shuffle-leader": lambda t, c: _op("shuffle-leader")},
          _op("del-shuffle-leader")),
        o({"shuffle-region": lambda t, c: _op("shuffle-region")},
          _op("del-shuffle-region")),
        o({"random-merge": lambda t, c: _op("random-merge")},
          _op("del-random-merge")),
        o({"partition-one": partition_one_gen,
           "partition-pd-leader": partition_pd_leader_gen,
           "partition-half": partition_half_gen,
           "partition-ring": partition_ring_gen},
          _op("stop-partition")),
        _opt_mix(n, {"clock-skew": clock_gen()}),
    ]
    modes = [m for m in modes if m is not None]
    if not modes:
        return None
    interval = n.get("interval", 10)
    if n.get("schedule") == "fixed":
        return gen.delay(interval, gen.mix(modes))
    return gen.stagger(interval, gen.mix(modes))


def final_generator(n: dict):
    """Recover everything the enabled faults may have broken
    (reference: nemesis.clj:285-306 final-generator)."""
    fs = []
    if n.get("clock-skew"):
        fs.append("reset-clock")
    for comp in ("pd", "kv", "db"):
        if n.get(f"pause-{comp}"):
            fs.append(f"resume-{comp}")
    for comp in ("pd", "kv", "db"):
        if n.get(f"kill-{comp}"):
            fs.append(f"start-{comp}")
    if n.get("shuffle-leader"):
        fs.append("del-shuffle-leader")
    if n.get("shuffle-region"):
        fs.append("del-shuffle-region")
    if n.get("random-merge"):
        fs.append("del-random-merge")
    if any(n.get(k) for k in
           ("partition-one", "partition-pd-leader", "partition-half",
            "partition-ring", "slow-primary")):
        fs.append("stop-partition")
    return [_op(f) for f in fs] or None


def restart_kv_without_pd_generator():
    """Pause all PDs, restart all KVs, wait, unpause: the cluster
    should recover, but a finite KV retry loop makes it fail
    (reference: nemesis.clj:308-320)."""
    def all_nodes(f):
        return lambda test, ctx: _op(f, list(test["nodes"]))

    return gen.phases(
        gen.sleep(10),
        gen.once(all_nodes("kill-kv")),
        gen.once(all_nodes("pause-pd")),
        [_op("start-kv")],
        gen.sleep(70),
        [_op("resume-pd")],
    )


def slow_primary_generator():
    """Alternate slow-primary windows with partition heals (reference:
    nemesis.clj:322-340 slow-primary-generator)."""
    return gen.cycle([
        _op("slow-primary"),
        gen.sleep(30),
        _op("stop-partition"),
        gen.sleep(30),
    ])


def full_generator(n: dict):
    """Special-case schedules take the whole generator; :long-recovery
    alternates 120 s fault windows with recovery + 60 s calm; else the
    plain mix (reference: nemesis.clj:342-359 full-generator)."""
    special = [f for f in ("restart-kv-without-pd", "slow-primary")
               if n.get(f)]
    if special:
        # a special schedule takes the whole generator; silently
        # dropping other requested faults would report scenarios never
        # exercised (the same contract suite_nemesis_package enforces)
        others = sorted(
            f for f in KNOWN_FAULTS
            if n.get(f) and f not in special
        )
        if others or len(special) > 1:
            raise ValueError(
                f"special schedule {special[0]!r} owns the whole fault "
                f"schedule; run {sorted(set(others) | set(special[1:]))} "
                "in a separate test"
            )
        if special[0] == "restart-kv-without-pd":
            return restart_kv_without_pd_generator()
        return slow_primary_generator()
    mixed = mixed_generator(n)
    if mixed is None:
        return None
    if n.get("long-recovery"):
        final = final_generator(n) or []
        window = gen.phases(
            gen.time_limit(120, mixed),
            list(final),
            gen.sleep(60),
        )
        return gen.cycle(window)
    return mixed


def package(opts: dict, db) -> dict:
    """The {nemesis, generator, final_generator} bundle build_test
    consumes, from a fault-name list (e.g. ["kill-kv",
    "partition-pd-leader", "clock-skew"]) or shorthands ("kill",
    "pause", "schedules", "partition") (reference: nemesis.clj:382-389
    nemesis)."""
    n = expand_options(
        {f: True for f in opts.get("faults", ())}
        | {"interval": opts.get("interval", 10),
           "long-recovery": bool(opts.get("long-recovery")),
           "schedule": opts.get("schedule")}
    )
    return {
        "nemesis": full_nemesis(db),
        "generator": full_generator(n),
        "final_generator": final_generator(n),
        "perf": {
            ("kill", frozenset({"kill-pd", "kill-kv", "kill-db"}),
             frozenset({"start-pd", "start-kv", "start-db"}), "#E9A4A0"),
            ("pause", frozenset({"pause-pd", "pause-kv", "pause-db"}),
             frozenset({"resume-pd", "resume-kv", "resume-db"}),
             "#A0B1E9"),
            ("partition", frozenset({"start-partition", "slow-primary"}),
             frozenset({"stop-partition"}), "#A0E9DB"),
        },
    }


#: fault names this module understands; tidb.test() routes to this
#: package when any appears in opts["faults"]
KNOWN_FAULTS = (
    (PROCESS_FS - RECOVERY_FS)
    | {f for f in SCHEDULE_FS if not f.startswith("del-")}
    | {
        "kill", "pause", "schedules", "partition",
        "partition-one", "partition-pd-leader", "partition-half",
        "partition-ring", "clock-skew", "slow-primary",
        "restart-kv-without-pd",
    }
)
