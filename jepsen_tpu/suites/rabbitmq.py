"""RabbitMQ suite.

Reference: rabbitmq/src/jepsen/rabbitmq.clj — install the
rabbitmq-server deb + erlang (:25-42), share an erlang cookie so nodes
can cluster (:43-50), ``rabbitmqctl join_cluster`` the nodes, and run
a **total-queue** workload over AMQP: durable queue declare (:137-141),
persistent publishes (:152-160), basic.get + ack dequeues with an
``:empty`` failure when the queue has nothing (:104-115), and a final
drain (:165-170).

The client rides the from-scratch AMQP 0-9-1 implementation in
:mod:`.proto.amqp`.
"""

from __future__ import annotations

from typing import Optional

from .. import client as client_mod
from .. import codec
from .. import control
from ..control import util as cu
from ..os_setup import debian
from . import common
from .proto import IndeterminateError
from .proto.amqp import AmqpClient, AmqpError

PORT = 5672
QUEUE = "jepsen.queue"  # (reference: rabbitmq.clj:102)
VERSION = "3.5.6"
COOKIE = "jepsen-rabbitmq"


class RabbitDB(common.DaemonDB):
    logfile = "/var/log/rabbitmq/rabbit.log"
    proc_name = "beam.smp"

    def __init__(self, opts: Optional[dict] = None):
        super().__init__(opts)
        self.version = (opts or {}).get("version", VERSION)

    def install(self, test, node):
        # (reference: rabbitmq.clj:25-42)
        debian.install(["erlang-nox"])
        url = (
            "http://www.rabbitmq.com/releases/rabbitmq-server/"
            f"v{self.version}/rabbitmq-server_{self.version}-1_all.deb"
        )
        with control.su():
            deb = cu.cached_wget(url)
            control.execute("dpkg", "-i", deb, check=False)
            # shared cookie for clustering (reference: :43-50)
            control.execute("service", "rabbitmq-server", "stop",
                            check=False)
            cu.write_file(COOKIE, "/var/lib/rabbitmq/.erlang.cookie")
            control.execute("chown", "rabbitmq:rabbitmq",
                            "/var/lib/rabbitmq/.erlang.cookie", check=False)
            control.execute("chmod", "400",
                            "/var/lib/rabbitmq/.erlang.cookie", check=False)

    def start(self, test, node):
        with control.su():
            control.execute("service", "rabbitmq-server", "start",
                            check=False)

    def setup(self, test, node):
        super().setup(test, node)
        primary = test["nodes"][0]
        if node != primary:
            with control.su():
                control.execute("rabbitmqctl", "stop_app", check=False)
                control.execute("rabbitmqctl", "join_cluster",
                                f"rabbit@{primary}", check=False)
                control.execute("rabbitmqctl", "start_app", check=False)

    def kill(self, test, node):
        with control.su():
            control.execute("service", "rabbitmq-server", "stop",
                            check=False)
            cu.grepkill("beam.smp")

    def await_ready(self, test, node):
        cu.await_tcp_port(PORT, timeout_s=300)

    def wipe(self, test, node):
        with control.su():
            control.execute("rm", "-rf", "/var/lib/rabbitmq/mnesia",
                            check=False)


class RabbitQueueClient(client_mod.Client):
    """(reference: rabbitmq.clj:118-170 QueueClient)"""

    def __init__(self, opts: Optional[dict] = None):
        self.opts = opts or {}
        self.conn: Optional[AmqpClient] = None

    def open(self, test, node):
        c = type(self)(self.opts)
        c.conn = AmqpClient(
            self.opts.get("host", str(node)),
            self.opts.get("port", PORT),
            user=self.opts.get("user", "guest"),
            password=self.opts.get("password", "guest"),
            timeout=self.opts.get("timeout", 10.0),
        )
        c.conn.connect()
        return c

    def setup(self, test):
        try:
            self.conn.queue_declare(QUEUE, durable=True)
        except (AmqpError, IndeterminateError):
            pass

    def teardown(self, test):
        try:
            self.conn.queue_purge(QUEUE)
        except (AmqpError, IndeterminateError):
            pass

    def _dequeue(self, op):
        got = self.conn.basic_get(QUEUE)
        if got is None:
            return {**op, "type": "fail", "error": "empty"}
        tag, body = got
        self.conn.basic_ack(tag)
        return {**op, "type": "ok", "value": codec.decode(body)}

    def invoke(self, test, op):
        try:
            if op["f"] == "enqueue":
                self.conn.basic_publish(
                    codec.encode(op["value"]), QUEUE, persistent=True
                )
                return {**op, "type": "ok"}
            if op["f"] == "dequeue":
                return self._dequeue(op)
            if op["f"] == "drain":
                values = []
                while True:
                    r = self._dequeue(op)
                    if r["type"] != "ok":
                        return {**op, "type": "ok", "value": values}
                    values.append(r["value"])
            raise ValueError(f"unknown f {op['f']!r}")
        except IndeterminateError as e:
            return {**op, "type": "info", "error": str(e)}
        except AmqpError as e:
            return {**op, "type": "fail", "error": str(e)}

    def close(self, test):
        if self.conn:
            self.conn.close()


def db(opts: Optional[dict] = None):
    return RabbitDB(opts)


def client(opts: Optional[dict] = None):
    return RabbitQueueClient(opts)


def workloads(opts: Optional[dict] = None) -> dict:
    return {
        "queue": common.queue_workload(dict(opts or {})),
        "linearizable-queue": common.linearizable_queue_workload(
            dict(opts or {})
        ),
    }


def test(opts: Optional[dict] = None) -> dict:
    opts = dict(opts or {})
    wname = opts.get("workload", "queue")
    w = workloads(opts)[wname]
    return common.build_test(
        f"rabbitmq-{wname}", opts, db=RabbitDB(opts),
        client=RabbitQueueClient(opts), workload=w,
    )
