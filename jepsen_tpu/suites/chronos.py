"""Chronos (Mesos job scheduler) suite.

Reference: chronos/src/jepsen/chronos.clj + chronos/checker.clj +
mesosphere.clj — install mesos + zookeeper + chronos from the
mesosphere apt repo (mesosphere.clj / chronos.clj:56-77), submit
repeating ISO8601 jobs (``R<count>/<start>/PT<interval>S``,
chronos.clj:103-131) whose shell command logs invocation/completion
times into ``/tmp/chronos-test/`` (chronos.clj:109-117), then read the
run files off every node and check that each job ran inside each of its
target windows ``[start + k*interval, + epsilon + duration]``
(checker.clj:30-213).

The checker here uses greedy interval matching per job (the reference
solves the same matching with a backtracking solution search —
checker.clj:78-191; greedy is exact for non-overlapping target
windows, and we flag overlapping windows as :unknown rather than
mis-assign runs).
"""

from __future__ import annotations

import datetime as dt
from typing import Dict, List, Optional

from .. import checker as checker_mod
from .. import client as client_mod
from .. import control
from .. import generator as gen
from ..control import util as cu
from ..os_setup import debian
from . import common
from .proto import IndeterminateError
from .proto.http import HttpError, JsonHttpClient

PORT = 4400
JOB_DIR = "/tmp/chronos-test/"  # (reference: chronos.clj:26)


def interval_str(job: dict) -> str:
    """R<count>/<ISO start>/PT<interval>S (reference: chronos.clj:103-108)"""
    start = job["start"].strftime("%Y-%m-%dT%H:%M:%S.000Z")
    return f"R{job['count']}/{start}/PT{job['interval']}S"


def command(job: dict) -> str:
    """Shell command logging job name + invocation/completion times.
    (reference: chronos.clj:110-117)"""
    return (
        f"MEW=$(mktemp -p {JOB_DIR}); "
        f"echo \"{job['name']}\" >> $MEW; "
        "date -u -Ins >> $MEW; "
        f"sleep {job['duration']}; "
        "date -u -Ins >> $MEW;"
    )


def job_to_json(job: dict) -> dict:
    """(reference: chronos.clj:119-131 job->json)"""
    return {
        "name": str(job["name"]),
        "command": command(job),
        "schedule": interval_str(job),
        "scheduleTimeZone": "UTC",
        "owner": "jepsen@jepsen.io",
        "epsilon": f"PT{job['epsilon']}S",
        "mem": 1,
        "disk": 1,
        "cpus": 0.001,
        "async": False,
    }


class ChronosDB(common.DaemonDB):
    """Installs zookeeper + mesos + chronos from the mesosphere repo.
    (reference: chronos.clj:56-77 db, mesosphere.clj)"""

    logfile = "/var/log/chronos.log"

    def __init__(self, opts: Optional[dict] = None):
        super().__init__(opts)

    def install(self, test, node):
        with control.su():
            control.execute(
                "apt-key", "adv", "--keyserver", "keyserver.ubuntu.com",
                "--recv", "E56151BF", check=False,
            )
            cu.write_file(
                "deb http://repos.mesosphere.com/debian jessie main\n",
                "/etc/apt/sources.list.d/mesosphere.list",
            )
            control.execute("apt-get", "update", check=False)
        debian.install(["zookeeper", "mesos", "chronos"])
        with control.su():
            control.execute("mkdir", "-p", JOB_DIR)

    #: masters run on the first MASTER_COUNT nodes (reference:
    #: mesosphere.clj:17 master-count, :60-67 start-master!)
    MASTER_COUNT = 3

    def zk_uri(self, test) -> str:
        """(reference: mesosphere.clj:38-46 zk-uri)"""
        hosts = ",".join(f"{n}:2181" for n in test["nodes"])
        return f"zk://{hosts}/mesos"

    def configure(self, test, node):
        """Mesos + chronos read the zk ensemble URI and master quorum
        from config files (reference: mesosphere.clj:48-57
        configure!).  The stock Debian zookeeper starts standalone, so
        the ensemble itself must be configured too (zoo.cfg server
        list + per-node myid) or the masters would elect leaders in
        disjoint ZK namespaces."""
        nodes = list(test["nodes"])
        masters = min(self.MASTER_COUNT, len(nodes))
        ensemble = "".join(
            f"server.{i + 1}={n}:2888:3888\n"
            for i, n in enumerate(nodes)
        )
        with control.su():
            cu.write_file(
                "tickTime=2000\ninitLimit=10\nsyncLimit=5\n"
                "dataDir=/var/lib/zookeeper\nclientPort=2181\n"
                + ensemble,
                "/etc/zookeeper/conf/zoo.cfg",
            )
            control.execute("mkdir", "-p", "/var/lib/zookeeper")
            cu.write_file(f"{nodes.index(node) + 1}\n",
                          "/var/lib/zookeeper/myid")
            cu.write_file(self.zk_uri(test) + "\n", "/etc/mesos/zk")
            cu.write_file(f"{masters // 2 + 1}\n",
                          "/etc/mesos-master/quorum")

    def master_nodes(self, test):
        return sorted(test["nodes"])[: self.MASTER_COUNT]

    def setup(self, test, node):
        self.install(test, node)
        self.configure(test, node)
        services = ["zookeeper"]
        # masters only on the first master-count sorted nodes
        # (reference: mesosphere.clj:60-67); every node runs an agent
        if node in self.master_nodes(test):
            services.append("mesos-master")
        services += ["mesos-slave", "chronos"]
        with control.su():
            for svc in services:
                control.execute("service", svc, "start", check=False)
        cu.await_tcp_port(PORT, timeout_s=120)

    def teardown(self, test, node):
        with control.su():
            for svc in ("chronos", "mesos-slave", "mesos-master", "zookeeper"):
                control.execute("service", svc, "stop", check=False)
            control.execute("rm", "-rf", JOB_DIR)

    # Process: chronos runs under service management
    def start(self, test, node):
        with control.su():
            control.execute("service", "chronos", "start", check=False)

    def kill(self, test, node):
        with control.su():
            control.execute("service", "chronos", "stop", check=False)
            cu.grepkill("chronos")

    def pause(self, test, node):
        cu.signal("chronos", "STOP")

    def resume(self, test, node):
        cu.signal("chronos", "CONT")

    def log_files(self, test, node):
        return ["/var/log/mesos/mesos-master.INFO", self.logfile]


def read_runs(test: dict) -> List[dict]:
    """Collect {node, name, start, end} run records from every node's
    job dir.  (reference: chronos.clj:160-171 read-runs)"""
    def per_node(test, node):
        runs = []
        for f in cu.ls_full(JOB_DIR):
            raw = cu.file_contents(f)
            lines = raw.strip().split("\n")
            if not lines or not lines[0].strip():
                continue
            name = int(lines[0])
            times = [
                _parse_time(t) for t in lines[1:3] if t.strip()
            ]
            runs.append(
                {
                    "node": control.current_node(),
                    "name": name,
                    "start": times[0] if times else None,
                    "end": times[1] if len(times) > 1 else None,
                }
            )
        return runs

    out = control.on_nodes(test, per_node)
    return [r for rs in out.values() for r in rs]


def _parse_time(t: str) -> Optional[dt.datetime]:
    # date -u -Ins may emit comma fractional separators
    # (reference: chronos.clj:143-149 parse-file-time)
    t = t.strip().replace(",", ".")
    try:
        return dt.datetime.fromisoformat(t)
    except ValueError:
        return None


class ChronosClient(client_mod.Client):
    """add-job → POST /scheduler/iso8601; read → read-runs off nodes.
    (reference: chronos.clj:173-198)"""

    def __init__(self, opts: Optional[dict] = None):
        self.opts = opts or {}
        self.conn: Optional[JsonHttpClient] = None

    def open(self, test, node):
        c = type(self)(self.opts)
        c.conn = JsonHttpClient(
            self.opts.get("host", str(node)),
            self.opts.get("port", PORT),
            timeout=10.0,
        )
        return c

    def invoke(self, test, op):
        try:
            if op["f"] == "add-job":
                self.conn.post(
                    "/scheduler/iso8601", job_to_json(op["value"]),
                    ok=(200, 204),
                )
                return {**op, "type": "ok"}
            if op["f"] == "read":
                return {**op, "type": "ok", "value": read_runs(test)}
            raise ValueError(f"unknown f {op['f']!r}")
        except IndeterminateError as e:
            return {**op, "type": "info", "error": str(e)}
        except HttpError as e:
            return {**op, "type": "fail", "error": f"{e.status}: {e.body}"}

    def close(self, test):
        if self.conn:
            self.conn.close()


# ---------------------------------------------------------------------
# Checker (reference: chronos/checker.clj)
# ---------------------------------------------------------------------


def job_targets(job: dict, final_time: dt.datetime) -> List[tuple]:
    """Target windows [start + k*interval, + epsilon + duration] for
    runs scheduled before final_time.  (reference: checker.clj:30-47)"""
    out = []
    for k in range(job["count"]):
        lo = job["start"] + dt.timedelta(seconds=k * job["interval"])
        if lo > final_time:
            break
        hi = lo + dt.timedelta(seconds=job["epsilon"] + job["duration"])
        out.append((lo, hi))
    return out


class _ChronosChecker(checker_mod.Checker):
    def check(self, test, history, opts=None):
        jobs: Dict[int, dict] = {}
        runs: List[dict] = []
        final_time = None
        for op in history:
            if op["type"] == "ok" and op["f"] == "add-job":
                j = op["value"]
                jobs[j["name"]] = j
            elif op["type"] == "ok" and op["f"] == "read":
                runs = op["value"]
                final_time = _nanos_to_time(test, op.get("time", 0))
        if final_time is None:
            return {"valid?": "unknown", "error": "no final read"}

        bad_jobs = []
        unknown_jobs = []
        for name, job in sorted(jobs.items()):
            targets = job_targets(job, final_time)
            # greedy matching is exact only for non-overlapping windows;
            # a shortfall on overlapping windows may be misassignment,
            # so it downgrades to :unknown instead of :invalid
            # (the reference solves the matching exactly —
            # chronos/checker.clj:78-191 job-solution)
            overlapping = any(
                b > c for (a, b), (c, d) in zip(targets, targets[1:])
            )
            mine = sorted(
                (r["start"] for r in runs
                 if r["name"] == name and r["start"] is not None),
            )
            hits, i = 0, 0
            for lo, hi in targets:
                while i < len(mine) and mine[i] < lo:
                    i += 1
                if i < len(mine) and mine[i] <= hi:
                    hits += 1
                    i += 1
            if hits < len(targets):
                entry = {"name": name, "targets": len(targets), "hits": hits}
                if overlapping:
                    unknown_jobs.append(entry)
                else:
                    bad_jobs.append(entry)
        if bad_jobs:
            valid = False
        elif unknown_jobs:
            valid = "unknown"
        else:
            valid = True
        return {
            "valid?": valid,
            "job-count": len(jobs),
            "run-count": len(runs),
            "bad-jobs": bad_jobs,
            "unknown-jobs": unknown_jobs,
        }


def _nanos_to_time(test: dict, nanos: int) -> dt.datetime:
    base = test.get("start-time") or dt.datetime.now(dt.timezone.utc)
    if isinstance(base, (int, float)):
        base = dt.datetime.fromtimestamp(base, dt.timezone.utc)
    return base + dt.timedelta(seconds=nanos / 1e9)


def checker() -> checker_mod.Checker:
    return _ChronosChecker()


def generator_jobs(opts: Optional[dict] = None):
    """Emit add-job ops with increasing names and randomized schedules.
    (reference: chronos.clj:204-221)"""
    opts = opts or {}
    state = {"n": 0}

    def next_job(test, ctx):
        state["n"] += 1
        now = dt.datetime.now(dt.timezone.utc)
        return {
            "type": "invoke",
            "f": "add-job",
            "value": {
                "name": state["n"],
                "start": now + dt.timedelta(seconds=gen.rng.randrange(30)),
                "count": gen.rng.randrange(1, 5),
                "interval": gen.rng.randrange(30, 120),
                "epsilon": gen.rng.randrange(5, 30),
                "duration": gen.rng.randrange(1, 10),
            },
        }

    return next_job


def db(opts: Optional[dict] = None):
    return ChronosDB(opts)


def client(opts: Optional[dict] = None):
    return ChronosClient(opts)


def workloads(opts: Optional[dict] = None) -> dict:
    opts = dict(opts or {})
    final = gen.clients(
        gen.each_thread(gen.once({"type": "invoke", "f": "read",
                                  "value": None}))
    )
    return {
        "jobs": {
            "generator": gen.stagger(10, generator_jobs(opts)),
            "final-generator": final,
            "checker": checker(),
        }
    }


def test(opts: Optional[dict] = None) -> dict:
    opts = dict(opts or {})
    w = workloads(opts)["jobs"]
    return common.build_test(
        "chronos", opts, db=ChronosDB(opts), client=ChronosClient(opts),
        workload=w,
    )
