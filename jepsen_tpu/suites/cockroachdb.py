"""CockroachDB suite.

Reference: cockroachdb/src/jepsen/cockroach.clj + cockroach/{auto,
client,register,bank,sets,monotonic,sequential,adya,comments,nemesis,
runner}.clj — install a cockroach tarball to /opt/cockroach
(auto.clj:143-150), start ``cockroach start --insecure --join=…`` on
every node (auto.clj:49-77), and run register/bank/sets/monotonic/g2
workloads over JDBC with retry handling (client.clj).  Clients here
ride the pgwire protocol (cockroach speaks it natively) via
:mod:`.sql`, dialect ``cockroach`` (UPSERT, 40001 retry errors).
"""

from __future__ import annotations

from typing import Optional

from ..control import util as cu
from ..control import execute, sudo
from . import common, sql

DIR = "/opt/cockroach"  # (reference: auto.clj:33)
PORT = 26257
HTTP_PORT = 8080
DEFAULT_TARBALL = (
    "https://binaries.cockroachdb.com/cockroach-v2.1.7.linux-amd64.tgz"
)


class CockroachDB(common.DaemonDB):
    dir = DIR
    binary = "cockroach"
    logfile = f"{DIR}/logs/cockroach.stderr"
    pidfile = f"{DIR}/cockroach.pid"

    def __init__(self, opts: Optional[dict] = None):
        super().__init__(opts)
        self.tarball = (opts or {}).get("tarball", DEFAULT_TARBALL)

    def install(self, test, node):
        with sudo():
            cu.install_archive(self.tarball, DIR)
            execute("mkdir", "-p", f"{DIR}/logs")

    def start_args(self, test, node):
        # (reference: auto.clj:49-77 start!/join flags)
        join = ",".join(f"{n}:{PORT}" for n in test["nodes"])
        return [
            "start", "--insecure",
            "--store", f"path={DIR}/data",
            "--listen-addr", f"0.0.0.0:{PORT}",
            "--advertise-addr", f"{node}:{PORT}",
            "--http-addr", f"0.0.0.0:{HTTP_PORT}",
            "--join", join,
            "--background",
        ]

    def setup(self, test, node):
        super().setup(test, node)
        if node == test["nodes"][0]:
            # first node bootstraps the cluster
            execute(f"{DIR}/cockroach", "init", "--insecure",
                    "--host", f"{node}:{PORT}", check=False)

    def await_ready(self, test, node):
        cu.await_tcp_port(PORT, timeout_s=120)

    def wipe(self, test, node):
        with sudo():
            execute("rm", "-rf", f"{DIR}/data", f"{DIR}/logs")


def _opts(opts: Optional[dict]) -> dict:
    o = dict(opts or {})
    o.setdefault("dialect", "cockroach")
    o.setdefault("port", PORT)
    o.setdefault("user", "root")
    o.setdefault("database", "defaultdb")
    return o


def db(opts: Optional[dict] = None):
    return CockroachDB(opts)


def client(opts: Optional[dict] = None):
    return sql.RegisterClient(_opts(opts))


WORKLOADS = ("register", "bank", "set", "list-append")


def workloads(opts: Optional[dict] = None) -> dict:
    from ..workloads import adya
    from . import comments, crdb_sets, monotonic, sequential

    opts = _opts(opts)
    out = {w: common.generic_workload(w, opts) for w in WORKLOADS}
    # suite-specific probes (reference: cockroach/monotonic.clj,
    # sequential.clj, comments.clj, adya.clj, sets.clj)
    out["monotonic"] = monotonic.workload(opts)
    out["sequential"] = sequential.workload(opts)
    out["comments"] = comments.workload(opts)
    out["g2"] = adya.workload(opts)
    out["sets"] = crdb_sets.workload(opts)
    return out


def _client_for(wname: str, opts: dict):
    from . import comments, g2_sql, monotonic, sequential

    if wname == "monotonic":
        return monotonic.MonotonicClient(opts)
    if wname == "sequential":
        return sequential.SequentialClient(opts)
    if wname == "comments":
        return comments.CommentsClient(opts)
    if wname == "g2":
        return g2_sql.G2Client(opts)
    if wname == "sets":
        # cockroach's SetsClient shape == the shared SQL set client
        # (sets.clj:96-131); only the checker differs
        return sql.client_for("set", opts)
    return sql.client_for(wname, opts)


def test(opts: Optional[dict] = None) -> dict:
    from . import crdb_nemesis

    opts = _opts(opts)
    wname = opts.get("workload", "register")
    w = workloads(opts)[wname]
    database = CockroachDB(opts)
    pkg = None
    name = f"cockroachdb-{wname}"
    if opts.get("nemesis"):
        # the named-bundle menu (reference: cockroach/nemesis.clj via
        # runner.clj --nemesis/--nemesis2); generic opts["faults"]
        # still rides build_test's default path when unset
        pkg = crdb_nemesis.package(opts, database)
        # the suffix comes from the menu package — compose_packages
        # below strips non-standard keys like "name"
        name = f"{name}-{pkg['name']}"
        if opts.get("faults"):
            # the menu consumes opts["nemesis"] only — every entry in
            # opts["faults"] is a leftover for the generic packages
            # (known=set(): a menu-named fault in "faults" would
            # otherwise be silently claimed-but-never-run)
            pkg = common.suite_nemesis_package(
                opts, database, pkg, set()
            )
    return common.build_test(
        name, opts, db=database,
        client=_client_for(wname, opts), workload=w,
        nemesis_package=pkg,
    )
