"""Apache Ignite suite.

Reference: ignite/src/jepsen/ignite.clj (+ ignite/{register,bank,
nemesis,runner}.clj) — install the Apache Ignite binary distribution
(ignite-url :62-67), generate a Spring XML config whose
TcpDiscoveryVmIpFinder lists every test node, start ``ignite.sh``, and
run register/bank workloads (the reference drives the Java thin
client).  Here the client uses Ignite's REST API
(``/ignite?cmd=get|put|cas``), which exposes the same atomic cache ops.
"""

from __future__ import annotations

from typing import Optional

from .. import client as client_mod
from .. import independent
from ..control import util as cu
from ..control import execute, sudo
from ..os_setup import debian
from . import common
from .proto import IndeterminateError
from .proto.http import HttpError, JsonHttpClient

VERSION = "2.7.0"
DIR = "/opt/ignite"
REST_PORT = 8080
DISCOVERY_PORT = 47500

CONFIG_PATH = f"{DIR}/config/jepsen.xml"

_CONFIG_TEMPLATE = """<?xml version="1.0" encoding="UTF-8"?>
<beans xmlns="http://www.springframework.org/schema/beans"
       xmlns:xsi="http://www.w3.org/2001/XMLSchema-instance"
       xsi:schemaLocation="http://www.springframework.org/schema/beans
       http://www.springframework.org/schema/beans/spring-beans.xsd">
  <bean id="ignite.cfg"
        class="org.apache.ignite.configuration.IgniteConfiguration">
    <property name="discoverySpi">
      <bean class="org.apache.ignite.spi.discovery.tcp.TcpDiscoverySpi">
        <property name="ipFinder">
          <bean class="org.apache.ignite.spi.discovery.tcp.ipfinder.vm.TcpDiscoveryVmIpFinder">
            <property name="addresses">
              <list>
{addresses}
              </list>
            </property>
          </bean>
        </property>
      </bean>
    </property>
  </bean>
</beans>
"""


class IgniteDB(common.DaemonDB):
    dir = DIR
    binary = "bin/ignite.sh"
    logfile = f"{DIR}/ignite.log"
    pidfile = f"{DIR}/ignite.pid"
    proc_name = "java"  # the server runs under the JVM

    def __init__(self, opts: Optional[dict] = None):
        super().__init__(opts)
        self.version = (opts or {}).get("version", VERSION)
        self.url = (opts or {}).get(
            "url",
            "https://archive.apache.org/dist/ignite/"
            f"{self.version}/apache-ignite-{self.version}-bin.zip",
        )

    def install(self, test, node):
        debian.install(["openjdk-8-jre-headless"])
        with sudo():
            cu.install_archive(self.url, DIR)

    def configure(self, test, node):
        addresses = "\n".join(
            f"                <value>{n}:{DISCOVERY_PORT}</value>"
            for n in test["nodes"]
        )
        with sudo():
            cu.write_file(
                _CONFIG_TEMPLATE.format(addresses=addresses), CONFIG_PATH
            )

    def start_args(self, test, node):
        return [CONFIG_PATH]

    def start_env(self, test, node):
        return {"IGNITE_HOME": DIR}

    def await_ready(self, test, node):
        cu.await_tcp_port(REST_PORT, timeout_s=120)

    def wipe(self, test, node):
        with sudo():
            execute("rm", "-rf", f"{DIR}/work")


class IgniteClient(client_mod.Client):
    """CAS register over the Ignite REST API: cmd=get/put/cas against
    an atomic REPLICATED cache (the semantics the reference's register
    workload gets from cache.get/put/compareAndSet;
    ignite/register.clj)."""

    CACHE = "jepsen"

    def __init__(self, opts: Optional[dict] = None):
        self.opts = opts or {}
        self.conn: Optional[JsonHttpClient] = None

    def open(self, test, node):
        c = type(self)(self.opts)
        c.conn = JsonHttpClient(
            self.opts.get("host", str(node)),
            self.opts.get("port", REST_PORT),
            timeout=10.0,
        )
        return c

    def _cmd(self, params: dict):
        params = {"cacheName": self.CACHE, **params}
        _, body = self.conn.get("/ignite", params=params, ok=(200,))
        if isinstance(body, dict):
            if body.get("successStatus", 0) != 0:
                raise HttpError(200, body.get("error"))
            return body.get("response")
        return body

    def invoke(self, test, op):
        k, v = op["value"] if isinstance(op["value"], (list, tuple)) else (
            0, op["value"])
        try:
            if op["f"] == "read":
                raw = self._cmd({"cmd": "get", "key": str(k)})
                val = int(raw) if raw is not None else None
                return {**op, "type": "ok", "value": independent.kv(k, val)}
            if op["f"] == "write":
                self._cmd({"cmd": "put", "key": str(k), "val": str(v)})
                return {**op, "type": "ok"}
            if op["f"] == "cas":
                old, new = v
                # REST cas: put val1 if current value == val2
                ok = self._cmd(
                    {"cmd": "cas", "key": str(k), "val1": str(new),
                     "val2": str(old)}
                )
                if ok in (True, "true"):
                    return {**op, "type": "ok"}
                return {**op, "type": "fail", "error": "cas-miss"}
            raise ValueError(f"unknown f {op['f']!r}")
        except IndeterminateError as e:
            return {**op, "type": "info", "error": str(e)}
        except HttpError as e:
            return {**op, "type": "fail", "error": f"{e.status}: {e.body}"}

    def close(self, test):
        if self.conn:
            self.conn.close()


class IgniteBankClient(client_mod.Client):
    """Bank transfers with the reference's atomicity through a single
    CAS'd cache entry.

    The reference's bank workload (ignite/bank.clj:19-130) runs
    READ_COMMITTED..SERIALIZABLE cache transactions over n=10 accounts
    seeded with 100 each and checks every read for wrong-n /
    wrong-total / negative balances.  The REST API exposes no
    transactions, so all balances live in ONE serialized entry and a
    transfer is a compareAndSet of the whole vector — the same
    atomic-multi-account semantics, checked by the same invariants
    (workloads/bank.py mirrors the reference's bank-checker)."""

    CACHE = "ACCOUNTS"  # (reference: bank.clj:22 cache-name)
    KEY = "balances"
    CAS_RETRIES = 8

    def __init__(self, opts: Optional[dict] = None):
        self.opts = opts or {}
        self.conn: Optional[JsonHttpClient] = None

    def open(self, test, node):
        c = type(self)(self.opts)
        c.conn = JsonHttpClient(
            self.opts.get("host", str(node)),
            self.opts.get("port", REST_PORT),
            timeout=10.0,
        )
        return c

    def _cmd(self, params: dict):
        params = {"cacheName": self.CACHE, **params}
        _, body = self.conn.get("/ignite", params=params, ok=(200,))
        if isinstance(body, dict):
            if body.get("successStatus", 0) != 0:
                raise HttpError(200, body.get("error"))
            return body.get("response")
        return body

    @staticmethod
    def _decode(raw) -> Optional[dict]:
        if raw in (None, ""):
            return None
        return {
            int(k): int(v)
            for k, v in (kv.split(":") for kv in str(raw).split(","))
        }

    @staticmethod
    def _encode(balances: dict) -> str:
        return ",".join(f"{k}:{v}" for k, v in sorted(balances.items()))

    def setup(self, test):
        # fallbacks mirror the bank workload's defaults
        # (workloads/bank.py test(): accounts range(8), total 100) so
        # a direct-use client seeds what the checker expects
        accounts = test.get("accounts", list(range(8)))
        total = test.get("total-amount", 100)
        per = total // len(accounts)
        init = {a: per for a in accounts}
        init[accounts[0]] += total - per * len(accounts)
        # putIfAbsent: first client in seeds, the rest see it
        self._cmd({"cmd": "add", "key": self.KEY,
                   "val": self._encode(init)})

    def invoke(self, test, op):
        try:
            if op["f"] == "read":
                return {**op, "type": "ok",
                        "value": self._decode(
                            self._cmd({"cmd": "get", "key": self.KEY}))}
            if op["f"] == "transfer":
                t = op["value"]
                for _ in range(self.CAS_RETRIES):
                    raw = self._cmd({"cmd": "get", "key": self.KEY})
                    balances = self._decode(raw)
                    if balances is None:
                        return {**op, "type": "fail", "error": "no-bank"}
                    if balances[t["from"]] < t["amount"]:
                        # the reference's transactions abort overdrafts
                        return {**op, "type": "fail",
                                "error": "insufficient-funds"}
                    balances[t["from"]] -= t["amount"]
                    balances[t["to"]] += t["amount"]
                    ok = self._cmd({
                        "cmd": "cas", "key": self.KEY,
                        "val1": self._encode(balances), "val2": str(raw),
                    })
                    if ok in (True, "true"):
                        return {**op, "type": "ok"}
                return {**op, "type": "fail", "error": "cas-contention"}
            raise ValueError(f"unknown f {op['f']!r}")
        except IndeterminateError as e:
            return {**op, "type": "info", "error": str(e)}
        except HttpError as e:
            return {**op, "type": "fail", "error": f"{e.status}: {e.body}"}

    def close(self, test):
        if self.conn:
            self.conn.close()


def db(opts: Optional[dict] = None):
    return IgniteDB(opts)


def client(opts: Optional[dict] = None):
    return IgniteClient(opts)


def workloads(opts: Optional[dict] = None) -> dict:
    from ..workloads import bank

    opts = dict(opts or {})
    return {
        "register": common.register_workload(opts),
        # reference: ignite/bank.clj (single-entry CAS redesign — see
        # IgniteBankClient)
        "bank": bank.test(opts),
    }


def test(opts: Optional[dict] = None) -> dict:
    opts = dict(opts or {})
    wname = opts.get("workload", "register")
    w = workloads(opts)[wname]
    cl = (IgniteBankClient(opts) if wname == "bank"
          else IgniteClient(opts))
    return common.build_test(
        f"ignite-{wname}", opts, db=IgniteDB(opts), client=cl,
        workload=w,
    )
