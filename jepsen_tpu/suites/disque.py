"""Disque (distributed job queue) suite.

Reference: disque/src/jepsen/disque.clj — build disque from source
(install!:40-53), start ``disque-server`` under start-stop-daemon
(:75-92), ``CLUSTER MEET`` every node to the primary (:94-104), and
run a total-queue workload over the Jedisque client: ADDJOB with
retry/replication params, GETJOB + ACKJOB for dequeues (:140-215).
The client here speaks disque's RESP protocol directly.
"""

from __future__ import annotations

from typing import Optional

from .. import client as client_mod
from .. import control
from ..control import util as cu
from ..os_setup import debian
from . import common
from .proto import IndeterminateError, ProtocolError
from .proto.resp import RespClient

DIR = "/opt/disque"
PORT = 7711
QUEUE = "jepsen"
JOB_TIMEOUT_MS = 100       # (reference: disque.clj addjob timeout)
GET_TIMEOUT_MS = 100


class DisqueDB(common.DaemonDB):
    dir = DIR
    binary = "src/disque-server"
    logfile = f"{DIR}/disque.log"
    pidfile = f"{DIR}/disque.pid"
    proc_name = "disque-server"

    def __init__(self, opts: Optional[dict] = None):
        super().__init__(opts)
        self.version = (opts or {}).get("version", "master")

    def install(self, test, node):
        # (reference: disque.clj:40-53 — git build)
        debian.install(["git-core", "build-essential"])
        with control.su():
            control.execute(
                "bash", "-c",
                f"test -d {DIR} || git clone "
                f"https://github.com/antirez/disque.git {DIR}",
            )
            with control.cd(DIR):
                control.execute("git", "reset", "--hard", self.version,
                                check=False)
                control.execute("make", check=False)

    def start_args(self, test, node):
        return ["--port", str(PORT), "--appendonly", "yes",
                "--dir", DIR]

    def setup(self, test, node):
        super().setup(test, node)
        # join everyone to the primary (reference: disque.clj:94-104)
        primary = test["nodes"][0]
        if node != primary:
            control.execute(
                f"{DIR}/src/disque", "-p", str(PORT),
                "cluster", "meet", str(primary), str(PORT), check=False,
            )

    def await_ready(self, test, node):
        cu.await_tcp_port(PORT, timeout_s=120)

    def wipe(self, test, node):
        with control.su():
            control.execute("rm", "-f", f"{DIR}/appendonly.aof",
                            f"{DIR}/nodes.conf", check=False)


class DisqueClient(client_mod.Client):
    """enqueue → ADDJOB, dequeue → GETJOB + ACKJOB, drain → GETJOB until
    empty (reference: disque.clj:140-215)."""

    def __init__(self, opts: Optional[dict] = None):
        self.opts = opts or {}
        self.conn: Optional[RespClient] = None

    def open(self, test, node):
        c = type(self)(self.opts)
        c.conn = RespClient(
            self.opts.get("host", str(node)),
            self.opts.get("port", PORT),
            timeout=self.opts.get("timeout", 5.0),
        )
        return c

    def _dequeue_one(self):
        jobs = self.conn.call(
            "GETJOB", "TIMEOUT", str(GET_TIMEOUT_MS), "FROM", QUEUE
        )
        if not jobs:
            return None
        # [[queue, job-id, body]]
        _qname, job_id, body = jobs[0][0], jobs[0][1], jobs[0][2]
        self.conn.call("ACKJOB", job_id)
        return int(body)

    def invoke(self, test, op):
        try:
            if op["f"] == "enqueue":
                self.conn.call(
                    "ADDJOB", QUEUE, str(op["value"]), str(JOB_TIMEOUT_MS)
                )
                return {**op, "type": "ok"}
            if op["f"] == "dequeue":
                v = self._dequeue_one()
                if v is None:
                    return {**op, "type": "fail", "error": "empty"}
                return {**op, "type": "ok", "value": v}
            if op["f"] == "drain":
                got = []
                while True:
                    v = self._dequeue_one()
                    if v is None:
                        break
                    got.append(v)
                return {**op, "type": "ok", "value": got}
            raise ValueError(f"unknown f {op['f']!r}")
        except IndeterminateError as e:
            return {**op, "type": "info", "error": str(e)}
        except ProtocolError as e:
            return {**op, "type": "fail", "error": str(e)}

    def close(self, test):
        if self.conn:
            self.conn.close()


def db(opts: Optional[dict] = None):
    return DisqueDB(opts)


def client(opts: Optional[dict] = None):
    return DisqueClient(opts)


def workloads(opts: Optional[dict] = None) -> dict:
    return {
        "queue": common.queue_workload(dict(opts or {})),
        "linearizable-queue": common.linearizable_queue_workload(
            dict(opts or {})
        ),
    }


def test(opts: Optional[dict] = None) -> dict:
    opts = dict(opts or {})
    wname = opts.get("workload", "queue")
    w = workloads(opts)[wname]
    return common.build_test(
        f"disque-{wname}", opts, db=DisqueDB(opts), client=DisqueClient(opts),
        workload=w,
    )
