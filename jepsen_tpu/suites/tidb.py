"""TiDB suite.

Reference: tidb/src/tidb/{db,sql,core,bank,register,sets,txn,long_fork,
monotonic,sequential,table}.clj — each node runs all three components:
``pd-server`` (placement driver, peer port 2380 / client 2379),
``tikv-server`` (port 20160), and ``tidb-server`` (MySQL protocol, port
4000), installed from a tarball and started in dependency order with
config files written per node (db.clj:19-170).  Clients speak the MySQL
protocol via :mod:`.sql` (dialect ``mysql``).
"""

from __future__ import annotations

from typing import Optional

from .. import generator as gen_mod_base
from ..checker import Checker
from ..control import util as cu
from ..control import execute, sudo
from . import common, sql

DIR = "/opt/tidb"          # (reference: db.clj tidb-dir)
PD_PEER_PORT = 2380
PD_CLIENT_PORT = 2379
KV_PORT = 20160
DB_PORT = 4000
DEFAULT_TARBALL = (
    "https://download.pingcap.org/tidb-v3.0.0-linux-amd64.tar.gz"
)


class TiDB(common.DaemonDB):
    """pd → tikv → tidb on every node (reference: db.clj:180-260)."""

    dir = DIR
    binary = "bin/tidb-server"
    logfile = f"{DIR}/tidb.log"
    pidfile = f"{DIR}/tidb.pid"

    pd_logfile = f"{DIR}/pd.log"      # (reference: db.clj:30-33)
    pd_pidfile = f"{DIR}/pd.pid"
    kv_logfile = f"{DIR}/tikv.log"
    kv_pidfile = f"{DIR}/tikv.pid"

    def __init__(self, opts: Optional[dict] = None):
        super().__init__(opts)
        self.tarball = (opts or {}).get("tarball", DEFAULT_TARBALL)

    def install(self, test, node):
        with sudo():
            cu.install_archive(self.tarball, DIR)

    def _pd_name(self, test, node) -> str:
        return f"pd{test['nodes'].index(node) + 1}"  # (reference: db.clj:53)

    def _pd_endpoints(self, test) -> str:
        return ",".join(f"{n}:{PD_CLIENT_PORT}" for n in test["nodes"])

    def start_pd(self, test, node):
        """(reference: db.clj start-pd!)"""
        initial = ",".join(
            f"{self._pd_name(test, n)}=http://{n}:{PD_PEER_PORT}"
            for n in test["nodes"]
        )
        cu.start_daemon(
            {"logfile": self.pd_logfile, "pidfile": self.pd_pidfile,
             "chdir": DIR},
            f"{DIR}/bin/pd-server",
            "--name", self._pd_name(test, node),
            "--data-dir", f"{DIR}/data/pd",
            "--client-urls", f"http://0.0.0.0:{PD_CLIENT_PORT}",
            "--advertise-client-urls", f"http://{node}:{PD_CLIENT_PORT}",
            "--peer-urls", f"http://0.0.0.0:{PD_PEER_PORT}",
            "--advertise-peer-urls", f"http://{node}:{PD_PEER_PORT}",
            "--initial-cluster", initial,
            "--log-file", f"{DIR}/pd.app.log",
        )

    def start_kv(self, test, node):
        """(reference: db.clj start-kv!)"""
        cu.start_daemon(
            {"logfile": self.kv_logfile, "pidfile": self.kv_pidfile,
             "chdir": DIR},
            f"{DIR}/bin/tikv-server",
            "--pd", self._pd_endpoints(test),
            "--addr", f"0.0.0.0:{KV_PORT}",
            "--advertise-addr", f"{node}:{KV_PORT}",
            "--data-dir", f"{DIR}/data/tikv",
            "--log-file", f"{DIR}/tikv.app.log",
        )

    def start_db(self, test, node):
        """(reference: db.clj start-db!)"""
        cu.start_daemon(
            {"logfile": self.logfile, "pidfile": self.pidfile, "chdir": DIR},
            f"{DIR}/bin/tidb-server",
            "--store", "tikv",
            "--path", self._pd_endpoints(test),
            "-P", str(DB_PORT),
            "--log-file", f"{DIR}/tidb.app.log",
        )

    def stop_pd(self, test, node):
        cu.stop_daemon(pidfile=self.pd_pidfile, cmd="pd-server")

    def stop_kv(self, test, node):
        cu.stop_daemon(pidfile=self.kv_pidfile, cmd="tikv-server")

    def stop_db(self, test, node):
        cu.stop_daemon(pidfile=self.pidfile, cmd="tidb-server")

    # SIGSTOP/SIGCONT per component (reference: nemesis.clj pause-*/
    # resume-* via cu/signal!)
    def pause_pd(self, test, node):
        cu.signal("pd-server", "STOP")

    def pause_kv(self, test, node):
        cu.signal("tikv-server", "STOP")

    def pause_db(self, test, node):
        cu.signal("tidb-server", "STOP")

    def resume_pd(self, test, node):
        cu.signal("pd-server", "CONT")

    def resume_kv(self, test, node):
        cu.signal("tikv-server", "CONT")

    def resume_db(self, test, node):
        cu.signal("tidb-server", "CONT")

    def start(self, test, node):
        self.start_pd(test, node)
        cu.await_tcp_port(PD_CLIENT_PORT, timeout_s=120)
        self.start_kv(test, node)
        cu.await_tcp_port(KV_PORT, timeout_s=120)
        self.start_db(test, node)

    def kill(self, test, node):
        self.stop_db(test, node)
        self.stop_kv(test, node)
        self.stop_pd(test, node)

    # -- PD control plane (HTTP API + pd-ctl) ------------------------
    # The reference drives these through pd-ctl and clj-http against
    # the PD client port (nemesis.clj slow-primary-nemesis,
    # schedule-nemesis; db.clj pd-members/pd-leader/pd-transfer-leader!).

    def _pd_http(self, node) -> "JsonHttpClient":
        from .proto.http import JsonHttpClient

        return JsonHttpClient(str(node), PD_CLIENT_PORT, timeout=5.0)

    def _pd_get(self, node, path):
        """GET a PD API path → parsed body, or "timeout" — nemesis
        probes must not throw."""
        c = self._pd_http(node)
        try:
            status, body = c.request(
                "GET", path, ok=(200,), raise_on_error=False,
            )
            return body if status == 200 else "timeout"
        except Exception:  # noqa: BLE001
            return "timeout"
        finally:
            c.close()

    def pd_members(self, test, node):
        """The PD membership map ({"members": [{"name": ...}, ...]}),
        or "timeout"."""
        return self._pd_get(node, "/pd/api/v1/members")

    def pd_leader(self, test, node):
        """The PD leader member map, or "timeout"."""
        return self._pd_get(node, "/pd/api/v1/leader")

    def pd_leader_node(self, test, node):
        """Map the PD leader's member name (pd1, pd2, …) back to its
        cluster node, or None."""
        leader = self.pd_leader(test, node)
        if not isinstance(leader, dict):
            return None
        name = leader.get("name")
        for n in test["nodes"]:
            if self._pd_name(test, n) == name:
                return n
        return None

    def pd_transfer_leader(self, test, node, member_name):
        """Ask PD to transfer leadership to ``member_name``.  Returns
        (status, body); (None, error) when PD is unreachable."""
        c = self._pd_http(node)
        try:
            return c.request(
                "POST", f"/pd/api/v1/leader/transfer/{member_name}",
                ok=(200,), raise_on_error=False,
            )
        except Exception as e:  # noqa: BLE001
            return None, repr(e)
        finally:
            c.close()

    def pd_ctl(self, test, node, *args):
        """Run one pd-ctl command on ``node`` (reference:
        nemesis.clj:63-68 — `echo cmds | pd-ctl -d`)."""
        from ..control import lit

        return execute(
            "echo", *args, lit("|"), f"{DIR}/bin/pd-ctl", "-d",
            "-u", f"http://127.0.0.1:{PD_CLIENT_PORT}",
        )

    def await_ready(self, test, node):
        cu.await_tcp_port(DB_PORT, timeout_s=300)

    def wipe(self, test, node):
        with sudo():
            execute("rm", "-rf", f"{DIR}/data")

    def log_files(self, test, node):
        return [self.logfile, self.kv_logfile, self.pd_logfile]


def _opts(opts: Optional[dict]) -> dict:
    o = dict(opts or {})
    o.setdefault("dialect", "mysql")
    o.setdefault("port", DB_PORT)
    o.setdefault("user", "root")
    o.setdefault("database", "test")
    return o


def db(opts: Optional[dict] = None):
    return TiDB(opts)


def client(opts: Optional[dict] = None):
    return sql.RegisterClient(_opts(opts))


WORKLOADS = ("register", "bank", "set", "list-append", "long-fork")


def workloads(opts: Optional[dict] = None) -> dict:
    from . import monotonic, sequential

    opts = _opts(opts)
    out = {w: common.generic_workload(w, opts) for w in WORKLOADS}
    # suite-specific probes (reference: tidb/txn.clj, table.clj,
    # monotonic.clj, sequential.clj — the latter two ride the shared
    # dialect-generic SQL implementations)
    out["txn"] = common.generic_workload("rw-register", opts)
    out["table"] = table_workload(opts)
    out["monotonic"] = monotonic.workload(opts)
    out["sequential"] = sequential.workload(opts)
    return out


def _client_for(wname: str, opts: dict):
    from . import monotonic, sequential

    if wname == "txn":
        return TidbTxnClient(opts)
    if wname == "list-append":
        # the reference serves append through the striped txn client
        # (txn.clj:41-49); val must be a string column for CONCAT
        return TidbTxnClient({**opts, "val-type": "text"})
    if wname == "table":
        return TableClient(opts)
    if wname == "monotonic":
        return monotonic.MonotonicClient(opts)
    if wname == "sequential":
        return sequential.SequentialClient(opts)
    return sql.client_for(
        wname if wname in sql.CLIENTS else "register", opts
    )


def test(opts: Optional[dict] = None) -> dict:
    from . import tidb_nemesis

    opts = _opts(opts)
    wname = opts.get("workload", "register")
    w = workloads(opts)[wname]
    database = TiDB(opts)
    pkg = None
    faults = set(opts.get("faults", ()))
    if faults & tidb_nemesis.KNOWN_FAULTS:
        # suite-specific fault menu (reference: tidb/nemesis.clj via
        # run.clj); anything the menu doesn't know rides the generic
        # packages alongside it
        pkg = common.suite_nemesis_package(
            opts, database,
            tidb_nemesis.package(opts, database),
            tidb_nemesis.KNOWN_FAULTS,
        )
    return common.build_test(
        f"tidb-{wname}", opts, db=database,
        client=_client_for(wname, opts),
        workload=w, nemesis_package=pkg,
    )


# ---------------------------------------------------------------------
# Striped transactional client (reference: tidb/src/tidb/txn.clj:1-92)
# ---------------------------------------------------------------------

TXN_TABLE_COUNT = 7  # (reference: txn.clj:92 table-count default)


class TidbTxnClient(sql._Base):
    """Micro-op transactions striped over ``txn0``..``txnN`` tables with
    a secondary ``sk`` column, serving the wr (rw-register) and
    list-append workloads.

    Reference: tidb/src/tidb/txn.clj — table-for striping by key hash
    (:13-16), mop! executing r (by id, or sk under use-index /
    predicate-read, with an optional read-lock suffix) / w (upsert) /
    append (CONCAT upsert) (:18-49), single-mop transactions skipping
    BEGIN (:58-66), and the (sk, val) index under use-index (:55-57).
    """

    dialect = "mysql"

    def __init__(self, opts: Optional[dict] = None):
        super().__init__(opts)
        self.table_count = int(self.opts.get("table-count", TXN_TABLE_COUNT))
        self.val_type = self.opts.get("val-type", "int")
        self.use_index = bool(self.opts.get("use-index"))
        self.read_lock = self.opts.get("read-lock", "")

    def table_for(self, k) -> str:
        return f"txn{hash(k) % self.table_count}"

    def setup(self, test):
        for i in range(self.table_count):
            self._exec_ddl(
                f"CREATE TABLE IF NOT EXISTS txn{i} "
                "(id INT NOT NULL PRIMARY KEY, sk INT NOT NULL, "
                f"val {self.val_type})"
            )
            if self.use_index:
                self._exec_ddl(
                    f"CREATE INDEX txn{i}_sk_val ON txn{i} (sk, val)"
                )

    def _mop(self, f, k, v):
        t = self.table_for(k)
        if f == "r":
            col = "sk" if self.use_index else "id"
            lock = f" {self.read_lock}" if self.read_lock else ""
            res = self.conn.query(
                f"SELECT val FROM {t} WHERE {col} = {int(k)}{lock}"
            )
            raw = res.rows[0][0] if res.rows else None
            if self.val_type == "int":
                return ["r", k, None if raw is None else int(raw)]
            vals = [int(x) for x in str(raw).split(",") if x != ""] if raw else []
            return ["r", k, vals]
        if f == "w":
            self.conn.query(
                f"INSERT INTO {t} (id, sk, val) "
                f"VALUES ({int(k)}, {int(k)}, {int(v)}) "
                f"ON DUPLICATE KEY UPDATE val = {int(v)}"
            )
            return ["w", k, v]
        if f == "append":
            self.conn.query(
                f"INSERT INTO {t} (id, sk, val) "
                f"VALUES ({int(k)}, {int(k)}, '{int(v)}') "
                f"ON DUPLICATE KEY UPDATE val = CONCAT(val, ',', '{int(v)}')"
            )
            return ["append", k, v]
        raise ValueError(f"unknown micro-op {f!r}")

    def invoke(self, test, op):
        txn = op["value"]
        use_txn = len(txn) > 1
        try:
            if use_txn:
                self.conn.query("BEGIN")
            try:
                out = [self._mop(f, k, v) for f, k, v in txn]
                if use_txn:
                    self.conn.query("COMMIT")
                return {**op, "type": "ok", "value": out}
            except (sql.PgError, sql.MysqlError) as e:
                if use_txn:
                    try:
                        self.conn.query("ROLLBACK")
                    except Exception:
                        pass
                return self._fail(op, e)
        except sql.IndeterminateError as e:
            return self._info(op, e)


# ---------------------------------------------------------------------
# Table-creation workload (reference: tidb/src/tidb/table.clj)
# ---------------------------------------------------------------------


class TableClient(sql._Base):
    """create-table / insert racing DDL visibility: inserting into a
    table whose creation was acknowledged must never fail with
    "doesn't exist".  (reference: table.clj:16-51 TableClient)"""

    dialect = "mysql"

    def invoke(self, test, op):
        try:
            if op["f"] == "create-table":
                self.conn.query(
                    f"CREATE TABLE IF NOT EXISTS t{int(op['value'])} "
                    "(id INT NOT NULL PRIMARY KEY, val INT)"
                )
                return {**op, "type": "ok"}
            if op["f"] == "insert":
                table, k = op["value"]
                try:
                    self.conn.query(
                        f"INSERT INTO t{int(table)} (id) VALUES ({int(k)})"
                    )
                    return {**op, "type": "ok"}
                except (sql.PgError, sql.MysqlError) as e:
                    msg = str(e)
                    if "doesn't exist" in msg or "no such table" in msg:
                        return {**op, "type": "fail",
                                "error": "doesn't-exist"}
                    if "Duplicate" in msg or "UNIQUE" in msg:
                        return {**op, "type": "fail",
                                "error": "duplicate-key"}
                    raise
            raise ValueError(f"unknown f {op['f']!r}")
        except sql.IndeterminateError as e:
            return self._info(op, e)
        except (sql.PgError, sql.MysqlError) as e:
            return self._fail(op, e)


class _TableGen(gen_mod_base.Generator):
    """80% insert into the last *acknowledged* table, else create the
    next one; acks tracked through generator update events — the same
    bookkeeping the reference keeps in a shared atom
    (table.clj:60-68 generator, :28-33 ack in invoke!)."""

    def __init__(self):
        self.last_created = None
        self.next_create = 0
        self.next_insert = 0

    def op(self, test, ctx):
        from .. import generator as gen_mod

        if self.last_created is not None and gen_mod.rng.random() < 0.8:
            # distinct ids per insert (the reference's fixed id 0 makes
            # every insert after the first a duplicate-key failure;
            # fresh ids keep the DDL-visibility race exercised all run
            # and the stats checker meaningful)
            self.next_insert += 1
            return (
                gen_mod.fill_in_op(
                    {"f": "insert",
                     "value": [self.last_created, self.next_insert]}, ctx
                ),
                self,
            )
        self.next_create += 1
        return (
            gen_mod.fill_in_op(
                {"f": "create-table", "value": self.next_create}, ctx
            ),
            self,
        )

    def update(self, test, ctx, event):
        if (
            event.get("type") == "ok"
            and event.get("f") == "create-table"
        ):
            v = event.get("value")
            if self.last_created is None or v > self.last_created:
                self.last_created = v
        return self


class TableChecker(Checker):
    """No insert may fail with doesn't-exist.  (reference:
    table.clj:69-77 checker)"""

    def check(self, test, history, opts=None):
        from ..history import FAIL

        bad = [
            {"op-index": op.index, "value": op.value}
            for op in history
            if op.type == FAIL and op.error == "doesn't-exist"
        ]
        return {"valid?": not bad, "errors": bad[:10]}


def table_workload(opts: Optional[dict] = None) -> dict:
    """(reference: table.clj:79-84 workload)"""
    return {"generator": _TableGen(), "checker": TableChecker()}
