"""TiDB suite.

Reference: tidb/src/tidb/{db,sql,core,bank,register,sets,txn,long_fork,
monotonic,sequential,table}.clj — each node runs all three components:
``pd-server`` (placement driver, peer port 2380 / client 2379),
``tikv-server`` (port 20160), and ``tidb-server`` (MySQL protocol, port
4000), installed from a tarball and started in dependency order with
config files written per node (db.clj:19-170).  Clients speak the MySQL
protocol via :mod:`.sql` (dialect ``mysql``).
"""

from __future__ import annotations

from typing import Optional

from .. import db as db_mod
from ..control import util as cu
from ..control import execute, sudo
from . import common, sql

DIR = "/opt/tidb"          # (reference: db.clj tidb-dir)
PD_PEER_PORT = 2380
PD_CLIENT_PORT = 2379
KV_PORT = 20160
DB_PORT = 4000
DEFAULT_TARBALL = (
    "https://download.pingcap.org/tidb-v3.0.0-linux-amd64.tar.gz"
)


class TiDB(common.DaemonDB):
    """pd → tikv → tidb on every node (reference: db.clj:180-260)."""

    dir = DIR
    binary = "bin/tidb-server"
    logfile = f"{DIR}/tidb.log"
    pidfile = f"{DIR}/tidb.pid"

    pd_logfile = f"{DIR}/pd.log"      # (reference: db.clj:30-33)
    pd_pidfile = f"{DIR}/pd.pid"
    kv_logfile = f"{DIR}/tikv.log"
    kv_pidfile = f"{DIR}/tikv.pid"

    def __init__(self, opts: Optional[dict] = None):
        super().__init__(opts)
        self.tarball = (opts or {}).get("tarball", DEFAULT_TARBALL)

    def install(self, test, node):
        with sudo():
            cu.install_archive(self.tarball, DIR)

    def _pd_name(self, test, node) -> str:
        return f"pd{test['nodes'].index(node) + 1}"  # (reference: db.clj:53)

    def start(self, test, node):
        nodes = test["nodes"]
        initial = ",".join(
            f"{self._pd_name(test, n)}=http://{n}:{PD_PEER_PORT}"
            for n in nodes
        )
        pd_endpoints = ",".join(f"{n}:{PD_CLIENT_PORT}" for n in nodes)
        cu.start_daemon(
            {"logfile": self.pd_logfile, "pidfile": self.pd_pidfile,
             "chdir": DIR},
            f"{DIR}/bin/pd-server",
            "--name", self._pd_name(test, node),
            "--data-dir", f"{DIR}/data/pd",
            "--client-urls", f"http://0.0.0.0:{PD_CLIENT_PORT}",
            "--advertise-client-urls", f"http://{node}:{PD_CLIENT_PORT}",
            "--peer-urls", f"http://0.0.0.0:{PD_PEER_PORT}",
            "--advertise-peer-urls", f"http://{node}:{PD_PEER_PORT}",
            "--initial-cluster", initial,
            "--log-file", f"{DIR}/pd.app.log",
        )
        cu.await_tcp_port(PD_CLIENT_PORT, timeout_s=120)
        cu.start_daemon(
            {"logfile": self.kv_logfile, "pidfile": self.kv_pidfile,
             "chdir": DIR},
            f"{DIR}/bin/tikv-server",
            "--pd", pd_endpoints,
            "--addr", f"0.0.0.0:{KV_PORT}",
            "--advertise-addr", f"{node}:{KV_PORT}",
            "--data-dir", f"{DIR}/data/tikv",
            "--log-file", f"{DIR}/tikv.app.log",
        )
        cu.await_tcp_port(KV_PORT, timeout_s=120)
        cu.start_daemon(
            {"logfile": self.logfile, "pidfile": self.pidfile, "chdir": DIR},
            f"{DIR}/bin/tidb-server",
            "--store", "tikv",
            "--path", pd_endpoints,
            "-P", str(DB_PORT),
            "--log-file", f"{DIR}/tidb.app.log",
        )

    def kill(self, test, node):
        for pidfile, name in [
            (self.pidfile, "tidb-server"),
            (self.kv_pidfile, "tikv-server"),
            (self.pd_pidfile, "pd-server"),
        ]:
            cu.stop_daemon(pidfile=pidfile, cmd=name)

    def await_ready(self, test, node):
        cu.await_tcp_port(DB_PORT, timeout_s=300)

    def wipe(self, test, node):
        with sudo():
            execute("rm", "-rf", f"{DIR}/data")

    def log_files(self, test, node):
        return [self.logfile, self.kv_logfile, self.pd_logfile]


def _opts(opts: Optional[dict]) -> dict:
    o = dict(opts or {})
    o.setdefault("dialect", "mysql")
    o.setdefault("port", DB_PORT)
    o.setdefault("user", "root")
    o.setdefault("database", "test")
    return o


def db(opts: Optional[dict] = None):
    return TiDB(opts)


def client(opts: Optional[dict] = None):
    return sql.RegisterClient(_opts(opts))


WORKLOADS = ("register", "bank", "set", "list-append", "long-fork")


def workloads(opts: Optional[dict] = None) -> dict:
    opts = _opts(opts)
    return {w: common.generic_workload(w, opts) for w in WORKLOADS}


def test(opts: Optional[dict] = None) -> dict:
    opts = _opts(opts)
    wname = opts.get("workload", "register")
    w = workloads(opts)[wname]
    return common.build_test(
        f"tidb-{wname}", opts, db=TiDB(opts),
        client=sql.client_for(
            wname if wname in sql.CLIENTS else "register", opts),
        workload=w,
    )
