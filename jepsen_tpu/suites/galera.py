"""MariaDB Galera Cluster suite.

Reference: galera/src/jepsen/galera.clj + galera/dirty_reads.clj —
install mariadb-galera-server from the mariadb apt repo with debconf
root-password preseeding (galera.clj:34-55), write a galera.cnf whose
``wsrep_cluster_address`` gossip URL lists every node, bootstrap the
first node with ``galera_new_cluster``, and probe for dirty reads /
lost updates over the MySQL protocol.  Clients via :mod:`.sql`
(dialect ``mysql``).
"""

from __future__ import annotations

from typing import Optional

from ..control import util as cu
from ..control import execute, sudo
from ..os_setup import debian
from . import common, sql

PORT = 3306
ROOT_PW = "jepsen"  # (reference: galera.clj:44-45 debconf preseed)

_CNF = """[mysqld]
bind-address = 0.0.0.0
binlog_format = ROW
default_storage_engine = InnoDB
innodb_autoinc_lock_mode = 2
wsrep_on = ON
wsrep_provider = /usr/lib/galera/libgalera_smm.so
wsrep_cluster_name = jepsen
wsrep_cluster_address = gcomm://{nodes}
wsrep_node_address = {node}
wsrep_node_name = {node}
wsrep_sst_method = rsync
"""


class GaleraDB(common.DaemonDB):
    logfile = "/var/log/mysql/error.log"
    proc_name = "mysqld"

    def install(self, test, node):
        # (reference: galera.clj:34-55 install!)
        with sudo():
            for line in (
                f"mariadb-galera-server-10.0 mysql-server/root_password "
                f"password {ROOT_PW}",
                f"mariadb-galera-server-10.0 mysql-server/root_password_again "
                f"password {ROOT_PW}",
            ):
                execute("bash", "-c",
                        f"echo '{line}' | debconf-set-selections")
        debian.install(["rsync", "mariadb-galera-server"])
        with sudo():
            execute("service", "mysql", "stop", check=False)

    def configure(self, test, node):
        cnf = _CNF.format(
            nodes=",".join(str(n) for n in test["nodes"]), node=node
        )
        with sudo():
            cu.write_file(cnf, "/etc/mysql/conf.d/galera.cnf")

    def start(self, test, node):
        with sudo():
            if node == test["nodes"][0]:
                # bootstrap the primary component on the first node
                execute("galera_new_cluster", check=False)
                execute("service", "mysql", "start", check=False)
            else:
                execute("service", "mysql", "start", check=False)

    def kill(self, test, node):
        with sudo():
            execute("service", "mysql", "stop", check=False)
            cu.grepkill("mysqld")

    def await_ready(self, test, node):
        cu.await_tcp_port(PORT, timeout_s=300)

    def wipe(self, test, node):
        with sudo():
            execute("rm", "-rf", "/var/lib/mysql/grastate.dat")


def _opts(opts: Optional[dict]) -> dict:
    o = dict(opts or {})
    o.setdefault("dialect", "mysql")
    o.setdefault("port", PORT)
    o.setdefault("user", "root")
    o.setdefault("password", ROOT_PW)
    return o


def db(opts: Optional[dict] = None):
    return GaleraDB(opts)


def client(opts: Optional[dict] = None):
    return sql.SetClient(_opts(opts))


WORKLOADS = ("set", "bank", "register")


def workloads(opts: Optional[dict] = None) -> dict:
    from . import dirty_reads_sql

    opts = _opts(opts)
    out = {w: common.generic_workload(w, opts) for w in WORKLOADS}
    # the suite's signature probe (reference: galera/
    # dirty_reads.clj): failed writers' values must never be read
    out["dirty-reads"] = dirty_reads_sql.workload(opts)
    return out


def test(opts: Optional[dict] = None) -> dict:
    from . import dirty_reads_sql

    opts = _opts(opts)
    wname = opts.get("workload", "bank")
    w = workloads(opts)[wname]
    if wname == "dirty-reads":
        return common.build_test(
            f"galera-{wname}", opts, db=db(opts),
            client=dirty_reads_sql.DirtyReadsClient(opts), workload=w,
        )
    return common.build_test(
        f"galera-{wname}", opts, db=GaleraDB(opts),
        client=sql.client_for(wname, opts), workload=w,
    )
