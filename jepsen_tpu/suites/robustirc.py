"""RobustIRC suite.

Reference: robustirc/src/jepsen/robustirc.clj — install the robustirc
server binaries, form a 3+-node Raft network, and run a **set workload
over IRC topics**: each add posts ``TOPIC #jepsen :<element>``
(:163-176), and the final read collects every topic message the
session observed, checked with the set checker (:176-210).

The client here speaks RFC-1459 IRC directly (the bridge protocol);
each client accumulates topics it has seen across invocations, exactly
like the reference's robustsession message backlog.
"""

from __future__ import annotations

from typing import Optional, Set

from .. import checker as checker_mod
from .. import client as client_mod
from .. import control
from .. import generator as gen
from ..control import util as cu
from . import common
from .proto import IndeterminateError
from .proto.irc import IrcClient

DIR = "/opt/robustirc"
PORT = 6667
HTTPS_PORT = 13001
CHANNEL = "#jepsen"

_ids = iter(range(10**9))


class RobustIrcDB(common.DaemonDB):
    dir = DIR
    binary = "robustirc"
    logfile = f"{DIR}/robustirc.log"
    pidfile = f"{DIR}/robustirc.pid"

    def install(self, test, node):
        # GOBIN pins the installed binary into DIR so start() finds it
        with control.su():
            control.execute(
                "bash", "-c",
                f"test -f {DIR}/{self.binary} || "
                f"(mkdir -p {DIR} && cd {DIR} && "
                f"GOBIN={DIR} go install "
                "github.com/robustirc/robustirc@latest)",
                check=False,
            )

    def start_args(self, test, node):
        primary = test["nodes"][0]
        args = [
            "-network_name", "jepsen.net",
            "-peer_addr", f"{node}:{HTTPS_PORT}",
            "-listen", f":{HTTPS_PORT}",
        ]
        if node != primary:
            args += ["-join", f"{primary}:{HTTPS_PORT}"]
        return args

    def await_ready(self, test, node):
        cu.await_tcp_port(HTTPS_PORT, timeout_s=120)


class RobustIrcSetClient(client_mod.Client):
    """add → TOPIC change; read → all topics this session observed.
    (reference: robustirc.clj:150-176 SetClient)"""

    def __init__(self, opts: Optional[dict] = None):
        self.opts = opts or {}
        self.conn: Optional[IrcClient] = None
        self.seen: Set[int] = set()

    def open(self, test, node):
        c = type(self)(self.opts)
        c.conn = IrcClient(
            self.opts.get("host", str(node)),
            self.opts.get("port", PORT),
            nick=f"jepsen{next(_ids)}",
            timeout=self.opts.get("timeout", 10.0),
        )
        c.conn.connect()
        c.conn.join(CHANNEL)
        return c

    def _drain(self):
        for _nick, target, text in self.conn.read_messages():
            if target == CHANNEL:
                try:
                    self.seen.add(int(text))
                except ValueError:
                    pass

    def invoke(self, test, op):
        try:
            if op["f"] == "add":
                self.conn.topic(CHANNEL, str(op["value"]))
                return {**op, "type": "ok"}
            if op["f"] == "read":
                self._drain()
                return {**op, "type": "ok", "value": sorted(self.seen)}
            raise ValueError(f"unknown f {op['f']!r}")
        except IndeterminateError as e:
            return {**op, "type": "info", "error": str(e)}

    def close(self, test):
        if self.conn:
            self.conn.close()


def set_workload(opts: Optional[dict] = None) -> dict:
    """(reference: robustirc.clj:185-210 sets-test; plain set checker —
    reads observe messages, not a stored collection)"""
    counter = {"n": 0}

    def add(test, ctx):
        v = counter["n"]
        counter["n"] += 1
        return {"type": "invoke", "f": "add", "value": v}

    final = gen.clients(
        gen.each_thread(gen.once({"type": "invoke", "f": "read",
                                  "value": None}))
    )
    return {
        "generator": gen.stagger(0.1, add),
        "final-generator": final,
        "checker": checker_mod.set_checker(),
    }


def db(opts: Optional[dict] = None):
    return RobustIrcDB(opts)


def client(opts: Optional[dict] = None):
    return RobustIrcSetClient(opts)


def workloads(opts: Optional[dict] = None) -> dict:
    return {"set": set_workload(dict(opts or {}))}


def test(opts: Optional[dict] = None) -> dict:
    opts = dict(opts or {})
    w = workloads(opts)["set"]
    return common.build_test(
        "robustirc-set", opts, db=RobustIrcDB(opts),
        client=RobustIrcSetClient(opts), workload=w,
    )
