"""Predicate-based G2 (anti-dependency cycle) client for SQL suites.

Each insert op reads *predicates* over two tables inside one
transaction — ``value % 3 = 0`` rather than a primary-key lookup, so
the database can't dodge the anti-dependency with per-key locks — and
inserts its row only when both predicate reads come back empty.  Under
serializability at most one insert of each pair may commit; the paired
generator and checker are the shared adya workload
(jepsen_tpu.workloads.adya).

Reference: cockroachdb/src/jepsen/cockroach/adya.clj:24-76 G2Client +
jepsen/src/jepsen/tests/adya.clj:12-58 (table shapes, predicate text,
and insert semantics).
"""

from __future__ import annotations

from typing import Optional

from . import sql

TABLES = ("a", "b")


class G2Client(sql._Base):
    def setup(self, test):
        self._exec_ddl(
            *(
                f"CREATE TABLE IF NOT EXISTS {t} "
                "(id INT PRIMARY KEY, key INT, value INT)"
                for t in TABLES
            )
        )

    def invoke(self, test, op):
        assert op["f"] == "insert", op
        k, ids = op["value"]
        a_id, b_id = ids
        table = "a" if a_id is not None else "b"
        id_ = a_id if a_id is not None else b_id
        try:
            self.conn.query("BEGIN")
            try:
                hit = False
                for t in TABLES:
                    res = self.conn.query(
                        f"SELECT id FROM {t} "
                        f"WHERE key = {int(k)} AND value % 3 = 0"
                    )
                    hit = hit or bool(res.rows)
                if hit:
                    self.conn.query("ROLLBACK")
                    return {**op, "type": "fail", "error": "conflict"}
                self.conn.query(
                    f"INSERT INTO {table} (id, key, value) "
                    f"VALUES ({int(id_)}, {int(k)}, 30)"
                )
                self.conn.query("COMMIT")
            except Exception:
                try:
                    self.conn.query("ROLLBACK")
                except Exception:
                    pass
                raise
            return {**op, "type": "ok"}
        except sql.IndeterminateError as e:
            return self._info(op, e)
        except (sql.PgError, sql.MysqlError) as e:
            return self._fail(op, e)


def client(opts: Optional[dict] = None) -> G2Client:
    return G2Client(opts)
