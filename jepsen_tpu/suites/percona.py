"""Percona XtraDB Cluster suite.

Reference: percona/src/jepsen/percona.clj + percona/dirty_reads.clj —
same shape as galera: install percona-xtradb-cluster from the percona
apt repo with debconf preseeding, configure wsrep gossip over the test
nodes, bootstrap node 1, and probe dirty reads / lost updates over the
MySQL protocol.  Clients via :mod:`.sql` (dialect ``mysql``).
"""

from __future__ import annotations

from typing import Optional

from ..control import util as cu
from ..control import execute, sudo
from ..os_setup import debian
from . import common, sql
from .galera import _CNF, ROOT_PW

PORT = 3306


class PerconaDB(common.DaemonDB):
    logfile = "/var/log/mysql/error.log"
    proc_name = "mysqld"

    def install(self, test, node):
        with sudo():
            for line in (
                f"percona-xtradb-cluster-server mysql-server/root_password "
                f"password {ROOT_PW}",
                f"percona-xtradb-cluster-server "
                f"mysql-server/root_password_again password {ROOT_PW}",
            ):
                execute("bash", "-c",
                        f"echo '{line}' | debconf-set-selections")
        debian.install(["rsync", "percona-xtradb-cluster-57"])
        with sudo():
            execute("service", "mysql", "stop", check=False)

    def configure(self, test, node):
        cnf = _CNF.format(
            nodes=",".join(str(n) for n in test["nodes"]), node=node
        ).replace(
            "/usr/lib/galera/libgalera_smm.so",
            "/usr/lib/libgalera_smm.so",
        )
        with sudo():
            cu.write_file(cnf, "/etc/mysql/conf.d/wsrep.cnf")

    def start(self, test, node):
        with sudo():
            if node == test["nodes"][0]:
                execute("service", "mysql", "bootstrap-pxc", check=False)
            else:
                execute("service", "mysql", "start", check=False)

    def kill(self, test, node):
        with sudo():
            execute("service", "mysql", "stop", check=False)
            cu.grepkill("mysqld")

    def await_ready(self, test, node):
        cu.await_tcp_port(PORT, timeout_s=300)

    def wipe(self, test, node):
        with sudo():
            execute("rm", "-rf", "/var/lib/mysql/grastate.dat")


def _opts(opts: Optional[dict]) -> dict:
    o = dict(opts or {})
    o.setdefault("dialect", "mysql")
    o.setdefault("port", PORT)
    o.setdefault("user", "root")
    o.setdefault("password", ROOT_PW)
    return o


def db(opts: Optional[dict] = None):
    return PerconaDB(opts)


def client(opts: Optional[dict] = None):
    return sql.SetClient(_opts(opts))


WORKLOADS = ("set", "bank", "register")


def workloads(opts: Optional[dict] = None) -> dict:
    from . import dirty_reads_sql

    opts = _opts(opts)
    out = {w: common.generic_workload(w, opts) for w in WORKLOADS}
    # the suite's signature probe (reference: percona/
    # dirty_reads.clj): failed writers' values must never be read
    out["dirty-reads"] = dirty_reads_sql.workload(opts)
    return out


def test(opts: Optional[dict] = None) -> dict:
    from . import dirty_reads_sql

    opts = _opts(opts)
    wname = opts.get("workload", "bank")
    w = workloads(opts)[wname]
    if wname == "dirty-reads":
        return common.build_test(
            f"percona-{wname}", opts, db=db(opts),
            client=dirty_reads_sql.DirtyReadsClient(opts), workload=w,
        )
    return common.build_test(
        f"percona-{wname}", opts, db=PerconaDB(opts),
        client=sql.client_for(wname, opts), workload=w,
    )
