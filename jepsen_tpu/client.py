"""Client protocol (reference: jepsen/src/jepsen/client.clj:9-126).

A client applies operations to the system under test.  Lifecycle:

- ``open(test, node)``  → a client bound to one node (returns self or a
  fresh instance; called once per process)
- ``setup(test)``       → one-time data setup
- ``invoke(test, op)``  → apply an op dict, return the completion dict
  (type "ok", "fail", or "info")
- ``teardown(test)``
- ``close(test)``       → release connections

``reusable(test)`` — if True, the same client instance is kept across
process crashes instead of being reopened (reference: client.clj:29-44).
"""

from __future__ import annotations

from typing import Any, Optional


class Client:
    def open(self, test: dict, node: Any) -> "Client":
        return self

    def setup(self, test: dict) -> None:
        pass

    def invoke(self, test: dict, op: dict) -> dict:
        raise NotImplementedError

    def teardown(self, test: dict) -> None:
        pass

    def close(self, test: dict) -> None:
        pass

    def reusable(self, test: dict) -> bool:
        return False


class NoopClient(Client):
    """Does nothing but complete ops successfully.
    (reference: client.clj:46-62 noop)"""

    def invoke(self, test, op):
        return {**op, "type": "ok"}

    def reusable(self, test):
        return True


def noop() -> Client:
    return NoopClient()


class ValidationError(Exception):
    pass


class Validate(Client):
    """Wraps a client, validating the well-formedness of invocation
    results.  (reference: client.clj:64-109)"""

    def __init__(self, client: Client):
        self.client = client

    def open(self, test, node):
        opened = self.client.open(test, node)
        if opened is None:
            raise ValidationError(
                f"Expected client open to return a client, got None from "
                f"{self.client!r}"
            )
        return Validate(opened)

    def setup(self, test):
        self.client.setup(test)

    def invoke(self, test, op):
        res = self.client.invoke(test, op)
        problems = []
        if not isinstance(res, dict):
            problems.append(f"should return an op dict, got {res!r}")
        else:
            if res.get("type") not in ("ok", "fail", "info"):
                problems.append(
                    f":type should be ok, fail, or info, got {res.get('type')!r}"
                )
            if res.get("process") != op.get("process"):
                problems.append(
                    f":process {res.get('process')!r} != invoked {op.get('process')!r}"
                )
            if res.get("f") != op.get("f"):
                problems.append(
                    f":f {res.get('f')!r} != invoked {op.get('f')!r}"
                )
        if problems:
            raise ValidationError(
                f"Client {self.client!r} returned an invalid completion for "
                f"{op!r}: " + "; ".join(problems)
            )
        return res

    def teardown(self, test):
        self.client.teardown(test)

    def close(self, test):
        self.client.close(test)

    def reusable(self, test):
        return self.client.reusable(test)


def validate(client: Client) -> Client:
    return Validate(client)


def is_reusable(client: Optional[Client], test: dict) -> bool:
    return client is not None and client.reusable(test)
