"""jtlint: the project-native static-analysis suite.

``python -m jepsen_tpu.lint [paths]`` runs four AST-based passes that
encode this repo's real invariants (doc/static-analysis.md):

- **trace-safety** — host impurity reachable inside jit/vmap/pmap
  traced code, and implicit device syncs in the dispatch path.
- **lock-discipline** — ``# jt: guarded-by(<lock>)`` lockset checking
  over the multi-threaded engine/obs/control state.
- **obs-hygiene** — span enter/exit pairing and ``jepsen_*`` metric
  naming/registration/doc conformance.
- **protocol** — checker ``check`` seam conformance and suite
  workload/fault/name-table drift.

Dependency-free (stdlib ``ast`` only — linting ``ops/`` never imports
JAX), wired into ``make lint`` / ``make check``, non-zero exit on any
finding not in the committed baseline (``jepsen_tpu/lint/baseline.json``).
Per-line suppression: ``# jt: allow[rule-id]``.
"""

from __future__ import annotations

from .core import (DEFAULT_BASELINE, Finding, LintResult,  # noqa: F401
                   Pass, Project, SourceFile, all_passes, all_rules,
                   lint_paths, load_baseline, make_baseline, write_baseline)

__all__ = [
    "DEFAULT_BASELINE", "Finding", "LintResult", "Pass", "Project",
    "SourceFile", "all_passes", "all_rules", "lint_paths",
    "load_baseline", "make_baseline", "write_baseline",
]
