"""jtlint: the project-native static-analysis suite.

``python -m jepsen_tpu.lint [paths]`` runs eight passes that encode
this repo's real invariants (doc/static-analysis.md):

- **trace-safety** — host impurity reachable inside jit/vmap/pmap
  traced code, and implicit device syncs in the dispatch path.
- **lock-discipline** — ``# jt: guarded-by(<lock>)`` lockset checking
  over the multi-threaded engine/obs/control state.
- **concurrency** — whole-program race inference: thread roots, call
  graph, escape analysis, and interprocedural locksets — no
  annotations required, existing annotations audited.
- **obs-hygiene** — span enter/exit pairing and ``jepsen_*`` metric
  naming/registration/doc conformance.
- **protocol** — checker ``check`` seam conformance and suite
  workload/fault/name-table drift.
- **contracts** — both sides of every serialized seam diffed
  statically: service frames, journal schema, calibration params, and
  the ``JEPSEN_TPU_*`` env registry (:mod:`jepsen_tpu.lint.envvars`).
- **budget** — every jit-kernel dispatch rides an Executor /
  ``safe_dispatch``-capped path (the ``has_cycle_batch`` bug class).
- **jaxpr-audit** — the one non-AST pass: every registered kernel is
  abstractly traced (``jax.make_jaxpr``, CPU, no device work) across
  the full knob cross-product and certified against declared
  ``# jt: jaxpr(...)`` contracts — per-row HBM budget bands,
  dot_general/dtype pins, host-sync and retrace hazards — plus AST
  dataflow from knob resolvers to lru/shard cache keys.

The seven AST passes are dependency-free (stdlib ``ast`` only —
linting ``ops/`` never imports JAX); the jaxpr audit imports jax only
on an incremental-cache miss (content-hashed results keep the warm
``make lint`` jax-free).  Wired into ``make lint`` / ``make check``,
non-zero exit on any finding not in the committed baseline
(``jepsen_tpu/lint/baseline.json``).
Per-line suppression: ``# jt: allow[rule-id]``.
"""

from __future__ import annotations

from .core import (DEFAULT_BASELINE, Finding, LintResult,  # noqa: F401
                   Pass, Project, SourceFile, all_passes, all_rules,
                   lint_paths, load_baseline, make_baseline, write_baseline)

__all__ = [
    "DEFAULT_BASELINE", "Finding", "LintResult", "Pass", "Project",
    "SourceFile", "all_passes", "all_rules", "lint_paths",
    "load_baseline", "make_baseline", "write_baseline",
]
