"""protocol-conformance pass: checker seams and suite reference drift.

The harness is table-driven at its edges: checkers all flow through one
``check(test, history, opts)`` seam (``checker.check_safe`` is the
universal funnel), suites look workloads up by name in the
``workloads``/``suites.common`` tables, and fault menus key the
``nemesis.combined`` packages.  String-keyed seams drift silently —
a suite naming a workload that was renamed keeps importing fine and
only crashes (or worse, silently runs the wrong checker) at run time.
With 40+ suite modules that drift is a *when*, not an *if*.

Rules:

- ``proto-check-signature`` — a class in ``checker/`` that subclasses
  ``Checker`` (or is named ``*Checker``) must define
  ``check(self, test, history, opts=None)``: exactly those four
  parameters, the last defaulted, no extras.
- ``proto-check-return`` — inside such a ``check``, a ``return`` of an
  obviously wrong literal: a dict literal missing ``"valid?"`` (unless
  it spreads ``**other``), or a list/tuple/str/number.  ``None`` is
  tolerated (``check_safe`` normalizes it); non-literal returns are
  assumed correct.
- ``proto-workload-ref`` — a workload name (literal argument to
  ``generic_workload``/``workload``, or an element of a module-level
  ``WORKLOADS`` constant) that exists in neither the generic table
  (``suites/common.py``) nor the core table
  (``workloads/__init__.py``).
- ``proto-fault-ref`` — a fault-name literal (elements of a list/set
  passed as the ``"faults"`` key or the ``opts.get("faults", …)``
  default) outside the known vocabulary: the builtin package names
  (partition/kill/pause/clock/disk) plus every ``KNOWN_FAULTS``
  constant declared across ``suites/``.
- ``proto-suite-exports`` — a name listed in ``suites/__init__.py``'s
  ``SUITES`` tuple whose module is missing or doesn't define the four
  documented seams (``db``/``client``/``workloads``/``test``).
- ``proto-unused-import`` — an import in a ``suites/`` module whose
  name is never referenced (scoped to suites: that's where dead
  protocol imports accumulate as clients get rewritten).

Suite rules key off directory names (``suites``/``checker``) so the
pass works identically on fixture trees in tests.  The known
workload/fault tables are parsed from this repo's own sources by
default and can be overridden through ``Project.options``
(``workload_names``/``fault_names``) for fixtures.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from .core import (Finding, FunctionIndex, Pass, Project, SourceFile,
                   cached_walk, dotted_name, load_file, register)

BUILTIN_FAULTS = {"partition", "kill", "pause", "clock", "disk"}
SUITE_SEAMS = ("db", "client", "workloads", "test")
CHECK_PARAMS = ("self", "test", "history", "opts")


def _pkg_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _literal_strs(node: ast.AST) -> Optional[List[str]]:
    """Elements of a tuple/list/set literal of string constants."""
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        out = []
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, str):
                out.append(el.value)
            else:
                return None  # non-literal element: don't judge
        return out
    return None


def _dict_keys(fn_body: ast.AST, dict_name: str) -> Set[str]:
    """String keys of every dict literal assigned to ``dict_name``
    inside ``fn_body`` (the `table = {...}` pattern)."""
    out: Set[str] = set()
    for node in cached_walk(fn_body):
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == dict_name
                for t in node.targets):
            if isinstance(node.value, ast.Dict):
                for k in node.value.keys:
                    if isinstance(k, ast.Constant) and isinstance(k.value, str):
                        out.add(k.value)
    return out


def known_workload_names(project: Project) -> Optional[Set[str]]:
    """The union of the generic table (suites/common.py) and the core
    table (workloads/__init__.py), parsed statically."""
    if "workload_names" in project.options:
        names = project.options["workload_names"]
        return set(names) if names is not None else None
    out: Set[str] = set()
    found = False
    for rel, fn_name in (
        (os.path.join("suites", "common.py"), "generic_workload"),
        (os.path.join("workloads", "__init__.py"), "_table"),
    ):
        path = os.path.join(_pkg_root(), rel)
        if not os.path.exists(path):
            continue
        sf = load_file(path, rel)
        if sf.tree is None:
            continue
        idx = FunctionIndex(sf.tree)
        for q, fn in idx.funcs.items():
            if q.rsplit(".", 1)[-1] == fn_name:
                keys = _dict_keys(fn, "table")
                if keys:
                    out |= keys
                    found = True
    return out if found else None


def known_fault_names(project: Project) -> Set[str]:
    if "fault_names" in project.options:
        return set(project.options["fault_names"] or ()) | BUILTIN_FAULTS
    out = set(BUILTIN_FAULTS)
    # every KNOWN_FAULTS constant across the scanned suites/ files AND
    # the real package (suites can import each other's menus)
    roots = [sf for sf in project.files_in("suites")]
    pkg_suites = os.path.join(_pkg_root(), "suites")
    if os.path.isdir(pkg_suites):
        for fn in sorted(os.listdir(pkg_suites)):
            if fn.endswith(".py"):
                roots.append(load_file(os.path.join(pkg_suites, fn),
                                       os.path.join("suites", fn)))
    for sf in roots:
        if sf.tree is None:
            continue
        for node in sf.tree.body:
            if isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Name) and "FAULTS" in t.id
                    for t in node.targets):
                vals = node.value
                if isinstance(vals, ast.Call):  # frozenset((...)) etc.
                    vals = vals.args[0] if vals.args else vals
                lits = _literal_strs(vals)
                if lits:
                    out |= set(lits)
    return out


class Protocol(Pass):
    name = "protocol"
    rules = ("proto-check-signature", "proto-check-return",
             "proto-workload-ref", "proto-fault-ref",
             "proto-suite-exports", "proto-unused-import")

    def run(self, project: Project) -> List[Finding]:
        out: List[Finding] = []
        for sf in project.files_in("checker"):
            if sf.tree is not None:
                self._check_checker(sf, out)
        suite_files = project.files_in("suites")
        if suite_files:
            workloads = known_workload_names(project)
            faults = known_fault_names(project)
            for sf in suite_files:
                if sf.tree is None:
                    continue
                if workloads is not None:
                    self._check_workload_refs(sf, workloads, out)
                self._check_fault_refs(sf, faults, out)
                self._check_unused_imports(sf, out)
            self._check_suite_exports(project, suite_files, out)
        return out

    # -- checker seam ------------------------------------------------------

    def _checker_classes(self, sf: SourceFile) -> List[Tuple[str, ast.ClassDef]]:
        idx = FunctionIndex(sf.tree)
        out = []
        for q, cls in idx.classes.items():
            if cls.name == "Checker":
                continue  # the ABC itself
            bases = {dotted_name(b) or "" for b in cls.bases}
            base_tail = {b.rsplit(".", 1)[-1] for b in bases}
            if "Checker" in base_tail or cls.name.endswith("Checker"):
                out.append((q, cls))
        return out

    def _check_checker(self, sf: SourceFile, out: List[Finding]) -> None:
        for q, cls in sorted(self._checker_classes(sf)):
            check_fn = None
            for node in cls.body:
                if (isinstance(node, ast.FunctionDef)
                        and node.name == "check"):
                    check_fn = node
                    break
            if check_fn is None:
                # inheriting check from a parent Checker subclass is
                # fine; only flag classes that directly subclass the ABC
                bases = {(dotted_name(b) or "").rsplit(".", 1)[-1]
                         for b in cls.bases}
                if bases == {"Checker"}:
                    self._emit(out, sf, "proto-check-signature", cls, q,
                               f"checker `{cls.name}` subclasses Checker"
                               " directly but defines no `check` method")
                continue
            self._check_signature(sf, q, check_fn, out)
            self._check_returns(sf, q, check_fn, out)

    def _check_signature(self, sf, q, fn: ast.FunctionDef, out) -> None:
        a = fn.args
        names = tuple(p.arg for p in a.args)
        ok = (
            names == CHECK_PARAMS
            and not a.posonlyargs and not a.kwonlyargs
            and a.vararg is None and a.kwarg is None
            and len(a.defaults) >= 1
            and isinstance(a.defaults[-1], ast.Constant)
            and a.defaults[-1].value is None
        )
        if not ok:
            self._emit(
                out, sf, "proto-check-signature", fn, f"{q}.check",
                f"`{q}.check` must have the universal seam signature"
                " `check(self, test, history, opts=None)` (check_safe and"
                f" compose call it positionally); found"
                f" ({', '.join(names) or 'no args'})")

    def _own_returns(self, fn: ast.FunctionDef) -> List[ast.Return]:
        """``return`` statements belonging to ``fn`` itself (nested
        defs/lambdas have their own contracts)."""
        out: List[ast.Return] = []

        def visit(node):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                    continue
                if isinstance(child, ast.Return):
                    out.append(child)
                visit(child)

        visit(fn)
        return out

    def _check_returns(self, sf, q, fn: ast.FunctionDef, out) -> None:
        for node in self._own_returns(fn):
            if node.value is None:
                continue
            v = node.value
            if isinstance(v, ast.Dict):
                keys = [k.value for k in v.keys
                        if isinstance(k, ast.Constant)]
                has_spread = any(k is None for k in v.keys)
                if "valid?" not in keys and not has_spread:
                    self._emit(
                        out, sf, "proto-check-return", node, f"{q}.check",
                        f"`{q}.check` returns a dict literal without a"
                        " \"valid?\" key — the verdict contract every"
                        " caller (check_safe, compose, CLI) reads")
            elif isinstance(v, (ast.List, ast.Tuple)) or (
                    isinstance(v, ast.Constant)
                    and v.value is not None
                    and not isinstance(v.value, dict)):
                self._emit(
                    out, sf, "proto-check-return", node, f"{q}.check",
                    f"`{q}.check` returns a non-dict literal — the seam"
                    " contract is a {\"valid?\": ...} dict (None is"
                    " normalized by check_safe)")

    # -- suite references --------------------------------------------------

    def _check_workload_refs(self, sf, known: Set[str], out) -> None:
        # direct literal calls
        for node in cached_walk(sf.tree):
            if isinstance(node, ast.Call):
                fname = (dotted_name(node.func) or "").rsplit(".", 1)[-1]
                if fname in ("generic_workload", "workload") and node.args:
                    arg = node.args[0]
                    if (isinstance(arg, ast.Constant)
                            and isinstance(arg.value, str)
                            and arg.value not in known):
                        self._emit(
                            out, sf, "proto-workload-ref", arg, "",
                            f"workload {arg.value!r} is not in the generic"
                            " or core workload tables (known:"
                            f" {', '.join(sorted(known))})")
        # module-level WORKLOADS constants (iterated into the tables)
        for node in sf.tree.body:
            if isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == "WORKLOADS"
                    for t in node.targets):
                lits = _literal_strs(node.value)
                for name in lits or ():
                    if name not in known:
                        self._emit(
                            out, sf, "proto-workload-ref", node, "",
                            f"WORKLOADS entry {name!r} is not in the"
                            " generic or core workload tables")

    def _check_fault_refs(self, sf, known: Set[str], out) -> None:
        for node in cached_walk(sf.tree):
            lists: List[ast.AST] = []
            if isinstance(node, ast.Call):
                # opts.get("faults", [...]) defaults
                fname = (dotted_name(node.func) or "").rsplit(".", 1)[-1]
                if (fname == "get" and len(node.args) == 2
                        and isinstance(node.args[0], ast.Constant)
                        and node.args[0].value == "faults"):
                    lists.append(node.args[1])
            elif isinstance(node, ast.Dict):
                for k, v in zip(node.keys, node.values):
                    if (isinstance(k, ast.Constant)
                            and k.value == "faults"):
                        lists.append(v)
            for lst in lists:
                for name in _literal_strs(lst) or ():
                    if name not in known:
                        self._emit(
                            out, sf, "proto-fault-ref", lst, "",
                            f"fault {name!r} is not a builtin package name"
                            f" ({', '.join(sorted(BUILTIN_FAULTS))}) or any"
                            " suite's KNOWN_FAULTS menu")

    def _check_unused_imports(self, sf, out) -> None:
        if os.path.basename(sf.path) == "__init__.py":
            has_all = any(
                isinstance(n, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == "__all__"
                    for t in n.targets)
                for n in sf.tree.body)
            if has_all:
                return  # re-export module
        imported: Dict[str, Tuple[int, int]] = {}
        for node in cached_walk(sf.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    name = (a.asname or a.name).split(".")[0]
                    imported[name] = (node.lineno, node.col_offset)
            elif isinstance(node, ast.ImportFrom):
                if node.module == "__future__":
                    continue
                for a in node.names:
                    if a.name == "*":
                        continue
                    imported[a.asname or a.name] = (node.lineno,
                                                    node.col_offset)
        used: Set[str] = set()
        for node in cached_walk(sf.tree):
            if isinstance(node, ast.Name):
                used.add(node.id)
        for name, (line, col) in sorted(imported.items(),
                                        key=lambda kv: kv[1]):
            if name in used:
                continue
            if sf.allowed(line, "proto-unused-import"):
                continue
            out.append(Finding(
                "proto-unused-import", sf.rel, line, col,
                f"`{name}` is imported but never used", ""))

    def _check_suite_exports(self, project, suite_files, out) -> None:
        init = None
        for sf in suite_files:
            if os.path.basename(sf.path) == "__init__.py":
                init = sf
                break
        if init is None or init.tree is None:
            return
        suites: List[str] = []
        decl = None
        for node in init.tree.body:
            if isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == "SUITES"
                    for t in node.targets):
                suites = _literal_strs(node.value) or []
                decl = node
        if not suites:
            return
        base = os.path.dirname(init.path)
        # resolve SIBLINGS of __init__.py only — a same-named module in
        # a subpackage (suites/proto/aerospike.py) is not the suite
        by_path = {os.path.abspath(sf.path): sf for sf in suite_files}
        for name in suites:
            fname = f"{name}.py"
            path = os.path.join(base, fname)
            sf = by_path.get(os.path.abspath(path))
            if sf is None and os.path.exists(path):
                sf = load_file(path, os.path.join("suites", fname))
            if sf is None or sf.tree is None:
                if not init.allowed(decl.lineno, "proto-suite-exports"):
                    out.append(Finding(
                        "proto-suite-exports", init.rel, decl.lineno, 0,
                        f"SUITES lists {name!r} but suites/{fname} does"
                        " not exist", "SUITES"))
                continue
            defined = {n.name for n in sf.tree.body
                       if isinstance(n, ast.FunctionDef)}
            missing = [s for s in SUITE_SEAMS if s not in defined]
            if missing and not init.allowed(decl.lineno,
                                            "proto-suite-exports"):
                out.append(Finding(
                    "proto-suite-exports", sf.rel, 1, 0,
                    f"suite `{name}` is missing the documented seam"
                    f" function(s): {', '.join(missing)} (suites/__init__"
                    " contract)", ""))

    def _emit(self, out, sf, rule, node, scope, msg) -> None:
        line = getattr(node, "lineno", 1)
        if sf.allowed(line, rule):
            return
        out.append(Finding(rule, sf.rel, line,
                           getattr(node, "col_offset", 0), msg, scope))


register(Protocol())
