"""obs-hygiene pass: span pairing + metric naming/registration checks.

The obs subsystem (PR 2) threads spans and metrics through every layer
seam.  Two failure modes are invisible at runtime: a span started but
never exited leaks onto the thread-local stack and silently re-parents
every later span on that thread; and a metric name that drifts from
the ``jepsen_*`` convention (or is registered under conflicting
instrument kinds) renders an invalid Prometheus exposition that only a
scraper would notice.

Rules:

- ``obs-span-discipline`` — a span handle (``obs.span(...)`` /
  ``tracer.span(...)``) used outside a ``with`` statement without a
  visible balanced ``__enter__``/``__exit__`` pair in the same
  function: a bare expression statement discards the context manager
  (the span never records), and a manual ``__enter__`` without an
  ``__exit__`` on all paths leaks it.  Returning the handle is fine —
  pairing becomes the caller's job (that's how ``obs.span`` itself
  delegates to the tracer).
- ``obs-metric-name`` — the metric name passed to a recording shorthand
  (``obs.count/gauge_set/gauge_max/observe``) or registry constructor
  (``.counter/.gauge/.histogram``) must be a string literal matching
  ``jepsen_[a-z0-9_]*`` (doc/observability.md's convention), or an
  f-string whose literal head carries the ``jepsen_`` prefix (the
  compile/execute-phase pattern).
- ``obs-metric-kind`` — one metric name used as two different
  instrument kinds across the scanned tree (e.g. ``obs.count`` in one
  module, ``obs.observe`` in another): the registry would intern both
  and the exposition would emit two conflicting TYPE lines.
- ``obs-metric-doc`` — a literal ``jepsen_*`` metric name recorded in
  code but missing from doc/observability.md's metric inventory:
  the doc is the operator contract; undocumented series are drift.
- ``obs-rate-kind`` — a ``*_rate1m`` metric name recorded as anything
  but a gauge: the ``_rate1m`` suffix is RESERVED for the
  sliding-window gauges ``metrics.prometheus_text`` synthesizes from
  cumulative instruments (doc/observability.md 'Fleet telemetry');
  hand-recording one as a counter/histogram would collide with the
  derived family.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from .core import (Finding, FunctionIndex, Pass, Project, SourceFile,
                   cached_walk, dotted_name, register)

METRIC_NAME_RE = re.compile(r"^jepsen_[a-z][a-z0-9_]*$")

#: obs-module shorthands -> instrument kind
OBS_SHORTHANDS = {
    "count": "counter",
    "gauge_set": "gauge",
    "gauge_max": "gauge",
    "observe": "histogram",
}
#: registry constructor methods -> kind (any receiver)
REGISTRY_CTORS = {
    "counter": "counter",
    "gauge": "gauge",
    "histogram": "histogram",
}

#: receivers whose ``.span(...)`` is a tracer span
SPAN_RECEIVERS = {"obs", "tracer", "_tracer", "self._tracer"}


def _default_doc_path() -> Optional[str]:
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    p = os.path.join(root, "doc", "observability.md")
    return p if os.path.exists(p) else None


def _metric_call(node: ast.Call) -> Optional[str]:
    """Instrument kind when this call registers/records a metric."""
    if not isinstance(node.func, ast.Attribute):
        return None
    attr = node.func.attr
    base = dotted_name(node.func.value)
    if attr in OBS_SHORTHANDS and base == "obs":
        return OBS_SHORTHANDS[attr]
    if attr in REGISTRY_CTORS:
        # registry method on any receiver — but require the first arg
        # to be string-ish so `histogram.observe(0.5)` style value
        # recordings (numeric arg) aren't misread as registrations
        if node.args and isinstance(node.args[0],
                                    (ast.Constant, ast.JoinedStr)):
            if isinstance(node.args[0], ast.JoinedStr):
                return REGISTRY_CTORS[attr]
            if isinstance(node.args[0].value, str):
                return REGISTRY_CTORS[attr]
        return None
    return None


class ObsHygiene(Pass):
    name = "obs-hygiene"
    rules = ("obs-span-discipline", "obs-metric-name", "obs-metric-kind",
             "obs-metric-doc", "obs-rate-kind")

    def run(self, project: Project) -> List[Finding]:
        out: List[Finding] = []
        #: name -> [(kind, sf, node)]
        sites: Dict[str, List[Tuple[str, SourceFile, ast.AST]]] = {}
        for sf in project.files:
            if sf.tree is None:
                continue
            idx = FunctionIndex(sf.tree)
            self._check_spans(sf, idx, out)
            self._check_metrics(sf, idx, sites, out)
        self._check_kinds(sites, out)
        self._check_doc(project, sites, out)
        return out

    # -- span pairing ------------------------------------------------------

    def _span_call(self, node: ast.Call) -> bool:
        if isinstance(node.func, ast.Attribute) and node.func.attr == "span":
            base = dotted_name(node.func.value)
            if base in SPAN_RECEIVERS:
                return True
            # tracer().span(...)
            if (isinstance(node.func.value, ast.Call)
                    and (dotted_name(node.func.value.func) or "").endswith(
                        "tracer")):
                return True
        return False

    def _check_spans(self, sf: SourceFile, idx: FunctionIndex,
                     out: List[Finding]) -> None:
        # classify every span call: with-item / returned / assigned /
        # bare.  Parent links via a single walk.
        parents: Dict[int, ast.AST] = {}
        for node in cached_walk(sf.tree):
            for child in ast.iter_child_nodes(node):
                parents[id(child)] = node
        for node in cached_walk(sf.tree):
            if not (isinstance(node, ast.Call) and self._span_call(node)):
                continue
            parent = parents.get(id(node))
            if isinstance(parent, ast.withitem):
                continue
            if isinstance(parent, ast.Return):
                continue  # delegation: pairing is the caller's job
            scope = idx.enclosing(sf.tree, node)
            if isinstance(parent, ast.Expr):
                self._emit(out, sf, "obs-span-discipline", node, scope,
                           "span created and discarded: the context manager"
                           " is never entered, so the span never records —"
                           " use `with obs.span(...):`")
                continue
            if isinstance(parent, ast.Assign) and all(
                    isinstance(t, ast.Name) for t in parent.targets):
                name = parent.targets[0].id
                fn_q = idx.enclosing(sf.tree, node)
                fn = idx.funcs.get(fn_q)
                body = fn if fn is not None else sf.tree
                entered = exited = False
                in_finally = False
                for n in cached_walk(body):
                    if (isinstance(n, ast.Call)
                            and isinstance(n.func, ast.Attribute)
                            and isinstance(n.func.value, ast.Name)
                            and n.func.value.id == name):
                        if n.func.attr == "__enter__":
                            entered = True
                        elif n.func.attr == "__exit__":
                            exited = True
                    if isinstance(n, ast.Try) and n.finalbody:
                        for fb in n.finalbody:
                            for m in cached_walk(fb):
                                if (isinstance(m, ast.Call)
                                        and isinstance(m.func, ast.Attribute)
                                        and isinstance(m.func.value, ast.Name)
                                        and m.func.value.id == name
                                        and m.func.attr == "__exit__"):
                                    in_finally = True
                    if (isinstance(n, ast.With) and any(
                            isinstance(it.context_expr, ast.Name)
                            and it.context_expr.id == name
                            for it in n.items)):
                        entered = exited = in_finally = True
                if entered and not in_finally:
                    self._emit(out, sf, "obs-span-discipline", node, scope,
                               f"span `{name}` is entered manually but has"
                               " no `__exit__` in a finally block — an"
                               " exception leaks the span onto the"
                               " thread-local stack")
                elif not entered and not exited:
                    self._emit(out, sf, "obs-span-discipline", node, scope,
                               f"span assigned to `{name}` but never"
                               " entered/exited in this function — use"
                               " `with`, or pair __enter__/__exit__ in a"
                               " try/finally")

    # -- metric naming -----------------------------------------------------

    def _check_metrics(self, sf, idx, sites, out) -> None:
        for node in cached_walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            kind = _metric_call(node)
            if kind is None:
                continue
            scope = idx.enclosing(sf.tree, node)
            if not node.args:
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                name = arg.value
                if not METRIC_NAME_RE.match(name):
                    self._emit(out, sf, "obs-metric-name", node, scope,
                               f"metric name {name!r} violates the"
                               " `jepsen_[a-z0-9_]*` naming convention"
                               " (doc/observability.md)")
                else:
                    if name.endswith("_rate1m") and kind != "gauge":
                        self._emit(out, sf, "obs-rate-kind", node, scope,
                                   f"metric {name!r} recorded as {kind}:"
                                   " the `_rate1m` suffix is reserved for"
                                   " the sliding-window gauges the"
                                   " exposition synthesizes — record the"
                                   " cumulative series and let"
                                   " prometheus_text derive the rate")
                    sites.setdefault(name, []).append((kind, sf, node))
            elif isinstance(arg, ast.JoinedStr):
                head = arg.values[0] if arg.values else None
                if not (isinstance(head, ast.Constant)
                        and isinstance(head.value, str)
                        and head.value.startswith("jepsen_")):
                    self._emit(out, sf, "obs-metric-name", node, scope,
                               "dynamic metric name must carry a literal"
                               " `jepsen_` prefix so the family is"
                               " greppable and convention-checked")
            else:
                self._emit(out, sf, "obs-metric-name", node, scope,
                           "metric name must be a string literal (or a"
                           " jepsen_-prefixed f-string): non-literal names"
                           " defeat static registration checks")

    def _check_kinds(self, sites, out) -> None:
        for name, entries in sorted(sites.items()):
            entries_sorted = sorted(
                entries, key=lambda e: (e[1].rel, e[2].lineno, e[2].col_offset)
            )
            first_kind = entries_sorted[0][0]
            for kind, sf, node in entries_sorted[1:]:
                if kind != first_kind:
                    # no line number in the message: it feeds the
                    # baseline fingerprint, which must survive line
                    # drift at the first site
                    self._emit(out, sf, "obs-metric-kind", node, "",
                               f"metric {name!r} recorded as {kind} here but"
                               f" as {first_kind} in"
                               f" {entries_sorted[0][1].rel} — one name,"
                               " one instrument kind")

    def _check_doc(self, project, sites, out) -> None:
        doc_path = project.options.get("metric_doc", "__default__")
        if doc_path == "__default__":
            doc_path = _default_doc_path()
        if not doc_path or not os.path.exists(doc_path):
            return
        with open(doc_path, "r", encoding="utf-8") as f:
            documented = set(re.findall(r"jepsen_[a-z0-9_]+", f.read()))
        for name, entries in sorted(sites.items()):
            if name in documented:
                continue
            kind, sf, node = sorted(
                entries, key=lambda e: (e[1].rel, e[2].lineno))[0]
            self._emit(out, sf, "obs-metric-doc", node, "",
                       f"metric {name!r} is recorded here but missing from"
                       f" {os.path.basename(doc_path)}'s inventory — "
                       "document the series or drop it")

    def _emit(self, out, sf, rule, node, scope, msg) -> None:
        if sf.allowed(node.lineno, rule):
            return
        out.append(Finding(rule, sf.rel, node.lineno, node.col_offset,
                           msg, scope))


register(ObsHygiene())
