"""Central registry of every ``JEPSEN_TPU_*`` environment variable.

The knobs grew organically — engine tuning, serve admission, probe
timeouts — and each one was documented (or not) wherever it was read.
This table is now the single source of truth: the ``seam-env-read``
rule (:mod:`jepsen_tpu.lint.contracts`) fails the build when code
reads a ``JEPSEN_TPU_*`` name that is not registered here, and
``seam-env-doc`` keeps the generated markdown table in
doc/configuration.md byte-identical to :func:`render_table`, so the
operator doc can never drift from the code again.

Regenerate the doc table with::

    python -m jepsen_tpu.lint.envvars > /tmp/t.md   # or paste inline

Registration is one tuple: name, default (as the operator sees it),
the module that reads it, and a one-line meaning.  Precedence for the
engine knobs is uniform (``tune.artifact.resolve_knob``): env var >
active calibration > pinned default.
"""

from __future__ import annotations

from typing import Iterator, NamedTuple, Tuple


class EnvVar(NamedTuple):
    name: str
    default: str
    read_in: str
    meaning: str


#: every environment variable the package reads, alphabetical
REGISTRY: Tuple[EnvVar, ...] = (
    EnvVar("JEPSEN_TPU_BREAKER_COOLDOWN", "5.0",
           "serve/client.py",
           "seconds an open circuit breaker waits before a half-open "
           "`/healthz` probe may close it again"),
    EnvVar("JEPSEN_TPU_BREAKER_FAILURES", "3",
           "serve/client.py",
           "consecutive connection-level failures that trip the "
           "breaker open; tripped calls fast-fail to in-process"),
    EnvVar("JEPSEN_TPU_CALIBRATION", "auto-discover",
           "tune/artifact.py",
           "calibration artifact path; `0`/`off` disables, unset "
           "auto-discovers `calibration.json`"),
    EnvVar("JEPSEN_TPU_CLIENT_BACKOFF", "0.1",
           "serve/client.py",
           "base seconds for client retry backoff (exponential with "
           "full jitter, capped by the deadline budget)"),
    EnvVar("JEPSEN_TPU_CLIENT_DEADLINE", "630.0",
           "serve/client.py",
           "per-request wall-clock budget in seconds across ALL retry "
           "attempts; a stalled daemon costs at most this"),
    EnvVar("JEPSEN_TPU_CLIENT_RETRIES", "2",
           "serve/client.py",
           "connection-level retries after the first attempt (never "
           "retries a 503 — the daemon answered)"),
    EnvVar("JEPSEN_TPU_CYCLES_CLOSURE", "auto",
           "ops/cycles.py",
           "closure kernel variant (`fixed`/`earlyexit`); env > "
           "calibration > default"),
    EnvVar("JEPSEN_TPU_CYCLES_IMPL", "auto",
           "ops/cycles.py",
           "closure squaring arithmetic (`uint8`/`packed32`/`bf16`); "
           "env > calibration > default"),
    EnvVar("JEPSEN_TPU_DENSE_UNION", "auto",
           "ops/dense.py",
           "dense-kernel subset-union lowering (`matmul`/`scan`); env "
           "> calibration > default"),
    EnvVar("JEPSEN_TPU_DRIFT", "1",
           "serve/daemon.py",
           "cost-model drift sentinel at the `serve()` production "
           "entry (rides the dispatch journal); falsy disables"),
    EnvVar("JEPSEN_TPU_DRIFT_THRESHOLD", "2.0",
           "obs/drift.py",
           "per-shape EWMA residual deviation (max(r, 1/r)) at which "
           "a dispatch shape counts as stale and the sentinel "
           "recommends a retune; must exceed 1.0"),
    EnvVar("JEPSEN_TPU_ELLE_SCREEN", "auto",
           "elle/cycles.py",
           "Elle cycle-screen routing: `auto`/`1` (device screens) or "
           "`0` (pure CPU classify)"),
    EnvVar("JEPSEN_TPU_ENGINE_BUCKETED", "1",
           "engine/planning.py",
           "shape bucketing; `0` pads every history to one (E, C)"),
    EnvVar("JEPSEN_TPU_ENGINE_DECOMPOSE", "1",
           "engine/decompose.py",
           "key-partition decomposition front-end; `0` disables"),
    EnvVar("JEPSEN_TPU_ENGINE_FLUSH_ROWS", "calibration or 1024",
           "engine/planning.py",
           "planner flush threshold in rows; env > calibration > "
           "default"),
    EnvVar("JEPSEN_TPU_ENGINE_MESH", "auto",
           "parallel/mesh.py",
           "device-mesh resolution: `auto`, `0` (single device), `1` "
           "(force, virtualizing on CPU)"),
    EnvVar("JEPSEN_TPU_ENGINE_ROW_BUCKET", "calibration or auto",
           "engine/execution.py",
           "dispatch row-bucket size; env > calibration > default"),
    EnvVar("JEPSEN_TPU_ENGINE_WINDOW", "calibration or 4",
           "engine/execution.py",
           "in-flight dispatch-window depth (1 = serial); env > "
           "calibration > default"),
    EnvVar("JEPSEN_TPU_FRONTIER_COMPACTION", "auto",
           "ops/wgl.py",
           "frontier hot-path compaction mode (`auto`/`on`/`off`)"),
    EnvVar("JEPSEN_TPU_JOURNAL", "dispatch-journal.jsonl",
           "serve/daemon.py",
           "dispatch-journal path for the `serve()` production entry; "
           "falsy disables"),
    EnvVar("JEPSEN_TPU_LINT_CACHE", "lint/.jaxpr_cache.json",
           "lint/jaxpr_audit.py",
           "jaxpr-audit incremental result cache path (package-"
           "relative default); falsy disables caching, every lint run "
           "re-traces"),
    EnvVar("JEPSEN_TPU_LINT_JAXPR", "1",
           "lint/jaxpr_audit.py",
           "`0` disables the traced half of the jaxpr audit (budget/"
           "shape-pin/host-sync/retrace); the AST rules still run"),
    EnvVar("JEPSEN_TPU_LIVE", "unset",
           "interpreter.py",
           "`1` ships history events to the checker daemon as they "
           "land (online checking); never blocks or fails the "
           "workload — a full buffer drops and counts.  Requires a "
           "test-level wire model; keyed workloads stay post-hoc"),
    EnvVar("JEPSEN_TPU_OBS", "1",
           "obs/__init__.py",
           "observability master switch; `0` disables span + metric "
           "recording globally"),
    EnvVar("JEPSEN_TPU_OBS_MAX_SERIES", "512",
           "obs/metrics.py",
           "per-family label-cardinality cap; overflow folds into an "
           "`{overflow=\"1\"}` series"),
    EnvVar("JEPSEN_TPU_ORACLE_WORKERS", "4",
           "checker/linear.py",
           "CPU-oracle worker-pool width for concurrent fallback "
           "searches"),
    EnvVar("JEPSEN_TPU_PROBE_RETRIES", "3",
           "platform.py",
           "TPU backend probe attempts before falling back"),
    EnvVar("JEPSEN_TPU_PROBE_TIMEOUT", "90",
           "platform.py",
           "seconds per backend probe attempt"),
    EnvVar("JEPSEN_TPU_PROBE_TRAIL", "unset",
           "platform.py",
           "path for the probe's diagnostic trail file; unset "
           "disables"),
    EnvVar("JEPSEN_TPU_ROUTE_PROBE_INTERVAL", "1.0",
           "serve/router.py",
           "seconds between the fleet router's `/healthz` membership "
           "sweeps; a dead member's keys re-route within one "
           "interval"),
    EnvVar("JEPSEN_TPU_ROUTE_PROBE_TIMEOUT", "0.5",
           "serve/router.py",
           "per-member timeout for one router health probe"),
    EnvVar("JEPSEN_TPU_SERVE_AOT_CACHE", "unset",
           "serve/daemon.py",
           "shared fleet-wide AOT executable cache directory "
           "(manifest + persistent XLA cache); a restarted member "
           "warms from it before `/healthz` goes ready and answers "
           "its first request with zero cold dispatches; unset "
           "disables"),
    EnvVar("JEPSEN_TPU_SERVE_COALESCE_WAIT", "0.0",
           "serve/daemon.py",
           "seconds the device thread lingers after the first queued "
           "request, collecting coalescing company"),
    EnvVar("JEPSEN_TPU_SERVE_HOST", "127.0.0.1",
           "serve/client.py",
           "daemon host the service client targets"),
    EnvVar("JEPSEN_TPU_SERVE_JIT_CACHE", "unset",
           "serve/daemon.py",
           "persistent jit-compilation cache directory for the "
           "`serve()` production entry; a supervised restart rewarms "
           "from it; unset disables"),
    EnvVar("JEPSEN_TPU_SERVE_MAX_QUEUE", "8",
           "serve/daemon.py",
           "admission bound in queued runs; excess requests get 503 "
           "and fall back in-process"),
    EnvVar("JEPSEN_TPU_SERVE_PORT", "8519",
           "serve/client.py",
           "daemon TCP port (client and daemon sides)"),
    EnvVar("JEPSEN_TPU_SERVE_REQUEST_TIMEOUT", "600.0",
           "serve/daemon.py",
           "seconds a handler waits on the device thread before "
           "answering 500"),
    EnvVar("JEPSEN_TPU_SERVICE", "unset",
           "serve/client.py",
           "service routing: `1` requires the resident daemon, `auto` "
           "spawns one, `0`/unset stays in-process"),
    EnvVar("JEPSEN_TPU_WAL", "verdict-wal.jsonl",
           "serve/daemon.py",
           "verdict write-ahead-log path for the `serve()` production "
           "entry; settled verdicts survive kill -9 and replay into "
           "retried request ids; falsy disables"),
    EnvVar("JEPSEN_TPU_WAL_COMPACT_BYTES", "33554432",
           "serve/daemon.py",
           "WAL size past which the daemon compacts away completed "
           "runs' rows during idle turns; `0` disables"),
)


def names() -> frozenset:
    return frozenset(v.name for v in REGISTRY)


def render_table() -> str:
    """The generated markdown table for doc/configuration.md —
    ``seam-env-doc`` pins the committed doc to exactly this output."""
    lines = [
        "| variable | default | read in | meaning |",
        "|---|---|---|---|",
    ]
    for v in REGISTRY:
        lines.append(
            f"| `{v.name}` | {v.default} | `{v.read_in}` | {v.meaning} |"
        )
    return "\n".join(lines)


def iter_registry() -> Iterator[EnvVar]:
    return iter(REGISTRY)


if __name__ == "__main__":
    print(render_table())
