"""jtlint v3: jaxpr-level kernel certification.

The AST passes certify the *source text*; this pass certifies the
*lowered program*.  Every registered kernel — the ``# jt: traced``
step roots plus the knob-tunable kernel factories — is abstractly
traced with ``jax.make_jaxpr`` over ``ShapeDtypeStruct`` specs (CPU
only, no device work, no compilation) across the full knob
cross-product (``closure_impl × closure_mode × union`` and the shape
buckets the registry declares), and four contracts are checked
against each traced jaxpr:

- ``jaxpr-budget`` — the measured peak loop-carried resident bytes
  per batch row (walking the jaxpr, while_loop carries and scan
  residents included) must sit inside the declared band relative to
  the budget math's claimed per-row pricing (``cycles_max_dispatch``
  / ``frontier_max_dispatch`` words × word size).  A mispriced knob
  is a lint failure instead of a chip OOM.
- ``jaxpr-shape-pin`` — declared ``dot_general``-count and dominant
  loop-carry-dtype contracts, checked per knob combination, so the
  one-off pins that used to live in bespoke tests become per-kernel
  annotations.
- ``jaxpr-host-sync`` — callback/infeed/outfeed primitives inside a
  kernel jaxpr (a host round-trip per dispatch).
- ``jaxpr-retrace`` — weak-typed 0-d closure captures (a python
  scalar funneled through ``jnp``): every new python value retraces
  the kernel silently.

Two further rules need no tracing:

- ``jaxpr-cache-key`` — AST dataflow from tuned-knob resolver call
  sites (any function whose body calls ``resolve_knob``) to cache-key
  construction: a resolver called *inside* an ``lru_cache`` body
  bypasses the key; a wrapper that resolves a knob but doesn't pass
  the value into its cached-factory call leaks it; a cached factory
  taking a knob parameter must stamp it on the returned fn
  (``fn.closure_impl`` &c.) and ``shard_fn``'s executable cache key
  must read every stamped knob back.
- ``jaxpr-coverage`` — a ``# jt: traced`` def in a registry module
  with no audit registry entry: the new kernel is invisible to
  certification until registered.

Contract annotations ride the ``# jt:`` directive channel, on the
kernel/factory def line (or the line above)::

    # jt: jaxpr(dot_generals<=2*log2n+3, dtype[packed32]=uint32, budget=0.2..0.6)

Clauses (comma-separated, all optional):

- ``dot_generals<=EXPR`` — upper bound on dot_general count (scan
  bodies multiply by trip count); EXPR is an integer expression over
  ``n``, ``log2n``, ``E``, ``C``, ``F``, ``V`` and literals with
  ``+``/``-``/``*``.
- ``dtype=DT`` / ``dtype[KNOBVALUE]=DT`` — dominant (largest-byte)
  loop-carry dtype, optionally conditional on a knob value in the
  active combination.
- ``budget=LO..HI`` — declared band for measured/claimed per-row
  bytes.  The measured metric is the *slope* of peak resident bytes
  between two batch sizes, so closure state and top-level inputs
  (priced separately, by row count) don't pollute it.

Tracing is expensive (~seconds across the cross-product), so results
are cached content-addressed: sha1 of the rule version, this module's
own source, and every registry anchor file's text.  A warm ``make
lint`` never imports jax at all.  ``JEPSEN_TPU_LINT_CACHE`` moves (or
falsily disables) the cache file; ``JEPSEN_TPU_LINT_JAXPR=0``
disables the traced half outright (the AST rules still run).
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
import re
from typing import (Any, Callable, Dict, Iterable, List, Optional, Sequence,
                    Tuple)

from .core import (Finding, FunctionIndex, Pass, Project, SourceFile,
                   cached_walk, dotted_name, register)

#: bump to invalidate every cached audit result
RULE_VERSION = "1"

#: default incremental-cache location (package-relative, like the
#: baseline; gitignored)
DEFAULT_CACHE = os.path.join(os.path.dirname(__file__), ".jaxpr_cache.json")

#: knob-named factory parameters and the fn attribute each must be
#: stamped as, so ``mesh.shard_fn``'s executable cache key can read it
KNOB_PARAM_ATTR: Dict[str, str] = {
    "mode": "closure_mode",
    "impl": "closure_impl",
    "closure_mode": "closure_mode",
    "closure_impl": "closure_impl",
    "union": "union_mode",
    "union_mode": "union_mode",
    "compaction": "compaction",
}

#: the stampable knob attributes (values of KNOB_PARAM_ATTR)
KNOB_ATTRS = tuple(sorted(set(KNOB_PARAM_ATTR.values())))


# -- contract annotations ----------------------------------------------------


_JAXPR_RE = re.compile(r"jaxpr\(([^)]*)\)")
_BUDGET_RE = re.compile(r"^budget=([0-9.]+)\.\.([0-9.]+)$")
_DOTS_RE = re.compile(r"^dot_generals<=(.+)$")
_DTYPE_RE = re.compile(r"^dtype(?:\[([A-Za-z0-9_]+)\])?=([A-Za-z0-9_]+)$")


class Contract:
    """One parsed ``jaxpr(...)`` annotation."""

    __slots__ = ("dot_generals", "dtypes", "budget")

    def __init__(self) -> None:
        self.dot_generals: Optional[str] = None
        #: knob-value condition (None = unconditional) -> dtype name
        self.dtypes: Dict[Optional[str], str] = {}
        self.budget: Optional[Tuple[float, float]] = None


def parse_contract(directives: Iterable[str]) -> Optional[Contract]:
    """The contract in a directive list, or None.  Unknown clauses are
    ignored (forward compatibility: an older lint must not fail on a
    newer clause)."""
    for d in directives:
        m = _JAXPR_RE.search(d)
        if not m:
            continue
        c = Contract()
        for clause in m.group(1).split(","):
            clause = clause.strip().replace(" ", "")
            if not clause:
                continue
            b = _BUDGET_RE.match(clause)
            if b:
                c.budget = (float(b.group(1)), float(b.group(2)))
                continue
            g = _DOTS_RE.match(clause)
            if g:
                c.dot_generals = g.group(1)
                continue
            t = _DTYPE_RE.match(clause)
            if t:
                c.dtypes[t.group(1)] = t.group(2)
        return c
    return None


def eval_bound(expr: str, env: Dict[str, int]) -> Optional[int]:
    """Evaluate a ``dot_generals`` bound expression: integer literals
    and the names in ``env`` under ``+``/``-``/``*`` only."""
    try:
        tree = ast.parse(expr, mode="eval")
    except SyntaxError:
        return None

    def ev(node: ast.AST) -> Optional[int]:
        if isinstance(node, ast.Expression):
            return ev(node.body)
        if isinstance(node, ast.Constant) and isinstance(node.value, int):
            return node.value
        if isinstance(node, ast.Name):
            v = env.get(node.id)
            return int(v) if isinstance(v, int) else None
        if isinstance(node, ast.BinOp) and isinstance(
                node.op, (ast.Add, ast.Sub, ast.Mult)):
            left, right = ev(node.left), ev(node.right)
            if left is None or right is None:
                return None
            if isinstance(node.op, ast.Add):
                return left + right
            if isinstance(node.op, ast.Sub):
                return left - right
            return left * right
        return None

    return ev(tree)


# -- jaxpr walking (duck-typed: no jax import needed at module load) ---------


def _as_jaxpr(v: Any):
    """The raw Jaxpr behind ``v`` (Jaxpr or ClosedJaxpr), else None."""
    if hasattr(v, "eqns"):
        return v
    inner = getattr(v, "jaxpr", None)
    if inner is not None and hasattr(inner, "eqns"):
        return inner
    return None


def _sub_jaxprs(eqn) -> Iterable[Any]:
    for v in eqn.params.values():
        j = _as_jaxpr(v)
        if j is not None:
            yield j
        elif isinstance(v, (tuple, list)):
            for x in v:
                j = _as_jaxpr(x)
                if j is not None:
                    yield j


def aval_bytes(v: Any) -> int:
    aval = getattr(v, "aval", v)
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    total = 1
    for d in shape:
        total *= int(d)
    return total * int(dtype.itemsize)


def peak_resident(jaxpr, outer: int = 0) -> int:
    """Peak loop-carried resident bytes: for every structured-control
    region, the bytes that must stay live across iterations (while
    carries; scan carries + consumed xs + stacked ys), maximized over
    nesting.  Deliberately NOT full liveness — XLA fuses away most
    intermediate values, so the loop-carried state is the stable,
    fusion-independent floor the budget math prices."""
    best = outer
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name == "while":
            carry = sum(aval_bytes(v) for v in eqn.outvars)
            body = _as_jaxpr(eqn.params["body_jaxpr"])
            cond = _as_jaxpr(eqn.params["cond_jaxpr"])
            best = max(best, peak_resident(body, outer + carry))
            best = max(best, peak_resident(cond, outer + carry))
        elif name == "scan":
            nc = eqn.params["num_carry"]
            ncon = eqn.params["num_consts"]
            resident = (
                sum(aval_bytes(v) for v in eqn.invars[ncon:])
                + sum(aval_bytes(v) for v in eqn.outvars[nc:])
            )
            best = max(best, peak_resident(_as_jaxpr(eqn.params["jaxpr"]),
                                           outer + resident))
        else:
            for sub in _sub_jaxprs(eqn):
                best = max(best, peak_resident(sub, outer))
    return best


def count_dot_generals(jaxpr) -> int:
    """dot_general count, scan bodies multiplied by trip count (the
    unrolled-program count the MXU actually sees)."""
    total = 0
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name == "dot_general":
            total += 1
        elif name == "scan":
            total += eqn.params["length"] * count_dot_generals(
                _as_jaxpr(eqn.params["jaxpr"]))
        else:
            for sub in _sub_jaxprs(eqn):
                total += count_dot_generals(sub)
    return total


def _carries(jaxpr, acc: List[Tuple[int, str]]) -> List[Tuple[int, str]]:
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name == "while":
            for v in eqn.outvars:
                acc.append((aval_bytes(v), str(v.aval.dtype)))
            _carries(_as_jaxpr(eqn.params["body_jaxpr"]), acc)
        elif name == "scan":
            nc = eqn.params["num_carry"]
            ncon = eqn.params["num_consts"]
            for v in eqn.invars[ncon:ncon + nc]:
                acc.append((aval_bytes(v), str(v.aval.dtype)))
            _carries(_as_jaxpr(eqn.params["jaxpr"]), acc)
        else:
            for sub in _sub_jaxprs(eqn):
                _carries(sub, acc)
    return acc


def dominant_dtype(closed) -> Optional[str]:
    """Dominant (largest-byte) loop-carry dtype; kernels with no loops
    fall back to the dominant output dtype."""
    cand = _carries(closed.jaxpr, [])
    if not cand:
        cand = [(aval_bytes(v), str(v.aval.dtype))
                for v in closed.jaxpr.outvars]
    if not cand:
        return None
    return max(cand)[1]


def host_sync_prims(jaxpr) -> List[str]:
    """Host round-trip primitives anywhere in the jaxpr, sorted."""
    out: set = set()

    def walk(j) -> None:
        for eqn in j.eqns:
            name = eqn.primitive.name
            if "callback" in name or name in ("infeed", "outfeed"):
                out.add(name)
            for sub in _sub_jaxprs(eqn):
                walk(sub)

    walk(jaxpr)
    return sorted(out)


def weak_scalar_consts(closed) -> List[str]:
    """Dtypes of weak-typed 0-d closure captures (python scalars that
    went through jnp): each new python value silently retraces."""
    out: List[str] = []
    for c in getattr(closed, "consts", ()):
        aval = getattr(c, "aval", None)
        if (aval is not None and getattr(aval, "weak_type", False)
                and getattr(aval, "shape", None) == ()):
            out.append(str(aval.dtype))
    return sorted(out)


# -- kernel registry ---------------------------------------------------------


class KernelEntry:
    """One certifiable kernel: where it anchors in the source (path
    suffix + def qualname — the contract annotation and suppressions
    live there), how to build it per knob combination, and the spec
    shapes to trace it at."""

    __slots__ = ("name", "path", "scope", "axes", "shapes", "build",
                 "arg_specs", "claimed")

    def __init__(
        self,
        name: str,
        path: str,
        scope: str,
        build: Callable[[dict, dict], Any],
        arg_specs: Callable[[dict, int], tuple],
        axes: Optional[Dict[str, Tuple[str, ...]]] = None,
        shapes: Sequence[dict] = ({},),
        claimed: Optional[Callable[[dict, dict], Optional[float]]] = None,
    ):
        self.name = name
        self.path = path
        self.scope = scope
        self.build = build
        self.arg_specs = arg_specs
        self.axes = dict(axes or {})
        self.shapes = tuple(shapes)
        self.claimed = claimed


def knob_combos(axes: Dict[str, Tuple[str, ...]]) -> List[Dict[str, str]]:
    combos: List[Dict[str, str]] = [{}]
    for key in sorted(axes):
        combos = [dict(c, **{key: v}) for c in combos for v in axes[key]]
    return combos


def combo_label(shape: dict, knobs: dict) -> str:
    items = [(k, v) for k, v in shape.items() if isinstance(v, (int, str))]
    items += list(knobs.items())
    return " ".join(f"{k}={v}" for k, v in sorted(items))


def _history_specs(shape: dict, batch: int) -> tuple:
    """The batched history checkers' 6-array input contract
    (ops/encode.py EncodedBatch)."""
    import jax.numpy as jnp
    from jax import ShapeDtypeStruct as SDS
    E, C = shape["E"], shape["C"]
    return (
        SDS((batch,), jnp.int32),
        SDS((batch, E), jnp.int32),
        SDS((batch, E, C), jnp.int8),
        SDS((batch, E, C), jnp.int8),
        SDS((batch, E, C), jnp.int16),
        SDS((batch, E, C), jnp.int16),
    )


def _claimed_cycles(n_filters: int, n_lifted: int):
    def claimed(shape: dict, knobs: dict) -> Optional[float]:
        from jepsen_tpu.ops import cycles
        cap = cycles.cycles_max_dispatch(
            shape["n"], n_filters, n_lifted, max_dispatch=10 ** 9,
            impl=knobs["impl"])
        if not cap:
            return None
        words = cycles.CYCLES_DISPATCH_BUDGET // cap
        word_bytes = 4 if knobs["impl"] == "packed32" else 2
        return float(words * word_bytes)

    return claimed


def _claimed_frontier(shape: dict, knobs: dict) -> Optional[float]:
    from jepsen_tpu.ops import wgl
    cap = wgl.frontier_max_dispatch(
        shape["F"], shape["E"], shape["C"], max_dispatch=10 ** 9)
    if not cap:
        return None
    return float((wgl.FRONTIER_DISPATCH_BUDGET // cap) * 4)


def _step_entry(fn_name: str) -> KernelEntry:
    def build(shape: dict, knobs: dict):
        from jepsen_tpu.ops import step_kernels
        return getattr(step_kernels, fn_name)

    def args(shape: dict, batch: int) -> tuple:
        import jax.numpy as jnp
        from jax import ShapeDtypeStruct as SDS
        return tuple(SDS((), jnp.int32) for _ in range(4))

    return KernelEntry(fn_name, "ops/step_kernels.py", fn_name, build, args)


_STEP_NAMES = (
    "register_step", "cas_register_step", "mutex_step",
    "reentrant_mutex_step", "multi_register_step", "unordered_queue_step",
)

#: the transactional-screen probe profile the audit traces at (one
#: representative mask/nonadjacency set; the contract must hold for
#: any, the budget formula is parametric in (F, Q))
_SCREEN_MASKS = (1, 3, 7)
_SCREEN_NONADJ = ((4, 3),)

_CLOSURE_AXES = {
    "mode": ("fixed", "earlyexit"),
    "impl": ("uint8", "packed32", "bf16"),
}


def default_registry() -> Tuple[KernelEntry, ...]:
    """Every production kernel the audit certifies.  Builders import
    lazily so a warm cache hit (or a fixture run with no anchors)
    never imports jax or the ops modules."""

    def build_cyclic(shape: dict, knobs: dict):
        from jepsen_tpu.ops import cycles
        return cycles._cyclic_fn(shape["n"], knobs["mode"], knobs["impl"])

    def args_rel_bool(shape: dict, batch: int) -> tuple:
        import jax.numpy as jnp
        from jax import ShapeDtypeStruct as SDS
        return (SDS((batch, shape["n"], shape["n"]), jnp.bool_),)

    def build_screen(shape: dict, knobs: dict):
        from jepsen_tpu.ops import cycles
        return cycles._screen_fn_variant(
            shape["n"], _SCREEN_MASKS, _SCREEN_NONADJ, True,
            knobs["mode"], knobs["impl"])

    def args_rel_u8(shape: dict, batch: int) -> tuple:
        import jax.numpy as jnp
        from jax import ShapeDtypeStruct as SDS
        return (SDS((batch, shape["n"], shape["n"]), jnp.uint8),)

    def build_dense(shape: dict, knobs: dict):
        from jepsen_tpu.ops import dense
        return dense._make_dense_fn_cached(
            shape["spec"], shape["E"], shape["C"], shape["V"],
            knobs["union"])

    def build_frontier(shape: dict, knobs: dict):
        from jepsen_tpu.ops import wgl
        return wgl._make_check_fn(
            shape["spec"], shape["E"], shape["C"], shape["F"],
            shape["max_closure"], knobs["compaction"])

    entries = [_step_entry(n) for n in _STEP_NAMES]
    entries.append(KernelEntry(
        "cyclic", "ops/cycles.py", "_cyclic_fn",
        build_cyclic, args_rel_bool, axes=_CLOSURE_AXES,
        shapes=({"n": 32}, {"n": 64}),
        claimed=_claimed_cycles(1, 0),
    ))
    entries.append(KernelEntry(
        "screen", "ops/cycles.py", "_screen_fn_variant",
        build_screen, args_rel_u8, axes=_CLOSURE_AXES,
        shapes=({"n": 32},),
        claimed=_claimed_cycles(len(_SCREEN_MASKS), len(_SCREEN_NONADJ)),
    ))
    entries.append(KernelEntry(
        "dense", "ops/dense.py", "_make_dense_fn_cached",
        build_dense, _history_specs,
        axes={"union": ("unroll", "gather", "matmul")},
        shapes=({"spec": "register", "E": 16, "C": 4, "V": 8},
                {"spec": "unordered-queue", "E": 16, "C": 4, "V": 0}),
    ))
    entries.append(KernelEntry(
        "frontier", "ops/wgl.py", "_make_check_fn",
        build_frontier, _history_specs,
        axes={"compaction": ("hash", "sort")},
        shapes=({"spec": "register", "E": 16, "C": 4, "F": 64,
                 "max_closure": 5},),
        claimed=_claimed_frontier,
    ))
    return tuple(entries)


# -- the pass ----------------------------------------------------------------


_LRU_NAMES = ("lru_cache", "cache")


def _is_cached(fn: ast.AST) -> bool:
    for dec in getattr(fn, "decorator_list", ()):
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = dotted_name(target) or ""
        if name.rsplit(".", 1)[-1] in _LRU_NAMES:
            return True
    return False


def _is_jit_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = dotted_name(node.func) or ""
    return name.rsplit(".", 1)[-1] == "jit"


def _returns_jitted(fn: ast.AST) -> bool:
    """Does this factory hand back a jitted callable?  Either a
    ``jax.jit(...)`` call in the body or a nested def decorated with
    jit."""
    for node in cached_walk(fn):
        if _is_jit_call(node):
            return True
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node is not fn and any(
                    _is_jit_call(d) or (dotted_name(d) or "").endswith("jit")
                    for d in node.decorator_list):
                return True
    return False


def _call_name(node: ast.Call) -> str:
    return (dotted_name(node.func) or "").rsplit(".", 1)[-1]


def _fn_index(sf: SourceFile) -> FunctionIndex:
    """Per-file FunctionIndex, memoized on the SourceFile (the pass
    walks every file twice: resolver discovery, then the dataflow
    checks)."""
    idx = getattr(sf, "_jaxpr_fn_index", None)
    if idx is None:
        idx = FunctionIndex(sf.tree)
        sf._jaxpr_fn_index = idx
    return idx


def _knob_stamps(fn: ast.AST) -> set:
    """Knob attributes stamped on fn objects in this function's body
    (``anything.closure_impl = …`` with a knob-attr name)."""
    stamps: set = set()
    for node in cached_walk(fn):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Attribute)
                and node.targets[0].attr in KNOB_ATTRS
                and isinstance(node.targets[0].value, ast.Name)
                and node.targets[0].value.id != "self"):
            stamps.add(node.targets[0].attr)
    return stamps


class JaxprAudit(Pass):
    name = "jaxpr-audit"
    rules = (
        "jaxpr-budget",
        "jaxpr-cache-key",
        "jaxpr-coverage",
        "jaxpr-host-sync",
        "jaxpr-retrace",
        "jaxpr-shape-pin",
    )

    # -- plumbing ------------------------------------------------------------

    def run(self, project: Project) -> List[Finding]:
        out: List[Finding] = []
        registry = project.options.get("jaxpr_registry")
        custom = registry is not None
        if registry is None:
            registry = default_registry()
        self._check_cache_keys(project, out)
        self._check_coverage(project, registry, out)
        self._run_traced(project, registry, custom, out)
        return out

    def _emit(self, out: List[Finding], sf: SourceFile, rule: str,
              line: int, col: int, scope: str, msg: str) -> None:
        if not sf.allowed(line, rule):
            out.append(Finding(rule, sf.rel, line, col, msg, scope))

    # -- jaxpr-cache-key (AST dataflow, no tracing) --------------------------

    def _resolver_names(self, project: Project) -> set:
        """Program-wide tuned-knob resolvers: any function whose body
        calls ``resolve_knob`` (the one sanctioned env > calibration >
        default ladder, tune/artifact.py)."""
        names = set()
        for sf in project.files:
            if sf.tree is None:
                continue
            idx = _fn_index(sf)
            for q, fn in idx.funcs.items():
                for node in cached_walk(fn):
                    if (isinstance(node, ast.Call)
                            and _call_name(node) == "resolve_knob"):
                        names.add(q.rsplit(".", 1)[-1])
                        break
        names.discard("resolve_knob")
        return names

    def _check_cache_keys(self, project: Project,
                          out: List[Finding]) -> None:
        resolvers = self._resolver_names(project)
        stamped_attrs: set = set()
        shard_fns: List[Tuple[SourceFile, ast.AST, str]] = []
        for sf in project.files:
            if sf.tree is None:
                continue
            idx = FunctionIndex(sf.tree)
            cached_factories = {
                q.rsplit(".", 1)[-1]: fn for q, fn in idx.funcs.items()
                if _is_cached(fn)
            }
            for q, fn in idx.funcs.items():
                if q.rsplit(".", 1)[-1] == "shard_fn":
                    shard_fns.append((sf, fn, q))
                stamped_attrs.update(_knob_stamps(fn))
                if _is_cached(fn):
                    self._cached_body_resolvers(sf, fn, q, resolvers, out)
                    self._knob_params_stamped(sf, fn, q, out)
                else:
                    self._resolved_reaches_factory(
                        sf, fn, q, resolvers, cached_factories, out)
        for sf, fn, q in shard_fns:
            self._shard_key_reads(sf, fn, q, stamped_attrs, out)

    def _cached_body_resolvers(self, sf: SourceFile, fn: ast.AST, q: str,
                               resolvers: set, out: List[Finding]) -> None:
        """A knob resolver called inside an lru_cache body: the
        resolved value can flip under the cached entry's feet — the
        caller must resolve and pass it as a key parameter."""
        for node in cached_walk(fn):
            if isinstance(node, ast.Call) and _call_name(node) in resolvers:
                self._emit(
                    out, sf, "jaxpr-cache-key", node.lineno, node.col_offset,
                    q,
                    f"knob resolver `{_call_name(node)}()` is called inside"
                    f" the lru_cache'd body of `{q}` — the resolved value"
                    " bypasses the cache key, so a knob flip resolves a"
                    " stale cached kernel; resolve in the caller and pass"
                    " the value as a parameter")

    def _resolved_reaches_factory(self, sf: SourceFile, fn: ast.AST, q: str,
                                  resolvers: set, factories: Dict[str, Any],
                                  out: List[Finding]) -> None:
        """A wrapper that resolves a knob AND calls a cached factory
        must pass the resolved value into the factory call (directly
        or via a local), or the factory's key can't distinguish knob
        states."""
        factory_calls = [
            node for node in cached_walk(fn)
            if isinstance(node, ast.Call) and _call_name(node) in factories
        ]
        if not factory_calls:
            return
        arg_nodes: List[ast.AST] = []
        for call in factory_calls:
            for a in call.args:
                arg_nodes.extend(cached_walk(a))
            for kw in call.keywords:
                arg_nodes.extend(cached_walk(kw.value))
        arg_names = {n.id for n in arg_nodes if isinstance(n, ast.Name)}
        direct_arg_calls = {id(n) for n in arg_nodes
                            if isinstance(n, ast.Call)}
        for node in cached_walk(fn):
            if not (isinstance(node, ast.Call)
                    and _call_name(node) in resolvers):
                continue
            if id(node) in direct_arg_calls:
                continue
            bound: Optional[str] = None
            for stmt in cached_walk(fn):
                if (isinstance(stmt, ast.Assign) and stmt.value is node
                        and len(stmt.targets) == 1
                        and isinstance(stmt.targets[0], ast.Name)):
                    bound = stmt.targets[0].id
            if bound is not None and bound in arg_names:
                continue
            self._emit(
                out, sf, "jaxpr-cache-key", node.lineno, node.col_offset, q,
                f"`{q}` resolves `{_call_name(node)}()` and calls a cached"
                " kernel factory, but the resolved value is not passed"
                " into the factory call — the factory's lru key cannot"
                " see this knob")

    def _knob_params_stamped(self, sf: SourceFile, fn: ast.AST, q: str,
                             out: List[Finding]) -> None:
        """A cached factory taking a knob-named parameter must stamp it
        on the returned fn (``fn.closure_impl = impl`` style) so the
        mesh shard_fn executable cache can key on it."""
        if not _returns_jitted(fn):
            return
        stamps = _knob_stamps(fn)
        for arg in getattr(fn.args, "args", ()):
            attr = KNOB_PARAM_ATTR.get(arg.arg)
            if attr is None or attr in stamps:
                continue
            self._emit(
                out, sf, "jaxpr-cache-key", fn.lineno, fn.col_offset, q,
                f"cached kernel factory `{q}` keys on knob parameter"
                f" `{arg.arg}` but never stamps it on the returned fn"
                f" (`fn.{attr} = {arg.arg}`) — mesh.shard_fn's executable"
                " cache key cannot see it, so two knob states share one"
                " sharded executable")

    def _shard_key_reads(self, sf: SourceFile, fn: ast.AST, q: str,
                         stamped_attrs: set, out: List[Finding]) -> None:
        """Every knob attribute any factory stamps must be read back by
        ``shard_fn`` (``getattr(check_fn, "<attr>", ...)``) into its
        cache key."""
        read: set = set()
        for node in cached_walk(fn):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "getattr" and len(node.args) >= 2
                    and isinstance(node.args[1], ast.Constant)
                    and isinstance(node.args[1].value, str)):
                read.add(node.args[1].value)
        for attr in sorted(stamped_attrs - read):
            self._emit(
                out, sf, "jaxpr-cache-key", fn.lineno, fn.col_offset, q,
                f"kernel factories stamp `fn.{attr}` but `{q}`'s"
                " executable cache key never reads it back"
                f" (`getattr(check_fn, \"{attr}\", ...)`) — the sharded"
                " executable cache keys on fewer fields than the kernel"
                " lru key")

    # -- jaxpr-coverage ------------------------------------------------------

    def _check_coverage(self, project: Project,
                        registry: Sequence[KernelEntry],
                        out: List[Finding]) -> None:
        suffixes = sorted({e.path for e in registry})
        covered = {(e.path, e.scope) for e in registry}
        for sf in project.files:
            if sf.tree is None:
                continue
            match = [sfx for sfx in suffixes if sf.rel.endswith(sfx)]
            if not match:
                continue
            idx = _fn_index(sf)
            for q, fn in idx.funcs.items():
                if not sf.marked(fn.lineno, "traced"):
                    continue
                if any((sfx, q) in covered for sfx in match):
                    continue
                self._emit(
                    out, sf, "jaxpr-coverage", fn.lineno, fn.col_offset, q,
                    f"`{q}` is marked `# jt: traced` in a registry module"
                    " but has no jaxpr-audit registry entry — the kernel"
                    " ships uncertified; add a KernelEntry (see"
                    " doc/static-analysis.md \"jaxpr audit\")")

    # -- traced rules --------------------------------------------------------

    def _trace_enabled(self) -> bool:
        v = os.environ.get("JEPSEN_TPU_LINT_JAXPR", "1").strip().lower()
        return v not in ("0", "off", "false", "no", "")

    def _cache_path(self, project: Project, custom: bool) -> Optional[str]:
        if "jaxpr_cache" in project.options:
            return project.options["jaxpr_cache"] or None
        if custom:
            # a custom registry's identity isn't content-hashable;
            # don't share the default cache with it
            return None
        env = os.environ.get("JEPSEN_TPU_LINT_CACHE")
        if env is not None:
            env = env.strip()
            if env.lower() in ("", "0", "off", "false", "no"):
                return None
            return env
        return DEFAULT_CACHE

    def _cache_key(self, anchored) -> str:
        h = hashlib.sha1()
        h.update(RULE_VERSION.encode())
        try:
            with open(__file__, "rb") as f:
                h.update(f.read())
        except OSError:  # pragma: no cover — zipapp install
            pass
        for entry, sf, line, _ in sorted(
                anchored, key=lambda a: (a[1].rel, a[0].scope)):
            h.update(f"\x1f{entry.name}\x1f{entry.path}\x1f{entry.scope}"
                     f"\x1f{sorted(entry.axes.items())!r}"
                     f"\x1f{entry.shapes!r}\x1f{sf.rel}\x1f".encode())
            h.update(sf.text.encode())
        return h.hexdigest()

    def _anchor(self, project: Project, registry: Sequence[KernelEntry]):
        """Registry entries whose anchor def exists in the scanned file
        set.  Tracing only ever happens for anchored entries, so
        fixture runs (and path-subset runs) never import jax for
        kernels outside their scope."""
        anchored = []
        for entry in registry:
            sf = project.file_named(entry.path)
            if sf is None or sf.tree is None:
                continue
            fn = _fn_index(sf).funcs.get(entry.scope)
            if fn is None:
                continue
            contract = parse_contract(sf._at(fn.lineno))
            anchored.append((entry, sf, fn.lineno, contract))
        anchored.sort(key=lambda a: (a[1].rel, a[0].scope, a[0].name))
        return anchored

    def _run_traced(self, project: Project,
                    registry: Sequence[KernelEntry], custom: bool,
                    out: List[Finding]) -> None:
        if not self._trace_enabled():
            return
        anchored = self._anchor(project, registry)
        if not anchored:
            return
        cache_path = self._cache_path(project, custom)
        key = self._cache_key(anchored) if cache_path else None
        if cache_path and os.path.exists(cache_path):
            try:
                with open(cache_path, "r", encoding="utf-8") as f:
                    data = json.load(f)
                if (isinstance(data, dict) and data.get("version") == 1
                        and data.get("key") == key):
                    for d in data.get("findings", ()):
                        out.append(Finding(
                            d["rule"], d["path"], d["line"], d["col"],
                            d["message"], d.get("scope", "")))
                    return
            except (OSError, ValueError, KeyError, TypeError):
                pass  # unreadable cache = miss
        fresh: List[Finding] = []
        for entry, sf, line, contract in anchored:
            self._audit_entry(entry, sf, line, contract, fresh)
        out.extend(fresh)
        if cache_path:
            payload = {
                "version": 1,
                "key": key,
                "findings": [
                    {"rule": f.rule, "path": f.path, "line": f.line,
                     "col": f.col, "message": f.message, "scope": f.scope}
                    for f in fresh
                ],
            }
            try:
                with open(cache_path, "w", encoding="utf-8") as f:
                    json.dump(payload, f, indent=2, sort_keys=True)
                    f.write("\n")
            except OSError:
                pass  # read-only checkout: audit still ran, just uncached

    def _audit_entry(self, entry: KernelEntry, sf: SourceFile, line: int,
                     contract: Optional[Contract],
                     out: List[Finding]) -> None:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        import jax
        for shape in entry.shapes:
            env = {k: v for k, v in shape.items() if isinstance(v, int)}
            if "n" in env:
                env["log2n"] = max(0, env["n"] - 1).bit_length()
            for knobs in knob_combos(entry.axes):
                label = combo_label(shape, knobs)
                try:
                    fn = entry.build(shape, knobs)
                    closed = jax.make_jaxpr(fn)(*entry.arg_specs(shape, 2))
                except Exception as e:  # noqa: BLE001 — a kernel that
                    # won't abstractly trace is itself a finding, not a
                    # crashed lint run
                    self._emit(
                        out, sf, "jaxpr-shape-pin", line, 0, entry.scope,
                        f"kernel `{entry.name}` failed to trace at"
                        f" [{label}]: {type(e).__name__}: {e}")
                    continue
                self._rule_host_sync(entry, sf, line, closed, label, out)
                self._rule_retrace(entry, sf, line, closed, label, out)
                if contract is not None:
                    self._rule_shape_pin(
                        entry, sf, line, contract, closed, env, knobs,
                        label, out)
                    self._rule_budget(
                        entry, sf, line, contract, closed, shape, knobs,
                        label, fn, out)

    def _rule_host_sync(self, entry, sf, line, closed, label, out) -> None:
        for prim in host_sync_prims(closed.jaxpr):
            self._emit(
                out, sf, "jaxpr-host-sync", line, 0, entry.scope,
                f"kernel `{entry.name}` contains host round-trip"
                f" primitive `{prim}` at [{label}] — every dispatch"
                " synchronizes with the host; hoist the callback out of"
                " the traced region")

    def _rule_retrace(self, entry, sf, line, closed, label, out) -> None:
        weak = weak_scalar_consts(closed)
        if weak:
            self._emit(
                out, sf, "jaxpr-retrace", line, 0, entry.scope,
                f"kernel `{entry.name}` closes over {len(weak)} weak-typed"
                f" python scalar(s) ({', '.join(weak)}) at [{label}] —"
                " each new python value silently retraces; capture via"
                " an explicitly-dtyped array or pass as a traced"
                " argument")

    def _rule_shape_pin(self, entry, sf, line, contract, closed, env,
                        knobs, label, out) -> None:
        if contract.dot_generals is not None:
            bound = eval_bound(contract.dot_generals, env)
            if bound is None:
                self._emit(
                    out, sf, "jaxpr-shape-pin", line, 0, entry.scope,
                    f"kernel `{entry.name}`: dot_generals bound"
                    f" `{contract.dot_generals}` does not evaluate over"
                    f" {sorted(env)} — fix the annotation")
            else:
                dots = count_dot_generals(closed.jaxpr)
                if dots > bound:
                    self._emit(
                        out, sf, "jaxpr-shape-pin", line, 0, entry.scope,
                        f"kernel `{entry.name}` lowers to {dots}"
                        f" dot_generals at [{label}], above the declared"
                        f" pin dot_generals<={contract.dot_generals}"
                        f" (={bound}) — the MXU recast regressed")
        if contract.dtypes:
            expected = None
            for value in sorted(knobs.values()):
                if value in contract.dtypes:
                    expected = contract.dtypes[value]
                    break
            if expected is None:
                expected = contract.dtypes.get(None)
            if expected is not None:
                dom = dominant_dtype(closed)
                if dom is not None and dom != expected:
                    self._emit(
                        out, sf, "jaxpr-shape-pin", line, 0, entry.scope,
                        f"kernel `{entry.name}`'s dominant loop-carry"
                        f" dtype is {dom} at [{label}], contract declares"
                        f" {expected} — the lowering changed arithmetic"
                        " width")

    def _rule_budget(self, entry, sf, line, contract, closed2, shape,
                     knobs, label, fn, out) -> None:
        if contract.budget is None or entry.claimed is None:
            return
        claimed = entry.claimed(shape, knobs)
        if not claimed:
            return
        import jax
        closed4 = jax.make_jaxpr(fn)(*entry.arg_specs(shape, 4))
        p2 = peak_resident(closed2.jaxpr)
        p4 = peak_resident(closed4.jaxpr)
        per_row = (p4 - p2) / 2.0
        ratio = per_row / claimed
        lo, hi = contract.budget
        if not (lo <= ratio <= hi):
            self._emit(
                out, sf, "jaxpr-budget", line, 0, entry.scope,
                f"kernel `{entry.name}` measures {per_row:.0f} resident"
                f" bytes/row at [{label}] = {ratio:.2f}x the claimed"
                f" per-row pricing ({claimed:.0f} B), outside the"
                f" declared band {lo}..{hi} — the budget math and the"
                " lowering disagree; reprice or re-band with a rationale")


register(JaxprAudit())
