"""jtlint core: source model, pass registry, suppressions, baseline.

The framework half of :mod:`jepsen_tpu.lint` (the passes live in
sibling modules).  Everything is stdlib-``ast`` based — no imports of
the code under analysis, so linting ``ops/`` never initializes JAX and
a syntax error in one file is one finding, not a crashed run.

Concepts:

- :class:`SourceFile` — one parsed file: text, lines, AST, and the
  ``# jt: …`` directives found in it.  Parses are cached per
  ``(path, mtime, size)`` in a module-level table so the common
  lint-twice pattern (CLI run + self-check test, or ``--write-baseline``
  followed by a verify run) never re-parses an unchanged file.
- :class:`Project` — the whole scanned file set plus resolved options;
  passes that need cross-file context (workload tables, metric name
  registry, the suite list) read it here.
- :class:`Pass` — one registered analysis.  A pass owns one or more
  rule ids; ``lint_paths(rules=…)`` filters at the finding level so a
  pass may be partially enabled.
- :class:`Finding` — one diagnostic, with a stable fingerprint
  (rule + path + enclosing scope + message + occurrence index — line
  numbers deliberately excluded so unrelated edits above a grandfathered
  finding don't churn the baseline).
- Baseline — a committed JSON file of fingerprints for grandfathered
  findings.  Matching findings are demoted to "baselined" (reported
  only with ``--show-baselined``, never failing); baseline entries with
  no matching finding are reported as **stale** warnings so the file
  monotonically shrinks (see ``doc/static-analysis.md``).

Directive syntax (one trailing comment, same line or the line above):

- ``# jt: allow[rule-id]`` / ``# jt: allow[rule-a, rule-b]`` /
  ``# jt: allow[*]`` — suppress findings of those rules on that line.
- ``# jt: guarded-by(<lock>)`` — the attribute assigned on this line is
  protected by ``self.<lock>`` (or the reserved name ``owner-thread``:
  single-thread confinement).
- ``# jt: holds(<lock>)`` — this function runs with ``<lock>`` already
  held by its caller.
- ``# jt: thread-entry`` — this function runs on a foreign thread.
- ``# jt: traced`` — this function is traced by jit/vmap/pmap through
  an indirection the call-graph builder can't see (e.g. a spec table).
- ``# jt: timing`` — this function is a declared measurement loop
  (the autotuner's dispatch-and-sync harness): ``trace-sync`` findings
  inside it are sanctioned as a unit, nested defs included.
"""

from __future__ import annotations

import ast
import hashlib
import io
import json
import os
import re
import time
import tokenize
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple

#: the committed baseline of grandfathered findings (package-relative,
#: so the CLI finds it from any working directory)
DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")

#: a directive must START its comment (`# jt: …`), so prose comments
#: *mentioning* the syntax — or string literals containing it — are
#: never live directives (comments come from the tokenizer, not a
#: line-level regex, exactly to keep strings out)
_DIRECTIVE_RE = re.compile(r"^#+\s*jt:\s*(.+?)\s*$")
_ALLOW_RE = re.compile(r"allow\[([^\]]*)\]")
_GUARDED_RE = re.compile(r"guarded-by\(([^)]+)\)")
_HOLDS_RE = re.compile(r"holds\(([^)]+)\)")

#: reserved guarded-by "lock" meaning single-thread confinement
OWNER_THREAD = "owner-thread"


class Finding:
    """One diagnostic.  ``scope`` is the enclosing class/function
    qualname (fingerprint stability under line drift); ``occurrence``
    disambiguates identical findings in one scope."""

    __slots__ = ("rule", "path", "line", "col", "message", "scope",
                 "occurrence", "baselined")

    def __init__(self, rule: str, path: str, line: int, col: int,
                 message: str, scope: str = ""):
        self.rule = rule
        self.path = path
        self.line = line
        self.col = col
        self.message = message
        self.scope = scope
        self.occurrence = 0
        self.baselined = False

    def fingerprint(self) -> str:
        raw = "\x1f".join(
            (self.rule, self.path, self.scope, self.message,
             str(self.occurrence))
        )
        return hashlib.sha1(raw.encode("utf-8")).hexdigest()[:16]

    def sort_key(self) -> tuple:
        return (self.path, self.line, self.col, self.rule, self.message)

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "scope": self.scope,
            "fingerprint": self.fingerprint(),
            "baselined": self.baselined,
        }

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"


class SourceFile:
    """One parsed source file plus its ``# jt:`` directives."""

    def __init__(self, path: str, rel: str, text: str):
        self.path = path
        self.rel = rel
        self.text = text
        self.lines = text.splitlines()
        self.parse_error: Optional[SyntaxError] = None
        try:
            self.tree: Optional[ast.Module] = ast.parse(text, filename=path)
        except SyntaxError as e:
            self.tree = None
            self.parse_error = e
        # line -> directive text (the part after "jt:"); real COMMENT
        # tokens only, so a docstring documenting the syntax or a
        # string literal containing it can never suppress anything
        self.directives: Dict[int, str] = {}
        if "jt:" in text:
            try:
                for tok in tokenize.generate_tokens(
                        io.StringIO(text).readline):
                    if tok.type == tokenize.COMMENT:
                        m = _DIRECTIVE_RE.match(tok.string)
                        if m:
                            self.directives[tok.start[0]] = m.group(1)
            except (tokenize.TokenError, IndentationError, SyntaxError):
                pass  # unparseable file: parse-error finding, no directives

    # -- directive lookups -------------------------------------------------

    def _at(self, line: int) -> List[str]:
        """Directives attached to ``line``: its own trailing comment or a
        standalone comment on the line immediately above."""
        out = []
        d = self.directives.get(line)
        if d is not None:
            out.append(d)
        prev = self.directives.get(line - 1)
        if prev is not None and line - 2 < len(self.lines):
            if self.lines[line - 2].lstrip().startswith("#"):
                out.append(prev)
        return out

    def allowed(self, line: int, rule: str) -> bool:
        for d in self._at(line):
            m = _ALLOW_RE.search(d)
            if not m:
                continue
            ids = {s.strip() for s in m.group(1).split(",")}
            if "*" in ids or rule in ids:
                return True
        return False

    def guarded_by(self, line: int) -> Optional[str]:
        for d in self._at(line):
            m = _GUARDED_RE.search(d)
            if m:
                return m.group(1).strip()
        return None

    def holds(self, line: int) -> Optional[str]:
        for d in self._at(line):
            m = _HOLDS_RE.search(d)
            if m:
                return m.group(1).strip()
        return None

    def marked(self, line: int, word: str) -> bool:
        return any(
            word in re.split(r"[\s,]+", d) for d in self._at(line)
        )


#: parse cache: abspath -> (mtime_ns, size, SourceFile)
_CACHE: Dict[str, Tuple[int, int, SourceFile]] = {}


def load_file(path: str, rel: str) -> SourceFile:
    st = os.stat(path)
    key = os.path.abspath(path)
    hit = _CACHE.get(key)
    if hit is not None and hit[0] == st.st_mtime_ns and hit[1] == st.st_size:
        return hit[2]
    with open(path, "r", encoding="utf-8") as f:
        text = f.read()
    sf = SourceFile(path, rel, text)
    _CACHE[key] = (st.st_mtime_ns, st.st_size, sf)
    return sf


class Project:
    """The scanned file set plus resolved cross-file context."""

    def __init__(self, files: List[SourceFile], options: Optional[dict] = None):
        self.files = files
        self.options = dict(options or {})

    def files_in(self, dirname: str) -> List[SourceFile]:
        """Files whose path contains a directory component ``dirname``."""
        out = []
        for sf in self.files:
            parts = os.path.normpath(sf.path).split(os.sep)
            if dirname in parts[:-1]:
                out.append(sf)
        return out

    def file_named(self, suffix: str) -> Optional[SourceFile]:
        suffix = suffix.replace("/", os.sep)
        for sf in self.files:
            if sf.path.endswith(suffix):
                return sf
        return None


class Pass:
    """One registered analysis pass."""

    name: str = ""
    rules: Tuple[str, ...] = ()

    def run(self, project: Project) -> List[Finding]:
        raise NotImplementedError


_PASSES: List[Pass] = []


def register(p: Pass) -> Pass:
    _PASSES.append(p)
    return p


def all_passes() -> List[Pass]:
    _ensure_registered()
    return list(_PASSES)


def all_rules() -> List[str]:
    out = []
    for p in all_passes():
        out.extend(p.rules)
    return sorted(out)


_registered = False


def _ensure_registered() -> None:
    global _registered
    if _registered:
        return
    _registered = True
    # importing the pass modules registers them
    from . import budget  # noqa: F401
    from . import concurrency  # noqa: F401
    from . import contracts  # noqa: F401
    from . import jaxpr_audit  # noqa: F401
    from . import lock_discipline  # noqa: F401
    from . import obs_hygiene  # noqa: F401
    from . import protocol  # noqa: F401
    from . import trace_safety  # noqa: F401


# -- path collection --------------------------------------------------------


def _rel_for(path: str) -> str:
    """Display/baseline path: stable ``jepsen_tpu/…`` for package files
    regardless of cwd; cwd-relative otherwise; absolute as a last
    resort."""
    ap = os.path.abspath(path)
    pkg_parent = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    for base in (pkg_parent, os.getcwd()):
        try:
            rel = os.path.relpath(ap, base)
        except ValueError:  # pragma: no cover — windows drive mismatch
            continue
        if not rel.startswith(".."):
            return rel.replace(os.sep, "/")
    return ap.replace(os.sep, "/")


def collect_files(paths: Sequence[str]) -> List[SourceFile]:
    seen = set()
    out: List[SourceFile] = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(
                    d for d in dirnames
                    if d != "__pycache__" and not d.startswith(".")
                )
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        fp = os.path.join(dirpath, fn)
                        ap = os.path.abspath(fp)
                        if ap not in seen:
                            seen.add(ap)
                            out.append(load_file(fp, _rel_for(fp)))
        elif p.endswith(".py") and os.path.isfile(p):
            ap = os.path.abspath(p)
            if ap not in seen:
                seen.add(ap)
                out.append(load_file(p, _rel_for(p)))
    out.sort(key=lambda sf: sf.rel)
    return out


# -- runner -----------------------------------------------------------------


class LintResult:
    def __init__(self, findings: List[Finding], stale: List[dict],
                 n_files: int, timings: Dict[str, float]):
        self.findings = findings          # every non-baselined finding
        self.baselined: List[Finding] = []
        self.stale = stale                # stale baseline entries
        self.n_files = n_files
        self.timings = timings
        self.scanned_paths: set = set()   # rel paths of scanned files

    @property
    def ok(self) -> bool:
        return not self.findings


def _dedup_occurrences(findings: List[Finding]) -> None:
    """Assign occurrence indices so identical findings in one scope get
    distinct fingerprints (keyed in sorted order for determinism)."""
    counts: Dict[tuple, int] = {}
    for f in sorted(findings, key=Finding.sort_key):
        key = (f.rule, f.path, f.scope, f.message)
        f.occurrence = counts.get(key, 0)
        counts[key] = f.occurrence + 1


def lint_paths(
    paths: Sequence[str],
    rules: Optional[Iterable[str]] = None,
    options: Optional[dict] = None,
    baseline: Optional[dict] = None,
) -> LintResult:
    """Run every registered pass over ``paths``; returns the result with
    baseline matching already applied (``baseline=None`` skips it)."""
    files = collect_files(paths)
    project = Project(files, options)
    enabled = set(rules) if rules is not None else None
    findings: List[Finding] = []
    timings: Dict[str, float] = {}
    for sf in files:
        if sf.parse_error is not None:
            findings.append(Finding(
                "parse-error", sf.rel, sf.parse_error.lineno or 1, 0,
                f"syntax error: {sf.parse_error.msg}",
            ))
    for p in all_passes():
        if enabled is not None and not (set(p.rules) & enabled):
            continue
        t0 = time.perf_counter()
        for f in p.run(project):
            if enabled is not None and f.rule not in enabled:
                continue
            findings.append(f)
        timings[p.name] = time.perf_counter() - t0
    findings.sort(key=Finding.sort_key)
    _dedup_occurrences(findings)

    scanned = {sf.rel for sf in files}
    stale: List[dict] = []
    kept: List[Finding] = []
    baselined: List[Finding] = []
    if baseline:
        entries = {e["fp"]: e for e in baseline.get("findings", ())}
        matched = set()
        for f in findings:
            fp = f.fingerprint()
            if fp in entries:
                f.baselined = True
                matched.add(fp)
                baselined.append(f)
            else:
                kept.append(f)
        for fp, e in sorted(entries.items()):
            # an entry is stale only when its FILE was scanned, its
            # RULE was enabled, and the finding is gone — a subset run
            # (`lint suites/a.py`, `--rules trace-sync`) must not
            # report out-of-scope grandfathered entries as stale
            if (fp not in matched and e.get("path") in scanned
                    and (enabled is None or e.get("rule") in enabled)):
                stale.append(e)
    else:
        kept = findings
    res = LintResult(kept, stale, len(files), timings)
    res.baselined = baselined
    res.scanned_paths = scanned
    return res


# -- baseline I/O -----------------------------------------------------------


def load_baseline(path: str) -> Optional[dict]:
    if not os.path.exists(path):
        return None
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    if not isinstance(data, dict) or data.get("version") != 1:
        raise ValueError(f"unrecognized baseline format in {path!r}")
    return data


def make_baseline(findings: List[Finding]) -> dict:
    return {
        "version": 1,
        "findings": [
            {
                "fp": f.fingerprint(),
                "rule": f.rule,
                "path": f.path,
                "message": f.message,
            }
            for f in sorted(findings, key=Finding.sort_key)
        ],
    }


def write_baseline(path: str, findings: List[Finding]) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(make_baseline(findings), f, indent=2, sort_keys=True)
        f.write("\n")


# -- shared AST helpers (used by several passes) ----------------------------


def cached_walk(node: ast.AST):
    """Flattened ``ast.walk`` order, memoized on the node itself.

    The seven passes traverse the same trees dozens of times (whole
    module, per function, per class), and the trees are immutable once
    parsed — so the flattened order is computed once per root and
    cached as an attribute.  This is the single biggest lever on the
    suite's 10 s interactive wall-clock budget."""
    cached = getattr(node, "_jt_walk_cache", None)
    if cached is None:
        cached = tuple(ast.walk(node))
        try:
            node._jt_walk_cache = cached
        except AttributeError:  # pragma: no cover — slotted node types
            return cached
    return cached


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class FunctionIndex:
    """Every function/method in a module, by qualname, with parents."""

    def __init__(self, tree: ast.Module):
        self.funcs: Dict[str, ast.AST] = {}
        self.parents: Dict[str, Optional[str]] = {}
        self.classes: Dict[str, ast.ClassDef] = {}
        self._walk(tree.body, None)

    def _walk(self, body, scope: Optional[str]) -> None:
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{scope}.{node.name}" if scope else node.name
                self.funcs[q] = node
                self.parents[q] = scope
                self._walk(node.body, q)
            elif isinstance(node, ast.ClassDef):
                q = f"{scope}.{node.name}" if scope else node.name
                self.classes[q] = node
                self._walk(node.body, q)
            else:
                # descend through compound statements (if/with/try/for)
                # so conditionally-defined functions are indexed in the
                # same scope
                self._walk(list(ast.iter_child_nodes(node)), scope)

    def qualname_at(self, target: ast.AST) -> str:
        for q, fn in self.funcs.items():
            if fn is target:
                return q
        return ""

    def enclosing(self, tree: ast.Module, node: ast.AST) -> str:
        """Qualname of the innermost function/class containing ``node``
        (by position)."""
        best = ""
        best_span = None
        for table in (self.funcs, self.classes):
            for q, f in table.items():
                if (f.lineno <= node.lineno
                        and node.lineno <= (f.end_lineno or f.lineno)):
                    span = (f.end_lineno or f.lineno) - f.lineno
                    if best_span is None or span < best_span:
                        best, best_span = q, span
        return best


def call_targets(fn: ast.AST) -> List[str]:
    """Simple names called inside ``fn`` (``g(...)`` and
    ``self.g(...)``), nested defs/lambdas included — a closure defined
    here runs on behalf of this function as far as reachability is
    concerned (conservative for both tracing and thread analysis).
    Bare names merely *referenced* (e.g. passed as a callback) count
    too, for the same reason."""
    out: List[str] = []
    for node in cached_walk(fn):
        if isinstance(node, ast.Call):
            if isinstance(node.func, ast.Name):
                out.append(node.func.id)
            elif (isinstance(node.func, ast.Attribute)
                  and isinstance(node.func.value, ast.Name)
                  and node.func.value.id == "self"):
                out.append(node.func.attr)
        elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            out.append(node.id)
    return out
