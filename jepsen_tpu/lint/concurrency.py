"""concurrency pass: whole-program race inference, no annotations needed.

lock-discipline (PR 5) is opt-in: it checks the five modules that
declare `# jt: guarded-by` contracts and is silent everywhere else.
The serve tier and the fleet work stacked on top of it multiply the
thread surface faster than hand annotation can follow, so this pass
inverts the burden of proof: it *infers* which state is shared and
which locks actually protect it, across every scanned file at once.

The inference, in order:

1. **Thread roots.**  ``# jt: thread-entry`` marks, ``threading.Thread
   (target=f)``, ``pool.submit(f, …)``, ``on_retire=f`` retirement
   callbacks, and — structurally — ``do_*`` methods of classes whose
   bases mention ``RequestHandler`` (http.server dispatches each
   request on its own thread; the mark inside daemon.py's ``do_GET``
   comment is prose, the class shape is the contract).
2. **Call graph.**  Same-module calls, ``self.m()``, imported-module
   ``alias.f()``, constructor-typed locals and ``self.attr`` receivers
   (``executor = execution.Executor(…)`` → ``executor.submit`` →
   ``Executor.submit``), and a conservative class-hierarchy fallback:
   an unresolved ``x.m()`` edges to ``m`` only when at most
   :data:`CHA_MAX` scanned classes define it and ``m`` isn't a builtin
   collection method (``seen.add(…)`` must not edge into
   ``_SlotRing.add``).  Nested defs run on behalf of their parent.
3. **Colors.**  Every root seeds its own color; functions nothing in
   the scanned tree calls (public API) and module-import-time call
   targets seed ``main``.  Colors flow caller → callee to a fixpoint;
   state touched under ≥2 colors is *shared*.
4. **Locksets.**  A function's effective lockset is ``holds(fn)`` ∪
   the *intersection* over its call sites of (``with``-scope locks at
   the site ∪ the caller's effective set) — a decreasing fixpoint
   from ⊤.  This proves e.g. that a helper is only ever entered with
   the registry lock held, without any ``holds`` annotation.
5. **Happens-before.**  Hand-offs through ``Future.result()`` /
   ``queue.get()`` are modeled implicitly: accesses through typed
   *locals* of another class are out of scope (the request object
   crossing the queue is the hand-off), and accesses textually after
   a ``.wait()``/``.join()``/``.result()`` in the same body are
   exempt from drift findings (the write they observe was published
   before the synchronization edge).

State tracked: ``self._*`` attributes accessed in their owning class,
and module globals.  ``__init__`` is exempt (construction precedes
sharing); attributes holding synchronization primitives are skipped;
attributes already carrying ``# jt: guarded-by`` stay lock-discipline's
contract (this pass instead *audits the annotations themselves*).

Rules:

- ``concurrency-unguarded-shared`` — shared state mutated with an
  empty effective lockset.  The worst bug class a checker can have:
  corruption that only *occasionally* happens.
- ``concurrency-guard-drift`` — every mutation of the state agrees on
  a lock, but this access doesn't hold it (the classic forgotten-lock
  read that works until it doesn't).
- ``concurrency-lock-missing`` — a ``guarded-by(L)``/``holds(L)``
  annotation naming a lock the module never constructs: the
  annotation drifted from the code it documents.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, FrozenSet, List, NamedTuple, Optional, Set, Tuple

from .core import (Finding, FunctionIndex, OWNER_THREAD, Pass, Project,
                   SourceFile, cached_walk, dotted_name, register)

#: method calls that mutate their receiver
MUTATORS = frozenset({
    "append", "appendleft", "extend", "insert", "add", "discard",
    "remove", "pop", "popleft", "popitem", "clear", "update",
    "setdefault", "rotate", "sort", "reverse", "write", "writelines",
    "put", "put_nowait",
})

#: constructors whose product is a synchronization object (or a thread
#: handle) — the primitive itself is not a data race
SYNC_CTORS = frozenset({
    "Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore",
    "Event", "Barrier", "Queue", "SimpleQueue", "LifoQueue",
    "PriorityQueue", "local", "Thread",
})

#: calls that establish a happens-before edge for what follows them
WAIT_CALLS = frozenset({"wait", "join", "result"})

#: builtin collection methods the CHA fallback must never edge through
CHA_BLOCKLIST = frozenset({
    "add", "append", "get", "pop", "update", "clear", "remove",
    "discard", "items", "keys", "values", "extend", "insert", "sort",
    "count", "index", "copy", "join", "split", "read", "write",
    "close", "put", "set", "release", "acquire", "notify",
    "notify_all", "start",
})

#: max program classes defining a method before CHA gives up on it
CHA_MAX = 3

MAIN_COLOR = "main"

FnKey = Tuple[str, str]          # (module, fn qualname)
StateKey = Tuple[str, str, str]  # (module, class qualname or "", attr)


class Access(NamedTuple):
    key: StateKey
    kind: str                    # "read" | "write"
    site_locks: FrozenSet[str]
    fn: FnKey
    node: ast.AST
    sf: SourceFile
    in_init: bool
    hb_shielded: bool


def _module_of(rel: str) -> str:
    rel = rel.replace(os.sep, "/")
    if rel.endswith(".py"):
        rel = rel[:-3]
    if rel.endswith("/__init__"):
        rel = rel[: -len("/__init__")]
    return rel.replace("/", ".")


def _ctor_last(call: ast.Call) -> str:
    return (dotted_name(call.func) or "").rsplit(".", 1)[-1]


def _value_candidates(v: ast.AST) -> List[ast.AST]:
    """The leaf expressions an assignment value may evaluate to —
    unwraps conditional expressions (`C(...) if flag else None`)."""
    if isinstance(v, ast.IfExp):
        return _value_candidates(v.body) + _value_candidates(v.orelse)
    return [v]


class _ModModel:
    """Per-module facts: imports, classes, globals, annotations."""

    def __init__(self, sf: SourceFile):
        self.sf = sf
        self.idx = FunctionIndex(sf.tree)
        self.module = _module_of(sf.rel)
        self.package = self.module.rsplit(".", 1)[0] \
            if "." in self.module else ""
        #: alias -> imported module dotted name
        self.import_mods: Dict[str, str] = {}
        #: name -> (module, original name) for `from m import n`
        self.import_names: Dict[str, Tuple[str, str]] = {}
        #: module-level assigned names
        self.globals: Set[str] = set()
        self.sync_globals: Set[str] = set()
        #: (class qualname, attr) -> constructor call for typing
        self.attr_ctors: Dict[Tuple[str, str], ast.Call] = {}
        self.sync_attrs: Set[Tuple[str, str]] = set()
        #: guarded-by annotations: (line, lock, state key)
        self.guards: List[Tuple[int, str, StateKey]] = []
        self.holds_decls: List[Tuple[int, str, str]] = []
        #: resolved types, filled program-wide: (cls, attr) -> class key
        self.attr_types: Dict[Tuple[str, str], Tuple[str, str]] = {}
        self._collect()

    # -- local collection ---------------------------------------------------

    def _collect(self) -> None:
        self._collect_imports()
        self._collect_globals()
        self._collect_attrs()
        self._collect_annotations()

    def _collect_imports(self) -> None:
        for node in cached_walk(self.sf.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        self.import_mods[a.asname] = a.name
                    else:
                        head = a.name.split(".", 1)[0]
                        self.import_mods[head] = head
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:
                    parts = self.module.split(".")
                    parts = parts[: len(parts) - node.level]
                    base = ".".join(parts + ([node.module]
                                             if node.module else []))
                for a in node.names:
                    bound = a.asname or a.name
                    self.import_names[bound] = (base, a.name)
                    self.import_mods[bound] = (f"{base}.{a.name}"
                                               if base else a.name)

    def _collect_globals(self) -> None:
        for stmt in self.sf.tree.body:
            targets: List[ast.AST] = []
            if isinstance(stmt, ast.Assign):
                targets = stmt.targets
            elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
                targets = [stmt.target]
            for t in targets:
                if isinstance(t, ast.Name):
                    self.globals.add(t.id)
                    if (isinstance(getattr(stmt, "value", None), ast.Call)
                            and _ctor_last(stmt.value) in SYNC_CTORS):
                        self.sync_globals.add(t.id)

    def _collect_attrs(self) -> None:
        for cq, cls in self.idx.classes.items():
            for node in cached_walk(cls):
                if not isinstance(node, ast.Assign):
                    continue
                for t in node.targets:
                    if not (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        continue
                    if isinstance(node.value, ast.Call):
                        self.attr_ctors.setdefault((cq, t.attr),
                                                   node.value)
                        if _ctor_last(node.value) in SYNC_CTORS:
                            self.sync_attrs.add((cq, t.attr))

    def _collect_annotations(self) -> None:
        for cq, cls in self.idx.classes.items():
            for node in cached_walk(cls):
                target = None
                if isinstance(node, ast.Assign) and node.targets:
                    target = node.targets[0]
                elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                    target = node.target
                if not (isinstance(target, ast.Attribute)
                        and isinstance(target.value, ast.Name)
                        and target.value.id == "self"):
                    continue
                lock = self.sf.guarded_by(node.lineno)
                if lock:
                    self.guards.append(
                        (node.lineno, lock,
                         (self.module, cq, target.attr)))
        for stmt in self.sf.tree.body:
            targets = []
            if isinstance(stmt, ast.Assign):
                targets = stmt.targets
            elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
                targets = [stmt.target]
            for t in targets:
                if isinstance(t, ast.Name):
                    lock = self.sf.guarded_by(stmt.lineno)
                    if lock:
                        self.guards.append(
                            (stmt.lineno, lock, (self.module, "", t.id)))
        for q, fn in self.idx.funcs.items():
            lock = self.sf.holds(fn.lineno)
            if lock:
                self.holds_decls.append((fn.lineno, lock, q))

    # -- program-phase helpers ----------------------------------------------

    def owning_class(self, fn_q: str) -> Optional[str]:
        parent = self.idx.parents.get(fn_q)
        while parent is not None:
            if parent in self.idx.classes:
                return parent
            parent = self.idx.parents.get(parent)
        return None

    def lock_names(self) -> Set[str]:
        out = set(self.sync_globals)
        out.update(attr for (_, attr) in self.sync_attrs)
        return out


class _Program:
    """The cross-module view: types, call sites, colors, locksets."""

    def __init__(self, models: List[_ModModel]):
        self.models = {m.module: m for m in models}
        self.fn_node: Dict[FnKey, ast.AST] = {}
        #: method name -> class keys defining it (CHA fallback)
        self.method_defs: Dict[str, List[Tuple[str, str]]] = {}
        for m in models:
            for q, fn in m.idx.funcs.items():
                self.fn_node[(m.module, q)] = fn
                cls = m.owning_class(q)
                if cls is not None and "." not in q[len(cls) + 1:]:
                    self.method_defs.setdefault(
                        q.rsplit(".", 1)[-1], []).append((m.module, cls))
        self._resolve_attr_types()
        self.entries: Set[FnKey] = set()
        self.main_seeds: Set[FnKey] = set()
        #: http.server handler classes: one instance per request, so
        #: their own attrs are request-confined by the framework
        self.handler_classes: Set[Tuple[str, str]] = set()
        #: classes whose instances are stored in module globals
        self.global_stored: Set[Tuple[str, str]] = set()
        #: callee -> [(caller, site locks)]
        self.call_sites: Dict[FnKey, List[Tuple[FnKey,
                                                FrozenSet[str]]]] = {}
        self.accesses: List[Access] = []
        for m in models:
            self._collect_entries(m)
        for m in models:
            self._walk_module(m)

    def shared_classes(self) -> Set[Tuple[str, str]]:
        """Instance-escape fixpoint: a class is *shared* when its
        instances are reachable from more than one thread — it hosts a
        thread root itself, lives in a module global, or is stored in
        an attribute of a shared class.  Everything else (per-worker
        protocol clients, the per-run RunContext, request handlers) is
        instance-confined no matter how many colors its methods get."""
        shared: Set[Tuple[str, str]] = set(self.global_stored)
        for (mod, q) in self.entries:
            m = self.models.get(mod)
            if m is None:
                continue
            cls = m.owning_class(q)
            if cls is not None and (mod, cls) not in self.handler_classes:
                shared.add((mod, cls))
        changed = True
        while changed:
            changed = False
            for m in self.models.values():
                for (cq, _attr), t in m.attr_types.items():
                    if (m.module, cq) in shared and t not in shared \
                            and t not in self.handler_classes:
                        shared.add(t)
                        changed = True
        return shared

    # -- constructor typing -------------------------------------------------

    def resolve_class(self, m: _ModModel,
                      node: ast.AST) -> Optional[Tuple[str, str]]:
        """The scanned class a constructor expression refers to."""
        name = dotted_name(node)
        if name is None:
            return None
        if "." not in name:
            if name in m.idx.classes:
                return (m.module, name)
            imp = m.import_names.get(name)
            if imp and imp[0] in self.models:
                m2 = self.models[imp[0]]
                if imp[1] in m2.idx.classes:
                    return (imp[0], imp[1])
            return None
        head, last = name.rsplit(".", 1)
        mod2 = m.import_mods.get(head)
        if mod2 and mod2 in self.models:
            m2 = self.models[mod2]
            if last in m2.idx.classes:
                return (mod2, last)
        return None

    def _resolve_attr_types(self) -> None:
        for m in self.models.values():
            for (cq, attr), call in m.attr_ctors.items():
                t = self.resolve_class(m, call.func)
                if t is not None:
                    m.attr_types[(cq, attr)] = t

    # -- thread roots -------------------------------------------------------

    def _collect_entries(self, m: _ModModel) -> None:
        for q, fn in m.idx.funcs.items():
            if m.sf.marked(fn.lineno, "thread-entry"):
                self.entries.add((m.module, q))
        for cq, cls in m.idx.classes.items():
            if not any("RequestHandler" in (dotted_name(b) or "")
                       for b in cls.bases):
                continue
            self.handler_classes.add((m.module, cq))
            for q in m.idx.funcs:
                if (m.idx.parents.get(q) == cq
                        and q.rsplit(".", 1)[-1].startswith("do_")):
                    self.entries.add((m.module, q))
        for q, fn in m.idx.funcs.items():
            cls = m.owning_class(q)
            for node in cached_walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                ref: Optional[ast.AST] = None
                if (isinstance(node.func, ast.Attribute)
                        and node.func.attr == "submit" and node.args):
                    ref = node.args[0]
                if (dotted_name(node.func) or "").rsplit(
                        ".", 1)[-1] == "Thread":
                    for kw in node.keywords:
                        if kw.arg == "target":
                            ref = kw.value
                # NOT a root: `on_retire=` callbacks — DispatchWindow
                # runs retirement on the *owner* thread (and enforces
                # it at runtime), so they inherit the caller's color
                # via a plain call edge instead (see _walk_fn)
                if ref is None:
                    continue
                for key in self._resolve_ref(m, cls, ref, {}):
                    self.entries.add(key)

    def _resolve_ref(self, m: _ModModel, cls: Optional[str],
                     ref: ast.AST,
                     local_types: Dict[str, Tuple[str, str]]
                     ) -> List[FnKey]:
        """A callable reference (callback or call target) -> fn keys."""
        if isinstance(ref, ast.Name):
            if (m.module, ref.id) in self.fn_node:
                return [(m.module, ref.id)]
            imp = m.import_names.get(ref.id)
            if imp and (imp[0], imp[1]) in self.fn_node:
                return [(imp[0], imp[1])]
            t = None
            if ref.id in local_types:
                t = local_types[ref.id]
            if ref.id in m.idx.classes:
                t = (m.module, ref.id)
            elif imp and imp[0] in self.models \
                    and imp[1] in self.models[imp[0]].idx.classes:
                t = (imp[0], imp[1])
            if t is not None and (t[0], f"{t[1]}.__init__") in self.fn_node:
                return [(t[0], f"{t[1]}.__init__")]
            return []
        if not isinstance(ref, ast.Attribute):
            return []
        last = ref.attr
        base = ref.value
        if isinstance(base, ast.Name):
            if base.id == "self":
                if cls is not None:
                    key = (m.module, f"{cls}.{last}")
                    if key in self.fn_node:
                        return [key]
                return self._cha(last)
            if base.id in local_types:
                t = local_types[base.id]
                key = (t[0], f"{t[1]}.{last}")
                return [key] if key in self.fn_node else self._cha(last)
            mod2 = m.import_mods.get(base.id)
            if mod2 and mod2 in self.models:
                if (mod2, last) in self.fn_node:
                    return [(mod2, last)]
                if last in self.models[mod2].idx.classes:
                    key = (mod2, f"{last}.__init__")
                    return [key] if key in self.fn_node else []
                return []
            return self._cha(last)
        if (isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id == "self" and cls is not None):
            t = m.attr_types.get((cls, base.attr))
            if t is not None:
                key = (t[0], f"{t[1]}.{last}")
                return [key] if key in self.fn_node else []
            return self._cha(last)
        return self._cha(last)

    def _cha(self, method: str) -> List[FnKey]:
        if method in CHA_BLOCKLIST:
            return []
        defs = self.method_defs.get(method, [])
        if not defs or len(defs) > CHA_MAX:
            return []
        out = []
        for (mod, cls) in defs:
            key = (mod, f"{cls}.{method}")
            if key in self.fn_node:
                out.append(key)
        return out

    # -- per-function walk: edges + accesses --------------------------------

    def _walk_module(self, m: _ModModel) -> None:
        # module-import-time call targets run on the main thread
        self._top_level_calls(m)
        # module-level `G = C(...)`: C escapes to every importer
        for stmt in m.sf.tree.body:
            if isinstance(stmt, ast.Assign) \
                    and any(isinstance(t, ast.Name) for t in stmt.targets):
                for v in _value_candidates(stmt.value):
                    if isinstance(v, ast.Call):
                        t = self.resolve_class(m, v.func)
                        if t is not None:
                            self.global_stored.add(t)
        for q, fn in sorted(m.idx.funcs.items()):
            self._walk_fn(m, q, fn)

    def _top_level_calls(self, m: _ModModel) -> None:
        def scan(body) -> None:
            for stmt in body:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    continue
                if isinstance(stmt, ast.ClassDef):
                    scan(stmt.body)
                    continue
                for node in cached_walk(stmt):
                    if isinstance(node, ast.Call) \
                            and isinstance(node.func, ast.Name):
                        for key in self._resolve_ref(m, None,
                                                     node.func, {}):
                            self.main_seeds.add(key)
        scan(m.sf.tree.body)

    def _walk_fn(self, m: _ModModel, q: str, fn: ast.AST) -> None:
        caller: FnKey = (m.module, q)
        cls = m.owning_class(q)
        in_init = q.rsplit(".", 1)[-1] == "__init__"
        local_types: Dict[str, Tuple[str, str]] = {}
        global_decls: Set[str] = set()
        shadowed: Set[str] = set()
        min_wait = [None]  # type: List[Optional[int]]

        # pre-pass: global decls first (walk order is arbitrary), then
        # local constructor types, shadowing, earliest HB call
        for node in cached_walk(fn):
            if isinstance(node, ast.Global):
                global_decls.update(node.names)
        for node in cached_walk(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                tid = node.targets[0].id
                if isinstance(node.value, ast.Call):
                    t = self.resolve_class(m, node.value.func)
                    if t is not None:
                        local_types[tid] = t
                if tid in m.globals and tid not in global_decls:
                    shadowed.add(tid)
                if tid in global_decls:
                    # a scanned-class instance published to a module
                    # global escapes to every thread (e.g. the journal
                    # singleton `_active = DispatchJournal(...) if path
                    # else None` — the IfExp is unwrapped)
                    for v in _value_candidates(node.value):
                        t = None
                        if isinstance(v, ast.Call):
                            t = self.resolve_class(m, v.func)
                        elif isinstance(v, ast.Name):
                            t = local_types.get(v.id)
                        if t is not None:
                            self.global_stored.add(t)
            elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Attribute) \
                    and isinstance(node.targets[0].value, ast.Name) \
                    and node.targets[0].value.id == "self" \
                    and cls is not None \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id in local_types:
                # `self.x = <typed local>`: the attr carries the type
                # (escape + receiver resolution)
                m.attr_types.setdefault((cls, node.targets[0].attr),
                                        local_types[node.value.id])
            elif (isinstance(node, ast.Call)
                  and isinstance(node.func, ast.Attribute)
                  and node.func.attr in WAIT_CALLS):
                if min_wait[0] is None or node.lineno < min_wait[0]:
                    min_wait[0] = node.lineno

        def shielded(node: ast.AST) -> bool:
            return min_wait[0] is not None and node.lineno > min_wait[0]

        def record(attr_key: StateKey, kind: str, locks: FrozenSet[str],
                   node: ast.AST) -> None:
            self.accesses.append(Access(
                attr_key, kind, locks, caller, node, m.sf,
                in_init, shielded(node)))

        def visit(node: ast.AST, locks: FrozenSet[str]) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # nested def: its body is its own fn key, reached on
                # behalf of this one
                nested = (m.module, self.idx_qual(m, node) or q)
                if nested in self.fn_node and nested != caller:
                    self.call_sites.setdefault(nested, []).append(
                        (caller, frozenset()))
                return
            if isinstance(node, ast.With):
                added = set()
                for item in node.items:
                    ctx = item.context_expr
                    if isinstance(ctx, ast.Call):
                        continue
                    name = dotted_name(ctx)
                    if name:
                        added.add(name.rsplit(".", 1)[-1])
                inner = locks | added
                for item in node.items:
                    visit(item.context_expr, locks)
                for stmt in node.body:
                    visit(stmt, inner)
                return
            if isinstance(node, ast.Call):
                refs = self._resolve_ref(m, cls, node.func, local_types)
                for key in refs:
                    self.call_sites.setdefault(key, []).append(
                        (caller, locks))
                for kw in node.keywords:
                    # retirement callbacks run on the window-owner
                    # thread: a plain call edge, not a thread root
                    if kw.arg == "on_retire":
                        for key in self._resolve_ref(m, cls, kw.value,
                                                     local_types):
                            self.call_sites.setdefault(key, []).append(
                                (caller, locks))
                if (not refs and isinstance(node.func, ast.Attribute)
                        and node.func.attr in MUTATORS):
                    recv = node.func.value
                    self._mutation(m, cls, recv, locks, node, record,
                                   shadowed)
            if (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self" and cls is not None):
                if isinstance(node.ctx, (ast.Store, ast.Del)):
                    record((m.module, cls, node.attr), "write", locks,
                           node)
                elif isinstance(node.ctx, ast.Load) \
                        and not self._is_receiver(node):
                    record((m.module, cls, node.attr), "read", locks,
                           node)
            if isinstance(node, ast.Subscript) \
                    and isinstance(node.ctx, (ast.Store, ast.Del)):
                self._mutation(m, cls, node.value, locks, node, record,
                               shadowed)
            if isinstance(node, ast.Name) \
                    and node.id in m.globals and node.id not in shadowed:
                if isinstance(node.ctx, ast.Store):
                    if node.id in global_decls:
                        record((m.module, "", node.id), "write", locks,
                               node)
                elif isinstance(node.ctx, ast.Load):
                    record((m.module, "", node.id), "read", locks, node)
            for child in ast.iter_child_nodes(node):
                visit(child, locks)

        for stmt in fn.body:
            visit(stmt, frozenset())

    def _is_receiver(self, node: ast.Attribute) -> bool:
        # marker so `self.x.append(...)` isn't double-counted; the
        # mutation record carries the write, the Load is implied
        return getattr(node, "_jt_receiver", False)

    def _mutation(self, m: _ModModel, cls: Optional[str], recv: ast.AST,
                  locks: FrozenSet[str], node: ast.AST, record,
                  shadowed: Set[str]) -> None:
        """A mutating method call / subscript store on ``recv``."""
        if (isinstance(recv, ast.Attribute)
                and isinstance(recv.value, ast.Name)
                and recv.value.id == "self" and cls is not None):
            recv._jt_receiver = True  # type: ignore[attr-defined]
            record((m.module, cls, recv.attr), "write", locks, node)
        elif isinstance(recv, ast.Name) and recv.id in m.globals \
                and recv.id not in shadowed:
            record((m.module, "", recv.id), "write", locks, node)

    def idx_qual(self, m: _ModModel, fn: ast.AST) -> Optional[str]:
        for q, node in m.idx.funcs.items():
            if node is fn:
                return q
        return None

    # -- fixpoints ----------------------------------------------------------

    def colors(self) -> Dict[FnKey, FrozenSet[str]]:
        out: Dict[FnKey, Set[str]] = {k: set() for k in self.fn_node}
        for e in self.entries:
            if e in out:
                out[e].add(f"{e[0]}:{e[1]}")
        for k in self.fn_node:
            if k in self.main_seeds or (
                    k not in self.entries and not self.call_sites.get(k)):
                out[k].add(MAIN_COLOR)
        changed = True
        while changed:
            changed = False
            for callee, sites in self.call_sites.items():
                if callee not in out:
                    continue
                for caller, _ in sites:
                    add = out.get(caller, set()) - out[callee]
                    if add:
                        out[callee].update(add)
                        changed = True
        return {k: frozenset(v) for k, v in out.items()}

    def eff_locks(self) -> Dict[FnKey, Optional[FrozenSet[str]]]:
        holds: Dict[FnKey, FrozenSet[str]] = {}
        for m in self.models.values():
            for (_, lock, q) in m.holds_decls:
                if lock != OWNER_THREAD:
                    holds[(m.module, q)] = frozenset({lock})
        eff: Dict[FnKey, Optional[FrozenSet[str]]] = {
            k: None for k in self.fn_node}  # None = ⊤ (unconstrained)
        changed = True
        while changed:
            changed = False
            for k in self.fn_node:
                sites = self.call_sites.get(k, [])
                acc: Optional[FrozenSet[str]] = None
                constrained = False
                if k in self.entries or k in self.main_seeds \
                        or not sites:
                    acc = frozenset()
                    constrained = True
                for caller, locks in sites:
                    ce = eff.get(caller)
                    if ce is None:
                        continue
                    s = locks | ce
                    acc = s if not constrained else (acc & s)
                    constrained = True
                if not constrained:
                    continue
                new = holds.get(k, frozenset()) | acc
                if eff[k] is None or new != eff[k]:
                    # decreasing from ⊤: only ever shrink
                    if eff[k] is None or new < eff[k]:
                        eff[k] = new
                        changed = True
        return eff


def _display(key: StateKey) -> str:
    mod, cls, attr = key
    short = mod.rsplit(".", 1)[-1]
    if cls:
        return f"{short}.{cls.rsplit('.', 1)[-1]}.{attr}"
    return f"{short} global `{attr}`"


class ConcurrencyPass(Pass):
    name = "concurrency"
    rules = ("concurrency-unguarded-shared", "concurrency-guard-drift",
             "concurrency-lock-missing")

    def run(self, project: Project) -> List[Finding]:
        out: List[Finding] = []
        models = [
            _ModModel(sf) for sf in project.files if sf.tree is not None
        ]
        if not models:
            return out
        prog = _Program(models)
        colors = prog.colors()
        eff = prog.eff_locks()
        self._check_shared(models, prog, colors, eff, out)
        self._check_annotations(models, out)
        return out

    # -- shared-state rules -------------------------------------------------

    def _check_shared(self, models, prog: _Program, colors, eff,
                      out: List[Finding]) -> None:
        by_mod = {m.module: m for m in models}
        guarded: Set[StateKey] = set()
        for m in models:
            for (_, _, key) in m.guards:
                guarded.add(key)

        shared_cls = prog.shared_classes()
        grouped: Dict[StateKey, List[Access]] = {}
        for a in prog.accesses:
            grouped.setdefault(a.key, []).append(a)

        for key in sorted(grouped):
            mod, cls, attr = key
            m = by_mod[mod]
            if key in guarded:
                continue
            if cls and (mod, cls) not in shared_cls:
                continue
            if cls and (cls, attr) in m.sync_attrs:
                continue
            if not cls and attr in m.sync_globals:
                continue
            accesses = grouped[key]
            shared_colors: Set[str] = set()
            for a in accesses:
                if not a.in_init:
                    shared_colors |= colors.get(a.fn, frozenset())
            if len(shared_colors) < 2:
                continue

            def locked(a: Access) -> FrozenSet[str]:
                e = eff.get(a.fn)
                return a.site_locks | (e or frozenset())

            writes = [a for a in accesses
                      if a.kind == "write" and not a.in_init]
            if not writes:
                continue
            naked = [a for a in writes if not locked(a)]
            for a in naked:
                self._emit(
                    out, a, "concurrency-unguarded-shared",
                    f"`{_display(key)}` is mutated without any lock"
                    " held, but it is reachable from more than one"
                    " thread root — guard the mutation or annotate the"
                    " confinement (`# jt: guarded-by(...)`)")
            if naked:
                continue
            common = frozenset.intersection(
                *[locked(a) for a in writes])
            if not common:
                continue
            for a in accesses:
                if a.in_init or a.hb_shielded:
                    continue
                if locked(a) & common:
                    continue
                lock_disp = "`, `".join(sorted(common))
                self._emit(
                    out, a, "concurrency-guard-drift",
                    f"every mutation of `{_display(key)}` holds"
                    f" `{lock_disp}`, but this access doesn't — a"
                    " torn read/write window on shared state")

    # -- annotation audit ---------------------------------------------------

    def _check_annotations(self, models, out: List[Finding]) -> None:
        for m in models:
            known = m.lock_names()
            decls = [(line, lock, f"guarded-by({lock})")
                     for (line, lock, _) in m.guards]
            decls += [(line, lock, f"holds({lock})")
                      for (line, lock, _) in m.holds_decls]
            for line, lock, disp in sorted(decls):
                if lock == OWNER_THREAD:
                    continue
                base = lock.rsplit(".", 1)[-1]
                if base in known:
                    continue
                if m.sf.allowed(line, "concurrency-lock-missing"):
                    continue
                probe = ast.Pass()
                probe.lineno = line
                scope = m.idx.enclosing(m.sf.tree, probe)
                out.append(Finding(
                    "concurrency-lock-missing", m.sf.rel, line, 0,
                    f"`# jt: {disp}` names a lock this module never"
                    " constructs — the annotation drifted from the"
                    " code it documents", scope))

    def _emit(self, out: List[Finding], a: Access, rule: str,
              msg: str) -> None:
        if a.sf.allowed(a.node.lineno, rule):
            return
        scope = a.fn[1]
        out.append(Finding(rule, a.sf.rel, a.node.lineno,
                           a.node.col_offset, msg, scope))


register(ConcurrencyPass())
