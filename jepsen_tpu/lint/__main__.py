"""CLI for jtlint: ``python -m jepsen_tpu.lint [paths]``.

Exit status: 0 when every finding is baselined (stale-baseline entries
warn but never fail — the baseline may only shrink), 1 on any new
finding, 2 on usage errors.  ``--json [FILE]`` additionally writes a
machine-readable report (default ``lint.json``) for trend tracking.
"""

from __future__ import annotations

import argparse
import fnmatch
import json
import os
import subprocess
import sys
import time
from typing import List, Optional

from .core import (DEFAULT_BASELINE, all_passes, all_rules, lint_paths,
                   load_baseline, make_baseline)


def _default_paths() -> List[str]:
    """The installed package tree (works from any cwd)."""
    return [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]


def _changed_paths(paths: List[str]) -> Optional[List[str]]:
    """The ``--changed`` file set: python files under ``paths`` that
    differ from git HEAD (staged, unstaged, or untracked).  Returns
    None when git is unavailable (fall back to the full set — CI must
    never silently lint nothing)."""
    roots = [os.path.abspath(p) for p in paths]
    try:
        top = subprocess.run(
            ["git", "rev-parse", "--show-toplevel"],
            capture_output=True, text=True, timeout=30,
        )
        if top.returncode != 0:
            return None
        repo = top.stdout.strip()
        diff = subprocess.run(
            ["git", "-C", repo, "diff", "--name-only", "HEAD", "--"],
            capture_output=True, text=True, timeout=30,
        )
        untracked = subprocess.run(
            ["git", "-C", repo, "ls-files", "--others",
             "--exclude-standard"],
            capture_output=True, text=True, timeout=30,
        )
        if diff.returncode != 0 or untracked.returncode != 0:
            return None
    except (OSError, subprocess.SubprocessError):
        return None
    out: List[str] = []
    for rel in sorted(set(diff.stdout.splitlines())
                      | set(untracked.stdout.splitlines())):
        if not rel.endswith(".py"):
            continue
        ap = os.path.join(repo, rel)
        if not os.path.isfile(ap):
            continue  # deleted files have nothing to lint
        if any(ap == r or ap.startswith(r + os.sep) for r in roots):
            out.append(ap)
    return out


def _expand_rules(tokens: List[str], known: List[str]) -> List[str]:
    """fnmatch-expand rule tokens (``jaxpr-*``); literal ids pass
    through so unknown-rule detection still works."""
    out: List[str] = []
    for tok in tokens:
        if any(ch in tok for ch in "*?["):
            matches = fnmatch.filter(known, tok)
            if matches:
                out.extend(matches)
            else:
                out.append(tok)  # surfaces as unknown below
        else:
            out.append(tok)
    return out


def _sarif_report(result) -> dict:
    """SARIF 2.1.0 (the subset GitHub code scanning consumes): one run,
    one rule descriptor per distinct rule, one result per finding.
    Fingerprints ride along so annotation identity survives line drift
    exactly like the baseline does."""
    seen_rules = sorted({f.rule for f in result.findings})
    results = []
    for f in result.findings:
        results.append({
            "ruleId": f.rule,
            "level": "error",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": f.path.replace(os.sep, "/"),
                    },
                    "region": {
                        "startLine": f.line,
                        "startColumn": max(1, f.col + 1),
                    },
                },
            }],
            "partialFingerprints": {"jtlint/v1": f.fingerprint()},
        })
    return {
        "version": "2.1.0",
        "$schema": "https://raw.githubusercontent.com/oasis-tcs/"
                   "sarif-spec/master/Schemata/sarif-schema-2.1.0.json",
        "runs": [{
            "tool": {"driver": {
                "name": "jtlint",
                "informationUri":
                    "doc/static-analysis.md",
                "rules": [{"id": r} for r in seen_rules],
            }},
            "results": results,
        }],
    }


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m jepsen_tpu.lint",
        description="jtlint: trace-safety, lock-discipline, concurrency "
                    "(whole-program race inference), obs-hygiene, "
                    "protocol-conformance, seam-contract and "
                    "dispatch-budget static analysis",
    )
    ap.add_argument("paths", nargs="*",
                    help="files or directories (default: the jepsen_tpu "
                         "package)")
    ap.add_argument("--rules", metavar="ID[,ID...]",
                    help="run only these rule ids (fnmatch globs "
                         "allowed: --rules 'jaxpr-*')")
    ap.add_argument("--changed", action="store_true",
                    help="lint only files changed vs git HEAD (plus "
                         "untracked) under the given paths — the CI "
                         "fast path; exits 0 when nothing changed")
    ap.add_argument("--list-rules", action="store_true",
                    help="list every rule id and exit")
    ap.add_argument("--baseline", metavar="PATH", default=DEFAULT_BASELINE,
                    help="baseline file (default: the committed "
                         "jepsen_tpu/lint/baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline: report every finding")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write all current findings to the baseline "
                         "file and exit 0")
    ap.add_argument("--show-baselined", action="store_true",
                    help="also print baselined (grandfathered) findings")
    ap.add_argument("--json", metavar="FILE", nargs="?", const="lint.json",
                    default=None,
                    help="write a JSON report (default file: lint.json)")
    ap.add_argument("--sarif", metavar="FILE", nargs="?",
                    const="lint.sarif", default=None,
                    help="write a SARIF 2.1.0 report (default file: "
                         "lint.sarif) — CI renders it as annotations")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="suppress the summary line")
    args = ap.parse_args(argv)

    if args.list_rules:
        for p in all_passes():
            for r in p.rules:
                print(f"{r}  [{p.name}]")
        return 0

    rules = None
    if args.rules:
        if args.write_baseline:
            # a rule-filtered run sees only a slice of the findings;
            # writing that slice would drop every other grandfathered
            # entry from the baseline
            print("--write-baseline cannot be combined with --rules: "
                  "the baseline must cover the full rule set",
                  file=sys.stderr)
            return 2
        rules = _expand_rules(
            [r.strip() for r in args.rules.split(",") if r.strip()],
            all_rules())
        unknown = set(rules) - set(all_rules()) - {"parse-error"}
        if unknown:
            print(f"unknown rule(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2

    paths = args.paths or _default_paths()
    for p in paths:
        if not os.path.exists(p):
            print(f"no such path: {p}", file=sys.stderr)
            return 2
    lint_options = None
    if args.changed:
        changed = _changed_paths(paths)
        if changed is None:
            print("warning: --changed needs a git checkout; "
                  "linting the full path set", file=sys.stderr)
        elif not changed:
            if not args.quiet:
                print("jtlint: no changed files")
            return 0
        else:
            paths = changed
            # whole-tree-only checks (e.g. registered-but-unread env
            # vars) are unsound over a changed-file subset
            lint_options = {"subset_scan": True}

    baseline = None
    if not args.no_baseline and not args.write_baseline:
        try:
            baseline = load_baseline(args.baseline)
        except (ValueError, json.JSONDecodeError) as e:
            print(f"bad baseline {args.baseline!r}: {e}", file=sys.stderr)
            return 2

    t0 = time.perf_counter()
    result = lint_paths(paths, rules=rules, options=lint_options,
                        baseline=baseline)
    elapsed = time.perf_counter() - t0

    if args.write_baseline:
        # merge, don't clobber: a subset run (`lint suites/a.py
        # --write-baseline`) regenerates entries for the SCANNED files
        # only and preserves grandfathered entries for everything else
        everything = result.findings + result.baselined
        current = make_baseline(everything)["findings"]
        try:
            prior = load_baseline(args.baseline) or {"findings": []}
        except (ValueError, json.JSONDecodeError):
            prior = {"findings": []}
        kept_prior = [e for e in prior["findings"]
                      if e.get("path") not in result.scanned_paths]
        merged = sorted(kept_prior + current,
                        key=lambda e: (e.get("path", ""),
                                       e.get("rule", ""),
                                       e.get("message", ""), e["fp"]))
        with open(args.baseline, "w", encoding="utf-8") as fh:
            json.dump({"version": 1, "findings": merged}, fh,
                      indent=2, sort_keys=True)
            fh.write("\n")
        print(f"wrote {len(merged)} finding(s) to {args.baseline}"
              + (f" ({len(kept_prior)} preserved for unscanned files)"
                 if kept_prior else ""))
        return 0

    for f in result.findings:
        print(f.render())
    if args.show_baselined:
        for f in result.baselined:
            print(f"{f.render()}  [baselined]")
    for e in result.stale:
        print(
            f"warning: stale baseline entry {e['fp']} "
            f"({e.get('rule', '?')} in {e.get('path', '?')}): the finding "
            "no longer exists — remove it (re-run --write-baseline) so "
            "the baseline keeps shrinking",
            file=sys.stderr,
        )

    if args.json is not None:
        report = {
            "version": 1,
            "files": result.n_files,
            "elapsed_s": round(elapsed, 3),
            "findings": [f.to_dict() for f in result.findings],
            "baselined": [f.to_dict() for f in result.baselined],
            "stale_baseline": list(result.stale),
            "pass_timings_s": {k: round(v, 4)
                               for k, v in sorted(result.timings.items())},
        }
        with open(args.json, "w", encoding="utf-8") as fh:
            json.dump(report, fh, indent=2, sort_keys=True)
            fh.write("\n")

    if args.sarif is not None:
        with open(args.sarif, "w", encoding="utf-8") as fh:
            json.dump(_sarif_report(result), fh, indent=2, sort_keys=True)
            fh.write("\n")

    if not args.quiet:
        n = len(result.findings)
        nb = len(result.baselined)
        extra = f", {nb} baselined" if nb else ""
        extra += f", {len(result.stale)} stale" if result.stale else ""
        print(f"jtlint: {result.n_files} files, {n} finding(s){extra} "
              f"in {elapsed:.2f}s")
    return 1 if result.findings else 0


if __name__ == "__main__":
    sys.exit(main())
