"""lock-discipline pass: annotated lockset checking.

After PR 4 the production verdict path is multi-threaded: the engine
pipeline overlaps device dispatch with a CPU-oracle worker pool, the
obs tracer/metrics buffers are written from every thread, and
``RetryRemote`` connections live on worker threads.  A missed lock
there doesn't crash — it corrupts counters or verdicts occasionally,
which is the worst possible failure mode for a consistency checker.

The check is **opt-in per module**: only modules containing at least
one ``# jt: guarded-by(...)`` or ``# jt: thread-entry`` annotation are
analyzed, so the annotation is both documentation and contract.

Annotations:

- ``self.attr = ...  # jt: guarded-by(<lock>)`` — every later access
  to ``self.attr`` in this class must be lexically inside a
  ``with self.<lock>:`` (or ``with <lock>:``) block, or in a function
  annotated ``# jt: holds(<lock>)`` (lock acquired by the caller).
  ``__init__`` is exempt: construction precedes sharing.
- ``GLOBAL = ...  # jt: guarded-by(<lock>)`` at module level — same
  check for module-global state (reads and writes inside functions).
- ``# jt: guarded-by(owner-thread)`` — the attribute is confined to
  the owning thread, never locked.  Accesses are clean *unless* they
  happen in a thread-entry-reachable function (see below), which would
  break the confinement.
- ``# jt: thread-entry`` on a ``def`` — the function runs on a foreign
  thread.  Also inferred from ``<pool>.submit(f, ...)``,
  ``threading.Thread(target=f)``, and window-drain callbacks
  (``on_retire=f``); reachability closes over the module-local call
  graph.

Rules:

- ``lock-discipline`` — guarded state accessed without the lock held.
- ``lock-thread-confined`` — owner-thread state touched from a
  thread-entry-reachable function.

Known limits (by design, documented in doc/static-analysis.md): the
analysis is lexical and per-module — accesses through a *different*
object reference (``other._spans``) or from another module aren't
seen, and a ``with`` block entered in one function doesn't cover
callees unless they carry ``holds``.  It still catches the bug class
that matters: a method of the owning class touching its own guarded
state outside the lock.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import (OWNER_THREAD, Finding, FunctionIndex, Pass, Project,
                   SourceFile, cached_walk, call_targets, dotted_name,
                   register)


def _target_attr(stmt: ast.AST) -> Optional[str]:
    """Attribute/global name assigned by this statement, for annotation
    attachment: ``self.x = …``, ``self.x: T = …``, ``X = …``,
    ``X: T = …``."""
    targets = []
    if isinstance(stmt, ast.Assign):
        targets = stmt.targets
    elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
        targets = [stmt.target]
    for t in targets:
        if (isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name) and t.value.id == "self"):
            return t.attr
        if isinstance(t, ast.Name):
            return t.id
    return None


def _with_locks(stack: List[ast.With]) -> Set[str]:
    """Lock names held by an enclosing ``with`` stack: the final
    attribute name of each context expression (``self._lock`` and
    ``other._lock`` both yield ``_lock``; a bare ``_lock`` yields
    itself)."""
    out: Set[str] = set()
    for w in stack:
        for item in w.items:
            expr = item.context_expr
            # unwrap common wrappers: `with lock:` / `with self.lock:`
            # / `with contextlib.ExitStack() …` (ignored)
            if isinstance(expr, ast.Call):
                continue
            if isinstance(expr, ast.Attribute):
                out.add(expr.attr)
            elif isinstance(expr, ast.Name):
                out.add(expr.id)
    return out


class _ModuleLockModel:
    def __init__(self, sf: SourceFile):
        self.sf = sf
        self.index = FunctionIndex(sf.tree)
        #: class qualname -> {attr: lock}
        self.guarded_attrs: Dict[str, Dict[str, str]] = {}
        #: module-global name -> lock
        self.guarded_globals: Dict[str, str] = {}
        #: function qualnames running on (or reachable from) foreign threads
        self.thread_reachable: Set[str] = set()
        self._collect_guards()
        self._collect_thread_entries()

    def _collect_guards(self) -> None:
        sf = self.sf
        # module-level globals
        for stmt in sf.tree.body:
            lock = sf.guarded_by(stmt.lineno)
            if lock:
                name = _target_attr(stmt)
                if name:
                    self.guarded_globals[name] = lock
        # class attributes (annotation on any `self.x = …` line in any
        # method, or on a class-level assignment)
        for cq, cls in self.index.classes.items():
            attrs: Dict[str, str] = {}
            for node in cached_walk(cls):
                if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                    lock = sf.guarded_by(node.lineno)
                    if lock:
                        name = _target_attr(node)
                        if name:
                            attrs[name] = lock
            if attrs:
                self.guarded_attrs[cq] = attrs

    def _collect_thread_entries(self) -> None:
        sf = self.sf
        idx = self.index
        by_name: Dict[str, List[str]] = {}
        for q in idx.funcs:
            by_name.setdefault(q.rsplit(".", 1)[-1], []).append(q)
        entries: Set[str] = set()
        for q, fn in idx.funcs.items():
            if sf.marked(fn.lineno, "thread-entry"):
                entries.add(q)
        for node in cached_walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = dotted_name(node.func) or ""
            target: Optional[ast.AST] = None
            if fname.endswith(".submit") and node.args:
                target = node.args[0]
            elif fname in ("threading.Thread", "Thread"):
                for kw in node.keywords:
                    if kw.arg == "target":
                        target = kw.value
            for kw in node.keywords:
                if kw.arg == "on_retire":
                    for q2 in self._resolve(kw.value, by_name):
                        entries.add(q2)
            if target is not None:
                for q2 in self._resolve(target, by_name):
                    entries.add(q2)
        # close over the module-local call graph
        changed = True
        while changed:
            changed = False
            for q in list(entries):
                fn = idx.funcs.get(q)
                if fn is None:
                    continue
                for callee in call_targets(fn):
                    for q2 in by_name.get(callee, ()):
                        if q2 not in entries:
                            entries.add(q2)
                            changed = True
        self.thread_reachable = entries

    def _resolve(self, node: ast.AST, by_name) -> List[str]:
        if isinstance(node, ast.Name):
            return by_name.get(node.id, [])
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"):
            return by_name.get(node.attr, [])
        return []


class LockDiscipline(Pass):
    name = "lock-discipline"
    rules = ("lock-discipline", "lock-thread-confined")

    def run(self, project: Project) -> List[Finding]:
        out: List[Finding] = []
        for sf in project.files:
            if sf.tree is None:
                continue
            if not any("guarded-by" in d or "thread-entry" in d
                       for d in sf.directives.values()):
                continue
            model = _ModuleLockModel(sf)
            self._check(sf, model, out)
        return out

    def _check(self, sf: SourceFile, model: _ModuleLockModel,
               out: List[Finding]) -> None:
        idx = model.index
        for q, fn in sorted(idx.funcs.items()):
            cls = self._owning_class(q, idx)
            attrs = model.guarded_attrs.get(cls, {}) if cls else {}
            held_by_contract = sf.holds(fn.lineno)
            is_init = q.rsplit(".", 1)[-1] == "__init__"
            self._walk_fn(sf, model, q, fn, attrs, held_by_contract,
                          is_init, out)

    def _owning_class(self, q: str, idx: FunctionIndex) -> Optional[str]:
        parent = idx.parents.get(q)
        while parent is not None:
            if parent in idx.classes:
                return parent
            parent = idx.parents.get(parent)
        # fall back: longest class-qualname prefix
        best = None
        for cq in idx.classes:
            if q.startswith(cq + ".") and (best is None or len(cq) > len(best)):
                best = cq
        return best

    def _walk_fn(self, sf, model, q, fn, attrs, held_contract, is_init,
                 out) -> None:
        thread_reachable = q in model.thread_reachable

        def visit(node, with_stack: Tuple[ast.With, ...]):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue  # indexed separately with a fresh stack
                if isinstance(child, ast.With):
                    visit(child, with_stack + (child,))
                    continue
                self._check_node(sf, model, q, child, attrs, held_contract,
                                 is_init, thread_reachable,
                                 _with_locks(list(with_stack)), out)
                visit(child, with_stack)

        visit(fn, ())

    def _check_node(self, sf, model, q, node, attrs, held_contract,
                    is_init, thread_reachable, held_locks, out) -> None:
        accesses: List[Tuple[str, str, ast.AST]] = []  # (kind, name, node)
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self" and node.attr in attrs):
            accesses.append(("attr", node.attr, node))
        elif (isinstance(node, ast.Name)
              and node.id in model.guarded_globals
              and not isinstance(node.ctx, ast.Del)):
            accesses.append(("global", node.id, node))
        for kind, name, n in accesses:
            lock = (attrs[name] if kind == "attr"
                    else model.guarded_globals[name])
            if lock == OWNER_THREAD:
                if thread_reachable:
                    self._emit(
                        out, sf, "lock-thread-confined", n, q,
                        f"`{name}` is owner-thread confined but"
                        f" `{q}` is reachable from a thread entry point"
                        " — confinement broken")
                continue
            if is_init:
                continue
            if lock in held_locks or held_contract == lock:
                continue
            self._emit(
                out, sf, "lock-discipline", n, q,
                f"`{name}` is guarded by `{lock}` but accessed in `{q}`"
                f" without holding it (wrap in `with {lock}:` or annotate"
                " the function `# jt: holds(...)`)")

    def _emit(self, out, sf, rule, node, scope, msg) -> None:
        if sf.allowed(node.lineno, rule):
            return
        out.append(Finding(rule, sf.rel, node.lineno, node.col_offset,
                           msg, scope))


register(LockDiscipline())
