"""trace-safety pass: host impurity inside traced code, implicit syncs.

The paper's core bet is that model step functions survive translation
into jit/vmap/pmap kernels — which only holds if the kernel code stays
*trace-pure*.  Host side effects under ``jax.jit`` run once at trace
time and silently vanish from every cached re-execution (a verdict
corrupted without an exception), and an implicit host sync inside the
dispatch path re-opens exactly the host/device bubble the pipelined
engine exists to close.

The pass builds a per-module "traced set":

1. roots: functions decorated ``@jax.jit`` / ``@jit`` / ``@jax.vmap``
   / ``@jax.pmap`` / ``@partial(jax.jit, …)``, functions wrapped at a
   call site (``jax.jit(f)``, ``jax.vmap(f)``), and functions marked
   ``# jt: traced`` (for registry indirection the call graph can't
   see, e.g. ``step_kernels.SPECS``);
2. closure: functions defined inside traced functions, and module-local
   functions a traced function calls (name-level fixpoint).

Rules, inside traced code:

- ``trace-host-mutation`` — ``global``/``nonlocal`` declarations: the
  mutation happens at trace time only.
- ``trace-impure-call`` — ``time.*`` / ``random.*`` / ``np.random.*``
  calls: the value is frozen into the compiled executable.
- ``trace-print`` — ``print(...)``: fires once at trace time (use
  ``jax.debug.print`` for runtime prints).
- ``trace-host-convert`` — ``.item()`` / ``.tolist()`` on anything, or
  ``np.asarray``/``np.array`` applied to a function parameter (a
  tracer): host conversion of a tracer raises at best, silently
  constant-folds at worst.

And outside traced code:

- ``trace-sync`` — ``.block_until_ready()`` anywhere, and
  ``np.asarray``/``np.array`` wrapped directly around a call to a
  traced function (or a traced-fn *producer* — a builder that returns
  one): an inline dispatch-and-materialize blocks the host for the
  full kernel, which inside the engine's dispatch window is exactly
  the bubble PR 4 removed.  Sanctioned sync points (the window's
  retirement ``_materialize``, single-item convenience APIs) carry
  ``# jt: allow[trace-sync]`` with a rationale — that comment IS the
  allowlist.  A function marked ``# jt: timing`` (on or above its
  ``def``) is a **measurement loop** — the autotuner's dispatch-and-
  sync timing harness (jepsen_tpu/tune) — where the inline sync IS
  the point: every ``trace-sync`` finding inside it (nested defs
  included) is sanctioned by the one function-level annotation, so
  timing code never needs a blanket per-line suppression trail.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from .core import (Finding, FunctionIndex, Pass, Project, SourceFile,
                   cached_walk, call_targets, dotted_name, register)

#: decorator / wrapper dotted names that make a function traced
TRACING_WRAPPERS = {
    "jax.jit", "jit", "jax.vmap", "vmap", "jax.pmap", "pmap",
}

IMPURE_PREFIXES = ("time.", "random.", "np.random.", "numpy.random.")

HOST_CONVERT_ATTRS = {"item", "tolist"}
NP_CONVERT = {"np.asarray", "np.array", "numpy.asarray", "numpy.array",
              "onp.asarray", "onp.array"}


def _is_tracing_wrapper(node: ast.AST) -> bool:
    name = dotted_name(node)
    if name in TRACING_WRAPPERS:
        return True
    # partial(jax.jit, ...) / functools.partial(jit, static_argnums=...)
    if isinstance(node, ast.Call):
        fname = dotted_name(node.func)
        if fname in ("partial", "functools.partial") and node.args:
            return _is_tracing_wrapper(node.args[0])
        # jax.jit(f, static_argnums=...) used as a decorator factory
        if fname in TRACING_WRAPPERS:
            return True
    return False


class _ModuleTraceModel:
    """Traced set + producer set for one module."""

    def __init__(self, sf: SourceFile):
        self.sf = sf
        self.index = FunctionIndex(sf.tree)
        self.traced: Set[str] = set()
        self.producers: Set[str] = set()
        self._build()

    def _build(self) -> None:
        idx = self.index
        # 1. decorated / annotated roots
        for q, fn in idx.funcs.items():
            for dec in getattr(fn, "decorator_list", ()):
                if _is_tracing_wrapper(dec):
                    self.traced.add(q)
            if self.sf.marked(fn.lineno, "traced"):
                self.traced.add(q)
        # 2. wrap-at-call-site roots: jax.jit(f) / jax.vmap(f) with a
        # plain name argument resolving to a local function
        by_name: Dict[str, List[str]] = {}
        for q in idx.funcs:
            by_name.setdefault(q.rsplit(".", 1)[-1], []).append(q)
        for node in cached_walk(self.sf.tree):
            if (isinstance(node, ast.Call)
                    and _is_tracing_wrapper(node.func) and node.args
                    and isinstance(node.args[0], ast.Name)):
                for q in by_name.get(node.args[0].id, ()):
                    self.traced.add(q)
        # 3. closure: nested defs of traced fns + called local fns
        changed = True
        while changed:
            changed = False
            for q in list(self.traced):
                # nested definitions
                for q2, parent in idx.parents.items():
                    if parent == q and q2 not in self.traced:
                        self.traced.add(q2)
                        changed = True
                fn = idx.funcs.get(q)
                if fn is None:
                    continue
                for callee in call_targets(fn):
                    for q2 in by_name.get(callee, ()):
                        if q2 not in self.traced:
                            self.traced.add(q2)
                            changed = True
        # 4. producers: functions whose return statement returns a
        # traced local fn (by name) or a tracing-wrapper call
        for q, fn in idx.funcs.items():
            if q in self.traced:
                continue
            for node in cached_walk(fn):
                if not isinstance(node, ast.Return) or node.value is None:
                    continue
                v = node.value
                if (isinstance(v, ast.Name)
                        and any(t.rsplit(".", 1)[-1] == v.id
                                and idx.parents.get(t) == q
                                for t in self.traced)):
                    self.producers.add(q)
                elif isinstance(v, ast.Call) and _is_tracing_wrapper(v.func):
                    self.producers.add(q)

    def is_device_call(self, node: ast.AST) -> bool:
        """Does this expression subtree contain a call that dispatches a
        traced fn — ``traced(...)`` or ``producer(...)(…)``?"""
        names = {q.rsplit(".", 1)[-1] for q in self.traced}
        prod = {q.rsplit(".", 1)[-1] for q in self.producers}
        for n in cached_walk(node):
            if not isinstance(n, ast.Call):
                continue
            if isinstance(n.func, ast.Name) and n.func.id in names:
                return True
            if (isinstance(n.func, ast.Call)
                    and isinstance(n.func.func, ast.Name)
                    and n.func.func.id in prod):
                return True
        return False


def _params_of(fn: ast.AST) -> Set[str]:
    a = fn.args
    out = {p.arg for p in
           list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)}
    if a.vararg:
        out.add(a.vararg.arg)
    if a.kwarg:
        out.add(a.kwarg.arg)
    out.discard("self")
    return out


class TraceSafety(Pass):
    name = "trace-safety"
    rules = ("trace-host-mutation", "trace-impure-call", "trace-print",
             "trace-host-convert", "trace-sync")

    def run(self, project: Project) -> List[Finding]:
        out: List[Finding] = []
        for sf in project.files:
            if sf.tree is None:
                continue
            model = _ModuleTraceModel(sf)
            self._check_traced(sf, model, out)
            self._check_syncs(sf, model, out)
        return out

    def _emit(self, out, sf, rule, node, msg, scope) -> None:
        if sf.allowed(node.lineno, rule):
            return
        out.append(Finding(rule, sf.rel, node.lineno,
                           getattr(node, "col_offset", 0), msg, scope))

    def _own_nodes(self, fn: ast.AST):
        """Nodes of ``fn`` excluding nested def subtrees — each nested
        def is in the traced set itself (nesting rule) and reports its
        own violations exactly once.  Lambdas stay in: they have no
        qualname of their own."""
        def visit(node):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    continue
                yield child
                yield from visit(child)
        yield from visit(fn)

    def _check_traced(self, sf: SourceFile, model: _ModuleTraceModel,
                      out: List[Finding]) -> None:
        idx = model.index
        for q in sorted(model.traced):
            fn = idx.funcs.get(q)
            if fn is None:
                continue
            params = _params_of(fn)
            for node in self._own_nodes(fn):
                if isinstance(node, (ast.Global, ast.Nonlocal)):
                    self._emit(
                        out, sf, "trace-host-mutation", node,
                        f"`{'global' if isinstance(node, ast.Global) else 'nonlocal'}"
                        f" {', '.join(node.names)}` inside traced function"
                        f" `{q}`: the mutation runs once at trace time and"
                        " is absent from every cached re-execution", q)
                elif isinstance(node, ast.Call):
                    name = dotted_name(node.func) or ""
                    if name == "print":
                        self._emit(
                            out, sf, "trace-print", node,
                            f"print() inside traced function `{q}` fires at"
                            " trace time only; use jax.debug.print for"
                            " runtime output", q)
                    elif any(name.startswith(p) for p in IMPURE_PREFIXES):
                        self._emit(
                            out, sf, "trace-impure-call", node,
                            f"call to `{name}` inside traced function `{q}`:"
                            " the result is frozen into the compiled"
                            " executable at trace time", q)
                    elif (isinstance(node.func, ast.Attribute)
                          and node.func.attr in HOST_CONVERT_ATTRS
                          and not node.args):
                        self._emit(
                            out, sf, "trace-host-convert", node,
                            f"`.{node.func.attr}()` inside traced function"
                            f" `{q}` forces a tracer to the host", q)
                    elif (name in NP_CONVERT and node.args
                          and isinstance(node.args[0], ast.Name)
                          and node.args[0].id in params):
                        self._emit(
                            out, sf, "trace-host-convert", node,
                            f"`{name}({node.args[0].id})` inside traced"
                            f" function `{q}` converts a traced argument"
                            " on the host", q)

    def _check_syncs(self, sf: SourceFile, model: _ModuleTraceModel,
                     out: List[Finding]) -> None:
        idx = model.index
        traced_nodes = {id(idx.funcs[q]) for q in model.traced
                        if q in idx.funcs}

        def any_enclosing(node: ast.AST, pred) -> bool:
            """Walk the enclosing-function chain outward; True when
            ``pred(fn_node)`` holds for any level."""
            q = idx.enclosing(sf.tree, node)
            while q:
                f = idx.funcs.get(q)
                if f is not None and pred(f):
                    return True
                q = q.rsplit(".", 1)[0] if "." in q else ""
            return False

        def in_traced(node: ast.AST) -> bool:
            return any_enclosing(node, lambda f: id(f) in traced_nodes)

        def in_timing(node: ast.AST) -> bool:
            # inside a `# jt: timing`-annotated function (any level):
            # a declared measurement loop, where the dispatch-and-sync
            # IS the measurement — sanctioned as a unit instead of one
            # allow[] per sync line
            return any_enclosing(
                node, lambda f: sf.marked(f.lineno, "timing")
            )

        for node in cached_walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "block_until_ready"):
                if in_timing(node):
                    continue
                scope = idx.enclosing(sf.tree, node)
                self._emit(
                    out, sf, "trace-sync", node,
                    "explicit `.block_until_ready()` sync: stalls the host"
                    " on the device — inside the dispatch window this is"
                    " the bubble the pipelined engine removes", scope)
                continue
            name = dotted_name(node.func)
            if name in NP_CONVERT and node.args:
                if (model.is_device_call(node.args[0])
                        and not in_traced(node) and not in_timing(node)):
                    scope = idx.enclosing(sf.tree, node)
                    self._emit(
                        out, sf, "trace-sync", node,
                        f"`{name}(...)` materializes a traced-kernel result"
                        " inline (dispatch-and-sync); route device work"
                        " through the engine DispatchWindow or annotate the"
                        " sanctioned sync point", scope)
        return None


register(TraceSafety())
