"""budget-discipline pass: every kernel dispatch rides a capped path.

PR 10's review found ``has_cycle_batch`` had shipped calling its jit
closure directly — no ``safe_dispatch`` cap, no chunking — so one
oversized batch could blow the device-memory budget that every other
dispatch path respects.  This pass closes that bug class structurally.

The model, whole-program and inference-based:

- A **builder** is a function that manufactures a dispatchable kernel:
  its body returns a ``jax.jit(...)`` result (directly, through a
  local, or as a ``@jax.jit``-decorated inner ``def``), or stamps a
  ``safe_dispatch`` attribute, or merely delegates by returning a call
  to another builder (``make_check_fn`` → ``_make_check_fn``).
  Builder names are collected across every scanned file first, so
  cross-module construction sites resolve.
- A **kernel value** is the result of calling a builder: a local
  (``fn = make_check_fn(...)``), an instance attribute
  (``self.fn = _cyclic_fn(...)``), or an immediate call
  (``builder(...)(...)``).

Rules:

- ``budget-direct-dispatch`` — a kernel value *called* outside the
  sanctioned dispatch paths.  Sanctioned: ``engine/execution.py`` (the
  Executor owns chunking), ``*smoke.py`` files, a call inside a lambda
  that is itself an argument of a ``jax.jit(...)`` call (the
  jit-of-jit rebatching wrapper), a function whose body visibly
  enforces the budget (reads ``.safe_dispatch``/``.disp`` or calls a
  ``*max_dispatch*`` helper), and lines annotated
  ``# jt: direct-dispatch`` (bench/tune measurement loops — a declared
  exception, with the annotation as the audit trail).
- ``budget-missing-cap`` — a builder that returns a jit result without
  stamping ``safe_dispatch`` anywhere in its body.  A builder wrapped
  by a capping builder carries ``# jt: allow[budget-missing-cap]``
  with the rationale naming its wrapper.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from .core import (Finding, FunctionIndex, Pass, Project, SourceFile,
                   cached_walk, dotted_name, register)

#: function names sanctioned to dispatch directly (the engine's own
#: chunk loop helpers take the kernel as a parameter, which this pass
#: never tracks — parameters are the *capped* hand-off idiom)
SANCTIONED_FILES = ("engine/execution.py",)


def _is_jit_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = dotted_name(node.func) or ""
    return name in ("jax.jit", "jit") or name.endswith(".jit")


def _jit_decorated(fn: ast.AST) -> bool:
    for dec in getattr(fn, "decorator_list", ()):
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = dotted_name(target) or ""
        if name in ("jax.jit", "jit") or name.endswith(".jit"):
            return True
    return False


class _FileModel:
    """Per-file builder/call facts, resolved program-wide later."""

    def __init__(self, sf: SourceFile):
        self.sf = sf
        self.idx = FunctionIndex(sf.tree)
        #: fn qualname -> set of bare names it `return <name>(...)`s
        self.delegations: Dict[str, Set[str]] = {}
        #: fn qualnames that are definitely builders (jit seen locally)
        self.local_builders: Set[str] = set()
        #: fn qualnames that stamp `.safe_dispatch` somewhere
        self.cappers: Set[str] = set()
        #: builders that return a jit result (missing-cap candidates)
        self.jit_returners: Dict[str, ast.AST] = {}
        self._scan()

    def _scan(self) -> None:
        for q, fn in self.idx.funcs.items():
            jit_vars: Set[str] = set()
            jit_defs: Set[str] = set()
            caps = False
            # first sweep: what the body defines (two sweeps because a
            # Return can precede the Assign feeding it in walk order)
            for node in _own_nodes(fn):
                if isinstance(node, ast.Assign):
                    if _is_jit_call(node.value):
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                jit_vars.add(t.id)
                    for t in node.targets:
                        if (isinstance(t, ast.Attribute)
                                and t.attr == "safe_dispatch"):
                            caps = True
                elif (isinstance(node, (ast.FunctionDef,
                                        ast.AsyncFunctionDef))
                      and node is not fn and _jit_decorated(node)):
                    jit_defs.add(node.name)
            # second sweep: what it returns
            returns_jit = False
            delegates: Set[str] = set()
            for node in _own_nodes(fn):
                if isinstance(node, ast.Return) and node.value is not None:
                    v = node.value
                    if _is_jit_call(v):
                        returns_jit = True
                    elif isinstance(v, ast.Name) and (v.id in jit_vars
                                                      or v.id in jit_defs):
                        returns_jit = True
                    elif (isinstance(v, ast.Call)
                          and isinstance(v.func, ast.Name)):
                        delegates.add(v.func.id)
            if returns_jit:
                self.jit_returners[q] = fn
                self.local_builders.add(q)
            if caps:
                self.cappers.add(q)
                self.local_builders.add(q)
            if delegates:
                self.delegations[q] = delegates


def _own_nodes(fn: ast.AST):
    """Walk ``fn`` without descending into nested defs (they are
    indexed — and judged — as their own functions)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _last(name: str) -> str:
    return name.rsplit(".", 1)[-1]


class BudgetDiscipline(Pass):
    name = "budget"
    rules = ("budget-direct-dispatch", "budget-missing-cap")

    def run(self, project: Project) -> List[Finding]:
        out: List[Finding] = []
        models = [
            _FileModel(sf) for sf in project.files if sf.tree is not None
        ]
        builders = self._builder_names(models)
        for m in models:
            self._check_missing_cap(m, builders, out)
            self._check_direct_dispatch(m, builders, out)
        return out

    # -- phase 1: program-wide builder name set ----------------------------

    def _builder_names(self, models: List[_FileModel]) -> Set[str]:
        names: Set[str] = set()
        for m in models:
            for q in m.local_builders:
                names.add(_last(q))
        # delegation fixpoint: `def make(): return _make(...)` where
        # _make is a builder makes `make` a builder too
        changed = True
        while changed:
            changed = False
            for m in models:
                for q, callees in m.delegations.items():
                    if _last(q) not in names and callees & names:
                        names.add(_last(q))
                        changed = True
        return names

    # -- budget-missing-cap ------------------------------------------------

    def _check_missing_cap(self, m: _FileModel, builders: Set[str],
                           out: List[Finding]) -> None:
        for q, fn in sorted(m.jit_returners.items()):
            if q in m.cappers:
                continue
            self._emit(
                out, m.sf, "budget-missing-cap", fn, q,
                f"`{_last(q)}` returns a jit kernel without stamping"
                " `safe_dispatch` — every dispatchable fn must carry"
                " its footprint-safe row cap (or the wrapping builder"
                " must, with an allow naming it)")

    # -- budget-direct-dispatch --------------------------------------------

    def _sanctioned_file(self, sf: SourceFile) -> bool:
        rel = sf.rel.replace(os.sep, "/")
        if rel.endswith("smoke.py"):
            return True
        return any(rel.endswith(s) for s in SANCTIONED_FILES)

    def _enforcing_fn(self, fn: ast.AST) -> bool:
        """The enclosing function visibly participates in budget
        enforcement: it reads the cap or calls a `*max_dispatch*`
        helper before dispatching."""
        for node in cached_walk(fn):
            if (isinstance(node, ast.Attribute)
                    and node.attr in ("safe_dispatch", "disp")
                    and isinstance(node.ctx, ast.Load)):
                return True
            if isinstance(node, ast.Call):
                name = dotted_name(node.func) or ""
                if "max_dispatch" in _last(name):
                    return True
        return False

    def _check_direct_dispatch(self, m: _FileModel, builders: Set[str],
                               out: List[Finding]) -> None:
        if self._sanctioned_file(m.sf):
            return
        sf, idx = m.sf, m.idx
        # lambda bodies that are arguments of a jax.jit(...) call: the
        # jit-of-jit rebatching wrapper (`jax.jit(lambda adj:
        # base(adj))`) re-enters the tracer, it does not dispatch
        jit_lambda_spans: List[Tuple[int, int]] = []
        for node in cached_walk(sf.tree):
            if _is_jit_call(node):
                for arg in list(node.args) + [kw.value
                                              for kw in node.keywords]:
                    if isinstance(arg, ast.Lambda):
                        jit_lambda_spans.append(
                            (arg.lineno, arg.end_lineno or arg.lineno))

        def in_jit_lambda(n: ast.AST) -> bool:
            return any(lo <= n.lineno <= hi for lo, hi in jit_lambda_spans)

        # per-class kernel attrs: self.x = <builder>(...)
        kernel_attrs: Dict[str, Set[str]] = {}
        for cq, cls in idx.classes.items():
            attrs: Set[str] = set()
            for node in cached_walk(cls):
                if not isinstance(node, ast.Assign):
                    continue
                if not (isinstance(node.value, ast.Call)
                        and _last(dotted_name(node.value.func) or "")
                        in builders):
                    continue
                for t in node.targets:
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        attrs.add(t.attr)
            if attrs:
                kernel_attrs[cq] = attrs

        for q, fn in sorted(idx.funcs.items()):
            cls = self._owning_class(q, idx)
            attrs = kernel_attrs.get(cls, set()) if cls else set()
            enforcing = self._enforcing_fn(fn)
            kernel_vars: Set[str] = set()
            for node in cached_walk(fn):
                if isinstance(node, ast.Assign):
                    if (isinstance(node.value, ast.Call)
                            and _last(dotted_name(node.value.func) or "")
                            in builders):
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                kernel_vars.add(t.id)
                if not isinstance(node, ast.Call):
                    continue
                target: Optional[str] = None
                if (isinstance(node.func, ast.Name)
                        and node.func.id in kernel_vars):
                    target = node.func.id
                elif (isinstance(node.func, ast.Attribute)
                      and isinstance(node.func.value, ast.Name)
                      and node.func.value.id == "self"
                      and node.func.attr in attrs):
                    target = f"self.{node.func.attr}"
                elif (isinstance(node.func, ast.Call)
                      and _last(dotted_name(node.func.func) or "")
                      in builders):
                    target = _last(dotted_name(node.func.func) or "")
                if target is None:
                    continue
                if enforcing or in_jit_lambda(node):
                    continue
                if sf.marked(node.lineno, "direct-dispatch"):
                    continue
                self._emit(
                    out, sf, "budget-direct-dispatch", node, q,
                    f"kernel `{target}` dispatched directly — route it"
                    " through the Executor or a `safe_dispatch`-capped"
                    " chunk loop (or annotate a measurement loop"
                    " `# jt: direct-dispatch`)")

    def _owning_class(self, q: str, idx: FunctionIndex) -> Optional[str]:
        parent = idx.parents.get(q)
        while parent is not None:
            if parent in idx.classes:
                return parent
            parent = idx.parents.get(parent)
        return None

    def _emit(self, out, sf, rule, node, scope, msg) -> None:
        if sf.allowed(node.lineno, rule):
            return
        out.append(Finding(rule, sf.rel, node.lineno, node.col_offset,
                           msg, scope))


register(BudgetDiscipline())
