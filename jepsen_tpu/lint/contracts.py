"""seam-contract pass: both sides of every serialized seam agree.

Every seam in this package is a pair of dict-shaped frames meeting
over JSON: the service protocol (client stamps a frame, daemon parses
it, and back), the dispatch journal (``execution.py`` emits rows,
``validate_row`` gates them, doc/observability.md documents them),
the calibration artifact (``PARAM_KEYS`` names what ``tune`` writes,
the ``cal.*()`` accessors read it back), and the environment
(``JEPSEN_TPU_*`` reads vs the :mod:`jepsen_tpu.lint.envvars`
registry vs the operator doc).  PR 6's review caught a JSON
key-stringification wire bug by hand; this pass catches the whole
drift class statically, on both sides at once.

The frame model (no imports, pure AST):

- **Writer keys** of a function: the string keys of dict literals it
  returns (directly, inside a returned tuple, or via a local later
  returned or passed to ``encode_body``), plus constant subscript
  stores on that local (``body["trace_ctx"] = …``).  A ``**spread``
  is chased through ``x = dict(self.attr)`` / ``x = self.attr`` to a
  class-wide ``self.attr = {…literal…}``; an unresolvable spread
  marks the frame *open* (reads can no longer be proven unwritten).
  Nested dict literals contribute to the readable key set but not to
  the top-level frame (a nested payload is its own seam).
- **Reader keys** of a function: constant ``var["k"]`` loads and
  ``var.get("k")`` calls on the seam's designated payload variables.

Rules:

- ``seam-frame-drift`` — a key parsed on one side and never written
  on the other (dead read: the reader sees only its default), or —
  for request seams, where both ends are ours — written and never
  parsed (dead weight on the wire).
- ``seam-journal-schema`` — an ``emit(...)`` site in
  ``engine/execution.py`` passing a key ``validate_row`` would drop,
  or omitting a schema field (rows silently vanish from the journal:
  exactly the failure the journal exists to record), or a schema
  field missing from the doc/observability.md table.
- ``seam-calibration-params`` — a ``.params["k"]`` accessor reading a
  key ``PARAM_KEYS`` doesn't persist (always-default accessor), or a
  persisted key no accessor reads (dead artifact weight).
- ``seam-env-read`` — a ``JEPSEN_TPU_*`` environment read absent
  from the :mod:`jepsen_tpu.lint.envvars` registry.
- ``seam-env-doc`` — the registry vs the generated
  doc/configuration.md table vs actual reads: undocumented registry
  entries, documented-but-unregistered names, and (on full-tree
  runs) registered names nothing reads any more.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, NamedTuple, Optional, Set, Tuple

from .core import (Finding, FunctionIndex, Pass, Project, SourceFile,
                   cached_walk, dotted_name, register)

_BACKTICK = re.compile(r"`([A-Za-z_][A-Za-z0-9_]*)`")
_ENV_TOKEN = re.compile(r"`(JEPSEN_TPU_[A-Z0-9_]+)`")


class Seam(NamedTuple):
    name: str
    writer_file: str
    writer_fns: Tuple[str, ...]
    reader_file: str
    reader_fns: Tuple[str, ...]
    reader_vars: Tuple[str, ...]
    #: request frames have both ends in this package, so a written-
    #: never-parsed key is drift too; response/status frames tolerate
    #: extra keys (operator-facing surface, `jq`-able on purpose)
    two_way: bool


#: the serialized seams of the service tier.  A seam engages only
#: when both files (and at least one function on each side) are in
#: the scanned set, so subset runs and fixtures stay honest.
SEAMS: Tuple[Seam, ...] = (
    Seam("check-request", "serve/protocol.py", ("check_request",),
         "serve/daemon.py", ("handle_check", "_check_flow"),
         ("payload", "body"), True),
    Seam("elle-request", "serve/protocol.py", ("elle_request",),
         "serve/daemon.py", ("handle_elle",),
         ("payload", "body"), True),
    Seam("check-response", "serve/daemon.py", ("_check_flow",),
         "serve/client.py", ("check_batch",),
         ("payload",), False),
    Seam("elle-response", "serve/daemon.py", ("_elle_flow",),
         "serve/client.py", ("screen_graphs",),
         ("payload",), False),
    Seam("status", "serve/daemon.py", ("status",),
         "serve/client.py", ("format_status", "format_live",
                             "format_top", "mesh_matches_daemon"),
         ("st", "live"), False),
    Seam("trace", "serve/daemon.py", ("trace_dump",),
         "serve/client.py", ("fetch_trace",),
         ("payload",), True),
)

#: journal fields stamped by the journal itself, not by emit sites
JOURNAL_AUTO_KEYS = frozenset({"v", "ts"})


class _Frame(NamedTuple):
    top_keys: Set[str]       # keys of the frame dict itself
    all_keys: Set[str]       # + nested dict-literal keys
    open: bool               # an unresolved **spread widens the frame


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _dict_keys(d: ast.Dict) -> Tuple[Set[str], bool]:
    """(constant string keys, has-spread) of one dict literal."""
    keys: Set[str] = set()
    spread = False
    for k in d.keys:
        if k is None:
            spread = True
            continue
        s = _const_str(k)
        if s is not None:
            keys.add(s)
    return keys, spread


def _nested_keys(d: ast.Dict) -> Set[str]:
    out: Set[str] = set()
    for v in d.values:
        for sub in cached_walk(v):
            if isinstance(sub, ast.Dict):
                out |= _dict_keys(sub)[0]
    return out


class _ClassAttrLiterals:
    """``self.attr = {…literal…}`` keys, class-wide — resolves the
    ``**stats`` spread in ``status()`` back to the ``__init__``
    counter literal."""

    def __init__(self, idx: FunctionIndex, fn_q: str):
        self.keys: Dict[str, Set[str]] = {}
        cls = _owning_class(fn_q, idx)
        if cls is None:
            return
        for node in cached_walk(idx.classes[cls]):
            if not isinstance(node, ast.Assign):
                continue
            if not isinstance(node.value, ast.Dict):
                continue
            for t in node.targets:
                if (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    self.keys.setdefault(t.attr, set()).update(
                        _dict_keys(node.value)[0])


def _owning_class(q: str, idx: FunctionIndex) -> Optional[str]:
    parent = idx.parents.get(q)
    while parent is not None:
        if parent in idx.classes:
            return parent
        parent = idx.parents.get(parent)
    return None


def writer_frame(fn: ast.AST, idx: FunctionIndex, fn_q: str) -> _Frame:
    """The union frame a writer function puts on the wire."""
    top: Set[str] = set()
    all_keys: Set[str] = set()
    is_open = False

    # locals holding dict literals, plus spread-resolution aliases.
    # Resolution is deferred until AFTER the walk: ast.walk is
    # breadth-first, so a Return at the top of the body is visited
    # before an alias assignment nested inside a `with` block.
    dict_vars: Dict[str, ast.Dict] = {}
    alias_of: Dict[str, str] = {}       # x = dict(self.attr) / self.attr
    frame_vars: Set[str] = set()        # locals that reach the wire
    sub_stores: Dict[str, Set[str]] = {}
    frame_dicts: List[ast.Dict] = []    # dict literals in return position

    for node in cached_walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if isinstance(t, ast.Name):
                if isinstance(node.value, ast.Dict):
                    dict_vars[t.id] = node.value
                else:
                    src = node.value
                    if (isinstance(src, ast.Call)
                            and dotted_name(src.func) == "dict"
                            and len(src.args) == 1):
                        src = src.args[0]
                    if (isinstance(src, ast.Attribute)
                            and isinstance(src.value, ast.Name)
                            and src.value.id == "self"):
                        alias_of[t.id] = src.attr
            elif (isinstance(t, ast.Subscript)
                  and isinstance(t.value, ast.Name)):
                key = _const_str(t.slice)
                if key is not None:
                    sub_stores.setdefault(t.value.id, set()).add(key)
        elif isinstance(node, ast.Return) and node.value is not None:
            values = [node.value]
            if isinstance(node.value, ast.Tuple):
                values = list(node.value.elts)
            for v in values:
                if isinstance(v, ast.Dict):
                    frame_dicts.append(v)
                elif isinstance(v, ast.Name):
                    frame_vars.add(v.id)
                elif isinstance(v, ast.Call):
                    for a in v.args:
                        if isinstance(a, ast.Name):
                            frame_vars.add(a.id)
        elif isinstance(node, ast.Call):
            name = dotted_name(node.func) or ""
            if name.rsplit(".", 1)[-1] == "encode_body":
                for a in node.args:
                    if isinstance(a, ast.Name):
                        frame_vars.add(a.id)

    for var in frame_vars:
        d = dict_vars.get(var)
        if d is not None:
            frame_dicts.append(d)
            stored = sub_stores.get(var, set())
            top |= stored
            all_keys |= stored

    for d in frame_dicts:
        k, spread = _dict_keys(d)
        top |= k
        all_keys |= k | _nested_keys(d)
        # a value that is a local dict literal (`"live": live`)
        # contributes its keys to the readable set — the reader
        # indexes into the nested payload by those names
        for v in d.values:
            if isinstance(v, ast.Name) and v.id in dict_vars:
                nest = dict_vars[v.id]
                all_keys |= _dict_keys(nest)[0] | _nested_keys(nest)
        if spread:
            is_open |= _resolve_spread(d, alias_of, idx, fn_q, top,
                                       all_keys)
    return _Frame(top, all_keys, is_open)


def _resolve_spread(d: ast.Dict, alias_of: Dict[str, str],
                    idx: FunctionIndex, fn_q: str,
                    top: Set[str], all_keys: Set[str]) -> bool:
    """Fold resolvable ``**spread`` keys into the frame.  Returns
    True when any spread stays opaque (frame must be treated open)."""
    attrs = _ClassAttrLiterals(idx, fn_q)
    opaque = False
    for k, v in zip(d.keys, d.values):
        if k is not None:
            continue
        resolved: Optional[Set[str]] = None
        if isinstance(v, ast.Name):
            attr = alias_of.get(v.id)
            if attr is not None and attr in attrs.keys:
                resolved = attrs.keys[attr]
        elif (isinstance(v, ast.Attribute)
              and isinstance(v.value, ast.Name)
              and v.value.id == "self" and v.attr in attrs.keys):
            resolved = attrs.keys[v.attr]
        if resolved is None:
            opaque = True
        else:
            top.update(resolved)
            all_keys.update(resolved)
    return opaque


def reader_keys(fn: ast.AST,
                var_names: Tuple[str, ...]) -> List[Tuple[str, ast.AST]]:
    """(key, node) for every constant read off a designated payload
    variable: ``var["k"]`` loads and ``var.get("k")`` calls."""
    out: List[Tuple[str, ast.AST]] = []
    for node in cached_walk(fn):
        if (isinstance(node, ast.Subscript)
                and isinstance(node.value, ast.Name)
                and node.value.id in var_names
                and isinstance(node.ctx, ast.Load)):
            key = _const_str(node.slice)
            if key is not None:
                out.append((key, node))
        elif (isinstance(node, ast.Call)
              and isinstance(node.func, ast.Attribute)
              and node.func.attr == "get"
              and isinstance(node.func.value, ast.Name)
              and node.func.value.id in var_names
              and node.args):
            key = _const_str(node.args[0])
            if key is not None:
                out.append((key, node))
    return out


def _find_fns(sf: SourceFile, names: Tuple[str, ...]):
    idx = FunctionIndex(sf.tree)
    hits = []
    for q, fn in idx.funcs.items():
        if q.rsplit(".", 1)[-1] in names:
            hits.append((q, fn))
    return idx, sorted(hits)


def _doc_path(project: Project, option: str, filename: str) -> Optional[str]:
    configured = project.options.get(option, "__default__")
    if configured != "__default__":
        return configured
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    p = os.path.join(root, "doc", filename)
    return p if os.path.exists(p) else None


def _read_doc(path: Optional[str]) -> Optional[str]:
    if not path:
        return None
    try:
        with open(path, "r", encoding="utf-8") as f:
            return f.read()
    except OSError:
        return None


# subprocess entry points that block until the child exits — on the
# control plane the child is an ssh/scp/kubectl talking to the network
_SUBPROCESS_BLOCKERS = {"run", "call", "check_call", "check_output"}


class SeamContracts(Pass):
    name = "contracts"
    rules = ("seam-frame-drift", "seam-journal-schema",
             "seam-calibration-params", "seam-env-read", "seam-env-doc",
             "net-timeout")

    def run(self, project: Project) -> List[Finding]:
        out: List[Finding] = []
        for seam in SEAMS:
            self._check_seam(project, seam, out)
        self._check_journal(project, out)
        self._check_calibration(project, out)
        self._check_env(project, out)
        self._check_net_timeout(project, out)
        return out

    # -- seam-frame-drift ---------------------------------------------------

    def _check_seam(self, project: Project, seam: Seam,
                    out: List[Finding]) -> None:
        wf = project.file_named(seam.writer_file)
        rf = project.file_named(seam.reader_file)
        if wf is None or rf is None or wf.tree is None or rf.tree is None:
            return
        widx, writers = _find_fns(wf, seam.writer_fns)
        _, readers = _find_fns(rf, seam.reader_fns)
        if not writers or not readers:
            return

        frame_top: Set[str] = set()
        frame_all: Set[str] = set()
        is_open = False
        for q, fn in writers:
            fr = writer_frame(fn, widx, q)
            frame_top |= fr.top_keys
            frame_all |= fr.all_keys
            is_open |= fr.open
        if not frame_all:
            return

        read: Set[str] = set()
        for q, fn in readers:
            for key, node in reader_keys(fn, seam.reader_vars):
                read.add(key)
                if key not in frame_all and not is_open:
                    self._emit(
                        out, rf, "seam-frame-drift", node, q,
                        f"`{seam.name}` seam: `{key}` is parsed here but"
                        f" never written by"
                        f" `{seam.writer_file}:{seam.writer_fns[0]}` —"
                        " the read only ever sees its default")
        if seam.two_way:
            for q, fn in writers:
                fr = writer_frame(fn, widx, q)
                for key in sorted(fr.top_keys - read):
                    self._emit(
                        out, wf, "seam-frame-drift", fn, q,
                        f"`{seam.name}` seam: `{key}` is written here but"
                        f" never parsed by"
                        f" `{seam.reader_file}` — dead weight on the wire")

    # -- seam-journal-schema ------------------------------------------------

    def _schema_keys(self, sf: SourceFile):
        for node in cached_walk(sf.tree):
            target = None
            if isinstance(node, ast.AnnAssign):
                target, value = node.target, node.value
            elif isinstance(node, ast.Assign) and len(node.targets) == 1:
                target, value = node.targets[0], node.value
            if (isinstance(target, ast.Name) and target.id == "_SCHEMA"
                    and isinstance(value, ast.Dict)):
                return _dict_keys(value)[0], node
        return None, None

    def _check_journal(self, project: Project, out: List[Finding]) -> None:
        jf = project.file_named("obs/journal.py")
        if jf is None or jf.tree is None:
            return
        schema, schema_node = self._schema_keys(jf)
        if not schema:
            return

        ef = project.file_named("engine/execution.py")
        if ef is not None and ef.tree is not None:
            idx = FunctionIndex(ef.tree)
            for node in cached_walk(ef.tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "emit"):
                    continue
                recv = dotted_name(node.func.value) or ""
                if "journal" not in recv:
                    continue
                scope = idx.enclosing(ef.tree, node)
                kwargs = {kw.arg for kw in node.keywords
                          if kw.arg is not None}
                spread = any(kw.arg is None for kw in node.keywords)
                for extra in sorted(kwargs - schema):
                    self._emit(
                        out, ef, "seam-journal-schema", node, scope,
                        f"journal emit passes `{extra}`, which"
                        " `validate_row` drops — the whole row is"
                        " silently discarded; add the field to _SCHEMA"
                        " or remove it here")
                if not spread:
                    missing = sorted(schema - JOURNAL_AUTO_KEYS - kwargs)
                    for m in missing:
                        self._emit(
                            out, ef, "seam-journal-schema", node, scope,
                            f"journal emit omits schema field `{m}` —"
                            " `validate_row` requires every field, so"
                            " this row is silently dropped")

        doc = _read_doc(_doc_path(project, "journal_doc",
                                  "observability.md"))
        if doc is not None:
            documented = set(_BACKTICK.findall(doc))
            for key in sorted(schema - documented):
                self._emit(
                    out, jf, "seam-journal-schema", schema_node,
                    "obs/journal._SCHEMA",
                    f"journal schema field `{key}` is missing from the"
                    " doc/observability.md schema table — the doc is"
                    " the operator contract")

    # -- seam-calibration-params --------------------------------------------

    def _check_calibration(self, project: Project,
                           out: List[Finding]) -> None:
        af = project.file_named("tune/artifact.py")
        if af is None or af.tree is None:
            return
        keys: Optional[Set[str]] = None
        keys_node = None
        for node in cached_walk(af.tree):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == "PARAM_KEYS"
                    and isinstance(node.value, (ast.Tuple, ast.List))):
                keys = {e.value for e in node.value.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, str)}
                keys_node = node
        if not keys:
            return
        idx = FunctionIndex(af.tree)
        read: Set[str] = set()
        for node in cached_walk(af.tree):
            if not (isinstance(node, ast.Subscript)
                    and isinstance(node.value, ast.Attribute)
                    and node.value.attr == "params"):
                continue
            key = _const_str(node.slice)
            if key is None:
                continue
            read.add(key)
            if key not in keys:
                self._emit(
                    out, af, "seam-calibration-params", node,
                    idx.enclosing(af.tree, node),
                    f"accessor reads params[`{key}`] but PARAM_KEYS never"
                    " persists it — the accessor always answers its"
                    " default")
        for key in sorted(keys - read):
            self._emit(
                out, af, "seam-calibration-params", keys_node,
                "tune/artifact.PARAM_KEYS",
                f"PARAM_KEYS persists `{key}` but no accessor reads it"
                " back — dead weight in every calibration artifact")

    # -- seam-env-read / seam-env-doc ---------------------------------------

    def _registry_names(self, project: Project) -> Optional[Set[str]]:
        override = project.options.get("env_registry")
        if override is not None:
            return set(override)
        try:
            from . import envvars
        except ImportError:          # pragma: no cover - sibling module
            return None
        return set(envvars.names())

    def _env_reads(self, sf: SourceFile) -> List[Tuple[str, ast.AST]]:
        out: List[Tuple[str, ast.AST]] = []
        for node in cached_walk(sf.tree):
            name: Optional[str] = None
            if isinstance(node, ast.Call):
                fn = dotted_name(node.func) or ""
                last = fn.rsplit(".", 1)[-1]
                if (fn in ("os.environ.get", "environ.get", "os.getenv",
                           "getenv")
                        or last == "resolve_knob"
                        or last.startswith("_env")):
                    if node.args:
                        name = _const_str(node.args[0])
            elif (isinstance(node, ast.Subscript)
                  and isinstance(node.ctx, ast.Load)
                  and dotted_name(node.value) in ("os.environ", "environ")):
                name = _const_str(node.slice)
            if name is not None and name.startswith("JEPSEN_TPU_"):
                out.append((name, node))
        return out

    def _check_env(self, project: Project, out: List[Finding]) -> None:
        registry = self._registry_names(project)
        if registry is None:
            return
        anchor = project.file_named("lint/envvars.py")
        # the registered-but-never-read check is only sound when every
        # potential reader is in the scanned set; a --changed subset
        # that happens to include envvars.py must not fire it
        full_tree = ((anchor is not None
                      or project.options.get("env_registry") is not None)
                     and not project.options.get("subset_scan"))
        read_anywhere: Set[str] = set()
        for sf in project.files:
            if sf.tree is None:
                continue
            for name, node in self._env_reads(sf):
                read_anywhere.add(name)
                if name not in registry:
                    self._emit(
                        out, sf, "seam-env-read", node,
                        FunctionIndex(sf.tree).enclosing(sf.tree, node),
                        f"`{name}` is read here but not registered in"
                        " lint/envvars.py — every JEPSEN_TPU_* knob"
                        " must appear in the central registry (and the"
                        " generated doc table)")

        anchor_sf = anchor or (project.files[0] if project.files else None)
        if anchor_sf is None or anchor_sf.tree is None:
            return
        anchor_node = anchor_sf.tree

        doc = _read_doc(_doc_path(project, "env_doc", "configuration.md"))
        if doc is not None:
            documented = set(_ENV_TOKEN.findall(doc))
            for name in sorted(registry - documented):
                self._emit(
                    out, anchor_sf, "seam-env-doc", anchor_node,
                    "lint/envvars.REGISTRY",
                    f"registered variable `{name}` is missing from the"
                    " generated doc/configuration.md table — regenerate"
                    " it with `python -m jepsen_tpu.lint.envvars`")
            for name in sorted(documented - registry):
                self._emit(
                    out, anchor_sf, "seam-env-doc", anchor_node,
                    "lint/envvars.REGISTRY",
                    f"doc/configuration.md documents `{name}`, which the"
                    " registry doesn't know — remove the doc row or"
                    " register the variable")
        if full_tree:
            for name in sorted(registry - read_anywhere):
                self._emit(
                    out, anchor_sf, "seam-env-doc", anchor_node,
                    "lint/envvars.REGISTRY",
                    f"registered variable `{name}` is never read by any"
                    " scanned module — stale registry entry")

    # -- net-timeout ---------------------------------------------------------

    def _check_net_timeout(self, project: Project,
                           out: List[Finding]) -> None:
        """Every blocking call on the network-facing seams (``serve/``,
        the client's HTTP path included, and the ``control/`` transport
        plane) must carry an explicit bound.  A dead peer must cost a
        timeout, never a hang: the chaos harness
        (``python -m jepsen_tpu.serve.chaos``) proves the dynamic half;
        this rule keeps new call sites from regressing the static half.
        Sanctioned indefinite waits (a supervisor blocking on its
        child's lifetime, the HTTP server's accept loop) carry
        ``# jt: allow[net-timeout] — reason`` annotations."""
        files = {id(sf): sf for d in ("serve", "control")
                 for sf in project.files_in(d)}
        for sf in files.values():
            if sf.tree is None:
                continue
            idx = FunctionIndex(sf.tree)
            for node in cached_walk(sf.tree):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func) or ""
                last = name.rsplit(".", 1)[-1]
                kwargs = {kw.arg for kw in node.keywords
                          if kw.arg is not None}
                spread = any(kw.arg is None for kw in node.keywords)
                msg = None
                if last == "urlopen":
                    if "timeout" not in kwargs and not spread:
                        msg = ("urlopen without timeout= — a stalled"
                               " daemon holds this thread forever; pass"
                               " the remaining deadline budget")
                elif last == "create_connection":
                    if ("timeout" not in kwargs and len(node.args) < 2
                            and not spread):
                        msg = ("socket.create_connection without a"
                               " timeout — a black-holed peer blocks"
                               " until the kernel gives up (minutes)")
                elif (last in _SUBPROCESS_BLOCKERS
                      and name.startswith("subprocess.")):
                    if "timeout" not in kwargs and not spread:
                        msg = (f"subprocess.{last} without timeout= —"
                               " a hung ssh/scp/kubectl child blocks"
                               " the control plane indefinitely")
                elif (last == "wait" and isinstance(node.func,
                                                    ast.Attribute)):
                    if "timeout" not in kwargs and not node.args \
                            and not spread:
                        msg = ("unbounded .wait() — if the signalling"
                               " side died, this waits forever; pass a"
                               " timeout or annotate the sanctioned"
                               " block with jt: allow[net-timeout]")
                elif last == "serve_forever":
                    msg = ("serve_forever blocks this thread for the"
                           " process lifetime — annotate the sanctioned"
                           " accept loop with jt: allow[net-timeout]")
                if msg:
                    self._emit(out, sf, "net-timeout", node,
                               idx.enclosing(sf.tree, node), msg)

    def _emit(self, out, sf, rule, node, scope, msg) -> None:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        if sf.allowed(line, rule):
            return
        out.append(Finding(rule, sf.rel, line, col, msg, scope))


register(SeamContracts())
