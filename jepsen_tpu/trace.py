"""In-process span tracing (reference: dgraph/src/jepsen/dgraph/trace.clj).

The reference wraps client and nemesis operations in opencensus spans
sampled per a tracer config and exported to a jaeger collector
(trace.clj:9-39).  The same surface here: ``tracing(endpoint)`` turns
sampling on iff a destination is configured, ``with_trace(name)`` wraps
a body in a (nested) span, ``context()`` exposes the current
span/trace ids, and ``annotate``/``attribute`` decorate the live span
(trace.clj:41-73).  Export is a pluggable callable over finished spans;
the default ``JsonlExporter`` appends them to a file — the same
flight-recorder role without an external collector (a jaeger/OTLP
exporter would plug in at this seam).

Spans are tracked per thread (client workers are logically
single-threaded, interpreter.py), so nesting follows each worker's call
stack exactly like the reference's scoped spans.
"""

from __future__ import annotations

import json
import random
import threading
import time
from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Optional

_local = threading.local()


def _span_stack() -> list:
    st = getattr(_local, "spans", None)
    if st is None:
        st = _local.spans = []
    return st


def _hex_id(bits: int) -> str:
    return f"{random.getrandbits(bits):0{bits // 4}x}"


class Span:
    __slots__ = (
        "name", "trace_id", "span_id", "parent_id",
        "start", "end", "annotations", "attributes",
    )

    def __init__(self, name: str, trace_id: str, parent_id: Optional[str]):
        self.name = name
        self.trace_id = trace_id
        self.span_id = _hex_id(64)
        self.parent_id = parent_id
        self.start = time.time()
        self.end: Optional[float] = None
        self.annotations: List[dict] = []
        self.attributes: Dict[str, str] = {}

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "trace-id": self.trace_id,
            "span-id": self.span_id,
            "parent-id": self.parent_id,
            "start": self.start,
            "end": self.end,
            "annotations": self.annotations,
            "attributes": self.attributes,
        }


class JsonlExporter:
    """Appends finished spans to a JSONL file (thread-safe)."""

    def __init__(self, path: str):
        self.path = path
        self.lock = threading.Lock()

    def __call__(self, span: Span) -> None:
        line = json.dumps(span.to_dict())
        with self.lock:
            with open(self.path, "a") as f:
                f.write(line + "\n")


class Tracer:
    def __init__(
        self,
        sample: bool = False,
        exporter: Optional[Callable[[Span], None]] = None,
    ):
        self.sample = sample
        self.exporter = exporter


#: module-level tracer, configured by tracing(); never-sample default
#: mirrors the reference's Samplers/neverSample fallback (trace.clj:9-14)
_tracer = Tracer()


def tracing(
    endpoint: Optional[str] = None,
    exporter: Optional[Callable[[Span], None]] = None,
) -> dict:
    """Configure global tracing: sampling turns on iff a destination is
    given (reference: trace.clj:35-39 — always-sample when an endpoint
    is provided, never-sample otherwise).  ``endpoint`` names a JSONL
    file path here; pass a custom ``exporter`` callable to ship spans
    elsewhere."""
    global _tracer
    if exporter is None and endpoint:
        exporter = JsonlExporter(endpoint)
    _tracer = Tracer(sample=exporter is not None, exporter=exporter)
    return {"endpoint": endpoint, "config": _tracer.sample,
            "exporter": exporter}


@contextmanager
def with_trace(name: str):
    """Wrap a body in a tracing span (reference: trace.clj:41-49).  A
    no-op when sampling is off."""
    if not _tracer.sample:
        yield None
        return
    stack = _span_stack()
    parent = stack[-1] if stack else None
    span = Span(
        name,
        parent.trace_id if parent else _hex_id(128),
        parent.span_id if parent else None,
    )
    stack.append(span)
    try:
        yield span
    finally:
        span.end = time.time()
        stack.pop()
        if _tracer.exporter is not None:
            _tracer.exporter(span)


def context() -> Dict[str, str]:
    """Current {span-id, trace-id} (reference: trace.clj:51-58); zeros
    outside any span, like an invalid opencensus context."""
    stack = _span_stack()
    if not stack:
        return {"span-id": "0" * 16, "trace-id": "0" * 32}
    span = stack[-1]
    return {"span-id": span.span_id, "trace-id": span.trace_id}


def annotate(message: str) -> None:
    """Annotate the current span (reference: trace.clj:60-64)."""
    stack = _span_stack()
    if stack:
        stack[-1].annotations.append(
            {"time": time.time(), "message": str(message)}
        )


def attribute(k: Any, v: Any) -> None:
    """Set a string attribute on the current span; coerces both sides
    to str (the reference warns opencensus throws on non-strings,
    trace.clj:66-73 — coercion is the friendlier contract)."""
    stack = _span_stack()
    if stack:
        stack[-1].attributes[str(k)] = str(v)


class Traced:
    """Client decorator wrapping every protocol call in a span.

    The reference traces each dgraph client function body individually
    (dgraph/client.clj:55-377 wraps open!/close!/mutate/query/... in
    with-trace).  One wrapper at the Client-protocol seam covers every
    client flavor of a suite instead, and tags invoke spans with the
    op's :f (and key, when the value is an independent [k v] tuple)."""

    def __init__(self, client):
        self.client = client

    def open(self, test, node):
        with with_trace("client.open"):
            attribute("node", node)
            opened = self.client.open(test, node)
        return Traced(opened) if opened is not self.client else self

    def setup(self, test):
        with with_trace("client.setup"):
            return self.client.setup(test)

    def invoke(self, test, op):
        with with_trace("client.invoke"):
            attribute("f", op.get("f"))
            v = op.get("value")
            # tag independent [k v] pairs only — a 2-micro-op txn is
            # also a 2-element sequence, but its head is a micro-op
            # list, not a scalar key
            if (
                isinstance(v, (list, tuple))
                and len(v) == 2
                and not isinstance(v[0], (list, tuple, dict))
            ):
                attribute("key", v[0])
            return self.client.invoke(test, op)

    def teardown(self, test):
        with with_trace("client.teardown"):
            return self.client.teardown(test)

    def close(self, test):
        with with_trace("client.close"):
            return self.client.close(test)

    def reusable(self, test):
        inner = getattr(self.client, "reusable", None)
        return bool(inner and inner(test))


def wire(test: dict, endpoint: Optional[str]) -> dict:
    """Wire span tracing into a built test map: record the endpoint
    (core.run configures the global tracer from it at run start, and
    unconfigures it at run end) and wrap the client so every protocol
    call gets a span.  With no endpoint the test map is untouched —
    untraced runs pay nothing."""
    if endpoint:
        test["tracing"] = endpoint
        test["client"] = Traced(test["client"])
    return test
