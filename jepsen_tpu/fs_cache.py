"""Control-node file cache for downloaded artifacts, with atomic writes.

(reference: jepsen/src/jepsen/fs_cache.clj — cache layout and encoding,
write-atomic! :140-170, cached? :184-200, save-remote!/deploy-remote!
:244-278.)
"""

from __future__ import annotations

import hashlib
import os
import shutil
import tempfile
from contextlib import contextmanager
from typing import Any, Optional

from . import control

DEFAULT_DIR = os.path.expanduser("~/.jepsen_tpu/cache")


def _key_path(base: str, key: Any) -> str:
    """Encode an arbitrary key into a filesystem path."""
    if isinstance(key, (list, tuple)):
        digest = hashlib.sha256(repr(tuple(key)).encode()).hexdigest()[:32]
    else:
        digest = hashlib.sha256(str(key).encode()).hexdigest()[:32]
    return os.path.join(base, digest[:2], digest)


class Cache:
    def __init__(self, directory: str = DEFAULT_DIR):
        self.dir = directory

    def path(self, key: Any) -> str:
        return _key_path(self.dir, key)

    def cached(self, key: Any) -> bool:
        """(reference: fs_cache.clj:184-200)"""
        return os.path.exists(self.path(key))

    @contextmanager
    def atomic_write(self, key: Any):
        """Yield a temp path; on clean exit it's renamed into place.
        (reference: fs_cache.clj:140-170 write-atomic!)"""
        dest = self.path(key)
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(dest))
        os.close(fd)
        try:
            yield tmp
            os.replace(tmp, dest)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)

    def save_bytes(self, data: bytes, key: Any) -> str:
        with self.atomic_write(key) as tmp:
            with open(tmp, "wb") as f:
                f.write(data)
        return self.path(key)

    def load_bytes(self, key: Any) -> Optional[bytes]:
        if not self.cached(key):
            return None
        with open(self.path(key), "rb") as f:
            return f.read()

    def save_remote(self, remote_path: str, key: Any) -> str:
        """Download a file from the current node into the cache.
        (reference: fs_cache.clj:244-251)"""
        with self.atomic_write(key) as tmp:
            control.download(remote_path, tmp)
        return self.path(key)

    def deploy_remote(self, key: Any, remote_path: str) -> None:
        """Upload a cached file to the current node.
        (reference: fs_cache.clj:252-260)"""
        local = self.path(key)
        if not os.path.exists(local):
            raise FileNotFoundError(f"cache miss for {key!r}")
        control.upload(local, remote_path)

    def clear(self) -> None:
        if os.path.exists(self.dir):
            shutil.rmtree(self.dir)


cache = Cache()
