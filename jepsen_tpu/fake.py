"""In-process fake DB and client for integration tests without a cluster.

(reference: jepsen/src/jepsen/tests.clj:27-66 atom-db/atom-client, used by
core_test.clj's basic-cas-test to drive the *real* interpreter.)
"""

from __future__ import annotations

import threading
import time
from typing import Any, List, Optional

from . import client as client_mod


class AtomState:
    """A compare-and-settable cell guarded by a lock."""

    def __init__(self, value: Any = None):
        self.lock = threading.Lock()
        self.value = value

    def reset(self, value: Any) -> Any:
        with self.lock:
            self.value = value
            return value

    def deref(self) -> Any:
        with self.lock:
            return self.value

    def cas(self, old: Any, new: Any) -> bool:
        with self.lock:
            if self.value == old:
                self.value = new
                return True
            return False


class AtomClient(client_mod.Client):
    """CAS-register client over an AtomState.
    (reference: tests.clj:34-66)"""

    def __init__(
        self,
        state: AtomState,
        meta_log: Optional[List[str]] = None,
        latency: float = 0.001,
    ):
        self.state = state
        self.meta_log = meta_log if meta_log is not None else []
        self.latency = latency

    def open(self, test, node):
        self.meta_log.append("open")
        return AtomClient(self.state, self.meta_log, self.latency)

    def setup(self, test):
        self.meta_log.append("setup")

    def invoke(self, test, op):
        # sleep to get actual concurrency (reference: tests.clj:50)
        if self.latency:
            time.sleep(self.latency)
        f = op["f"]
        if f == "write":
            self.state.reset(op["value"])
            return {**op, "type": "ok"}
        elif f == "cas":
            old, new = op["value"]
            ok = self.state.cas(old, new)
            return {**op, "type": "ok" if ok else "fail"}
        elif f == "read":
            return {**op, "type": "ok", "value": self.state.deref()}
        raise ValueError(f"unknown op f={f!r}")

    def teardown(self, test):
        self.meta_log.append("teardown")

    def close(self, test):
        self.meta_log.append("close")


class KeyedAtomClient(client_mod.Client):
    """A map of independent CAS registers: understands ops whose value
    is an independent ``[k, v]`` tuple, routing v to the register for k.
    Drives the keyed workloads (linearizable-register etc.) in-process."""

    def __init__(self, registers=None, latency: float = 0.0):
        self.registers = registers if registers is not None else {}
        self.lock = threading.Lock()
        self.latency = latency

    def open(self, test, node):
        c = KeyedAtomClient(registers=self.registers, latency=self.latency)
        c.lock = self.lock
        return c

    def _register(self, k) -> AtomState:
        with self.lock:
            if k not in self.registers:
                self.registers[k] = AtomState(None)
            return self.registers[k]

    def invoke(self, test, op):
        from . import independent as ind

        if self.latency:
            time.sleep(self.latency)
        v = op.get("value")
        if not isinstance(v, ind.KV):
            raise ValueError(f"expected [k, v] tuple value, got {v!r}")
        k, inner_v = v.key, v.value
        reg = self._register(k)
        f = op["f"]
        if f == "write":
            reg.reset(inner_v)
            return {**op, "type": "ok"}
        if f == "cas":
            old, new = inner_v
            ok = reg.cas(old, new)
            return {**op, "type": "ok" if ok else "fail"}
        if f == "read":
            return {**op, "type": "ok", "value": ind.kv(k, reg.deref())}
        raise ValueError(f"unknown op f={f!r}")


class CrashingClient(AtomClient):
    """Like AtomClient but raises on a fraction of ops — exercises the
    interpreter's crash→:info→process-retirement path."""

    def __init__(self, state, crash_every: int = 5, **kw):
        super().__init__(state, **kw)
        self.crash_every = crash_every
        self.counter = {"n": 0}

    def open(self, test, node):
        self.meta_log.append("open")
        c = CrashingClient(
            self.state,
            crash_every=self.crash_every,
            meta_log=self.meta_log,
            latency=self.latency,
        )
        c.counter = self.counter
        return c

    def invoke(self, test, op):
        self.counter["n"] += 1
        if self.counter["n"] % self.crash_every == 0:
            raise RuntimeError("client crashed!")
        return super().invoke(test, op)
