"""In-process fake DB and client for integration tests without a cluster.

(reference: jepsen/src/jepsen/tests.clj:27-66 atom-db/atom-client, used by
core_test.clj's basic-cas-test to drive the *real* interpreter.)
"""

from __future__ import annotations

import threading
import time
from typing import Any, List, Optional

from . import client as client_mod


class AtomState:
    """A compare-and-settable cell guarded by a lock."""

    def __init__(self, value: Any = None):
        self.lock = threading.Lock()
        self.value = value

    def reset(self, value: Any) -> Any:
        with self.lock:
            self.value = value
            return value

    def deref(self) -> Any:
        with self.lock:
            return self.value

    def cas(self, old: Any, new: Any) -> bool:
        with self.lock:
            if self.value == old:
                self.value = new
                return True
            return False


class AtomClient(client_mod.Client):
    """CAS-register client over an AtomState.
    (reference: tests.clj:34-66)"""

    def __init__(
        self,
        state: AtomState,
        meta_log: Optional[List[str]] = None,
        latency: float = 0.001,
    ):
        self.state = state
        self.meta_log = meta_log if meta_log is not None else []
        self.latency = latency

    def open(self, test, node):
        self.meta_log.append("open")
        return AtomClient(self.state, self.meta_log, self.latency)

    def setup(self, test):
        self.meta_log.append("setup")

    def invoke(self, test, op):
        # sleep to get actual concurrency (reference: tests.clj:50)
        if self.latency:
            time.sleep(self.latency)
        f = op["f"]
        if f == "write":
            self.state.reset(op["value"])
            return {**op, "type": "ok"}
        elif f == "cas":
            old, new = op["value"]
            ok = self.state.cas(old, new)
            return {**op, "type": "ok" if ok else "fail"}
        elif f == "read":
            return {**op, "type": "ok", "value": self.state.deref()}
        raise ValueError(f"unknown op f={f!r}")

    def teardown(self, test):
        self.meta_log.append("teardown")

    def close(self, test):
        self.meta_log.append("close")


class KeyedAtomClient(client_mod.Client):
    """A map of independent CAS registers: understands ops whose value
    is an independent ``[k, v]`` tuple, routing v to the register for k.
    Drives the keyed workloads (linearizable-register etc.) in-process."""

    def __init__(self, registers=None, latency: float = 0.0):
        self.registers = registers if registers is not None else {}
        self.lock = threading.Lock()
        self.latency = latency

    def open(self, test, node):
        # type(self): subclasses (CausalAtomClient) must survive open
        c = type(self)(registers=self.registers, latency=self.latency)
        c.lock = self.lock
        return c

    def _register(self, k) -> AtomState:
        with self.lock:
            if k not in self.registers:
                self.registers[k] = AtomState(None)
            return self.registers[k]

    def invoke(self, test, op):
        from . import independent as ind

        if self.latency:
            time.sleep(self.latency)
        v = op.get("value")
        if not isinstance(v, ind.KV):
            raise ValueError(f"expected [k, v] tuple value, got {v!r}")
        k, inner_v = v.key, v.value
        reg = self._register(k)
        f = op["f"]
        if f == "write":
            reg.reset(inner_v)
            return {**op, "type": "ok"}
        if f == "cas":
            old, new = inner_v
            ok = reg.cas(old, new)
            return {**op, "type": "ok" if ok else "fail"}
        if f == "read":
            return {**op, "type": "ok", "value": ind.kv(k, reg.deref())}
        raise ValueError(f"unknown op f={f!r}")


class CrashingClient(AtomClient):
    """Like AtomClient but raises on a fraction of ops — exercises the
    interpreter's crash→:info→process-retirement path."""

    def __init__(self, state, crash_every: int = 5, **kw):
        super().__init__(state, **kw)
        self.crash_every = crash_every
        self.counter = {"n": 0}

    def open(self, test, node):
        self.meta_log.append("open")
        c = CrashingClient(
            self.state,
            crash_every=self.crash_every,
            meta_log=self.meta_log,
            latency=self.latency,
        )
        c.counter = self.counter
        return c

    def invoke(self, test, op):
        self.counter["n"] += 1
        if self.counter["n"] % self.crash_every == 0:
            raise RuntimeError("client crashed!")
        return super().invoke(test, op)


class KeyedAtomSetClient(client_mod.Client):
    """A map of independent grow-only sets: writes add the value to key
    k's set, reads return the sorted contents — the read-your-writes
    shape the causal/sequential probes expect (their checkers consume
    the LIST of writes a read observed; a single register value would
    be meaningless there)."""

    def __init__(self, sets=None, latency: float = 0.0):
        self.sets = sets if sets is not None else {}
        self.lock = threading.Lock()
        self.latency = latency

    def open(self, test, node):
        c = type(self)(sets=self.sets, latency=self.latency)
        c.lock = self.lock
        return c

    def invoke(self, test, op):
        from . import independent as ind

        if self.latency:
            time.sleep(self.latency)
        v = op.get("value")
        if not isinstance(v, ind.KV):
            raise ValueError(f"expected [k, v] tuple value, got {v!r}")
        k, inner_v = v.key, v.value
        f = op["f"]
        with self.lock:
            s = self.sets.setdefault(k, set())
            if f == "write" or f == "add":
                s.add(inner_v)
                return {**op, "type": "ok"}
            if f == "read":
                return {
                    **op, "type": "ok",
                    "value": ind.kv(k, sorted(s)),
                }
        raise ValueError(f"unknown op f={f!r}")


class BankAtomClient(client_mod.Client):
    """In-process bank: transfers move balance atomically between
    accounts (overdrafts fail, like the SQL clients' aborting
    transactions), reads return the full balance map.  Accounts seed
    lazily from the test map (total-amount split across accounts)."""

    def __init__(self, balances=None, latency: float = 0.0):
        self.balances = balances if balances is not None else {}
        self.lock = threading.Lock()
        self.latency = latency

    def open(self, test, node):
        c = type(self)(balances=self.balances, latency=self.latency)
        c.lock = self.lock
        return c

    def _seed(self, test):
        if not self.balances:
            accounts = list(test.get("accounts", range(8)))
            total = int(test.get("total-amount", 100))
            share = total // len(accounts)
            for i, a in enumerate(accounts):
                # first account takes the remainder so totals add up
                self.balances[a] = share + (
                    total - share * len(accounts) if i == 0 else 0
                )

    def invoke(self, test, op):
        if self.latency:
            time.sleep(self.latency)
        f = op["f"]
        with self.lock:
            self._seed(test)
            if f == "read":
                return {**op, "type": "ok", "value": dict(self.balances)}
            if f == "transfer":
                v = op["value"]
                frm, to, amount = v["from"], v["to"], v["amount"]
                if self.balances.get(frm, 0) < amount and not test.get(
                    "negative-balances?"
                ):
                    return {**op, "type": "fail", "error": "insufficient"}
                self.balances[frm] = self.balances.get(frm, 0) - amount
                self.balances[to] = self.balances.get(to, 0) + amount
                return {**op, "type": "ok"}
        raise ValueError(f"unknown op f={f!r}")


class TxnAtomClient(client_mod.Client):
    """Atomic micro-op transactions over a shared register map: ops
    carry mop lists ``[["w", k, v], ["r", k, None], ["append", k, v],
    ...]``; the whole list applies under one lock (a serializable
    in-memory store; appended keys hold lists).  Serves the long-fork
    and elle list-append/rw-register probes in-process."""

    def __init__(self, kv=None, latency: float = 0.0):
        self.kv = kv if kv is not None else {}
        self.lock = threading.Lock()
        self.latency = latency

    def open(self, test, node):
        c = type(self)(kv=self.kv, latency=self.latency)
        c.lock = self.lock
        return c

    def invoke(self, test, op):
        if self.latency:
            time.sleep(self.latency)
        mops = op.get("value") or []
        out = []
        with self.lock:
            for mf, k, v in mops:
                if mf in ("w", "write"):
                    self.kv[k] = v
                    out.append([mf, k, v])
                elif mf in ("r", "read"):
                    cur = self.kv.get(k)
                    out.append(
                        [mf, k, list(cur) if isinstance(cur, list) else cur]
                    )
                elif mf == "append":
                    self.kv.setdefault(k, []).append(v)
                    out.append([mf, k, v])
                else:
                    raise ValueError(f"unknown mop {mf!r}")
        return {**op, "type": "ok", "value": out}


class CausalAtomClient(KeyedAtomClient):
    """Keyed registers starting at 0 with the causal probe's
    ``read-init`` treated as a read — the CausalRegister model expects
    the initial value 0, not None."""

    def _register(self, k) -> AtomState:
        with self.lock:
            if k not in self.registers:
                self.registers[k] = AtomState(0)
            return self.registers[k]

    def invoke(self, test, op):
        from . import independent as ind

        if op["f"] == "read-init":
            v = op.get("value")
            k = v.key if isinstance(v, ind.KV) else 0
            reg = self._register(k)
            return {**op, "type": "ok", "value": ind.kv(k, reg.deref())}
        return super().invoke(test, op)


class InsertOnceAtomClient(client_mod.Client):
    """Keyed put-if-absent: the FIRST insert per key wins, later ones
    fail — the at-most-one-row guarantee the adya G2 probe checks."""

    def __init__(self, rows=None, latency: float = 0.0):
        self.rows = rows if rows is not None else {}
        self.lock = threading.Lock()
        self.latency = latency

    def open(self, test, node):
        c = type(self)(rows=self.rows, latency=self.latency)
        c.lock = self.lock
        return c

    def invoke(self, test, op):
        from . import independent as ind

        if self.latency:
            time.sleep(self.latency)
        v = op.get("value")
        if op["f"] != "insert" or not isinstance(v, ind.KV):
            raise ValueError(f"unknown op {op!r}")
        k = v.key
        with self.lock:
            if k in self.rows:
                return {**op, "type": "fail", "error": "exists"}
            self.rows[k] = v.value
        return {**op, "type": "ok"}
