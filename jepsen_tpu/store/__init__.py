"""Test persistence: directory layout, 3-phase save, logging, symlinks.

Tests save in three phases so crashes lose as little as possible
(reference: jepsen/src/jepsen/store.clj:404-456, called from
core.clj:386,402,236):

- :func:`save_0` — at test start: the initial test map
- :func:`save_1` — after the run: the history is durable (binary block
  + history.txt + history.jsonl), symlinks update
- :func:`save_2` — after analysis: results (valid? split out for cheap
  reads) + the final test map

Artifacts live in ``<base>/<name>/<start-time>/``: ``test.jtpu`` (the
incremental block file, jepsen_tpu.store.format), ``history.txt``,
``history.jsonl``, ``results.json``, ``jepsen.log``, plus whatever
checkers write.  ``latest``/``current`` symlinks mirror the reference
(store.clj:344-358).
"""

from __future__ import annotations

import json
import logging
import os
import shutil
from contextlib import contextmanager
from typing import Any, Dict, Iterator, List, Optional

from ..util import real_pmap

BASE = "store"

#: Test-map keys holding live objects that cannot serialize.
#: (reference: store.clj:91-99)
DEFAULT_NONSERIALIZABLE_KEYS = {
    "barrier", "db", "os", "net", "client", "checker", "nemesis",
    "generator", "model", "remote", "mesh", "mesh-fn", "writer",
}


def base_dir(test: dict) -> str:
    return test.get("store-base", BASE)


def test_dir(test: dict) -> str:
    """store/<name>/<start-time> for this test run."""
    name = test.get("name", "noname")
    start = str(test.get("start-time", "unknown"))
    return os.path.join(base_dir(test), name, start)


def path(test: dict, *components: Any) -> str:
    """Path to an artifact within the test's store directory.
    (reference: store.clj:40-56)"""
    parts = [str(c) for c in components if c is not None and str(c) != ""]
    return os.path.join(test_dir(test), *parts)


def path_(test: dict, *components: Any) -> str:
    """Like path, but ensures the parent directory exists.
    (reference: store.clj `path!`)"""
    p = path(test, *components)
    os.makedirs(os.path.dirname(p) or ".", exist_ok=True)
    return p


def nonserializable_keys(test: dict) -> set:
    """(reference: store.clj:96-100)"""
    return DEFAULT_NONSERIALIZABLE_KEYS | set(
        test.get("nonserializable-keys", ())
    )


def serializable_test(test: dict) -> dict:
    """The test without live objects (and without the huge history —
    the block format stores that separately)."""
    drop = nonserializable_keys(test) | {"history", "results"}
    return {k: v for k, v in test.items() if k not in drop}


def jtpu_file(test: dict) -> str:
    return path(test, "test.jtpu")


# ---------------------------------------------------------------------------
# Writer lifecycle + 3-phase save
# ---------------------------------------------------------------------------


@contextmanager
def with_writer(test: dict):
    """Open the block-file writer for a test run; the same writer spans
    all three save phases.  (reference: store.clj:404-411)"""
    from . import format as fmt

    os.makedirs(test_dir(test), exist_ok=True)
    w = fmt.Writer(jtpu_file(test))
    test = {**test, "writer": w}
    try:
        yield test
    finally:
        w.close()


def save_0(test: dict) -> dict:
    """Initial test map on disk.  (reference: store.clj:413-420)"""
    w = test.get("writer")
    if w is not None:
        base_id = w.write_partial_map(serializable_test(test))
        test = {**test, "base-block": base_id}
        w.set_root(base_id)
        w.save_index()
    return test


def save_1(test: dict) -> dict:
    """History durable: block + text artifacts, symlinks.
    (reference: store.clj:422-437)"""
    from ..history import History

    history: History = test.get("history") or History()
    w = test.get("writer")

    # One JSON pass serves both the block and the history.jsonl artifact.
    jsonl = "\n".join(
        json.dumps(op.to_dict(), default=repr) for op in history
    )

    def write_block():
        if w is None:
            return None
        h_id = w.write_history(history, jsonl=jsonl.encode())
        head_id = w.write_partial_map(
            {"history": {"$block-ref": h_id}}, rest_id=test.get("base-block", 0)
        )
        w.set_root(head_id)
        w.save_index()
        return head_id

    def write_txt():
        with open(path_(test, "history.txt"), "w") as f:
            for op in history:
                f.write(
                    f"{op.index}\t{op.process}\t{op.type}\t{op.f}\t"
                    f"{op.value!r}\n"
                )

    def write_jsonl():
        with open(path_(test, "history.jsonl"), "w") as f:
            f.write(jsonl)
            if jsonl:
                f.write("\n")

    head_id, _, _ = real_pmap(lambda fn: fn(), [write_block, write_txt, write_jsonl])
    if head_id is not None:
        test = {**test, "history-block": head_id}
    update_symlinks(test)
    return test


def save_2(test: dict) -> dict:
    """Results durable; final test map.  (reference: store.clj:439-456)"""
    results = test.get("results") or {}
    w = test.get("writer")

    def write_block():
        if w is None:
            return
        rest = {k: v for k, v in results.items() if k != "valid?"}
        rest_id = w.write_json(rest) if rest else 0
        res_id = w.write_partial_map(
            {"valid?": results.get("valid?")}, rest_id=rest_id
        )
        final_id = w.write_partial_map(
            {"results": {"$block-ref": res_id}},
            rest_id=test.get("history-block", test.get("base-block", 0)),
        )
        w.set_root(final_id)
        w.save_index()

    def write_json():
        with open(path_(test, "results.json"), "w") as f:
            json.dump(results, f, indent=2, default=repr)

    real_pmap(lambda fn: fn(), [write_block, write_json])
    return test


# ---------------------------------------------------------------------------
# Loading
# ---------------------------------------------------------------------------


def _open_reader(path: str):
    """Open a block file, falling back to torn-write recovery: a file
    whose tail was lost to a crash still loads from its longest valid
    block prefix (format.Reader(recover=True)), with a log line so the
    caller knows the view may predate the crash."""
    from . import format as fmt

    r = fmt.Reader(path, recover=True)
    if r.recovered:
        logging.getLogger("jepsen.store").warning(
            "%s: torn write detected; recovered from the valid block "
            "prefix ending at byte %s",
            path,
            r.valid_prefix_end,
        )
    return r


def load(name_or_test, start_time: Optional[str] = None) -> dict:
    """Load a stored test by {name, start-time} map or by name + time.
    Resolves block refs for history and results.
    (reference: store.clj:122-137)"""
    from . import format as fmt
    from ..history import History

    if isinstance(name_or_test, dict):
        test = name_or_test
    else:
        test = {"name": name_or_test, "start-time": start_time}
    r = _open_reader(jtpu_file(test))
    out = r.root_value()
    for key in ("history", "results"):
        v = out.get(key)
        if fmt.is_block_ref(v):
            out[key] = r.read_value(v["$block-ref"])
    if r.recovered:
        out["recovered"] = True
    return out


def load_packed_history(name_or_test, start_time: Optional[str] = None) -> dict:
    """The device-feed arrays of a stored history — no JSON parse."""
    from . import format as fmt

    if isinstance(name_or_test, dict):
        test = name_or_test
    else:
        test = {"name": name_or_test, "start-time": start_time}
    r = _open_reader(jtpu_file(test))
    root = r.root_value()
    v = root.get("history")
    if not fmt.is_block_ref(v):
        raise IOError("no history block saved")
    out = r.read_packed_history(v["$block-ref"])
    if r.recovered:
        # same flag load() sets: the arrays may predate a torn tail
        out["recovered"] = True
    return out


def tests(base: str = BASE, name: Optional[str] = None) -> Dict[str, List[str]]:
    """Map of test name → sorted run timestamps.
    (reference: store.clj tests listing used by web.clj:48-95)"""
    out: Dict[str, List[str]] = {}
    if not os.path.isdir(base):
        return out
    names = [name] if name else sorted(os.listdir(base))
    for n in names:
        d = os.path.join(base, n)
        if not os.path.isdir(d) or n in ("latest", "current"):
            continue
        runs = sorted(
            t
            for t in os.listdir(d)
            if t != "latest" and os.path.isdir(os.path.join(d, t))
        )
        if runs:
            out[n] = runs
    return out


def latest_time(base: str, name: str) -> Optional[str]:
    """The most recent start-time recorded for a named test — via the
    per-test "latest" symlink when present AND still pointing at a run
    dir (a dangling link falls back to the listing, like latest()),
    else the newest surviving run dir.  (start-times are ISO-ish
    timestamps, so lexicographic max = newest)"""
    link = os.path.join(base, name, "latest")
    if os.path.islink(link):
        target = os.path.realpath(link)
        start = os.path.basename(target)
        if start and start != "latest" and os.path.isdir(target):
            return start
    runs = tests(base, name).get(name, ())
    return max(runs) if runs else None


def latest(base: str = BASE) -> Optional[dict]:
    """The most recently saved test, via the latest symlink or listing.
    (reference: repl.clj:6-15)"""
    link = os.path.join(base, "latest")
    if os.path.islink(link):
        target = os.path.realpath(link)
        name = os.path.basename(os.path.dirname(target))
        start = os.path.basename(target)
        try:
            return load({"name": name, "start-time": start,
                         "store-base": base})
        except OSError:
            pass  # dangling symlink: fall back to the listing
    all_tests = tests(base)
    best = None
    for n, runs in all_tests.items():
        for t in runs:
            if best is None or t > best[1]:
                best = (n, t)
    if best is None:
        return None
    try:
        return load({"name": best[0], "start-time": best[1],
                     "store-base": base})
    except OSError:
        return None


# ---------------------------------------------------------------------------
# Symlinks, logging, deletion
# ---------------------------------------------------------------------------


def _update_symlink(target_dir: str, link_path: str) -> None:
    try:
        os.makedirs(os.path.dirname(link_path), exist_ok=True)
        if os.path.islink(link_path) or os.path.exists(link_path):
            os.unlink(link_path)
        os.symlink(
            os.path.relpath(target_dir, os.path.dirname(link_path)), link_path
        )
    except OSError:
        pass  # symlinks are conveniences; never fail a save over one


def update_symlinks(test: dict) -> None:
    """current, latest, and <name>/latest point here.
    (reference: store.clj:344-358)"""
    d = test_dir(test)
    if not os.path.isdir(d):
        return
    base = base_dir(test)
    for link in (
        os.path.join(base, "current"),
        os.path.join(base, "latest"),
        os.path.join(base, test.get("name", "noname"), "latest"),
    ):
        _update_symlink(d, link)


_log_handlers: Dict[str, tuple] = {}  # path -> (handler, prior root level)


def start_logging(test: dict, json_logging: bool = False) -> None:
    """Attach a jepsen.log file handler for this test run.
    (reference: store.clj:474-502 via unilog)"""
    p = path_(test, "jepsen.log")
    if p in _log_handlers:
        return
    handler = logging.FileHandler(p)
    if json_logging:
        class JsonFormatter(logging.Formatter):
            def format(self, record):
                return json.dumps(
                    {
                        "ts": self.formatTime(record),
                        "level": record.levelname,
                        "logger": record.name,
                        "msg": record.getMessage(),
                    }
                )

        handler.setFormatter(JsonFormatter())
    else:
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(levelname)s [%(name)s] %(message)s")
        )
    root = logging.getLogger()
    prior_level = root.level
    root.addHandler(handler)
    if root.level > logging.INFO or root.level == 0:
        root.setLevel(logging.INFO)
    _log_handlers[p] = (handler, prior_level)


def stop_logging(test: dict) -> None:
    p = path(test, "jepsen.log")
    entry = _log_handlers.pop(p, None)
    if entry is not None:
        handler, prior_level = entry
        root = logging.getLogger()
        root.removeHandler(handler)
        root.setLevel(prior_level)
        handler.close()


def delete(base: str = BASE, name: Optional[str] = None) -> None:
    """Delete stored tests (all, or one name's runs).
    (reference: store.clj:513-521)"""
    if name:
        shutil.rmtree(os.path.join(base, name), ignore_errors=True)
    else:
        shutil.rmtree(base, ignore_errors=True)
