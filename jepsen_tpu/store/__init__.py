"""Test persistence: directory layout and artifact paths.

Minimal core for now: the canonical path scheme
``<base>/<test-name>/<start-time>/...`` (reference:
jepsen/src/jepsen/store.clj:40-60 `path`).  The full 3-phase save,
binary format, and logging land with the store milestone.
"""

from __future__ import annotations

import os
from typing import Any

BASE = "store"


def base_dir(test: dict) -> str:
    return test.get("store-base", BASE)


def test_dir(test: dict) -> str:
    """store/<name>/<start-time> for this test run."""
    name = test.get("name", "noname")
    start = str(test.get("start-time", "unknown"))
    return os.path.join(base_dir(test), name, start)


def path(test: dict, *components: Any) -> str:
    """Path to an artifact within the test's store directory.
    (reference: store.clj:40-56)"""
    return os.path.join(test_dir(test), *[str(c) for c in components])


def path_(test: dict, *components: Any) -> str:
    """Like path, but ensures the parent directory exists.
    (reference: store.clj `path!`)"""
    p = path(test, *components)
    os.makedirs(os.path.dirname(p) or ".", exist_ok=True)
    return p
