"""The incremental binary test file: ``test.jtpu``.

Same architecture as the reference's custom block format
(jepsen/src/jepsen/store/format.clj:1-200): a magic header pointing at
the most recent *index block*, then append-only CRC-framed blocks.
Partial writes survive crashes — the index is only repointed after its
block is durable, and stale blocks are simply unreferenced.

Layout (little-endian):

    "JTPU" | u32 version | u64 index-offset | block | block | …

Block frame: ``u64 length(incl. frame) | u32 crc32 | u16 type | data``.
CRC is over data, then the frame with the crc field zeroed.

Block types:

- INDEX (1): JSON ``{"root": id, "blocks": {id: offset}}``
- JSON (2): a JSON document; large values may be ``{"$block-ref": id}``
- PARTIAL_MAP (3): ``u32 rest-block-id | JSON map`` — a cons cell so the
  cheap keys (e.g. results["valid?"]) decode without the huge rest
- HISTORY (4): ``u32 json_len | history JSONL | packed tensor section``
  — the packed section is the device-ready int encoding (npz of the
  structured op arrays), so analysis reloads feed the accelerator with
  no re-parse.  This is the TPU-native twist on the reference's lazy
  Fressian history block.

Byte-level writes go through the C++ writer (native/blockfile.cc) when
available; a pure-Python fallback produces identical bytes.
"""

from __future__ import annotations

import io
import json
import os
import struct
import zlib
from typing import Any, Dict, List, Optional, Tuple

from . import native

MAGIC = b"JTPU"
VERSION = 1
HEADER_SIZE = 4 + 4 + 8
FRAME_SIZE = 8 + 4 + 2

INDEX = 1
JSON_BLOCK = 2
PARTIAL_MAP = 3
HISTORY = 4
HISTORY_CHUNK = 5
CHUNKED_HISTORY = 6

BLOCK_TYPES = {INDEX: "index", JSON_BLOCK: "json", PARTIAL_MAP: "partial-map",
               HISTORY: "history", HISTORY_CHUNK: "history-chunk",
               CHUNKED_HISTORY: "chunked-history"}

#: ops per chunk when a history is large enough to split — the lazy-load
#: granularity (reference: store/format.clj's chunked BigVector history,
#: whose incremental loading is what makes multi-GB histories workable)
HISTORY_CHUNK_SIZE = 8192


def _frame(type_: int, data: bytes) -> bytes:
    frame_len = FRAME_SIZE + len(data)
    head = struct.pack("<QIH", frame_len, 0, type_)
    crc = zlib.crc32(head, zlib.crc32(data))
    return struct.pack("<QIH", frame_len, crc, type_) + data


def block_ref(block_id: int) -> dict:
    return {"$block-ref": block_id}


def is_block_ref(v: Any) -> bool:
    return isinstance(v, dict) and set(v.keys()) == {"$block-ref"}


def _dumps(obj: Any) -> bytes:
    return json.dumps(obj, default=repr).encode()


class Writer:
    """Append-only block writer over the native lib (or pure Python).

    Logical block ids are assigned sequentially from 1 (0 = nil
    sentinel, reference format.clj:95-97); save_index() appends an
    index block and atomically repoints the header at it.
    """

    def __init__(self, path: str):
        self.path = path
        self.blocks: Dict[int, int] = {}  # id -> offset
        self.next_id = 1
        self.root: int = 0
        self._native = None
        self._f = None
        lib = native.lib()
        if lib is not None:
            h = lib.bf_create(path.encode())
            if h:
                self._native = (lib, h)
        if self._native is None:
            self._f = open(path, "wb+")
            self._f.write(MAGIC + struct.pack("<IQ", VERSION, 0))
            self._f.flush()

    # -- low level ---------------------------------------------------------

    def _append(self, type_: int, data: bytes) -> int:
        """Append one framed block; returns its offset."""
        if self._native is not None:
            lib, h = self._native
            off = lib.bf_append_block(h, type_, data, len(data))
            if off == 0:
                raise IOError(f"native append failed at {self.path}")
            return off
        f = self._f
        f.seek(0, os.SEEK_END)
        off = f.tell()
        f.write(_frame(type_, data))
        return off

    def _set_index_offset(self, offset: int) -> None:
        if self._native is not None:
            lib, h = self._native
            if lib.bf_set_index_offset(h, offset) != 0:
                raise IOError(f"native index update failed at {self.path}")
            return
        f = self._f
        f.seek(8)
        f.write(struct.pack("<Q", offset))
        f.flush()

    # -- blocks ------------------------------------------------------------

    def write_block(self, type_: int, data: bytes) -> int:
        """Append a block; returns its logical id."""
        bid = self.next_id
        self.next_id += 1
        self.blocks[bid] = self._append(type_, data)
        return bid

    def write_json(self, obj: Any) -> int:
        return self.write_block(JSON_BLOCK, _dumps(obj))

    def write_partial_map(self, head: dict, rest_id: int = 0) -> int:
        data = struct.pack("<I", rest_id) + _dumps(head)
        return self.write_block(PARTIAL_MAP, data)

    def write_history(
        self,
        history,
        jsonl: Optional[bytes] = None,
        chunk_size: int = HISTORY_CHUNK_SIZE,
    ) -> int:
        """History block: JSONL + the packed device encoding.  Callers
        that already serialized the history (store.save_1 shares one
        pass with history.jsonl) pass the bytes in.

        Histories longer than ``chunk_size`` ops split into
        HISTORY_CHUNK blocks under one CHUNKED_HISTORY root, so readers
        can load (and iterate) them incrementally instead of decoding
        the whole run at once."""
        if jsonl is None:
            jsonl = "\n".join(
                json.dumps(op.to_dict(), default=repr) for op in history
            ).encode()
        else:
            # normalize caller-supplied bytes BEFORE either branch: blank
            # lines (trailing newline, interior gaps) would inflate the
            # chunk table's op counts AND the non-chunked
            # history_len()'s newline count, both of which readers treat
            # as authoritative
            lines = [ln for ln in jsonl.splitlines() if ln]
            if len(lines) != len(history):
                raise ValueError(
                    f"jsonl has {len(lines)} non-empty lines for "
                    f"{len(history)} ops — refusing to write a history "
                    "block with wrong op counts"
                )
            jsonl = b"\n".join(lines)
        if len(history) > chunk_size > 0:
            lines = jsonl.splitlines()
            chunks = []
            for i in range(0, len(lines), chunk_size):
                part = lines[i : i + chunk_size]
                cid = self.write_block(HISTORY_CHUNK, b"\n".join(part))
                chunks.append((cid, len(part)))
            packed = _pack_history(history)
            head = struct.pack("<I", len(chunks)) + b"".join(
                struct.pack("<II", cid, n) for cid, n in chunks
            )
            return self.write_block(CHUNKED_HISTORY, head + packed)
        packed = _pack_history(history)
        data = struct.pack("<I", len(jsonl)) + jsonl + packed
        return self.write_block(HISTORY, data)

    def set_root(self, block_id: int) -> None:
        self.root = block_id

    def save_index(self) -> None:
        """Append a fresh index block and commit it in the header."""
        payload = _dumps({"root": self.root, "blocks": self.blocks})
        off = self._append(INDEX, payload)
        self._set_index_offset(off)

    def flush(self) -> None:
        if self._native is not None:
            self._native[0].bf_flush(self._native[1])
        elif self._f is not None:
            self._f.flush()

    def close(self) -> None:
        if self._native is not None:
            self._native[0].bf_close(self._native[1])
            self._native = None
        elif self._f is not None:
            self._f.close()
            self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _pack_history(history) -> bytes:
    """The device-feed section: structured numpy arrays of the hot op
    fields (type/process/f/value codes + time), via np.savez."""
    import numpy as np

    from ..history import TYPE_CODES

    n = len(history)
    type_codes = np.zeros(n, dtype=np.int8)
    processes = np.zeros(n, dtype=np.int32)
    times = np.zeros(n, dtype=np.int64)
    f_ids = np.zeros(n, dtype=np.int32)
    value_ids = np.zeros(n, dtype=np.int32)
    f_table: Dict[Any, int] = {}
    value_table: Dict[Any, int] = {}

    def intern(table, v):
        key = repr(v)
        if key not in table:
            table[key] = len(table)
        return table[key]

    for i, op in enumerate(history):
        type_codes[i] = TYPE_CODES.get(op.type, 3)
        processes[i] = op.process if isinstance(op.process, int) else -1
        times[i] = op.time
        f_ids[i] = intern(f_table, op.f)
        value_ids[i] = intern(value_table, op.value)
    buf = io.BytesIO()
    np.savez(
        buf,
        type=type_codes,
        process=processes,
        time=times,
        f=f_ids,
        value=value_ids,
    )
    tables = _dumps({"f": list(f_table), "value": list(value_table)})
    npz = buf.getvalue()
    return struct.pack("<II", len(npz), len(tables)) + npz + tables


def scan_valid_prefix(path: str) -> Tuple[List[Tuple[int, int]], int]:
    """Forward-scan the block stream, returning ``([(offset, type), …],
    prefix_end)`` for the longest prefix of intact CRC-framed blocks.

    This is the torn-write recovery primitive (reference: the
    append-only design rationale of store/format.clj:1-120 — partial
    writes must survive crashes): a frame whose length field runs past
    EOF, whose bytes are truncated, or whose CRC fails ends the scan;
    everything before it is trustworthy."""
    size = os.path.getsize(path)
    frames: List[Tuple[int, int]] = []
    off = HEADER_SIZE
    with open(path, "rb") as f:
        while off + FRAME_SIZE <= size:
            f.seek(off)
            head = f.read(FRAME_SIZE)
            frame_len, want_crc, type_ = struct.unpack("<QIH", head)
            if frame_len < FRAME_SIZE or off + frame_len > size:
                break
            data = f.read(frame_len - FRAME_SIZE)
            if len(data) != frame_len - FRAME_SIZE:
                break
            zeroed = struct.pack("<QIH", frame_len, 0, type_)
            if zlib.crc32(zeroed, zlib.crc32(data)) != want_crc:
                break
            frames.append((off, type_))
            off += frame_len
    return frames, off


class Reader:
    """Lazy reader over a block file.

    With ``recover=True`` a file whose tail was torn off (crash mid
    write, disk full, truncated copy) is opened from its longest valid
    block prefix instead of raising: the newest intact index block wins;
    failing that, the id→offset map is rebuilt from append order (data
    block ids are assigned sequentially, index blocks carry no id) and
    the newest partial-map block whose reference chain fully resolves
    becomes the root.  ``reader.recovered`` reports that recovery ran
    and ``reader.valid_prefix_end`` where the intact prefix stops."""

    def __init__(self, path: str, recover: bool = False):
        self.path = path
        self.recovered = False
        self.valid_prefix_end: Optional[int] = None
        with open(path, "rb") as f:
            header = f.read(HEADER_SIZE)
        # Wrong-format errors are never recoverable-from: a different
        # magic or version must not be reinterpreted under v1 block
        # semantics by the recovery scan.
        if header[:4] != MAGIC:
            raise IOError(f"{path}: not a JTPU block file")
        if len(header) < HEADER_SIZE:
            if not recover:
                raise IOError(f"{path}: truncated header")
            self._recover()
            return
        version, index_off = struct.unpack("<IQ", header[4:])
        if version != VERSION:
            raise IOError(f"{path}: unsupported version {version}")
        try:
            if index_off == 0:
                raise IOError(
                    f"{path}: no committed index (crashed before save?)"
                )
            type_, data = self.read_block_at(index_off)
            if type_ != INDEX:
                raise IOError(f"{path}: index offset points at type {type_}")
            idx = json.loads(data)
            self.root = idx["root"]
            self.blocks = {int(k): v for k, v in idx["blocks"].items()}
        except Exception as e:
            if not recover:
                if isinstance(e, OSError):
                    raise
                raise IOError(f"{path}: corrupt index ({e!r})") from e
            self._recover()

    # -- torn-write recovery ----------------------------------------------

    def _recover(self) -> None:
        frames, prefix_end = scan_valid_prefix(self.path)
        self.recovered = True
        self.valid_prefix_end = prefix_end
        valid_offs = {off for off, _ in frames}
        # Newest intact index block first: it is the last committed
        # (or in-flight) view and its offsets are all behind it.
        for ioff in (off for off, t in reversed(frames) if t == INDEX):
            try:
                _, data = self.read_block_at(ioff)
                idx = json.loads(data)
                blocks = {
                    int(k): v
                    for k, v in idx["blocks"].items()
                    if v in valid_offs
                }
                root = idx.get("root", 0)
            except Exception:
                continue
            if root and root in blocks:
                self.root, self.blocks = root, blocks
                if self._root_resolves():
                    return
        # No usable index survived: data-block ids are append order
        # (write_block assigns sequentially; save_index appends the
        # index frame without consuming an id), so the map is implied
        # by the scan.  The newest partial-map whose chain resolves is
        # the best root — exactly the newest completed save phase.
        data_blocks = [(off, t) for off, t in frames if t != INDEX]
        self.blocks = {i + 1: off for i, (off, _t) in enumerate(data_blocks)}
        for bid in range(len(data_blocks), 0, -1):
            if data_blocks[bid - 1][1] != PARTIAL_MAP:
                continue
            self.root = bid
            if self._root_resolves():
                return
        raise IOError(
            f"{self.path}: no recoverable root in the valid block prefix "
            f"(0..{prefix_end})"
        )

    def _root_resolves(self) -> bool:
        """True when the candidate root decodes and every block ref in
        its top-level values points into the recovered block map — the
        recovered view must not hand out dangling references.  Membership
        is enough: every offset in ``self.blocks`` came from the CRC
        verified scan, so referenced blocks need not be re-decoded here
        (a multi-GB history stays lazy through recovery)."""
        try:
            out = self.root_value()
            if not isinstance(out, dict):
                return False
            return all(
                v["$block-ref"] in self.blocks
                for v in out.values()
                if is_block_ref(v)
            )
        except Exception:
            return False

    def read_block_at(self, offset: int, verify: bool = True) -> Tuple[int, bytes]:
        with open(self.path, "rb") as f:
            f.seek(offset)
            frame = f.read(FRAME_SIZE)
            if len(frame) < FRAME_SIZE:
                raise IOError(f"{self.path}: truncated frame at {offset}")
            frame_len, want_crc, type_ = struct.unpack("<QIH", frame)
            data = f.read(frame_len - FRAME_SIZE)
        if len(data) != frame_len - FRAME_SIZE:
            raise IOError(f"{self.path}: truncated block at {offset}")
        if verify:
            lib = native.lib()
            if lib is not None:
                got = lib.bf_check_block(self.path.encode(), offset, None)
                if got < 0:
                    raise IOError(f"{self.path}: CRC mismatch at {offset}")
            else:
                head = struct.pack("<QIH", frame_len, 0, type_)
                if zlib.crc32(head, zlib.crc32(data)) != want_crc:
                    raise IOError(f"{self.path}: CRC mismatch at {offset}")
        return type_, data

    def read_id(self, block_id: int) -> Tuple[int, bytes]:
        return self.read_block_at(self.blocks[block_id])

    def read_value(self, block_id: int) -> Any:
        """Decode a block to its logical value, resolving partial maps."""
        type_, data = self.read_id(block_id)
        if type_ == JSON_BLOCK:
            return json.loads(data)
        if type_ == PARTIAL_MAP:
            (rest_id,) = struct.unpack("<I", data[:4])
            head = json.loads(data[4:])
            if rest_id:
                rest = self.read_value(rest_id)
                return {**rest, **head}
            return head
        if type_ in (HISTORY, CHUNKED_HISTORY):
            return self.read_history(block_id)
        raise IOError(f"cannot decode block type {type_}")

    def _chunk_table(self, data: bytes):
        """Parse a CHUNKED_HISTORY head: [(chunk-id, op-count)…], and
        the offset where the packed section starts."""
        (n,) = struct.unpack("<I", data[:4])
        chunks = [
            struct.unpack("<II", data[4 + 8 * i : 12 + 8 * i])
            for i in range(n)
        ]
        return chunks, 4 + 8 * n

    def history_len(self, block_id: int) -> int:
        """Op count without decoding any chunk."""
        type_, data = self.read_id(block_id)
        if type_ == CHUNKED_HISTORY:
            chunks, _ = self._chunk_table(data)
            return sum(n for _cid, n in chunks)
        if type_ == HISTORY:
            (jsonl_len,) = struct.unpack("<I", data[:4])
            return data[4 : 4 + jsonl_len].count(b"\n") + (
                1 if jsonl_len else 0
            )
        raise IOError(f"block {block_id} is {type_}, not history")

    def iter_history(self, block_id: int):
        """Yield Ops lazily, one chunk in memory at a time — the
        incremental path for multi-GB histories (reference:
        store/format.clj's chunked history loading)."""
        from ..history import Op

        type_, data = self.read_id(block_id)
        if type_ == CHUNKED_HISTORY:
            chunks, _ = self._chunk_table(data)
            del data
            for cid, _n in chunks:
                ctype, cdata = self.read_id(cid)
                if ctype != HISTORY_CHUNK:
                    raise IOError(f"chunk {cid} has type {ctype}")
                for line in cdata.decode().splitlines():
                    if line:
                        yield Op.from_dict(json.loads(line))
        elif type_ == HISTORY:
            (jsonl_len,) = struct.unpack("<I", data[:4])
            for line in data[4 : 4 + jsonl_len].decode().splitlines():
                if line:
                    yield Op.from_dict(json.loads(line))
        else:
            raise IOError(f"block {block_id} is {type_}, not history")

    def read_history(self, block_id: int):
        from ..history import History

        return History(self.iter_history(block_id))

    def read_packed_history(self, block_id: int) -> dict:
        """The device-feed arrays without touching the JSONL section."""
        import numpy as np

        type_, data = self.read_id(block_id)
        if type_ == CHUNKED_HISTORY:
            _chunks, off = self._chunk_table(data)
            rest = data[off:]
        elif type_ == HISTORY:
            (jsonl_len,) = struct.unpack("<I", data[:4])
            rest = data[4 + jsonl_len :]
        else:
            raise IOError(f"block {block_id} is {type_}, not history")
        npz_len, tables_len = struct.unpack("<II", rest[:8])
        npz = np.load(io.BytesIO(rest[8 : 8 + npz_len]))
        tables = json.loads(rest[8 + npz_len : 8 + npz_len + tables_len])
        return {
            "arrays": {k: npz[k] for k in npz.files},
            "tables": tables,
        }

    def root_value(self) -> Any:
        return self.read_value(self.root)
