"""ctypes bindings for the C++ block-file writer (native/blockfile.cc),
with on-demand compilation and a graceful "not available" signal so the
pure-Python path (store.format) can take over.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "blockfile.cc")
_SO = os.path.join(_REPO_ROOT, "native", "libblockfile.so")


def _build() -> Optional[str]:
    if os.path.exists(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
        return _SO
    try:
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-o", _SO, _SRC],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return _SO
    except (OSError, subprocess.SubprocessError):
        return None


def lib() -> Optional[ctypes.CDLL]:
    """The loaded native library, building it on first use; None if the
    toolchain or sources are unavailable."""
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SRC):
            return None
        so = _build()
        if so is None:
            return None
        try:
            l = ctypes.CDLL(so)
        except OSError:
            return None
        l.bf_create.restype = ctypes.c_void_p
        l.bf_create.argtypes = [ctypes.c_char_p]
        l.bf_open_append.restype = ctypes.c_void_p
        l.bf_open_append.argtypes = [ctypes.c_char_p]
        l.bf_append_block.restype = ctypes.c_uint64
        l.bf_append_block.argtypes = [
            ctypes.c_void_p,
            ctypes.c_uint16,
            ctypes.c_char_p,
            ctypes.c_uint64,
        ]
        l.bf_set_index_offset.restype = ctypes.c_int
        l.bf_set_index_offset.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        l.bf_tell.restype = ctypes.c_uint64
        l.bf_tell.argtypes = [ctypes.c_void_p]
        l.bf_flush.restype = ctypes.c_int
        l.bf_flush.argtypes = [ctypes.c_void_p]
        l.bf_close.restype = None
        l.bf_close.argtypes = [ctypes.c_void_p]
        l.bf_check_block.restype = ctypes.c_int64
        l.bf_check_block.argtypes = [
            ctypes.c_char_p,
            ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint16),
        ]
        _lib = l
        return _lib


def available() -> bool:
    return lib() is not None
