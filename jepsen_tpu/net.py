"""Network fault primitives (reference: jepsen/src/jepsen/net.clj).

The Net protocol (:15-26): drop!/heal!/slow!/flaky!/fast!, plus the
grudge-bulk drop-all! (:29-44, with the iptables fast path :101-111).
A *grudge* maps each node to the set of nodes it should drop traffic
FROM.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, Optional

from . import control
from .control.core import RemoteError, lit
from .util import real_pmap

TC = "/sbin/tc"


def node_ip(node: Any) -> str:
    """Resolve a node's IP.  On real clusters this shells out to
    getent/host (reference: control/net.clj); nodes that already look
    like IPs (or dummy nodes) pass through."""
    s = str(node)
    if all(c.isdigit() or c == "." for c in s) and s.count(".") == 3:
        return s
    try:
        out = control.execute("getent", "ahostsv4", s, check=True)
        first = out.split()
        return first[0] if first else s
    except Exception:
        return s


class Net:
    def drop(self, test: dict, src: Any, dest: Any) -> None:
        raise NotImplementedError

    def heal(self, test: dict) -> None:
        raise NotImplementedError

    def slow(self, test: dict, opts: Optional[dict] = None) -> None:
        raise NotImplementedError

    def flaky(self, test: dict) -> None:
        raise NotImplementedError

    def fast(self, test: dict) -> None:
        raise NotImplementedError

    # PartitionAll fast path (reference: net/proto.clj + net.clj:101-111)
    def drop_all(self, test: dict, grudge: Dict[Any, Iterable[Any]]) -> None:
        pairs = [
            (src, dst) for dst, srcs in grudge.items() for src in srcs
        ]
        real_pmap(lambda p: self.drop(test, p[0], p[1]), pairs)


class NoopNet(Net):
    """(reference: net.clj:48-56)"""

    def drop(self, test, src, dest):
        pass

    def heal(self, test):
        pass

    def slow(self, test, opts=None):
        pass

    def flaky(self, test):
        pass

    def fast(self, test):
        pass

    def drop_all(self, test, grudge):
        pass


noop = NoopNet()


class IPTables(Net):
    """Default iptables implementation (reference: net.clj:58-111)."""

    def drop(self, test, src, dest):
        def thunk():
            with control.su():
                control.execute(
                    "iptables", "-A", "INPUT", "-s", node_ip(src), "-j",
                    "DROP", "-w",
                )

        control.on_many([dest], thunk)

    def heal(self, test):
        def thunk():
            with control.su():
                control.execute("iptables", "-F", "-w")
                control.execute("iptables", "-X", "-w")

        control.with_test_nodes(test, thunk)

    def slow(self, test, opts=None):
        opts = opts or {}
        mean = opts.get("mean", 50)
        variance = opts.get("variance", 10)
        distribution = opts.get("distribution", "normal")

        def thunk():
            with control.su():
                control.execute(
                    TC, "qdisc", "add", "dev", "eth0", "root", "netem",
                    "delay", f"{mean}ms", f"{variance}ms", "distribution",
                    distribution,
                )

        control.with_test_nodes(test, thunk)

    def flaky(self, test):
        def thunk():
            with control.su():
                control.execute(
                    TC, "qdisc", "add", "dev", "eth0", "root", "netem",
                    "loss", "20%", "75%",
                )

        control.with_test_nodes(test, thunk)

    def fast(self, test):
        def thunk():
            with control.su():
                try:
                    control.execute(TC, "qdisc", "del", "dev", "eth0", "root")
                except RemoteError as e:
                    if "RTNETLINK answers: No such file or directory" in str(e):
                        return
                    raise

        control.with_test_nodes(test, thunk)

    def drop_all(self, test, grudge):
        # one iptables rule per node with a comma-joined source list
        # (reference: net.clj:101-111 PartitionAll fast path)
        def snub(test_, node):
            srcs = list(grudge.get(node) or [])
            if not srcs:
                return
            with control.su():
                control.execute(
                    "iptables", "-A", "INPUT", "-s",
                    ",".join(node_ip(s) for s in srcs), "-j", "DROP", "-w",
                )

        control.on_nodes(test, list(grudge.keys()), snub)


iptables = IPTables()


class IPFilter(Net):
    """ipf-based variant for SmartOS/illumos (reference: net.clj:113-145)."""

    def drop(self, test, src, dest):
        def thunk():
            with control.su():
                control.execute(
                    lit(f"echo block in from {node_ip(src)} to any | ipf -f -")
                )

        control.on_many([dest], thunk)

    def heal(self, test):
        def thunk():
            with control.su():
                control.execute("ipf", "-Fa")

        control.with_test_nodes(test, thunk)

    slow = IPTables.slow
    flaky = IPTables.flaky

    def fast(self, test):
        def thunk():
            with control.su():
                control.execute(TC, "qdisc", "del", "dev", "eth0", "root")

        control.with_test_nodes(test, thunk)


ipfilter = IPFilter()


def drop_all(test: dict, grudge: Dict[Any, Iterable[Any]]) -> None:
    """Apply a grudge via the test's net.  (reference: net.clj:29-44)"""
    net = test.get("net", iptables)
    net.drop_all(test, grudge)


def heal(test: dict) -> None:
    net = test.get("net", iptables)
    net.heal(test)


class LoopbackProxyNet(Net):
    """Real connection-severing partitions on one host, no
    iptables/root: every (src, dst) node edge gets a localhost TCP
    forwarder; dropping the edge kills its live connections (clients
    see genuine resets, not polite errors) and refuses new ones until
    healed.  The loopback analogue of the iptables Net for integration
    tests and CI (reference behavior contract: net.clj:15-44 — drop!
    blocks src→dst traffic, heal! restores everything).

    Routes are registered up front with :meth:`add_route`; clients on
    node ``src`` talking to the service on node ``dst`` must connect to
    ``port(src, dst)``.
    """

    def __init__(self):
        import threading

        self._lock = threading.Lock()
        self._routes: Dict[tuple, "_Forwarder"] = {}

    def add_route(self, src: Any, dst: Any, target_host: str,
                  target_port: int) -> int:
        """Start a forwarder for the src→dst edge; returns its port."""
        fwd = _Forwarder(target_host, target_port)
        with self._lock:
            self._routes[(src, dst)] = fwd
        return fwd.port

    def port(self, src: Any, dst: Any) -> int:
        return self._routes[(src, dst)].port

    def close(self) -> None:
        with self._lock:
            for fwd in self._routes.values():
                fwd.close()

    def reset(self) -> None:
        """Close and forget every forwarder so add_route can wire the
        same Net instance afresh (a DB cycle tears down, then sets up
        again — the test map's net reference must stay valid across
        that)."""
        self.close()
        with self._lock:
            self._routes.clear()

    def drop(self, test, src, dest):
        fwd = self._routes.get((src, dest))
        if fwd is not None:
            fwd.block()

    def heal(self, test):
        for fwd in self._routes.values():
            fwd.unblock()

    def slow(self, test, opts=None):
        # mean is in MILLISECONDS, matching the tc-backed Net impls
        # (IPTables.slow default mean=50 → "50ms")
        delay_ms = float((opts or {}).get("mean", 50))
        for fwd in self._routes.values():
            fwd.delay = delay_ms / 1000.0

    def flaky(self, test):
        for fwd in self._routes.values():
            fwd.loss = 0.2

    def fast(self, test):
        for fwd in self._routes.values():
            fwd.delay = 0.0
            fwd.loss = 0.0


class _Forwarder:
    """One TCP forwarder: accept on a loopback port, pump bytes to the
    target; blocking kills live connections and refuses new ones."""

    def __init__(self, target_host: str, target_port: int):
        import socket
        import threading

        self.target = (target_host, target_port)
        self.blocked = False
        self.delay = 0.0
        self.loss = 0.0
        self._conns: list = []
        self._lock = threading.Lock()
        self._accept_done = threading.Event()
        self._listener = self._listen(0)
        self.port = self._listener.getsockname()[1]
        self._closed = False
        self._start_accepting()

    def _listen(self, port: int):
        import socket
        import time as _time

        # the previous accept thread closes its listener asynchronously
        # (see _accept_loop); tolerate a brief EADDRINUSE window when
        # rebinding the same port
        deadline = _time.monotonic() + 2.0
        while True:
            s = socket.socket()
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            try:
                s.bind(("127.0.0.1", port))
                s.listen(32)
                return s
            except OSError:
                s.close()
                if _time.monotonic() > deadline:
                    raise
                _time.sleep(0.01)

    def _start_accepting(self):
        import threading

        self._accept_done.clear()
        threading.Thread(target=self._accept_loop, daemon=True).start()

    def _accept_loop(self):
        import socket
        import threading

        listener = self._listener
        try:
            while not (self._closed or self.blocked):
                try:
                    client, _addr = listener.accept()
                except OSError:
                    break  # block()/close() shut the listener down
                if self.blocked or self._closed:
                    # woken by block()'s self-connect poke on platforms
                    # where shutdown() on a listener is ENOTCONN
                    # (BSD/macOS)
                    client.close()
                    break
                try:
                    upstream = socket.create_connection(
                        self.target, timeout=5
                    )
                except OSError:
                    client.close()
                    continue
                with self._lock:
                    self._conns.append((client, upstream))
                for a, b in ((client, upstream), (upstream, client)):
                    threading.Thread(
                        target=self._pump, args=(a, b), daemon=True
                    ).start()
        finally:
            # the accept thread owns the fd: closing it from another
            # thread while accept() blocks on it races in-process fd
            # reuse
            try:
                listener.close()
            except OSError:
                pass
            self._accept_done.set()

    def _pump(self, src, dst):
        import random as _random
        import time as _time

        try:
            while True:
                data = src.recv(65536)
                if not data:
                    break
                if self.blocked:
                    break
                if self.delay:
                    _time.sleep(self.delay)
                if self.loss and _random.random() < self.loss:
                    # the proxy terminates TCP, so silently dropping
                    # bytes would CORRUPT the stream (they were already
                    # ACKed to the sender) — flakiness at this layer
                    # means the connection dies, which clients see as a
                    # clean reset/indeterminate op
                    break
                dst.sendall(data)
        except OSError:
            pass
        finally:
            for s in (src, dst):
                try:
                    s.close()
                except OSError:
                    pass

    def block(self):
        import socket

        # lock-free fault flag: pump threads poll it per recv; the
        # store is atomic under the GIL and one extra forwarded chunk
        # is acceptable
        self.blocked = True  # jt: allow[concurrency-unguarded-shared] — lock-free fault flag (see above)
        # shut the listener down so NEW connection attempts are refused
        # outright (a definite, safe failure for clients) rather than
        # accepted-then-reset (which reads as an indeterminate cut).
        # shutdown — not close — wakes the accept thread, which then
        # closes the fd it owns.
        try:
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            # BSD/macOS: shutdown on a listener is ENOTCONN; poke the
            # accept loop awake with a throwaway connection instead
            try:
                socket.create_connection(
                    ("127.0.0.1", self.port), timeout=1
                ).close()
            except OSError:
                pass
        self._accept_done.wait(timeout=2)
        with self._lock:
            conns, self._conns = self._conns, []
        for a, b in conns:
            for s in (a, b):
                try:
                    s.close()  # live connections die mid-flight
                except OSError:
                    pass

    def unblock(self):
        # block/unblock/close all run on the nemesis control thread;
        # `blocked` is additionally polled lock-free by pump threads
        # (see block) and `_listener` is handed to the accept thread
        # only via _start_accepting, AFTER _accept_done ordered the
        # old accept loop's exit
        if not self.blocked or self._closed:
            self.blocked = False  # jt: allow[concurrency-unguarded-shared] — control-thread flag (see above)
            return
        self.blocked = False  # jt: allow[concurrency-unguarded-shared] — control-thread flag (see above)
        self._listener = self._listen(self.port)  # jt: allow[concurrency-unguarded-shared] — published via _start_accepting thread start
        self._start_accepting()

    def close(self):
        import socket

        # only the control thread reads `_closed` (unblock)
        self._closed = True  # jt: allow[concurrency-unguarded-shared] — control-thread flag, atomic store
        try:
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self.block()
        self.blocked = False  # jt: allow[concurrency-unguarded-shared] — control-thread flag (see unblock)
