"""Network fault primitives (reference: jepsen/src/jepsen/net.clj).

The Net protocol (:15-26): drop!/heal!/slow!/flaky!/fast!, plus the
grudge-bulk drop-all! (:29-44, with the iptables fast path :101-111).
A *grudge* maps each node to the set of nodes it should drop traffic
FROM.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, Optional

from . import control
from .control.core import RemoteError, lit
from .util import real_pmap

TC = "/sbin/tc"


def node_ip(node: Any) -> str:
    """Resolve a node's IP.  On real clusters this shells out to
    getent/host (reference: control/net.clj); nodes that already look
    like IPs (or dummy nodes) pass through."""
    s = str(node)
    if all(c.isdigit() or c == "." for c in s) and s.count(".") == 3:
        return s
    try:
        out = control.execute("getent", "ahostsv4", s, check=True)
        first = out.split()
        return first[0] if first else s
    except Exception:
        return s


class Net:
    def drop(self, test: dict, src: Any, dest: Any) -> None:
        raise NotImplementedError

    def heal(self, test: dict) -> None:
        raise NotImplementedError

    def slow(self, test: dict, opts: Optional[dict] = None) -> None:
        raise NotImplementedError

    def flaky(self, test: dict) -> None:
        raise NotImplementedError

    def fast(self, test: dict) -> None:
        raise NotImplementedError

    # PartitionAll fast path (reference: net/proto.clj + net.clj:101-111)
    def drop_all(self, test: dict, grudge: Dict[Any, Iterable[Any]]) -> None:
        pairs = [
            (src, dst) for dst, srcs in grudge.items() for src in srcs
        ]
        real_pmap(lambda p: self.drop(test, p[0], p[1]), pairs)


class NoopNet(Net):
    """(reference: net.clj:48-56)"""

    def drop(self, test, src, dest):
        pass

    def heal(self, test):
        pass

    def slow(self, test, opts=None):
        pass

    def flaky(self, test):
        pass

    def fast(self, test):
        pass

    def drop_all(self, test, grudge):
        pass


noop = NoopNet()


class IPTables(Net):
    """Default iptables implementation (reference: net.clj:58-111)."""

    def drop(self, test, src, dest):
        def thunk():
            with control.su():
                control.execute(
                    "iptables", "-A", "INPUT", "-s", node_ip(src), "-j",
                    "DROP", "-w",
                )

        control.on_many([dest], thunk)

    def heal(self, test):
        def thunk():
            with control.su():
                control.execute("iptables", "-F", "-w")
                control.execute("iptables", "-X", "-w")

        control.with_test_nodes(test, thunk)

    def slow(self, test, opts=None):
        opts = opts or {}
        mean = opts.get("mean", 50)
        variance = opts.get("variance", 10)
        distribution = opts.get("distribution", "normal")

        def thunk():
            with control.su():
                control.execute(
                    TC, "qdisc", "add", "dev", "eth0", "root", "netem",
                    "delay", f"{mean}ms", f"{variance}ms", "distribution",
                    distribution,
                )

        control.with_test_nodes(test, thunk)

    def flaky(self, test):
        def thunk():
            with control.su():
                control.execute(
                    TC, "qdisc", "add", "dev", "eth0", "root", "netem",
                    "loss", "20%", "75%",
                )

        control.with_test_nodes(test, thunk)

    def fast(self, test):
        def thunk():
            with control.su():
                try:
                    control.execute(TC, "qdisc", "del", "dev", "eth0", "root")
                except RemoteError as e:
                    if "RTNETLINK answers: No such file or directory" in str(e):
                        return
                    raise

        control.with_test_nodes(test, thunk)

    def drop_all(self, test, grudge):
        # one iptables rule per node with a comma-joined source list
        # (reference: net.clj:101-111 PartitionAll fast path)
        def snub(test_, node):
            srcs = list(grudge.get(node) or [])
            if not srcs:
                return
            with control.su():
                control.execute(
                    "iptables", "-A", "INPUT", "-s",
                    ",".join(node_ip(s) for s in srcs), "-j", "DROP", "-w",
                )

        control.on_nodes(test, list(grudge.keys()), snub)


iptables = IPTables()


class IPFilter(Net):
    """ipf-based variant for SmartOS/illumos (reference: net.clj:113-145)."""

    def drop(self, test, src, dest):
        def thunk():
            with control.su():
                control.execute(
                    lit(f"echo block in from {node_ip(src)} to any | ipf -f -")
                )

        control.on_many([dest], thunk)

    def heal(self, test):
        def thunk():
            with control.su():
                control.execute("ipf", "-Fa")

        control.with_test_nodes(test, thunk)

    slow = IPTables.slow
    flaky = IPTables.flaky

    def fast(self, test):
        def thunk():
            with control.su():
                control.execute(TC, "qdisc", "del", "dev", "eth0", "root")

        control.with_test_nodes(test, thunk)


ipfilter = IPFilter()


def drop_all(test: dict, grudge: Dict[Any, Iterable[Any]]) -> None:
    """Apply a grudge via the test's net.  (reference: net.clj:29-44)"""
    net = test.get("net", iptables)
    net.drop_all(test, grudge)


def heal(test: dict) -> None:
    net = test.get("net", iptables)
    net.heal(test)
