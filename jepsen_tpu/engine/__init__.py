"""Pipelined checker engine: the one production path from histories to
verdicts.

``wgl.check_batch`` / ``wgl.analysis`` (and everything above them —
``checker.linearizable``, ``independent.batched_linearizable``) route
through :mod:`jepsen_tpu.engine.pipeline`, which overlaps the three
stages the serial path used to run back-to-back:

1. **host encode** — each history encodes (ops/encode.py) and lands in
   a per-padded-(E, C)-shape bucket, so short histories stop paying the
   longest history's padding;
2. **device dispatch** — bucket chunks dispatch asynchronously through
   a bounded :class:`~jepsen_tpu.engine.pipeline.DispatchWindow` (encode
   chunk *k+1* while chunk *k* computes; sync only when the window
   fills);
3. **oracle fallback** — unencodable/overflowed histories run
   ``checker.linear`` searches on a worker pool *concurrently* with
   device work instead of after it.

Verdicts are independent of the window size and bucketing — window=1
is exactly the historical serial dispatch-sync-dispatch path (pinned
by ``tests/test_engine.py`` and ``make pipeline-smoke``).  Pipeline
occupancy, bubble time, in-flight depth, and bucket counts report
through the ``obs`` metrics registry (doc/observability.md).

The engine is split into a pure per-run **planning** layer
(:mod:`jepsen_tpu.engine.planning`: ``RunContext``, ``Planner``) and a
device-owning **execution** layer (:mod:`jepsen_tpu.engine.execution`:
``DispatchWindow``, ``Executor``); :mod:`~jepsen_tpu.engine.pipeline`
composes them per run, while the resident checker service
(:mod:`jepsen_tpu.serve`) shares one executor across concurrent runs.
Ahead of planning, the P-compositionality front-end
(:mod:`jepsen_tpu.engine.decompose`) splits partitionable models'
histories into per-partition sub-histories and ANDs the sub-verdicts
at settle — wide-keyspace workloads check as thousands of tiny dense
rows instead of one oracle-bound search.
"""

from .decompose import (  # noqa: F401
    DecomposedRun,
    SubmodelCache,
    merge_partition_results,
    split_history,
)
from .execution import (  # noqa: F401
    DEFAULT_WINDOW,
    DispatchWindow,
    Executor,
    default_window,
)
from .pipeline import run  # noqa: F401
from .planning import (  # noqa: F401
    DEFAULT_FLUSH_ROWS,
    Planner,
    PlannedBucket,
    RunContext,
    default_bucketed,
    estimated_cost,
    merge_buckets,
)
