"""The device-owning **execution** half of the checker engine.

Everything here touches shared, stateful resources: the bounded
:class:`DispatchWindow` of in-flight device dispatches, the compiled-
kernel cache (via ``wgl``'s claim helpers), the escalation ladder, and
the hand-off to the CPU-oracle worker pool.  The pure per-run half —
encode, bucketing, kernel planning — lives in
:mod:`jepsen_tpu.engine.planning`.

An :class:`Executor` is the unit of device ownership.  The in-process
pipeline (:func:`jepsen_tpu.engine.pipeline.run`) creates a private
one per run; the checker service daemon (:mod:`jepsen_tpu.serve`)
keeps ONE resident executor alive across runs, feeding it planned
buckets whose rows come from many concurrent client contexts — the
jit cache and the window stay warm, and same-shape rows from
different runs ride the same dispatch.

Both the window and the executor are **owner-thread confined**: all
``submit``/``drain`` calls must come from the thread that created
them (runtime-enforced by :meth:`DispatchWindow._check_owner`).  The
oracle worker pool interacts with execution only through Futures held
by each run's :class:`~jepsen_tpu.engine.planning.RunContext`, never
by driving the window.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .. import obs
from ..obs import drift as obs_drift
from ..obs import journal as obs_journal


def row_bucket_target(n: int) -> int:
    """Row count → its stable dispatch shape: the next power of two,
    floored at :func:`row_bucket_floor` (the calibration-aware floor;
    :data:`ROW_BUCKET` untuned)."""
    target = row_bucket_floor()
    while target < n:
        target *= 2
    return target


def _pow2_at_least(x: int) -> int:
    return 1 << max(0, int(x) - 1).bit_length()


def shard_row_target(n: int, n_shards: int) -> int:
    """Row count → its stable dispatch shape on an ``n_shards``-device
    mesh: the PER-SHARD row count rounds to its power-of-two bucket,
    floored so the GLOBAL shape never drops below
    :func:`row_bucket_floor` (the same floor the single-device path
    uses — a tiny batch pays
    the same ~64 neutral rows it always did, spread across the slice,
    not 64 per chip).  Keying the bucket on per-shard rows is what
    keeps jit executables stable as traffic varies: under
    ``shard_map`` the traced shape is the per-device shard, so 500
    rows and 400 rows on 8 devices both trace the 64-row-per-chip
    kernel.  ``n_shards=1`` degenerates to :func:`row_bucket_target`
    exactly, and the result is always divisible by ``n_shards``."""
    if n_shards <= 1:
        return row_bucket_target(n)
    per_floor = _pow2_at_least(max(1, -(-row_bucket_floor() // n_shards)))
    per = max(per_floor, _pow2_at_least(max(1, -(-n // n_shards))))
    return n_shards * per

#: default bound on concurrently in-flight device dispatches; 1 = the
#: strictly serial dispatch-sync-dispatch path
DEFAULT_WINDOW = 4

#: minimum dispatch row bucket: row counts round up to the next power
#: of two ≥ this (never past the chunk cap) with neutral all-padding
#: rows, so jit executables are keyed by STABLE shapes — two runs of
#: ~500 subhistories both dispatch at 512 rows and hit one compiled fn
#: instead of retracing at 506 vs 493.  Geometric buckets bound the
#: executable count at O(log max_dispatch) per (E, C) shape while
#: wasting < 2× rows of (cheap, neutral) padding — the trade every
#: serving stack makes, and what keeps the resident checker service's
#: warm path warm across real varying-size traffic.  In-process
#: one-shot runs pay at most one compile either way.
ROW_BUCKET = 64


def row_bucket_floor() -> int:
    """The resolved minimum dispatch row bucket:
    ``JEPSEN_TPU_ENGINE_ROW_BUCKET`` > active calibration
    (doc/tuning.md) > :data:`ROW_BUCKET`.  Always a power of two — a
    non-pow2 override rounds up so the geometric bucket ladder stays
    intact."""
    from ..tune import artifact as _cal

    return _cal.resolve_knob(
        "JEPSEN_TPU_ENGINE_ROW_BUCKET",
        lambda v: _pow2_at_least(max(1, int(v))),
        lambda cal: cal.row_bucket(),
        ROW_BUCKET,
    )


def default_window() -> int:
    """Resolved in-flight window: ``JEPSEN_TPU_ENGINE_WINDOW`` >
    active calibration (doc/tuning.md) > :data:`DEFAULT_WINDOW`."""
    from ..tune import artifact as _cal

    return _cal.resolve_knob(
        "JEPSEN_TPU_ENGINE_WINDOW",
        lambda v: max(1, int(v)),
        lambda cal: max(1, cal.window()),
        DEFAULT_WINDOW,
    )


def _materialize(out):
    """Force device work to the host (the sync point)."""
    if isinstance(out, (tuple, list)):
        return tuple(np.asarray(x) for x in out)
    return np.asarray(out)


class DispatchWindow:
    """A bounded window of in-flight device dispatches.

    ``submit(key, thunk)`` first retires (syncs) the oldest entries
    until fewer than ``window`` are in flight, then calls ``thunk`` —
    which must *dispatch* device work and return the lazy device
    arrays — and enqueues its result.  ``drain()`` retires everything
    left.  Retirement materializes the arrays via ``np.asarray`` and
    hands ``(key, materialized, t_dispatch)`` to ``on_retire`` (also
    returned from ``submit``/``drain`` for callers that prefer pull).

    window=1 is the serial contract: every dispatch fully settles
    before the next one is issued, reproducing the historical
    dispatch-sync-dispatch path exactly.  The window is shared
    machinery — ``check_batch`` dispatches bucket chunks through it,
    ``ops.cycles`` its Elle screen buckets, and ``bench.py`` its
    pipelined measurement, so the benchmark times the code users run.

    A window is **owner-thread confined** (``# jt: guarded-by
    (owner-thread)`` on its state, checked by the lock-discipline lint
    pass): the in-flight deque and bubble/peak bookkeeping are
    deliberately lock-free, so ``submit``/``drain`` refuse calls from
    any thread but the creating one rather than corrupt them silently
    — the oracle worker pool must interact with the engine only
    through Futures (see the pipeline's stage-3 drain), never by
    driving the window.

    Time spent blocked in retirement is recorded as
    ``jepsen_engine_bubble_seconds``; the post-submit depth feeds the
    ``jepsen_engine_inflight_depth`` high-water gauge.
    """

    def __init__(
        self,
        window: Optional[int] = None,
        on_retire: Optional[Callable[[Any, Any, float], None]] = None,
    ):
        self.window = max(
            1, int(window) if window is not None else default_window()
        )
        self.on_retire = on_retire
        #: (key, lazy-out, t_dispatch, attrs)
        self._inflight: deque = deque()  # jt: guarded-by(owner-thread)
        self.peak_depth = 0  # jt: guarded-by(owner-thread)
        self.bubble_s = 0.0  # jt: guarded-by(owner-thread)
        self.submitted = 0  # jt: guarded-by(owner-thread)
        self._owner = threading.get_ident()

    def _check_owner(self) -> None:
        if threading.get_ident() != self._owner:
            raise RuntimeError(
                "DispatchWindow is owner-thread confined: submit/drain "
                "must run on the creating thread (oracle workers hand "
                "results back through Futures, never drive the window)"
            )

    @property
    def depth(self) -> int:
        return len(self._inflight)

    def submit(self, key, thunk, attrs: Optional[dict] = None) -> list:
        """Dispatch one unit of device work; returns entries retired to
        make room (empty until the window fills)."""
        self._check_owner()
        retired = []
        while len(self._inflight) >= self.window:
            retired.append(self._retire())
        # stamp BEFORE the thunk: jit trace + XLA compile run
        # synchronously inside the first dispatch call, and the
        # compile-vs-execute histograms must keep containing them
        t_dispatch = time.perf_counter()
        out = thunk()
        self._inflight.append((key, out, t_dispatch, attrs))
        self.submitted += 1
        depth = len(self._inflight)
        if depth > self.peak_depth:
            self.peak_depth = depth
        obs.gauge_max("jepsen_engine_inflight_depth", depth)
        return retired

    def _retire(self):
        key, out, t_dispatch, attrs = self._inflight.popleft()
        t0 = time.perf_counter()
        if obs.enabled():
            with obs.span(
                "engine/dispatch", cat="engine", **(attrs or {})
            ):
                mat = _materialize(out)
        else:
            mat = _materialize(out)
        wait = time.perf_counter() - t0
        self.bubble_s += wait
        obs.observe("jepsen_engine_bubble_seconds", wait)
        if self.on_retire is not None:
            self.on_retire(key, mat, t_dispatch)
        return key, mat, t_dispatch

    def drain(self) -> list:
        """Retire every in-flight dispatch, oldest first."""
        self._check_owner()
        out = []
        while self._inflight:
            out.append(self._retire())
        return out

    def abandon(self) -> int:
        """Drop every in-flight entry WITHOUT retiring (no host sync,
        no ``on_retire``): the recovery path after a dispatch raised —
        syncing the survivors could re-raise the same device failure.
        The dropped device computations finish (or die) on their own
        and get collected.  Returns the number dropped."""
        self._check_owner()
        n = len(self._inflight)
        self._inflight.clear()
        return n


class Executor:
    """Device-owning execution of planned buckets.

    ``submit(planned_bucket)`` splits the bucket into footprint-safe
    chunks and dispatches them through the executor's bounded
    :class:`DispatchWindow`; ``drain()`` retires everything in flight
    and runs the deferred escalation ladder.  Row verdicts route
    through each row's ``(ctx, idx)`` token back to its
    :class:`~jepsen_tpu.engine.planning.RunContext` — rows from many
    concurrent runs can share one dispatch (the service's cross-run
    coalescing) without any result cross-talk.

    Safety under pipelining (inherited verbatim from the pipeline it
    was factored out of): the frontier footprint budget
    (``fn.safe_dispatch`` ← ``FRONTIER_DISPATCH_BUDGET``) is
    crash-calibrated for ONE in-flight dispatch **on one chip**, so
    with a window of W each frontier chunk takes 1/W of the safe rows
    — total in-flight HBM stays at the calibrated bound no matter how
    many client runs coalesce.  Shapes whose cap floors out below W
    dispatch strictly serially at the full single-dispatch cap.  Dense
    chunks keep the full cap: the kernel is overflow-free with a small
    per-row footprint, and multi-in-flight dense dispatch IS the
    measured flagship bench pattern.  Escalation reruns dispatch only
    while the window is empty (see :meth:`drain`).

    **Slice-native dispatch** (doc/checker-engines.md): with a mesh of
    n devices (an explicit ``mesh=`` or, when none is passed, the
    auto-resolved :func:`~jepsen_tpu.parallel.mesh.engine_default_mesh`
    — every attached device whenever more than one is present), every
    budget above is PER CHIP: chunk caps scale to ``n × per-chip
    safe_dispatch`` because ``shard_map`` splits each dispatch's rows
    evenly across the mesh, so no single chip ever holds more
    concurrent rows than the crash-calibrated single-chip cap — never
    a shared global pool that one chip could drain.  Chunk row counts
    pad to a device multiple (via the per-shard power-of-two bucket,
    :func:`shard_row_target`) with neutral all-padding rows sliced
    back at settle, so verdicts are untouched and non-divisible
    batches never retrace.  Per-chip in-flight rows are tracked in
    :attr:`chip_row_accounting` — the hook the budget tests assert on.

    Owner-thread confined like its window: create it on the thread
    that will drive it (the service daemon builds its resident
    executor ON the device thread, never on a request handler).
    """

    def __init__(
        self,
        window: Optional[int] = None,
        *,
        mesh=None,
        escalation=None,
        sufficient_rung: bool = True,
        max_dispatch: Optional[int] = None,
    ):
        from ..ops import wgl

        if mesh is None:
            from ..parallel import mesh as mesh_mod

            mesh = mesh_mod.engine_default_mesh()
        self.mesh = mesh
        self.escalation = (
            wgl.ESCALATION_FACTORS if escalation is None else escalation
        )
        self.sufficient_rung = sufficient_rung
        self.max_dispatch = (
            wgl.DEFAULT_MAX_DISPATCH if max_dispatch is None else max_dispatch
        )
        self._win = DispatchWindow(window, on_retire=self._settle_chunk)
        #: chunk_id -> {plan, arrays, rows, n, phase}
        self._chunks: Dict[int, dict] = {}  # jt: guarded-by(owner-thread)
        self._next_chunk = 0  # jt: guarded-by(owner-thread)
        #: chunks whose base pass overflowed, parked until the window
        #: drains: escalation reruns dispatch at LARGER capacities, and
        #: stacking one on top of `window` in-flight base dispatches
        #: would hold more concurrent footprint than the
        #: crash-calibrated per-dispatch budget was measured for.
        #: Deferring also matches the serial path's order (escalate
        #: after the base pass).  Overflow is the rare path; the
        #: common all-resolved chunk settles immediately.
        self._pending_escalations: List[tuple] = []  # jt: guarded-by(owner-thread)
        #: cumulative dispatch phases — the service's warm-hit
        #: accounting reads (and diffs) these across request batches
        self.phase_counts = {"compile": 0, "execute": 0}
        #: in-flight PER-CHIP rows and their peaks, keyed by
        #: (kernel, E, C, frontier, per-chip cap) — the accounting
        #: hook the per-chip budget acceptance tests assert on: for
        #: every frontier shape, peak ≤ its single-chip cap at any
        #: window depth (dense is allowed cap × window by design)
        self._chip_rows_inflight: Dict[int, int] = {}  # jt: guarded-by(owner-thread)
        self.chip_row_accounting: Dict[int, dict] = {}  # jt: guarded-by(owner-thread)
        #: per-device live/dispatched row totals (device occupancy)
        self._dev_rows_live: List[int] = [0] * self.n_devices
        self._dev_rows_total: List[int] = [0] * self.n_devices
        #: extra per-dispatch journal fields the caller owns (the serve
        #: daemon sets coalesced-run count + trace ids per group; the
        #: device thread is the only mutator, so no guard)
        self.journal_context: Dict[str, Any] = {}
        #: optional ``(plan, arrays, disp_shape)`` callback fired on
        #: every COLD dispatch (first claim of ``(fn, shape)``) — the
        #: serve daemon's AOT executable cache records the compile here
        #: so a restarted daemon can pre-warm the shape.  Runs on the
        #: owner thread inside the dispatch path; exceptions are
        #: swallowed (cache bookkeeping must never fail a dispatch).
        self.on_cold_compile: Optional[Callable[[Any, Any, Any], None]] = None

    # -- stats the pipeline's telemetry reads -----------------------------

    @property
    def n_devices(self) -> int:
        """Devices the engine shards each dispatch across (1 = no mesh)."""
        return 1 if self.mesh is None else int(self.mesh.devices.size)

    @property
    def window_size(self) -> int:
        return self._win.window

    @property
    def submitted(self) -> int:
        return self._win.submitted

    @property
    def peak_depth(self) -> int:
        return self._win.peak_depth

    @property
    def bubble_s(self) -> float:
        return self._win.bubble_s

    # -- settle path (runs inside window retirement, owner thread) -------

    def _settle_chunk(self, chunk_id, mat, t_dispatch):
        # on_retire runs synchronously inside the owner-checked
        # submit/drain (DispatchWindow._retire), never on a foreign
        # thread, so owner-thread state stays confined
        ch = self._chunks.pop(chunk_id)  # jt: allow[lock-thread-confined] — synchronous on_retire, owner thread
        plan = ch["plan"]
        n_live = ch["n"]
        fnk = ch["acct_key"]
        left = self._chip_rows_inflight.get(fnk, 0) - ch["chip_rows"]  # jt: allow[lock-thread-confined] — synchronous on_retire, owner thread
        self._chip_rows_inflight[fnk] = max(0, left)  # jt: allow[lock-thread-confined] — synchronous on_retire, owner thread
        elapsed = time.perf_counter() - t_dispatch
        if obs.enabled():
            # dispatch-to-materialized latency, split compile (first
            # dispatch of this fn at this shape: trace + XLA compile +
            # execute) vs execute (cache-hit) exactly as the serial
            # path recorded it — under pipelining these overlap, so
            # their sum can exceed wall clock by design
            obs.observe(
                f"jepsen_kernel_{ch['phase']}_seconds",
                elapsed,
                engine=plan.kernel,
            )
        if obs_journal.active() is not None:
            self._journal_dispatch(plan, ch, elapsed)
        settle = getattr(plan, "settle_rows", None)
        if settle is not None:
            # self-settling plan (the Elle cycle screens): the plan
            # owns its output contract — no escalation ladder, no
            # ok/failed_at/overflow unpack; it slices live rows itself
            settle(ch["rows"], mat, n_live)
            return
        # np.array (not asarray): jax outputs are read-only views and
        # the escalation pass writes back into these
        ok, failed_at, overflow = (np.array(x)[:n_live] for x in mat)
        if overflow.any():
            self._pending_escalations.append(  # jt: allow[lock-thread-confined] — synchronous on_retire, owner thread
                (plan, ch["arrays"], ch["rows"], ok, failed_at, overflow)
            )
        else:
            self._assign_rows(plan, ch["rows"], ok, failed_at, overflow)

    def _journal_dispatch(self, plan, ch: dict, elapsed: float) -> None:
        """One pinned-schema journal row per settled dispatch
        (obs.journal): the durable per-dispatch telemetry stream behind
        the learned cost model and on-TPU bench windows.  Best-effort —
        journal failures never fail a dispatch (emit() swallows them)."""
        from ..ops import dense
        from ..tune import artifact as _cal

        cal = _cal.active()
        compile_hit = ch["phase"] == "compile"
        ctx = self.journal_context
        row = obs_journal.emit(
            kernel=str(plan.kernel),
            E=int(getattr(plan, "E", 0) or 0),
            C=int(getattr(plan, "C", 0) or 0),
            F=int(getattr(plan, "frontier", 0) or 0),
            rows=int(ch["n"]),
            n_devices=int(self.n_devices),
            mesh_shape=(list(self.mesh.devices.shape)
                        if self.mesh is not None else [1]),
            window=int(self.window_size),
            compile_s=round(elapsed, 6) if compile_hit else 0.0,
            execute_s=0.0 if compile_hit else round(elapsed, 6),
            coalesced=int(ctx.get("coalesced", 1)),
            cache="miss" if compile_hit else "hit",
            closure_mode=str(getattr(plan, "closure_mode", "") or ""),
            union=(dense._union_mode() if plan.kernel == "dense" else ""),
            calibration=(cal.calibration_id if cal is not None else ""),
            trace_id=str(ctx.get("trace_id", "") or ""),
        )
        if row is not None:
            # drift sentinel rides the journal stream: score the
            # settled row's measured cost against the model's estimate
            # (obs.drift — observation only, never a dispatch decision)
            sentinel = obs_drift.active()
            if sentinel is not None:
                sentinel.observe_row(row)

    def _settle_rows(self, plan, arrays, rows, ok, failed_at, overflow):
        """Escalate a chunk's overflows on-device, then assign verdicts
        (still-overflowed rows join each row's oracle pool)."""
        from ..ops import wgl

        wgl.escalate_overflows(
            plan, arrays, ok, failed_at, overflow,
            mesh=self.mesh, escalation=self.escalation,
            sufficient_rung=self.sufficient_rung,
            max_dispatch=self.max_dispatch,
        )
        self._assign_rows(plan, rows, ok, failed_at, overflow)

    def _assign_rows(self, plan, rows, ok, failed_at, overflow):
        unresolved = "routed" if plan.kernel == "oracle" else "overflow"
        for row, (ctx, hist_idx) in enumerate(rows):
            if overflow[row]:
                # still overflowed after escalation: CPU oracle decides
                ctx.route_oracle(
                    hist_idx, plan.overflow_engine(), unresolved
                )
            elif ok[row]:
                ctx.assign(hist_idx, {
                    "valid?": True,
                    "engine": "tpu",
                    "kernel": plan.kernel,
                })
            else:
                ctx.assign(hist_idx, {
                    "valid?": False,
                    "engine": "tpu",
                    "kernel": plan.kernel,
                    "failed-event": int(failed_at[row]),
                })

    # -- dispatch path ----------------------------------------------------

    def _dispatch_chunk(self, plan, arrays, rows):
        """Queue one footprint-safe chunk on the device (async);
        ``arrays`` is already padded to the stable dispatch shape (a
        device multiple under a mesh)."""
        from ..ops import wgl

        chunk_id = self._next_chunk
        self._next_chunk += 1
        n_dev = self.n_devices
        B_pad = arrays[0].shape[0]
        n_live = len(rows)
        # under a mesh the executable is the shard_map wrapper: jit
        # traces the PER-SHARD shape, so the compile/execute phase
        # split keys on (fn, per-shard rows, mesh width) — a
        # single-device claim at the same global rows is a different
        # executable and must not mask a mesh compile (or vice versa)
        disp_shape = B_pad if n_dev == 1 else (B_pad // n_dev, n_dev)
        first = wgl._claim_shape(plan.fn, disp_shape)
        phase = "compile" if first else "execute"
        self.phase_counts[phase] += 1
        if first and self.on_cold_compile is not None:
            try:
                self.on_cold_compile(plan, arrays, disp_shape)
            except Exception:  # noqa: BLE001 — cache bookkeeping only
                pass
        # per-chip budget accounting: shard_map splits the chunk's rows
        # evenly, so each chip holds B_pad/n of them while the dispatch
        # is in flight.  Keyed on the plan's shape facts INCLUDING the
        # effective cap — not id(fn): an lru-evicted fn's id can be
        # reused by a new compile (corrupting a resident daemon's
        # accounting), and the daemon re-points max_dispatch per
        # request group, so the same kernel at a different cap must be
        # a different ledger entry, never a stale-cap false breach.
        chip_rows = -(-B_pad // n_dev)
        fnk = (plan.kernel, plan.E, plan.C, plan.frontier, plan.disp)
        acct = self.chip_row_accounting.setdefault(
            fnk, {"kernel": plan.kernel, "peak_chip_rows": 0,
                  "chip_cap": plan.disp},
        )
        # shard padding + device balance: pads sit at the tail, so the
        # last shards absorb them — the occupancy gauge makes chronic
        # imbalance (pad-heavy tails on every dispatch) visible
        if obs.enabled():
            obs.count(
                "jepsen_kernel_dispatches_total", 1,
                engine=plan.kernel, phase=phase,
            )
            if B_pad > n_live:
                obs.count(
                    "jepsen_engine_shard_pad_rows_total", B_pad - n_live,
                )
        shard = B_pad // n_dev
        for d in range(n_dev):
            self._dev_rows_total[d] += shard
            self._dev_rows_live[d] += min(max(n_live - d * shard, 0), shard)
        self._chunks[chunk_id] = {
            "plan": plan, "arrays": arrays, "rows": rows,
            "n": n_live, "phase": phase, "chip_rows": chip_rows,
            "acct_key": fnk,
        }

        # plans may carry their own dispatch lowering (the Elle screen
        # plans shard a single relation-matrix input; history plans
        # keep the 6-array sharded_check path)
        run_rows = getattr(plan, "run_rows", None)

        def thunk():
            # the in-flight increment lives INSIDE the thunk: submit
            # retires older entries (decrementing them via settle)
            # BEFORE dispatching, so counting earlier would overstate
            # the peak by one retired chunk.  Runs synchronously on
            # the owner thread, like everything the window calls.
            cur = self._chip_rows_inflight.get(fnk, 0) + chip_rows
            self._chip_rows_inflight[fnk] = cur
            if cur > acct["peak_chip_rows"]:
                acct["peak_chip_rows"] = cur
            if run_rows is not None:
                return run_rows(self.mesh, arrays)
            return wgl._run_rows(plan.fn, self.mesh, arrays)

        self._win.submit(
            chunk_id,
            thunk,
            attrs={"engine": plan.kernel, "rows": n_live,
                   "phase": phase},
        )

    def submit(self, pb) -> None:
        """Dispatch one planned bucket in footprint-safe chunks through
        the window (or settle it inline when no kernel can run)."""
        from ..ops import wgl

        plan, arrays, rows = pb.plan, pb.arrays, pb.rows
        B = arrays[0].shape[0]
        if plan.fn is None or plan.disp == 0:
            # no dispatchable kernel (oracle-routed shape, a dense-only
            # spec outside its envelope, or even one row would crash
            # the worker): every escalation rung is equally
            # undispatchable (caps shrink with capacity), so settling
            # INLINE is dispatch-free — and it hands the bucket's rows
            # to the oracle pool NOW, overlapping the remaining device
            # work instead of waiting for the window to drain
            ok = np.zeros((B,), bool)
            failed_at = np.zeros((B,), np.int32)
            overflow = np.ones((B,), bool)
            self._settle_rows(plan, arrays, rows, ok, failed_at, overflow)
            return
        # the frontier footprint budget (fn.safe_dispatch ←
        # FRONTIER_DISPATCH_BUDGET) is crash-calibrated for ONE
        # in-flight dispatch ON ONE CHIP; a window of W holds W
        # dispatches' HBM concurrently, so each frontier chunk gets
        # 1/W of the safe rows — total in-flight stays at the
        # calibrated bound.  When even that floors out (disp < W:
        # per-row footprint near the whole budget), the bucket
        # dispatches strictly serially at the full single-dispatch cap
        # instead — W one-row dispatches in flight would still
        # overshoot the bound.  Dense chunks keep the full cap: the
        # kernel is overflow-free with a small per-row footprint, and
        # multi-in-flight dense dispatch IS the measured flagship
        # bench pattern (B=16384 × window, on-chip).
        #
        # On a mesh every cap is PER CHIP: shard_map splits a chunk's
        # rows evenly across n devices, so the global chunk cap is
        # n × the per-chip cap — each chip holds exactly the rows the
        # single-chip calibration allows, never a share of a global
        # pool another chip could have drained.
        n_dev = self.n_devices
        # plans that carry their own arrays (the Elle screens' single
        # relation matrix) declare their own neutral pad fills; the
        # history kernels keep the shared 6-array convention
        pad_fills = getattr(plan, "pad_fills", wgl._PAD_FILLS)
        per_chip = plan.disp
        serialize = False
        if plan.kernel != "dense" and self._win.window > 1:
            if per_chip >= self._win.window:
                per_chip = per_chip // self._win.window
            else:
                serialize = True
        chunk_cap = per_chip * n_dev
        from ..parallel import mesh as mesh_mod

        if B <= chunk_cap:
            # stable-shape dispatch: round the PER-SHARD row count up
            # to its power-of-two bucket (shard_row_target; capped at
            # the footprint-safe chunk cap, itself a device multiple)
            # with neutral all-padding rows — settle slices the
            # outputs back to the live rows, so verdicts are untouched
            # while repeat traffic reuses one executable per bucket
            # and non-divisible batches shard cleanly
            target = min(chunk_cap, shard_row_target(B, n_dev))
            if target > B:
                arrays = tuple(
                    mesh_mod.pad_to_multiple(np.asarray(a), target, fill)
                    for a, fill in zip(arrays, pad_fills)
                )
            if serialize:
                self._win.drain()
            self._dispatch_chunk(plan, arrays, rows)
            if serialize:
                self._win.drain()
            return

        for lo in range(0, B, chunk_cap):
            hi = min(lo + chunk_cap, B)
            # every chunk (including the tail, padded with neutral
            # all-padding rows) dispatches at the same cap-row shape:
            # one executable, never a per-tail-size compile — and the
            # cap is a device multiple, so every chunk shards evenly
            chunk = tuple(
                mesh_mod.pad_to_multiple(
                    np.asarray(a[lo:hi]), chunk_cap, fill
                )
                for a, fill in zip(arrays, pad_fills)
            )
            if serialize:
                self._win.drain()
            self._dispatch_chunk(plan, chunk, rows[lo:hi])
        if serialize:
            self._win.drain()

    def reset(self) -> int:
        """Discard all transient dispatch state — in-flight window
        entries (unsynced, see :meth:`DispatchWindow.abandon`), the
        chunk map, parked escalations — WITHOUT assigning any verdicts.
        The resident service calls this when a batch raised: reusing
        the executor with a poisoned window would retire the failed
        batch's dispatches into the NEXT batch (re-raising its failure
        against innocent requests) and re-dispatch its parked
        escalation arrays into dead contexts.  Returns the number of
        abandoned dispatches."""
        n = self._win.abandon()
        self._chunks.clear()
        self._pending_escalations = []
        self._chip_rows_inflight.clear()
        return n

    def drain(self) -> None:
        """Retire every in-flight dispatch, then run the deferred
        escalation ladder with the window empty — exactly one
        in-flight dispatch, the regime the footprint budget was
        calibrated in (and the serial path's order).  Parked chunks
        merge per plan first (live rows only — tail chunks carry
        neutral padding rows that must not interleave), so a bucket
        pays ONE padded rerun per escalation rung like the serial
        batch-wide pass did, not one ladder per chunk."""
        self._win.drain()
        if obs.enabled() and self.mesh is not None:
            # per-device occupancy: the live (non-padding) share of the
            # rows each chip was handed across this executor's
            # dispatches.  Pads sit at the shard tail, so a chronically
            # pad-heavy last device reads as low occupancy here — the
            # shard-balance diagnostic for non-divisible traffic.
            for d, total in enumerate(self._dev_rows_total):
                if total:
                    obs.gauge_set(
                        "jepsen_engine_device_occupancy_ratio",
                        self._dev_rows_live[d] / total,
                        device=str(d),
                    )
        pending = self._pending_escalations
        self._pending_escalations = []
        merged: Dict[int, list] = {}
        merged_order: List[int] = []
        for item in pending:
            pid = id(item[0])
            if pid not in merged:
                merged[pid] = []
                merged_order.append(pid)
            merged[pid].append(item)
        for pid in merged_order:
            group = merged[pid]
            if len(group) == 1:
                self._settle_rows(*group[0])
                continue
            plan = group[0][0]
            arrays = tuple(
                np.concatenate(
                    [np.asarray(g[1][i][: len(g[2])]) for g in group]
                )
                for i in range(6)
            )
            rows = [r for g in group for r in g[2]]
            self._settle_rows(
                plan, arrays, rows,
                np.concatenate([g[3] for g in group]),
                np.concatenate([g[4] for g in group]),
                np.concatenate([g[5] for g in group]),
            )
