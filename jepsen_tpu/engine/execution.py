"""The device-owning **execution** half of the checker engine.

Everything here touches shared, stateful resources: the bounded
:class:`DispatchWindow` of in-flight device dispatches, the compiled-
kernel cache (via ``wgl``'s claim helpers), the escalation ladder, and
the hand-off to the CPU-oracle worker pool.  The pure per-run half —
encode, bucketing, kernel planning — lives in
:mod:`jepsen_tpu.engine.planning`.

An :class:`Executor` is the unit of device ownership.  The in-process
pipeline (:func:`jepsen_tpu.engine.pipeline.run`) creates a private
one per run; the checker service daemon (:mod:`jepsen_tpu.serve`)
keeps ONE resident executor alive across runs, feeding it planned
buckets whose rows come from many concurrent client contexts — the
jit cache and the window stay warm, and same-shape rows from
different runs ride the same dispatch.

Both the window and the executor are **owner-thread confined**: all
``submit``/``drain`` calls must come from the thread that created
them (runtime-enforced by :meth:`DispatchWindow._check_owner`).  The
oracle worker pool interacts with execution only through Futures held
by each run's :class:`~jepsen_tpu.engine.planning.RunContext`, never
by driving the window.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .. import obs


def row_bucket_target(n: int) -> int:
    """Row count → its stable dispatch shape: the next power of two,
    floored at :data:`ROW_BUCKET`."""
    target = ROW_BUCKET
    while target < n:
        target *= 2
    return target

#: default bound on concurrently in-flight device dispatches; 1 = the
#: strictly serial dispatch-sync-dispatch path
DEFAULT_WINDOW = 4

#: minimum dispatch row bucket: row counts round up to the next power
#: of two ≥ this (never past the chunk cap) with neutral all-padding
#: rows, so jit executables are keyed by STABLE shapes — two runs of
#: ~500 subhistories both dispatch at 512 rows and hit one compiled fn
#: instead of retracing at 506 vs 493.  Geometric buckets bound the
#: executable count at O(log max_dispatch) per (E, C) shape while
#: wasting < 2× rows of (cheap, neutral) padding — the trade every
#: serving stack makes, and what keeps the resident checker service's
#: warm path warm across real varying-size traffic.  In-process
#: one-shot runs pay at most one compile either way.
ROW_BUCKET = 64


def default_window() -> int:
    """Resolved in-flight window: ``JEPSEN_TPU_ENGINE_WINDOW`` if set,
    else :data:`DEFAULT_WINDOW`."""
    try:
        return max(
            1, int(os.environ.get("JEPSEN_TPU_ENGINE_WINDOW",
                                  DEFAULT_WINDOW))
        )
    except ValueError:
        return DEFAULT_WINDOW


def _materialize(out):
    """Force device work to the host (the sync point)."""
    if isinstance(out, (tuple, list)):
        return tuple(np.asarray(x) for x in out)
    return np.asarray(out)


class DispatchWindow:
    """A bounded window of in-flight device dispatches.

    ``submit(key, thunk)`` first retires (syncs) the oldest entries
    until fewer than ``window`` are in flight, then calls ``thunk`` —
    which must *dispatch* device work and return the lazy device
    arrays — and enqueues its result.  ``drain()`` retires everything
    left.  Retirement materializes the arrays via ``np.asarray`` and
    hands ``(key, materialized, t_dispatch)`` to ``on_retire`` (also
    returned from ``submit``/``drain`` for callers that prefer pull).

    window=1 is the serial contract: every dispatch fully settles
    before the next one is issued, reproducing the historical
    dispatch-sync-dispatch path exactly.  The window is shared
    machinery — ``check_batch`` dispatches bucket chunks through it,
    ``ops.cycles`` its Elle screen buckets, and ``bench.py`` its
    pipelined measurement, so the benchmark times the code users run.

    A window is **owner-thread confined** (``# jt: guarded-by
    (owner-thread)`` on its state, checked by the lock-discipline lint
    pass): the in-flight deque and bubble/peak bookkeeping are
    deliberately lock-free, so ``submit``/``drain`` refuse calls from
    any thread but the creating one rather than corrupt them silently
    — the oracle worker pool must interact with the engine only
    through Futures (see the pipeline's stage-3 drain), never by
    driving the window.

    Time spent blocked in retirement is recorded as
    ``jepsen_engine_bubble_seconds``; the post-submit depth feeds the
    ``jepsen_engine_inflight_depth`` high-water gauge.
    """

    def __init__(
        self,
        window: Optional[int] = None,
        on_retire: Optional[Callable[[Any, Any, float], None]] = None,
    ):
        self.window = max(
            1, int(window) if window is not None else default_window()
        )
        self.on_retire = on_retire
        #: (key, lazy-out, t_dispatch, attrs)
        self._inflight: deque = deque()  # jt: guarded-by(owner-thread)
        self.peak_depth = 0  # jt: guarded-by(owner-thread)
        self.bubble_s = 0.0  # jt: guarded-by(owner-thread)
        self.submitted = 0  # jt: guarded-by(owner-thread)
        self._owner = threading.get_ident()

    def _check_owner(self) -> None:
        if threading.get_ident() != self._owner:
            raise RuntimeError(
                "DispatchWindow is owner-thread confined: submit/drain "
                "must run on the creating thread (oracle workers hand "
                "results back through Futures, never drive the window)"
            )

    @property
    def depth(self) -> int:
        return len(self._inflight)

    def submit(self, key, thunk, attrs: Optional[dict] = None) -> list:
        """Dispatch one unit of device work; returns entries retired to
        make room (empty until the window fills)."""
        self._check_owner()
        retired = []
        while len(self._inflight) >= self.window:
            retired.append(self._retire())
        # stamp BEFORE the thunk: jit trace + XLA compile run
        # synchronously inside the first dispatch call, and the
        # compile-vs-execute histograms must keep containing them
        t_dispatch = time.perf_counter()
        out = thunk()
        self._inflight.append((key, out, t_dispatch, attrs))
        self.submitted += 1
        depth = len(self._inflight)
        if depth > self.peak_depth:
            self.peak_depth = depth
        obs.gauge_max("jepsen_engine_inflight_depth", depth)
        return retired

    def _retire(self):
        key, out, t_dispatch, attrs = self._inflight.popleft()
        t0 = time.perf_counter()
        if obs.enabled():
            with obs.span(
                "engine/dispatch", cat="engine", **(attrs or {})
            ):
                mat = _materialize(out)
        else:
            mat = _materialize(out)
        wait = time.perf_counter() - t0
        self.bubble_s += wait
        obs.observe("jepsen_engine_bubble_seconds", wait)
        if self.on_retire is not None:
            self.on_retire(key, mat, t_dispatch)
        return key, mat, t_dispatch

    def drain(self) -> list:
        """Retire every in-flight dispatch, oldest first."""
        self._check_owner()
        out = []
        while self._inflight:
            out.append(self._retire())
        return out

    def abandon(self) -> int:
        """Drop every in-flight entry WITHOUT retiring (no host sync,
        no ``on_retire``): the recovery path after a dispatch raised —
        syncing the survivors could re-raise the same device failure.
        The dropped device computations finish (or die) on their own
        and get collected.  Returns the number dropped."""
        self._check_owner()
        n = len(self._inflight)
        self._inflight.clear()
        return n


class Executor:
    """Device-owning execution of planned buckets.

    ``submit(planned_bucket)`` splits the bucket into footprint-safe
    chunks and dispatches them through the executor's bounded
    :class:`DispatchWindow`; ``drain()`` retires everything in flight
    and runs the deferred escalation ladder.  Row verdicts route
    through each row's ``(ctx, idx)`` token back to its
    :class:`~jepsen_tpu.engine.planning.RunContext` — rows from many
    concurrent runs can share one dispatch (the service's cross-run
    coalescing) without any result cross-talk.

    Safety under pipelining (inherited verbatim from the pipeline it
    was factored out of): the frontier footprint budget
    (``fn.safe_dispatch`` ← ``FRONTIER_DISPATCH_BUDGET``) is
    crash-calibrated for ONE in-flight dispatch, so with a window of W
    each frontier chunk takes 1/W of the safe rows — total in-flight
    HBM stays at the calibrated bound no matter how many client runs
    coalesce.  Shapes whose cap floors out below W dispatch strictly
    serially at the full single-dispatch cap.  Dense chunks keep the
    full cap: the kernel is overflow-free with a small per-row
    footprint, and multi-in-flight dense dispatch IS the measured
    flagship bench pattern.  Escalation reruns dispatch only while
    the window is empty (see :meth:`drain`).

    Owner-thread confined like its window: create it on the thread
    that will drive it (the service daemon builds its resident
    executor ON the device thread, never on a request handler).
    """

    def __init__(
        self,
        window: Optional[int] = None,
        *,
        mesh=None,
        escalation=None,
        sufficient_rung: bool = True,
        max_dispatch: Optional[int] = None,
    ):
        from ..ops import wgl

        self.mesh = mesh
        self.escalation = (
            wgl.ESCALATION_FACTORS if escalation is None else escalation
        )
        self.sufficient_rung = sufficient_rung
        self.max_dispatch = (
            wgl.DEFAULT_MAX_DISPATCH if max_dispatch is None else max_dispatch
        )
        self._win = DispatchWindow(window, on_retire=self._settle_chunk)
        #: chunk_id -> {plan, arrays, rows, n, phase}
        self._chunks: Dict[int, dict] = {}  # jt: guarded-by(owner-thread)
        self._next_chunk = 0  # jt: guarded-by(owner-thread)
        #: chunks whose base pass overflowed, parked until the window
        #: drains: escalation reruns dispatch at LARGER capacities, and
        #: stacking one on top of `window` in-flight base dispatches
        #: would hold more concurrent footprint than the
        #: crash-calibrated per-dispatch budget was measured for.
        #: Deferring also matches the serial path's order (escalate
        #: after the base pass).  Overflow is the rare path; the
        #: common all-resolved chunk settles immediately.
        self._pending_escalations: List[tuple] = []  # jt: guarded-by(owner-thread)
        #: cumulative dispatch phases — the service's warm-hit
        #: accounting reads (and diffs) these across request batches
        self.phase_counts = {"compile": 0, "execute": 0}

    # -- stats the pipeline's telemetry reads -----------------------------

    @property
    def window_size(self) -> int:
        return self._win.window

    @property
    def submitted(self) -> int:
        return self._win.submitted

    @property
    def peak_depth(self) -> int:
        return self._win.peak_depth

    @property
    def bubble_s(self) -> float:
        return self._win.bubble_s

    # -- settle path (runs inside window retirement, owner thread) -------

    def _settle_chunk(self, chunk_id, mat, t_dispatch):
        # on_retire runs synchronously inside the owner-checked
        # submit/drain (DispatchWindow._retire), never on a foreign
        # thread, so owner-thread state stays confined
        ch = self._chunks.pop(chunk_id)  # jt: allow[lock-thread-confined] — synchronous on_retire, owner thread
        plan = ch["plan"]
        n_live = ch["n"]
        if obs.enabled():
            # dispatch-to-materialized latency, split compile (first
            # dispatch of this fn at this shape: trace + XLA compile +
            # execute) vs execute (cache-hit) exactly as the serial
            # path recorded it — under pipelining these overlap, so
            # their sum can exceed wall clock by design
            obs.observe(
                f"jepsen_kernel_{ch['phase']}_seconds",
                time.perf_counter() - t_dispatch,
                engine=plan.kernel,
            )
        # np.array (not asarray): jax outputs are read-only views and
        # the escalation pass writes back into these
        ok, failed_at, overflow = (np.array(x)[:n_live] for x in mat)
        if overflow.any():
            self._pending_escalations.append(  # jt: allow[lock-thread-confined] — synchronous on_retire, owner thread
                (plan, ch["arrays"], ch["rows"], ok, failed_at, overflow)
            )
        else:
            self._assign_rows(plan, ch["rows"], ok, failed_at, overflow)

    def _settle_rows(self, plan, arrays, rows, ok, failed_at, overflow):
        """Escalate a chunk's overflows on-device, then assign verdicts
        (still-overflowed rows join each row's oracle pool)."""
        from ..ops import wgl

        wgl.escalate_overflows(
            plan, arrays, ok, failed_at, overflow,
            mesh=self.mesh, escalation=self.escalation,
            sufficient_rung=self.sufficient_rung,
            max_dispatch=self.max_dispatch,
        )
        self._assign_rows(plan, rows, ok, failed_at, overflow)

    def _assign_rows(self, plan, rows, ok, failed_at, overflow):
        unresolved = "routed" if plan.kernel == "oracle" else "overflow"
        for row, (ctx, hist_idx) in enumerate(rows):
            if overflow[row]:
                # still overflowed after escalation: CPU oracle decides
                ctx.route_oracle(
                    hist_idx, plan.overflow_engine(), unresolved
                )
            elif ok[row]:
                ctx.assign(hist_idx, {
                    "valid?": True,
                    "engine": "tpu",
                    "kernel": plan.kernel,
                })
            else:
                ctx.assign(hist_idx, {
                    "valid?": False,
                    "engine": "tpu",
                    "kernel": plan.kernel,
                    "failed-event": int(failed_at[row]),
                })

    # -- dispatch path ----------------------------------------------------

    def _dispatch_chunk(self, plan, arrays, rows):
        """Queue one ≤ plan.disp-row chunk on the device (async)."""
        from ..ops import wgl

        chunk_id = self._next_chunk
        self._next_chunk += 1
        disp_shape = arrays[0].shape[0]
        # claim-before-dispatch (wgl._claim_shape is lock-protected):
        # jit retraces per input shape, so the first dispatch at this
        # (fn, shape) is the compile-phase one, every later one execute
        first = wgl._claim_shape(plan.fn, disp_shape)
        phase = "compile" if first else "execute"
        self.phase_counts[phase] += 1
        if obs.enabled():
            obs.count(
                "jepsen_kernel_dispatches_total", 1,
                engine=plan.kernel, phase=phase,
            )
        self._chunks[chunk_id] = {
            "plan": plan, "arrays": arrays, "rows": rows,
            "n": len(rows), "phase": phase,
        }
        self._win.submit(
            chunk_id,
            lambda: wgl._run_rows(plan.fn, self.mesh, arrays),
            attrs={"engine": plan.kernel, "rows": len(rows),
                   "phase": phase},
        )

    def submit(self, pb) -> None:
        """Dispatch one planned bucket in footprint-safe chunks through
        the window (or settle it inline when no kernel can run)."""
        from ..ops import wgl

        plan, arrays, rows = pb.plan, pb.arrays, pb.rows
        B = arrays[0].shape[0]
        if plan.fn is None or plan.disp == 0:
            # no dispatchable kernel (oracle-routed shape, a dense-only
            # spec outside its envelope, or even one row would crash
            # the worker): every escalation rung is equally
            # undispatchable (caps shrink with capacity), so settling
            # INLINE is dispatch-free — and it hands the bucket's rows
            # to the oracle pool NOW, overlapping the remaining device
            # work instead of waiting for the window to drain
            ok = np.zeros((B,), bool)
            failed_at = np.zeros((B,), np.int32)
            overflow = np.ones((B,), bool)
            self._settle_rows(plan, arrays, rows, ok, failed_at, overflow)
            return
        # the frontier footprint budget (fn.safe_dispatch ←
        # FRONTIER_DISPATCH_BUDGET) is crash-calibrated for ONE
        # in-flight dispatch; a window of W holds W dispatches' HBM
        # concurrently, so each frontier chunk gets 1/W of the rows —
        # total in-flight stays at the calibrated bound.  When even
        # that floors out (disp < W: per-row footprint near the whole
        # budget), the bucket dispatches strictly serially at the full
        # single-dispatch cap instead — W one-row dispatches in flight
        # would still overshoot the bound.  Dense chunks keep the full
        # cap: the kernel is overflow-free with a small per-row
        # footprint, and multi-in-flight dense dispatch IS the
        # measured flagship bench pattern (B=16384 × window, on-chip).
        chunk_cap = plan.disp
        serialize = False
        if plan.kernel != "dense" and self._win.window > 1:
            if plan.disp >= self._win.window:
                chunk_cap = plan.disp // self._win.window
            else:
                serialize = True
        from ..parallel import mesh as mesh_mod

        if B <= chunk_cap:
            # stable-shape dispatch: round the row count up to its
            # power-of-two bucket (capped at the footprint-safe chunk
            # cap) with neutral all-padding rows — settle slices the
            # outputs back to the live rows, so verdicts are untouched
            # while repeat traffic reuses one executable per bucket
            target = min(chunk_cap, row_bucket_target(B))
            if target > B:
                arrays = tuple(
                    mesh_mod.pad_to_multiple(np.asarray(a), target, fill)
                    for a, fill in zip(arrays, wgl._PAD_FILLS)
                )
            if serialize:
                self._win.drain()
            self._dispatch_chunk(plan, arrays, rows)
            if serialize:
                self._win.drain()
            return

        for lo in range(0, B, chunk_cap):
            hi = min(lo + chunk_cap, B)
            # every chunk (including the tail, padded with neutral
            # all-padding rows) dispatches at the same cap-row shape:
            # one executable, never a per-tail-size compile
            chunk = tuple(
                mesh_mod.pad_to_multiple(
                    np.asarray(a[lo:hi]), chunk_cap, fill
                )
                for a, fill in zip(arrays, wgl._PAD_FILLS)
            )
            if serialize:
                self._win.drain()
            self._dispatch_chunk(plan, chunk, rows[lo:hi])
        if serialize:
            self._win.drain()

    def reset(self) -> int:
        """Discard all transient dispatch state — in-flight window
        entries (unsynced, see :meth:`DispatchWindow.abandon`), the
        chunk map, parked escalations — WITHOUT assigning any verdicts.
        The resident service calls this when a batch raised: reusing
        the executor with a poisoned window would retire the failed
        batch's dispatches into the NEXT batch (re-raising its failure
        against innocent requests) and re-dispatch its parked
        escalation arrays into dead contexts.  Returns the number of
        abandoned dispatches."""
        n = self._win.abandon()
        self._chunks.clear()
        self._pending_escalations = []
        return n

    def drain(self) -> None:
        """Retire every in-flight dispatch, then run the deferred
        escalation ladder with the window empty — exactly one
        in-flight dispatch, the regime the footprint budget was
        calibrated in (and the serial path's order).  Parked chunks
        merge per plan first (live rows only — tail chunks carry
        neutral padding rows that must not interleave), so a bucket
        pays ONE padded rerun per escalation rung like the serial
        batch-wide pass did, not one ladder per chunk."""
        self._win.drain()
        pending = self._pending_escalations
        self._pending_escalations = []
        merged: Dict[int, list] = {}
        merged_order: List[int] = []
        for item in pending:
            pid = id(item[0])
            if pid not in merged:
                merged[pid] = []
                merged_order.append(pid)
            merged[pid].append(item)
        for pid in merged_order:
            group = merged[pid]
            if len(group) == 1:
                self._settle_rows(*group[0])
                continue
            plan = group[0][0]
            arrays = tuple(
                np.concatenate(
                    [np.asarray(g[1][i][: len(g[2])]) for g in group]
                )
                for i in range(6)
            )
            rows = [r for g in group for r in g[2]]
            self._settle_rows(
                plan, arrays, rows,
                np.concatenate([g[3] for g in group]),
                np.concatenate([g[4] for g in group]),
                np.concatenate([g[5] for g in group]),
            )
