"""P-compositionality front-end: decompose histories before dispatch.

"Faster linearizability checking via P-compositionality"
(arXiv:1504.00204): when a model is a product of independent
per-partition sub-models — registers per key, locks per name, queue
bags per value — a history is linearizable iff every per-partition
sub-history is, and the product of small searches is exponentially
cheaper than one big one.  The unordered-queue direct checker exploits
this ad hoc; this module is the general pass, running **ahead of**
``wgl.plan_bucket`` in the engine planning layer:

- Models declare the factoring via the partition protocol on
  :mod:`jepsen_tpu.models` (``partition_key(op)`` /
  ``subhistory_model(key)`` / ``partition_op(op, key)``); models
  without a declared partition pass through unchanged.
- :func:`split_history` splits one history into per-partition
  sub-histories at encode time, pairing invocations with completions
  (a dequeue's value lives on the *ok* event) and keeping real-time
  order inside each partition.  Any op whose partition cannot be
  determined keeps the WHOLE history undecomposed — pass-through is
  always sound, so the pass never guesses.
- :class:`DecomposedRun` owns a batch's parent result slots and
  exposes up to two :class:`~jepsen_tpu.engine.planning.RunContext`
  streams — the undecomposed pass-through histories under the parent
  model, and the flattened sub-histories under the partition
  sub-model family — which flow through the UNCHANGED streaming
  bucket path (``Planner.stream`` / ``Planner.encode_buckets``):
  thousands of small sub-histories land in tight same-(E, C) buckets
  instead of one oracle-bound monster, each row tagged ``(ctx, idx)``
  so the execution layer needs no new routing.  Escalation and oracle
  fallback operate per sub-history — one pathological partition no
  longer drags the entire history to the CPU.
- Verdicts AND at settle (:func:`merge_partition_results`): the first
  ``valid? = false`` sub-verdict wins — "first" in deterministic
  partition order, never settle order, so results stay independent of
  window size, bucketing, and interleaving — and the failing
  partition is surfaced as ``failed-partition`` in the result dict.

The pass is on by default (``JEPSEN_TPU_ENGINE_DECOMPOSE=0``
disables; ``check_batch(..., decomposed=False)`` per call) and pinned
verdict-identical to the pass-through path three ways: unit/property
tests (tests/test_decompose.py), ``make decompose-smoke``, and the
op-soup fuzz sweep.  See doc/checker-engines.md "Decomposition
front-end".

Sub-model instances are interned per partition key through a BOUNDED
cache (:data:`DECOMPOSE_CACHE_SIZE`): a wide keyspace must not grow an
unbounded per-key dict the way an uncapped ``lru_cache`` would (the
``ops/cycles.py`` lesson — its closure caches are capped at
``CLOSURE_CACHE_SIZE`` for the same reason).  Evictions are counted as
``jepsen_engine_decompose_cache_evictions_total``.
"""

from __future__ import annotations

import os
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .. import obs
from ..history import FAIL, INVOKE, History
from .planning import RunContext

#: sub-model instances interned per partition key, per run.  Keys come
#: from op values, so a wide keyspace (the millions-of-users traffic
#: shape) could otherwise grow an unbounded per-key map; past the cap
#: the least-recently-used entry evicts (counted below) and the
#: sub-model is simply rebuilt — correctness never depends on a hit.
DECOMPOSE_CACHE_SIZE = 1024

#: partition-fanout histogram buckets: powers of two spanning "barely
#: decomposable" to "wide keyspace" (the seconds-oriented default
#: buckets would squash every fanout into the first bin)
FANOUT_BUCKETS = (2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0, 1024.0)

#: sentinel key for failed op pairs: dropped from every partition, the
#: same treatment ``linear.prepare`` gives them undecomposed
_DROPPED = object()


def default_enabled() -> bool:
    """Decomposition default: on unless ``JEPSEN_TPU_ENGINE_DECOMPOSE``
    is falsy."""
    return os.environ.get("JEPSEN_TPU_ENGINE_DECOMPOSE", "1").lower() not in (
        "0", "false", "off", "no",
    )


def partitioner(model):
    """The model's ``partition_key`` method, or None when the model
    declares no partition protocol (the base class pins the attribute
    to None)."""
    fn = getattr(model, "partition_key", None)
    return fn if callable(fn) else None


def routing_gain_possible(model) -> bool:
    """Whether splitting ``model``'s histories ahead of dispatch can
    change their routing for the better.  Specs the routing layer
    already hands to a CPU direct algorithm outright
    (``wgl.DIRECT_FIRST_SPECS`` — the unordered queue, whose direct
    checker factors per value *internally*) gain nothing from the
    engine-side split: every sub-history lands back on the same oracle
    path, multiplied by the partition fanout in per-task overhead
    (measured ~12x slower on a 100-value queue corpus).  Those models
    keep their protocol — the oracle's ``_partition_by_key`` and the
    soundness documentation live there — but the engine pass treats
    them as pass-through."""
    from ..ops import step_kernels, wgl

    spec = step_kernels.spec_for(model)
    return spec is None or spec.name not in wgl.DIRECT_FIRST_SPECS


class SubmodelCache:
    """Bounded per-run interning of ``model.subhistory_model(key)``:
    an OrderedDict LRU capped at ``cap`` entries, evictions counted as
    ``jepsen_engine_decompose_cache_evictions_total`` so per-partition
    key explosion is visible in the run's metrics instead of in its
    RSS."""

    __slots__ = ("model", "cap", "_map", "evictions")

    def __init__(self, model, cap: int = DECOMPOSE_CACHE_SIZE):
        self.model = model
        self.cap = max(1, cap)
        self._map: OrderedDict = OrderedDict()
        self.evictions = 0

    def get(self, key):
        try:
            sub = self._map[key]
        except KeyError:
            sub = self.model.subhistory_model(key)
            self._map[key] = sub
            if len(self._map) > self.cap:
                self._map.popitem(last=False)
                self.evictions += 1
                obs.count("jepsen_engine_decompose_cache_evictions_total")
            return sub
        except TypeError:  # unhashable key — protocol impls guard, but
            return self.model.subhistory_model(key)  # never corrupt
        self._map.move_to_end(key)
        return sub


def split_history(model, history, submodel_for=None):
    """Split one history into per-partition sub-histories, or return
    None when it must pass through undecomposed (model declares no
    partition, or any op's partition is undeterminable).

    Returns ``[(key, submodel, subhistory), ...]`` in first-seen key
    order.  Invocations pair with their completions by process (the
    single-outstanding-op discipline ``linear.prepare`` relies on);
    the pair's key resolves from the completion first — a dequeue's
    value, a read's observation live there — then the invocation.
    Failed pairs drop (they never took effect), orphan completions and
    non-client (non-int process) events are skipped exactly as
    ``prepare`` skips them, and each partition keeps its events in
    original real-time order.  Ops enter sub-histories through
    ``model.partition_op`` (identity unless the sub-model speaks a
    different vocabulary); originals are never mutated."""
    key_fn = partitioner(model)
    if key_fn is None:
        return None
    records: List[list] = []  # [invoke_op, completion_op | None]
    rec_of_event: List[int] = []  # per history position, -1 = skipped
    open_of: Dict[int, int] = {}
    for op in history:
        p = op.process
        if not isinstance(p, int):
            rec_of_event.append(-1)
            continue
        if op.type == INVOKE:
            open_of[p] = len(records)
            rec_of_event.append(len(records))
            records.append([op, None])
        else:
            ri = open_of.pop(p, None)
            if ri is None:
                rec_of_event.append(-1)  # orphan completion
                continue
            records[ri][1] = op
            rec_of_event.append(ri)

    keys: List[Any] = []
    for inv, comp in records:
        if comp is not None and comp.type == FAIL:
            keys.append(_DROPPED)  # never took effect; no key needed
            continue
        k = key_fn(comp) if comp is not None else None
        if k is None:
            k = key_fn(inv)
        if k is None:
            return None  # undeterminable partition: pass through whole
        keys.append(k)

    parts: Dict[Any, History] = {}
    order: List[Any] = []
    for pos, op in enumerate(history):
        ri = rec_of_event[pos]
        if ri < 0:
            continue
        k = keys[ri]
        if k is _DROPPED:
            continue
        sub = parts.get(k)
        if sub is None:
            sub = parts[k] = History()
            order.append(k)
        sub.append(model.partition_op(op, k))
    return [
        (
            k,
            submodel_for(k) if submodel_for else model.subhistory_model(k),
            parts[k],
        )
        for k in order
    ]


def merge_partition_results(parts: Sequence[Tuple[Any, dict]]) -> dict:
    """AND a decomposed history's sub-verdicts into one result dict.

    The first ``valid? = false`` sub-verdict wins (then the first
    non-True, i.e. "unknown") — "first" in partition order, which is
    deterministic first-seen order, so the merged result can never
    depend on dispatch interleaving.  The winning sub-result's fields
    (engine, kernel, failed-event — in SUB-history event coordinates)
    carry through, plus ``failed-partition`` naming the partition and
    ``partitions`` with the fanout.  An all-True history reports the
    uniform sub-engine (or ``"mixed"``) so engine-rate stats stay
    honest; whenever ANY sub-history routed to the oracle the count
    rides along as ``oracle-partitions`` — a ``"mixed"`` engine must
    not hide oracle load from routing accounting (bench --decompose,
    decompose-smoke)."""
    n = len(parts)
    n_oracle = sum(
        1 for _k, r in parts
        if str(r.get("engine", "")).startswith("oracle")
    )
    winner = next(
        ((k, r) for k, r in parts if r.get("valid?") is False), None
    )
    if winner is None:
        winner = next(
            ((k, r) for k, r in parts if r.get("valid?") is not True), None
        )
    if winner is not None:
        key, r = winner
        out = dict(r)
        out["failed-partition"] = key
        out["partitions"] = n
        if n_oracle:
            out["oracle-partitions"] = n_oracle
        return out
    engines = {r.get("engine") for _k, r in parts}
    out = {
        "valid?": True,
        "engine": engines.pop() if len(engines) == 1 else "mixed",
        "partitions": n,
    }
    # uniform routing facts carry through (kernel for device rows,
    # algorithm for direct-checker rows) so engine/algorithm stats and
    # assertions see decomposed histories the same way as whole ones;
    # mixed sub-routes omit them rather than guess
    if out["engine"] == "tpu":
        kernels = {r.get("kernel") for _k, r in parts}
        if len(kernels) == 1:
            out["kernel"] = kernels.pop()
    algorithms = {r.get("algorithm") for _k, r in parts}
    if len(algorithms) == 1 and None not in algorithms:
        out["algorithm"] = algorithms.pop()
    if n_oracle:
        out["oracle-partitions"] = n_oracle
    return out


class DecomposedRun:
    """One batch's decomposition bookkeeping: parent result slots plus
    up to two planning streams.

    - ``("main", ctx)`` — pass-through histories under the parent
      model (everything, for models without a partition protocol or
      with decomposition disabled: that degenerate case is bitwise the
      historical single-context run).
    - ``("sub", ctx)`` — the flattened per-partition sub-histories
      under the sub-model family, one
      :class:`~jepsen_tpu.engine.planning.RunContext` whose per-index
      ``models`` carry each partition's seeded sub-model.

    Both streams flow through the unchanged ``Planner`` machinery; the
    in-process pipeline streams them into one executor, the service
    daemon encodes each into raw buckets that coalesce ACROSS runs per
    stream tag.  :meth:`results` assigns pass-through results home and
    ANDs sub-verdicts (:func:`merge_partition_results`) into the
    decomposed parents' slots.
    """

    def __init__(
        self,
        model,
        histories: Sequence,
        *,
        oracle_fallback: bool = True,
        oracle_budget_s: Optional[float] = None,
        enabled: Optional[bool] = None,
        lazy: bool = False,
    ):
        self.model = model
        self._histories = histories
        self.n = len(histories)
        enabled = default_enabled() if enabled is None else bool(enabled)
        self._pass_idx: List[int] = []
        self._parts_of: Dict[int, List[Tuple[Any, int]]] = {}
        self.n_partitions = 0
        self.n_decomposed = 0
        self.cache: Optional[SubmodelCache] = None
        self._active = bool(
            enabled
            and partitioner(model) is not None
            and routing_gain_possible(model)
        )
        if self._active:
            self.cache = SubmodelCache(model)
        self._kw = dict(
            oracle_fallback=oracle_fallback, oracle_budget_s=oracle_budget_s
        )
        self.main_ctx: Optional[RunContext] = None
        self.sub_ctx: Optional[RunContext] = None
        self._fed = False
        self._next_i = 0  # split progress (restartable; see _split)
        #: optional ``(tag, idx, result)`` verdict sink (see
        #: :meth:`attach_wal`); applied to contexts as they are created
        self._settle_sink = None
        if not lazy:
            # eager construction (the service path, and every caller
            # that inspects streams()/counters right away): drain the
            # incremental split here so post-construction state is
            # exactly the historical one
            for _ in self.feed():
                pass

    def feed(self):
        """Generator: classify and split histories ONE AT A TIME,
        yielding ``(ctx, idx)`` for each planner row the moment it
        exists — the streaming seam that lets the pipeline interleave
        the stage-0 split with encode and device dispatch (ROADMAP
        item 3's leftover: the split used to be a serial host preamble
        over the whole batch, so the first dispatch waited on the last
        history's split).  Pass-through rows yield under
        :attr:`main_ctx`, per-partition sub-rows under
        :attr:`sub_ctx`; both contexts grow via
        :meth:`~jepsen_tpu.engine.planning.RunContext.append` as rows
        appear.  On an already-split run (eager construction) it
        replays the existing rows in deterministic order."""
        if self._fed:
            for ctx in (c for c in (self.main_ctx, self.sub_ctx)
                        if c is not None):
                for idx in range(len(ctx.histories)):
                    yield ctx, idx
            if self._next_i < self.n:  # resume an abandoned split
                yield from self._split()
            return
        self._fed = True
        yield from self._split()

    def _split(self):
        """The restartable split loop: :attr:`_next_i` advances the
        moment a history's bookkeeping is complete (before its rows
        yield), so a generator abandoned mid-way — GC closes
        delegated generators — never double-splits or loses a history
        when :meth:`_ensure_fed` restarts the loop."""
        rec = obs.enabled()
        while self._next_i < self.n:
            i = self._next_i
            h = self._histories[i]
            parts = (
                split_history(self.model, h, self.cache.get)
                if self._active else None
            )
            if parts is None or len(parts) <= 1:
                # ≤ 1 partition gains nothing and would only
                # re-tag the result dict; keep it byte-identical
                self._pass_idx.append(i)
                if self.main_ctx is None:
                    self.main_ctx = RunContext(self.model, [], **self._kw)
                    self._bind_sink("main", self.main_ctx)
                idx = self.main_ctx.append(h)
                if rec and self._active:
                    obs.count(
                        "jepsen_engine_decomposed_total",
                        route="passthrough",
                    )
                self._next_i = i + 1
                yield self.main_ctx, idx
                continue
            slots = []
            for key, submodel, subh in parts:
                if self.sub_ctx is None:
                    self.sub_ctx = RunContext(
                        submodel, [], models=[], **self._kw
                    )
                    self._bind_sink("sub", self.sub_ctx)
                slots.append((key, self.sub_ctx.append(subh, submodel)))
            self._parts_of[i] = slots
            self.n_partitions += len(slots)
            self.n_decomposed += 1
            if rec:
                obs.count(
                    "jepsen_engine_decomposed_total", route="decomposed"
                )
                obs.count("jepsen_engine_partitions_total", len(slots))
                obs.registry().histogram(
                    "jepsen_engine_partition_fanout",
                    buckets=FANOUT_BUCKETS,
                ).observe(len(slots))
            self._next_i = i + 1
            for _key, idx in slots:
                yield self.sub_ctx, idx
        if self.main_ctx is None and self.sub_ctx is None:
            # empty batch: keep the historical empty main context so
            # streams()/contexts stay non-surprising
            self.main_ctx = RunContext(self.model, [], **self._kw)
            self._bind_sink("main", self.main_ctx)

    def extend(self, histories: Sequence) -> List[Tuple[RunContext, int]]:
        """Streaming-ingest seam (``POST /feed``): append ``histories``
        to an already-constructed run and drive the restartable split
        over JUST the new tail, returning the fresh ``(ctx, idx)``
        planner rows — prior rows never re-split, re-encode, or
        re-settle, so a feed session dispatches each delta the moment
        it arrives.  Composes with :meth:`replay`: rows a previous
        daemon life already settled (same request id) pre-fill on the
        next replay call and skip encode entirely."""
        self._ensure_fed()  # classify everything before the new tail
        if not isinstance(self._histories, list):
            self._histories = list(self._histories)
        self._histories.extend(histories)
        self.n = len(self._histories)
        return list(self._split())

    def _ensure_fed(self) -> None:
        """Finish the split eagerly for consumers that need the whole
        picture (a lazy run whose feed was never driven — or was
        abandoned mid-way — the restartable :meth:`_split` picks up at
        the first unclassified history)."""
        if not self._fed or self._next_i < self.n:
            self._fed = True
            for _ in self._split():
                pass

    @property
    def contexts(self) -> List[RunContext]:
        self._ensure_fed()
        return [c for c in (self.main_ctx, self.sub_ctx) if c is not None]

    def streams(self) -> List[Tuple[str, RunContext]]:
        """Tagged planning streams — the service daemon merges same-tag
        buckets across concurrent runs (tags are stable per model, so a
        group's requests always align)."""
        self._ensure_fed()
        out: List[Tuple[str, RunContext]] = []
        if self.main_ctx is not None:
            out.append(("main", self.main_ctx))
        if self.sub_ctx is not None:
            out.append(("sub", self.sub_ctx))
        return out

    # -- verdict WAL seam (doc/checker-service.md "Failure modes") --------

    def _bind_sink(self, tag: str, ctx: RunContext) -> None:
        if self._settle_sink is None:
            return
        sink = self._settle_sink

        def _on_settle(_ctx, idx, result, _tag=tag, _sink=sink):
            _sink(_tag, idx, result)

        ctx.on_settle = _on_settle

    def attach_wal(self, sink) -> None:
        """Install a ``(tag, idx, result)`` verdict sink — every slot
        that settles from now on (in already-created contexts AND in
        contexts the split creates later) is appended to the WAL by
        the sink.  ``tag`` is the stream tag (``"main"``/``"sub"``)."""
        self._settle_sink = sink
        if self.main_ctx is not None:
            self._bind_sink("main", self.main_ctx)
        if self.sub_ctx is not None:
            self._bind_sink("sub", self.sub_ctx)

    def replay(self, rows: Dict[Tuple[str, int], dict]) -> int:
        """Pre-fill result slots from replayed WAL rows —
        ``{(tag, idx): result}`` — BYPASSING the settle hook (a
        replayed verdict must not re-append to the WAL).  Settled
        slots never re-encode (the planner skips them), so a restarted
        run re-dispatches only its unsettled partitions.  Returns the
        number of slots filled; out-of-range or already-settled slots
        are ignored (a WAL can outlive the request mix that wrote it).
        """
        self._ensure_fed()
        by_tag = {tag: ctx for tag, ctx in self.streams()}
        n = 0
        for (tag, idx), result in rows.items():
            ctx = by_tag.get(tag)
            if ctx is None or not (0 <= idx < len(ctx.results)):
                continue
            if ctx.results[idx] is None:
                ctx.results[idx] = result
                n += 1
        return n

    def settled_count(self) -> int:
        """Slots holding verdicts across both streams (replay + live)."""
        return sum(c.settled_count() for c in self.contexts)

    def drain_oracles(self) -> None:
        for ctx in self.contexts:
            ctx.drain_oracles()

    def abandon_oracles(self) -> int:
        return sum(ctx.abandon_oracles() for ctx in self.contexts)

    def results(self) -> List[dict]:
        self._ensure_fed()
        out: List[Optional[dict]] = [None] * self.n
        if self.main_ctx is not None:
            for local, parent in enumerate(self._pass_idx):
                out[parent] = self.main_ctx.results[local]
        if self.sub_ctx is not None:
            subres = self.sub_ctx.results
            for parent, slots in self._parts_of.items():
                out[parent] = merge_partition_results(
                    [(key, subres[s]) for key, s in slots]
                )
        return out  # type: ignore[return-value]
