"""The pipelined dispatch engine behind ``wgl.check_batch``.

The serial path ran encode → H2D → kernel → D2H → oracle-fallback
strictly in sequence, so the device idled during host work and the
exponential CPU searches started only after the last dispatch returned
(bench.py measured ~18% headroom from exactly this bubble).  This
module keeps the device saturated the way serving stacks keep their
accelerators fed (continuous batching / input pipelines):

- **Shape buckets.**  Histories encode one at a time and accumulate in
  per-``(E, C)`` buckets (``encode.bucket_key``), so a 30-op history in
  a batch with one 1000-op outlier no longer pays 1000 events of
  padding.  A bucket flushes into device chunks when it reaches
  :data:`DEFAULT_FLUSH_ROWS` rows (huge keyspaces stream: encode of
  the next flush overlaps the previous flush's device work) or at
  end-of-input.
- **Dispatch window.**  Chunk dispatches are asynchronous (JAX returns
  before the kernel finishes); :class:`DispatchWindow` bounds how many
  are in flight and syncs only the oldest when the window fills —
  window=1 degenerates to the historical dispatch-sync-dispatch serial
  path, which is the determinism baseline the tests pin.
- **Concurrent oracle.**  Unencodable histories go to the
  ``checker.linear`` worker pool *immediately* (before any dispatch),
  and oracle-routed/undispatchable buckets join at flush time — so
  oracle wall time hides behind device wall time on mixed batches
  instead of adding to it.  Rows that overflow the device ladder join
  only after the window drains (their escalation reruns must not stack
  on in-flight dispatches — see below), and searches with a wall-clock
  ``oracle_budget_s`` defer to a serial drain pass (GIL-sharing
  workers would burn the deadline ~workers× faster than the serial
  path and flip verdicts to "unknown").

Kernel routing, escalation rungs, and all result/telemetry contracts
are unchanged from the serial path: the engine calls
``wgl.plan_bucket`` / ``wgl.escalate_overflows`` and assembles the
exact result dicts ``check_batch`` always produced.  Verdicts are a
pure function of the histories — never of window size, bucketing, or
oracle interleaving.

Pipeline telemetry (obs registry; doc/observability.md):

- ``jepsen_engine_inflight_depth`` (gauge, high-water): peak
  concurrently in-flight device dispatches — >1 proves overlap.
- ``jepsen_engine_bubble_seconds`` (histogram): host time blocked
  waiting on an in-flight dispatch — the stall the window hides.
- ``jepsen_engine_bucket_count`` (gauge, high-water): peak shape
  buckets one batch split into.
- ``jepsen_engine_occupancy_ratio`` (gauge): 1 − bubble/wall for the
  last engine run.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import obs

#: default bound on concurrently in-flight device dispatches; 1 = the
#: strictly serial dispatch-sync-dispatch path
DEFAULT_WINDOW = 4

#: rows a shape bucket accumulates before flushing mid-stream.  Kept at
#: the default dispatch cap so ordinary batches flush exactly once per
#: bucket (identical routing/compile behavior to the one-shot encode),
#: while keyspaces past it stream: encode of flush k+1 overlaps the
#: device work of flush k.
DEFAULT_FLUSH_ROWS = 16384


def default_window() -> int:
    """Resolved in-flight window: ``JEPSEN_TPU_ENGINE_WINDOW`` if set,
    else :data:`DEFAULT_WINDOW`."""
    try:
        return max(
            1, int(os.environ.get("JEPSEN_TPU_ENGINE_WINDOW",
                                  DEFAULT_WINDOW))
        )
    except ValueError:
        return DEFAULT_WINDOW


def default_bucketed() -> bool:
    """Shape bucketing default: on unless ``JEPSEN_TPU_ENGINE_BUCKETED``
    is falsy."""
    return os.environ.get("JEPSEN_TPU_ENGINE_BUCKETED", "1").lower() not in (
        "0", "false", "off", "no",
    )


def _flush_rows() -> int:
    try:
        return max(
            1, int(os.environ.get("JEPSEN_TPU_ENGINE_FLUSH_ROWS",
                                  DEFAULT_FLUSH_ROWS))
        )
    except ValueError:
        return DEFAULT_FLUSH_ROWS


def _materialize(out):
    """Force device work to the host (the sync point)."""
    if isinstance(out, (tuple, list)):
        return tuple(np.asarray(x) for x in out)
    return np.asarray(out)


class DispatchWindow:
    """A bounded window of in-flight device dispatches.

    ``submit(key, thunk)`` first retires (syncs) the oldest entries
    until fewer than ``window`` are in flight, then calls ``thunk`` —
    which must *dispatch* device work and return the lazy device
    arrays — and enqueues its result.  ``drain()`` retires everything
    left.  Retirement materializes the arrays via ``np.asarray`` and
    hands ``(key, materialized, t_dispatch)`` to ``on_retire`` (also
    returned from ``submit``/``drain`` for callers that prefer pull).

    window=1 is the serial contract: every dispatch fully settles
    before the next one is issued, reproducing the historical
    dispatch-sync-dispatch path exactly.  The window is shared
    machinery — ``check_batch`` dispatches bucket chunks through it,
    ``ops.cycles`` its Elle screen buckets, and ``bench.py`` its
    pipelined measurement, so the benchmark times the code users run.

    A window is **owner-thread confined** (``# jt: guarded-by
    (owner-thread)`` on its state, checked by the lock-discipline lint
    pass): the in-flight deque and bubble/peak bookkeeping are
    deliberately lock-free, so ``submit``/``drain`` refuse calls from
    any thread but the creating one rather than corrupt them silently
    — the oracle worker pool must interact with the engine only
    through Futures (see ``run``'s stage-3 drain), never by driving
    the window.

    Time spent blocked in retirement is recorded as
    ``jepsen_engine_bubble_seconds``; the post-submit depth feeds the
    ``jepsen_engine_inflight_depth`` high-water gauge.
    """

    def __init__(
        self,
        window: Optional[int] = None,
        on_retire: Optional[Callable[[Any, Any, float], None]] = None,
    ):
        self.window = max(
            1, int(window) if window is not None else default_window()
        )
        self.on_retire = on_retire
        #: (key, lazy-out, t_dispatch, attrs)
        self._inflight: deque = deque()  # jt: guarded-by(owner-thread)
        self.peak_depth = 0  # jt: guarded-by(owner-thread)
        self.bubble_s = 0.0  # jt: guarded-by(owner-thread)
        self.submitted = 0  # jt: guarded-by(owner-thread)
        self._owner = threading.get_ident()

    def _check_owner(self) -> None:
        if threading.get_ident() != self._owner:
            raise RuntimeError(
                "DispatchWindow is owner-thread confined: submit/drain "
                "must run on the creating thread (oracle workers hand "
                "results back through Futures, never drive the window)"
            )

    @property
    def depth(self) -> int:
        return len(self._inflight)

    def submit(self, key, thunk, attrs: Optional[dict] = None) -> list:
        """Dispatch one unit of device work; returns entries retired to
        make room (empty until the window fills)."""
        self._check_owner()
        retired = []
        while len(self._inflight) >= self.window:
            retired.append(self._retire())
        # stamp BEFORE the thunk: jit trace + XLA compile run
        # synchronously inside the first dispatch call, and the
        # compile-vs-execute histograms must keep containing them
        t_dispatch = time.perf_counter()
        out = thunk()
        self._inflight.append((key, out, t_dispatch, attrs))
        self.submitted += 1
        depth = len(self._inflight)
        if depth > self.peak_depth:
            self.peak_depth = depth
        obs.gauge_max("jepsen_engine_inflight_depth", depth)
        return retired

    def _retire(self):
        key, out, t_dispatch, attrs = self._inflight.popleft()
        t0 = time.perf_counter()
        if obs.enabled():
            with obs.span(
                "engine/dispatch", cat="engine", **(attrs or {})
            ):
                mat = _materialize(out)
        else:
            mat = _materialize(out)
        wait = time.perf_counter() - t0
        self.bubble_s += wait
        obs.observe("jepsen_engine_bubble_seconds", wait)
        if self.on_retire is not None:
            self.on_retire(key, mat, t_dispatch)
        return key, mat, t_dispatch

    def drain(self) -> list:
        """Retire every in-flight dispatch, oldest first."""
        self._check_owner()
        out = []
        while self._inflight:
            out.append(self._retire())
        return out


def run(
    model,
    histories: Sequence,
    *,
    frontier: int,
    slot_cap: int,
    max_closure: Optional[int] = None,
    mesh=None,
    escalation=None,
    oracle_fallback: bool = True,
    sufficient_rung: bool = True,
    max_dispatch: Optional[int] = None,
    oracle_budget_s: Optional[float] = None,
    window: Optional[int] = None,
    bucketed: Optional[bool] = None,
) -> List[dict]:
    """Check ``histories`` through the full pipeline; per-history result
    dicts in input order, exactly the shapes ``wgl.check_batch``
    documents.  This is ``check_batch``'s engine — call that, not this,
    unless you are the dispatch layer."""
    from ..checker import linear
    from ..ops import encode as encode_mod
    from ..ops import wgl
    from ..ops.step_kernels import spec_for

    if escalation is None:
        escalation = wgl.ESCALATION_FACTORS
    if max_dispatch is None:
        max_dispatch = wgl.DEFAULT_MAX_DISPATCH
    bucketed = default_bucketed() if bucketed is None else bool(bucketed)
    flush_rows = _flush_rows()

    spec = spec_for(model)
    results: List[Optional[dict]] = [None] * len(histories)
    oracle_futs: Dict[int, Tuple[Any, str]] = {}
    oracle_deferred: List[Tuple[int, str]] = []

    def submit_oracle(idx: int, engine_tag: str, unresolved_tag: str):
        """Queue one history for the CPU oracle worker pool (running
        concurrently with device work), or tag it unknown when the
        caller runs the oracle itself (race mode).

        Budgeted searches (``oracle_budget_s``) are NOT overlapped:
        the budget is a wall-clock deadline, and GIL-sharing worker
        threads would burn it ~workers× faster than the serial path —
        flipping verdicts that passed serially to "unknown".  Those
        defer to a serial drain pass after device work, exactly the
        historical order."""
        if not oracle_fallback:
            results[idx] = {"valid?": "unknown", "engine": unresolved_tag}
            return
        if oracle_budget_s is not None:
            oracle_deferred.append((idx, engine_tag))
            return
        pure = spec.pure_fs if spec else ()
        oracle_futs[idx] = (
            linear.analysis_async(
                model, histories[idx], pure_fs=pure,
                budget_s=oracle_budget_s,
            ),
            engine_tag,
        )

    #: chunks whose base pass overflowed, parked until the window
    #: drains: escalation reruns dispatch at LARGER capacities, and
    #: stacking one on top of `window` in-flight base dispatches would
    #: hold more concurrent footprint than the crash-calibrated
    #: per-dispatch budget (FRONTIER_DISPATCH_BUDGET) was measured for.
    #: Deferring also matches the serial path's order (escalate after
    #: the base pass).  Overflow is the rare path; the common
    #: all-resolved chunk settles immediately.
    pending_escalations: List[tuple] = []

    def settle_rows(plan, arrays, rows, ok, failed_at, overflow):
        """Escalate a chunk's overflows on-device, then assign verdicts
        (still-overflowed rows join the oracle pool)."""
        wgl.escalate_overflows(
            plan, arrays, ok, failed_at, overflow,
            mesh=mesh, escalation=escalation,
            sufficient_rung=sufficient_rung, max_dispatch=max_dispatch,
        )
        assign_rows(plan, rows, ok, failed_at, overflow)

    def assign_rows(plan, rows, ok, failed_at, overflow):
        unresolved = "routed" if plan.kernel == "oracle" else "overflow"
        for row, hist_idx in enumerate(rows):
            if overflow[row]:
                # still overflowed after escalation: CPU oracle decides
                submit_oracle(hist_idx, plan.overflow_engine(), unresolved)
            elif ok[row]:
                results[hist_idx] = {
                    "valid?": True,
                    "engine": "tpu",
                    "kernel": plan.kernel,
                }
            else:
                results[hist_idx] = {
                    "valid?": False,
                    "engine": "tpu",
                    "kernel": plan.kernel,
                    "failed-event": int(failed_at[row]),
                }

    chunks: Dict[int, dict] = {}
    next_chunk = [0]

    def settle_chunk(chunk_id, mat, t_dispatch):
        ch = chunks.pop(chunk_id)
        plan = ch["plan"]
        n_live = ch["n"]
        if obs.enabled():
            # dispatch-to-materialized latency, split compile (first
            # dispatch of this fn at this shape: trace + XLA compile +
            # execute) vs execute (cache-hit) exactly as the serial
            # path recorded it — under pipelining these overlap, so
            # their sum can exceed wall clock by design
            obs.observe(
                f"jepsen_kernel_{ch['phase']}_seconds",
                time.perf_counter() - t_dispatch,
                engine=plan.kernel,
            )
        # np.array (not asarray): jax outputs are read-only views and
        # the escalation pass writes back into these
        ok, failed_at, overflow = (np.array(x)[:n_live] for x in mat)
        if overflow.any():
            pending_escalations.append(
                (plan, ch["arrays"], ch["rows"], ok, failed_at, overflow)
            )
        else:
            assign_rows(plan, ch["rows"], ok, failed_at, overflow)

    win = DispatchWindow(window, on_retire=settle_chunk)

    def dispatch_chunk(plan, arrays, rows):
        """Queue one ≤ plan.disp-row chunk on the device (async)."""
        chunk_id = next_chunk[0]
        next_chunk[0] += 1
        disp_shape = arrays[0].shape[0]
        # claim-before-dispatch (wgl._claim_shape is lock-protected):
        # jit retraces per input shape, so the first dispatch at this
        # (fn, shape) is the compile-phase one, every later one execute
        first = wgl._claim_shape(plan.fn, disp_shape)
        phase = "compile" if first else "execute"
        if obs.enabled():
            obs.count(
                "jepsen_kernel_dispatches_total", 1,
                engine=plan.kernel, phase=phase,
            )
        chunks[chunk_id] = {
            "plan": plan, "arrays": arrays, "rows": rows,
            "n": len(rows), "phase": phase,
        }
        win.submit(
            chunk_id,
            lambda: wgl._run_rows(plan.fn, mesh, arrays),
            attrs={"engine": plan.kernel, "rows": len(rows),
                   "phase": phase},
        )

    n_flushes = [0]

    def flush(key, acc):
        """Stack one bucket's encoded histories, plan its kernel, and
        dispatch it in safe-cap chunks through the window."""
        encs, rows = acc
        if not encs:
            return
        if key is not None:
            E, C = key
        else:
            # unbucketed (historical) stacking: one global padded shape
            E, C = encode_mod.global_shape(encs, slot_cap)
        batch = encode_mod.stack_encoded(encs, rows, E, C)
        arrays = (
            batch.init_state, batch.ev_slot, batch.cand_slot,
            batch.cand_f, batch.cand_a, batch.cand_b,
        )
        n_flushes[0] += 1
        plan = wgl.plan_bucket(
            model, spec, arrays, frontier=frontier,
            max_closure=max_closure, max_dispatch=max_dispatch,
        )
        B = arrays[0].shape[0]
        if plan.fn is None or plan.disp == 0:
            # no dispatchable kernel (oracle-routed shape, a dense-only
            # spec outside its envelope, or even one row would crash
            # the worker): every escalation rung is equally
            # undispatchable (caps shrink with capacity), so settling
            # INLINE is dispatch-free — and it hands the bucket's rows
            # to the oracle pool NOW, overlapping the remaining device
            # work instead of waiting for the window to drain
            ok = np.zeros((B,), bool)
            failed_at = np.zeros((B,), np.int32)
            overflow = np.ones((B,), bool)
            settle_rows(plan, arrays, batch.row_history, ok, failed_at,
                        overflow)
            return
        # the frontier footprint budget (fn.safe_dispatch ←
        # FRONTIER_DISPATCH_BUDGET) is crash-calibrated for ONE
        # in-flight dispatch; a window of W holds W dispatches' HBM
        # concurrently, so each frontier chunk gets 1/W of the rows —
        # total in-flight stays at the calibrated bound.  When even
        # that floors out (disp < W: per-row footprint near the whole
        # budget), the bucket dispatches strictly serially at the full
        # single-dispatch cap instead — W one-row dispatches in flight
        # would still overshoot the bound.  Dense chunks keep the full
        # cap: the kernel is overflow-free with a small per-row
        # footprint, and multi-in-flight dense dispatch IS the
        # measured flagship bench pattern (B=16384 × window, on-chip).
        chunk_cap = plan.disp
        serialize = False
        if plan.kernel != "dense" and win.window > 1:
            if plan.disp >= win.window:
                chunk_cap = plan.disp // win.window
            else:
                serialize = True
        if B <= chunk_cap:
            if serialize:
                win.drain()
            dispatch_chunk(plan, arrays, batch.row_history)
            if serialize:
                win.drain()
            return
        from ..parallel import mesh as mesh_mod

        for lo in range(0, B, chunk_cap):
            hi = min(lo + chunk_cap, B)
            # every chunk (including the tail, padded with neutral
            # all-padding rows) dispatches at the same cap-row shape:
            # one executable, never a per-tail-size compile
            chunk = tuple(
                mesh_mod.pad_to_multiple(
                    np.asarray(a[lo:hi]), chunk_cap, fill
                )
                for a, fill in zip(arrays, wgl._PAD_FILLS)
            )
            if serialize:
                win.drain()
            dispatch_chunk(plan, chunk, batch.row_history[lo:hi])
        if serialize:
            win.drain()

    t0 = time.perf_counter()
    with obs.span("engine/pipeline", cat="engine") as sp:
        # -- stage 1: stream host encode into shape buckets ------------
        buckets: Dict[Any, Tuple[list, list]] = {}
        order: List[Any] = []  # first-seen bucket order (deterministic)
        for idx, hist in enumerate(histories):
            e = (
                encode_mod.encode_history(hist, model, slot_cap, spec)
                if spec is not None
                else None
            )
            if e is None:
                # stage 3 starts NOW: the oracle search runs on its
                # worker pool while the device batches are still being
                # encoded and dispatched
                submit_oracle(idx, "oracle-fallback", "unencodable")
                continue
            key = (
                encode_mod.bucket_key(e, slot_cap) if bucketed else None
            )
            acc = buckets.get(key)
            if acc is None:
                acc = buckets[key] = ([], [])
                order.append(key)
            acc[0].append(e)
            acc[1].append(idx)
            # -- stage 2 interleaves: a full bucket flushes into the
            # dispatch window while later histories are still encoding
            if bucketed and len(acc[0]) >= flush_rows:
                flush(key, acc)
                buckets[key] = ([], [])
        for key in order:
            flush(key, buckets[key])
        win.drain()
        # escalation reruns dispatch now, with the window empty —
        # exactly one in-flight dispatch, the regime the footprint
        # budget was calibrated in (and the serial path's order).
        # Parked chunks merge per plan first (live rows only — tail
        # chunks carry neutral padding rows that must not interleave),
        # so a bucket pays ONE padded rerun per escalation rung like
        # the serial batch-wide pass did, not one ladder per chunk.
        merged: Dict[int, list] = {}
        merged_order: List[int] = []
        for item in pending_escalations:
            pid = id(item[0])
            if pid not in merged:
                merged[pid] = []
                merged_order.append(pid)
            merged[pid].append(item)
        for pid in merged_order:
            group = merged[pid]
            if len(group) == 1:
                settle_rows(*group[0])
                continue
            plan = group[0][0]
            arrays = tuple(
                np.concatenate(
                    [np.asarray(g[1][i][: len(g[2])]) for g in group]
                )
                for i in range(6)
            )
            rows = [r for g in group for r in g[2]]
            settle_rows(
                plan, arrays, rows,
                np.concatenate([g[3] for g in group]),
                np.concatenate([g[4] for g in group]),
                np.concatenate([g[5] for g in group]),
            )
        t_device_end = time.perf_counter()

        # -- stage 3 drain: collect concurrent oracle verdicts ----------
        for idx, (fut, engine_tag) in oracle_futs.items():
            r = fut.result()
            r["engine"] = engine_tag
            results[idx] = r
        # budgeted searches run serially here (see submit_oracle)
        pure = spec.pure_fs if spec else ()
        for idx, engine_tag in oracle_deferred:
            r = linear.analysis(
                model, histories[idx], pure_fs=pure,
                budget_s=oracle_budget_s,
            )
            r["engine"] = engine_tag
            results[idx] = r

        if sp:
            # buckets = DISTINCT shape buckets (what the gauge reports);
            # flushes can exceed it when a bucket streams mid-input
            sp.set("buckets", len(order))
            sp.set("flushes", n_flushes[0])
            sp.set("chunks", win.submitted)
            sp.set("peak-inflight", win.peak_depth)
            sp.set("window", win.window)

    if obs.enabled():
        if order:
            obs.gauge_max("jepsen_engine_bucket_count", len(order))
        # occupancy over the DEVICE phase only (encode→dispatch→drain→
        # escalate): including the stage-3 oracle drain would let an
        # oracle-dominated run report near-100% occupancy while the
        # device sat idle — the opposite of what the metric diagnoses
        elapsed = t_device_end - t0
        if win.submitted and elapsed > 0:
            obs.gauge_set(
                "jepsen_engine_occupancy_ratio",
                max(0.0, 1.0 - win.bubble_s / elapsed),
            )
        if results:
            # per-subhistory engine outcomes (the observable half of
            # P-compositional tuning): tpu rows count under their
            # kernel name, everything else under its engine tag
            stats = wgl.batch_stats([r for r in results if r is not None])
            for eng, cnt in stats["engines"].items():
                if eng == "tpu":
                    continue
                obs.count("jepsen_engine_rows_total", cnt, engine=eng)
            for k, cnt in stats["kernels"].items():
                obs.count("jepsen_engine_rows_total", cnt, engine=k)

    return results  # type: ignore[return-value]
