"""The pipelined dispatch engine behind ``wgl.check_batch``.

The serial path ran encode → H2D → kernel → D2H → oracle-fallback
strictly in sequence, so the device idled during host work and the
exponential CPU searches started only after the last dispatch returned
(bench.py measured ~18% headroom from exactly this bubble).  This
module keeps the device saturated the way serving stacks keep their
accelerators fed (continuous batching / input pipelines):

- **Shape buckets.**  Histories encode one at a time and accumulate in
  per-``(E, C)`` buckets (``encode.bucket_key``), so a 30-op history in
  a batch with one 1000-op outlier no longer pays 1000 events of
  padding.  A bucket flushes into device chunks when it reaches
  :data:`DEFAULT_FLUSH_ROWS` rows (huge keyspaces stream: encode of
  the next flush overlaps the previous flush's device work) or at
  end-of-input.
- **Dispatch window.**  Chunk dispatches are asynchronous (JAX returns
  before the kernel finishes); :class:`DispatchWindow` bounds how many
  are in flight and syncs only the oldest when the window fills —
  window=1 degenerates to the historical dispatch-sync-dispatch serial
  path, which is the determinism baseline the tests pin.
- **Concurrent oracle.**  Unencodable histories go to the
  ``checker.linear`` worker pool *immediately* (before any dispatch),
  and oracle-routed/undispatchable buckets join at flush time — so
  oracle wall time hides behind device wall time on mixed batches
  instead of adding to it.
- **P-compositional decomposition.**  Partitionable models (the
  partition protocol on :mod:`jepsen_tpu.models`) split each history
  into per-partition sub-histories BEFORE planning
  (:mod:`jepsen_tpu.engine.decompose`): thousands of small
  sub-histories land in tight same-(E, C) buckets on the dense kernel
  instead of one oracle-bound monster, escalation/oracle fallback run
  per sub-history, and verdicts AND at settle — byte-identical to the
  undecomposed run (``make decompose-smoke`` pins it).
- **Slice-native dispatch.**  With more than one device attached the
  engine resolves a mesh itself
  (:func:`jepsen_tpu.parallel.mesh.engine_default_mesh`) and every
  chunk dispatches through a cached ``shard_map`` wrapper — chunk
  caps scale to ``n × per-chip cap``, rows pad to device multiples
  with neutral rows sliced at settle, and verdicts stay byte-identical
  to the single-device run (``make mesh-smoke`` pins it; see
  doc/checker-engines.md "Slice-native dispatch").

Since the checker-service split, this module is the **composition**
of the engine's two halves, not their implementation:

- :mod:`jepsen_tpu.engine.planning` — the pure per-run layer:
  :class:`~jepsen_tpu.engine.planning.RunContext` (result slots +
  oracle hand-off) and :class:`~jepsen_tpu.engine.planning.Planner`
  (streaming encode → shape buckets → ``wgl.plan_bucket``).
- :mod:`jepsen_tpu.engine.execution` — the device-owning layer:
  :class:`DispatchWindow` and
  :class:`~jepsen_tpu.engine.execution.Executor` (chunk dispatch,
  escalation ladder, footprint-safe chunk caps).

:func:`run` wires one private context/planner/executor per call — the
in-process path.  The checker service daemon (:mod:`jepsen_tpu.serve`)
wires the same two halves differently: one *resident* executor shared
by many concurrent client contexts, with same-shape buckets coalesced
across runs.  Verdicts are a pure function of the histories in both
compositions — never of window size, bucketing, interleaving, or
which composition ran them (``make serve-smoke`` pins the equality).

Kernel routing, escalation rungs, and all result/telemetry contracts
are unchanged from the serial path; the engine assembles the exact
result dicts ``check_batch`` always produced.

Pipeline telemetry (obs registry; doc/observability.md):

- ``jepsen_engine_inflight_depth`` (gauge, high-water): peak
  concurrently in-flight device dispatches — >1 proves overlap.
- ``jepsen_engine_bubble_seconds`` (histogram): host time blocked
  waiting on an in-flight dispatch — the stall the window hides.
- ``jepsen_engine_bucket_count`` (gauge, high-water): peak shape
  buckets one batch split into.
- ``jepsen_engine_occupancy_ratio`` (gauge): 1 − bubble/wall for the
  last engine run.
"""

from __future__ import annotations

import time
from typing import List, Optional, Sequence

from .. import obs
from .execution import (  # noqa: F401 — back-compat re-exports
    DEFAULT_WINDOW,
    DispatchWindow,
    Executor,
    default_window,
)
from .planning import (  # noqa: F401 — back-compat re-exports
    DEFAULT_FLUSH_ROWS,
    Planner,
    RunContext,
    default_bucketed,
    estimated_cost,
    finish_run_telemetry,
)


def run(
    model,
    histories: Sequence,
    *,
    frontier: int,
    slot_cap: int,
    max_closure: Optional[int] = None,
    mesh=None,
    escalation=None,
    oracle_fallback: bool = True,
    sufficient_rung: bool = True,
    max_dispatch: Optional[int] = None,
    oracle_budget_s: Optional[float] = None,
    window: Optional[int] = None,
    bucketed: Optional[bool] = None,
    decomposed: Optional[bool] = None,
    wal_sink=None,
    wal_replay=None,
) -> List[dict]:
    """Check ``histories`` through the full pipeline; per-history result
    dicts in input order, exactly the shapes ``wgl.check_batch``
    documents.  This is ``check_batch``'s engine — call that, not this,
    unless you are the dispatch layer."""
    from ..parallel import mesh as mesh_mod
    from . import decompose as decompose_mod

    # slice-native by default: no explicit mesh resolves to every
    # attached device whenever more than one is present
    # (doc/checker-engines.md "Slice-native dispatch")
    if mesh is None:
        mesh = mesh_mod.engine_default_mesh()
    n_devices = 1 if mesh is None else int(mesh.devices.size)
    # -- stage 0: the P-compositionality front-end splits partitionable
    # histories into per-partition sub-histories ahead of planning
    # (doc/checker-engines.md "Decomposition front-end"); models
    # without a declared partition — and ``decomposed=False`` /
    # JEPSEN_TPU_ENGINE_DECOMPOSE=0 runs — degenerate to the exact
    # historical single-context run.  lazy=True: the split STREAMS
    # through dec.feed() below, interleaved with encode and device
    # dispatch, instead of running as a serial host preamble over the
    # whole batch (the ROADMAP item 3 follow-up, closed)
    dec = decompose_mod.DecomposedRun(
        model, histories,
        oracle_fallback=oracle_fallback, oracle_budget_s=oracle_budget_s,
        enabled=decomposed, lazy=True,
    )
    # -- crash-safe resumption (doc/checker-service.md "Failure modes
    # & recovery"): WAL-replayed verdicts pre-fill result slots (they
    # never re-encode — the planner skips settled rows), and a settle
    # sink appends every NEW verdict so a later restart resumes here
    if wal_sink is not None:
        dec.attach_wal(wal_sink)
    if wal_replay:
        dec.replay(wal_replay)
    ex = Executor(
        window, mesh=mesh, escalation=escalation,
        sufficient_rung=sufficient_rung, max_dispatch=max_dispatch,
    )

    t0 = time.perf_counter()
    n_buckets = n_flushes = 0
    with obs.span("engine/pipeline", cat="engine") as sp:
        # -- stage 0+1+2 interleaved: the decomposition front-end's
        # stage-0 split now STREAMS (dec.feed yields each pass-through
        # history / sub-history row the moment it is classified), and
        # each row feeds its stream's planner immediately — so past
        # flush_rows() the split of later histories overlaps the
        # device work of earlier flushes instead of running as a
        # serial host preamble over the whole batch.  Unencodable
        # histories start stage 3 (the oracle pool) inside the feed.
        # End-of-input buckets dispatch largest-estimated-cost first
        # (BucketStream.finish — the per-run half of the daemon's
        # largest-cost-first scheduling).
        planners = {}  # id(ctx) -> (planner, BucketStream)
        for ctx, idx in dec.feed():
            st = planners.get(id(ctx))
            if st is None:
                planner = Planner(
                    ctx.model, spec=ctx.spec, slot_cap=slot_cap,
                    frontier=frontier, max_closure=max_closure,
                    max_dispatch=max_dispatch, bucketed=bucketed,
                    n_devices=n_devices,
                )
                st = planners[id(ctx)] = (planner, planner.open_stream())
            for pb in st[1].feed(ctx, idx):
                ex.submit(pb)
        # end-of-input buckets order by cost GLOBALLY across every
        # stream, not per stream: a decomposed run finishes with a
        # pass-through stream AND a sub-history stream, and a parent's
        # cost lives in the sum of its sub-bucket rows — finishing the
        # streams one after another would let a small early stream's
        # buckets under-schedule a high-fanout run's big sub-buckets
        # (the ROADMAP items 3+4 leftover).  finish() already sorts
        # within each stream; the stable global sort composes them.
        finished = []
        for planner, stream in planners.values():
            finished.extend(stream.finish())
            n_buckets += planner.n_buckets
            n_flushes += planner.n_flushes
        finished.sort(key=estimated_cost, reverse=True)
        for pb in finished:
            ex.submit(pb)
        ex.drain()
        t_device_end = time.perf_counter()

        # -- stage 3 drain: collect concurrent oracle verdicts
        dec.drain_oracles()

        if sp:
            # buckets = DISTINCT shape buckets (what the gauge reports);
            # flushes can exceed it when a bucket streams mid-input
            sp.set("buckets", n_buckets)
            sp.set("flushes", n_flushes)
            sp.set("chunks", ex.submitted)
            sp.set("peak-inflight", ex.peak_depth)
            sp.set("window", ex.window_size)
            sp.set("devices", ex.n_devices)
            if dec.n_decomposed:
                sp.set("decomposed", dec.n_decomposed)
                sp.set("partitions", dec.n_partitions)

    results = dec.results()
    if obs.enabled():
        if n_buckets:
            obs.gauge_max("jepsen_engine_bucket_count", n_buckets)
        # occupancy over the DEVICE phase only (encode→dispatch→drain→
        # escalate): including the stage-3 oracle drain would let an
        # oracle-dominated run report near-100% occupancy while the
        # device sat idle — the opposite of what the metric diagnoses
        elapsed = t_device_end - t0
        if ex.submitted and elapsed > 0:
            obs.gauge_set(
                "jepsen_engine_occupancy_ratio",
                max(0.0, 1.0 - ex.bubble_s / elapsed),
            )
        finish_run_telemetry(results)

    return results
