"""The pure per-run **planning** half of the checker engine.

The pipeline used to be one function owning everything from host
encode to device dispatch.  Serving many concurrent runs from one
resident device (jepsen_tpu.serve) forces the split the ROADMAP names:
everything *per-run and pure* lives here — encoding histories into
per-(E, C) shape buckets, stacking a bucket into padded arrays, and
planning its kernel route (``wgl.plan_bucket``) — while everything
*device-owning and shared* (the dispatch window, chunk dispatch,
escalation reruns, oracle-pool interaction) lives in
:mod:`jepsen_tpu.engine.execution`.

Two compositions consume this module:

- :func:`jepsen_tpu.engine.pipeline.run` — one run, one
  :class:`RunContext`, one private executor: ``Planner.stream`` yields
  planned buckets as encode proceeds (a full bucket flushes while
  later histories are still encoding, preserving the encode/device
  overlap the pipelined engine was built for).
- the checker service daemon (:mod:`jepsen_tpu.serve.daemon`) — many
  concurrent runs share ONE resident executor: request handlers call
  :meth:`Planner.encode_buckets` (pure, parallel-safe), the daemon's
  device thread merges same-key buckets *across runs* and stacks each
  merged bucket once via :meth:`Planner.plan_rows`.

Row identity is an opaque token ``(ctx, idx)``: every planned row
carries the :class:`RunContext` it belongs to, so the execution layer
can interleave rows from many runs in one dispatch and still route
each verdict home (per-client result routing is what makes cross-run
coalescing sound).
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

#: rows a shape bucket accumulates before flushing mid-stream.  Kept at
#: the default dispatch cap so ordinary batches flush exactly once per
#: bucket (identical routing/compile behavior to the one-shot encode),
#: while keyspaces past it stream: encode of flush k+1 overlaps the
#: device work of flush k.
DEFAULT_FLUSH_ROWS = 16384

_UNSET = object()

#: sentinel distinct from every bucket key (``None`` is the legitimate
#: key of unbucketed mode): this history routed to the oracle pool
_ROUTED_ORACLE = object()


def _note_settle_times(result: dict) -> None:
    """Record the run's time-to-first-verdict / time-to-violation
    gauges (doc/observability.md "Online checking") the moment a slot
    settles: seconds since the tracer's wall origin, written once — the
    FIRST settle and the FIRST ``valid? = false`` verdict win.  This is
    the summary seam the online monitor keys on (a violation at op 40k
    should show a detect time near op 40k, not at run end)."""
    from .. import obs

    if not obs.enabled():
        return
    import time as _time

    reg = obs.registry()
    dt = _time.time() - obs.tracer().wall_origin
    if reg.value("jepsen_run_first_verdict_seconds") is None:
        obs.gauge_set("jepsen_run_first_verdict_seconds", round(dt, 6))
    if (result.get("valid?") is False
            and reg.value("jepsen_run_first_violation_seconds") is None):
        obs.gauge_set("jepsen_run_first_violation_seconds", round(dt, 6))


def default_bucketed() -> bool:
    """Shape bucketing default: on unless ``JEPSEN_TPU_ENGINE_BUCKETED``
    is falsy."""
    return os.environ.get("JEPSEN_TPU_ENGINE_BUCKETED", "1").lower() not in (
        "0", "false", "off", "no",
    )


def flush_rows_default() -> int:
    """Resolved streaming flush threshold:
    ``JEPSEN_TPU_ENGINE_FLUSH_ROWS`` > active calibration
    (doc/tuning.md) > :data:`DEFAULT_FLUSH_ROWS`."""
    from ..tune import artifact as _cal

    return _cal.resolve_knob(
        "JEPSEN_TPU_ENGINE_FLUSH_ROWS",
        lambda v: max(1, int(v)),
        lambda cal: max(1, cal.flush_rows()),
        DEFAULT_FLUSH_ROWS,
    )


class RunContext:
    """One run's bookkeeping: the histories being checked, their result
    slots, and the oracle hand-off state.

    Owns no device resources — a resident execution layer can
    interleave rows from many live contexts into shared dispatches.
    Thread contract (enforced by phase ordering, not locks): during
    planning only the planning thread touches the context; during
    execution only the executor thread assigns results; the consumer
    may call :meth:`drain_oracles` / read :attr:`results` only after
    execution for this context has finished (the service daemon
    signals that with a per-request event, the in-process pipeline by
    plain sequencing).
    """

    def __init__(
        self,
        model,
        histories: Sequence,
        *,
        spec=_UNSET,
        models: Optional[Sequence] = None,
        oracle_fallback: bool = True,
        oracle_budget_s: Optional[float] = None,
    ):
        from ..ops.step_kernels import spec_for

        self.model = model
        self.histories = histories
        #: per-history model overrides — the decomposition front-end's
        #: sub-history contexts carry one seeded sub-model per row
        #: (same spec family as ``model``, different init state); None
        #: = every history checks against ``model``
        self.models = models
        self.spec = spec_for(model) if spec is _UNSET else spec
        self.oracle_fallback = oracle_fallback
        self.oracle_budget_s = oracle_budget_s
        self.results: List[Optional[dict]] = [None] * len(histories)
        self.oracle_futs: Dict[int, Tuple[Any, str]] = {}
        self.oracle_deferred: List[Tuple[int, str]] = []
        #: optional ``(ctx, idx, result)`` hook fired once per settled
        #: slot — the verdict-WAL seam.  Monotone: a slot that already
        #: holds a verdict never re-settles, so the hook fires at most
        #: once per index.
        self.on_settle: Optional[Any] = None

    def model_for(self, idx: int):
        """The model history ``idx`` checks against (encode init state
        and oracle fallback both read this, so the two can never
        disagree about a sub-history's seeded state)."""
        return self.model if self.models is None else self.models[idx]

    def append(self, history, model=None) -> int:
        """Grow the context by one history (its result slot rides
        along); returns the new index.  This is the streaming-
        decomposition seam: the front-end's stage-0 split feeds
        sub-histories in one at a time, interleaved with encode, so
        the context must accept rows incrementally.  Only valid while
        planning this context is still in progress (the same
        phase-ordering contract as the rest of the class)."""
        idx = len(self.histories)
        self.histories.append(history)
        self.results.append(None)
        if self.models is not None:
            self.models.append(model if model is not None else self.model)
        elif model is not None and model is not self.model:
            self.models = [self.model] * idx + [model]
        return idx

    def assign(self, idx: int, result: dict) -> None:
        """Settle one result slot — monotone accumulation.

        A slot settles exactly once: re-assignment of an
        already-settled index is a no-op, which makes replayed
        (WAL-pre-filled) verdicts authoritative over any re-dispatch
        and lets the settle hook fire at most once per index.
        """
        if self.results[idx] is not None:
            return
        self.results[idx] = result
        _note_settle_times(result)
        if self.on_settle is not None:
            self.on_settle(self, idx, result)

    def settled(self, idx: int) -> bool:
        """True when ``idx`` already holds a verdict (replayed or
        settled this run) — such rows must not re-encode/re-dispatch."""
        return self.results[idx] is not None

    def settled_count(self) -> int:
        return sum(1 for r in self.results if r is not None)

    def route_oracle(self, idx: int, engine_tag: str,
                     unresolved_tag: str) -> None:
        """Queue one history for the CPU oracle worker pool (running
        concurrently with device work), or tag it unknown when the
        caller runs the oracle itself (race mode).

        Budgeted searches (``oracle_budget_s``) are NOT overlapped:
        the budget is a wall-clock deadline, and GIL-sharing worker
        threads would burn it ~workers× faster than the serial path —
        flipping verdicts that passed serially to "unknown".  Those
        defer to a serial drain pass after device work, exactly the
        historical order."""
        from ..checker import linear

        if not self.oracle_fallback:
            self.assign(idx, {"valid?": "unknown",
                              "engine": unresolved_tag})
            return
        if self.oracle_budget_s is not None:
            self.oracle_deferred.append((idx, engine_tag))
            return
        pure = self.spec.pure_fs if self.spec else ()
        self.oracle_futs[idx] = (
            linear.analysis_async(
                self.model_for(idx), self.histories[idx], pure_fs=pure,
                budget_s=self.oracle_budget_s,
            ),
            engine_tag,
        )

    def abandon_oracles(self) -> int:
        """Best-effort cancellation of this run's oracle work — the
        service calls it when a request is refused or timed out AFTER
        planning already submitted searches: queued-not-started
        futures cancel outright (the common case under overload, when
        the pool is the bottleneck); an already-running exponential
        search cannot be interrupted and completes into the discarded
        future (bounded by the pool width).  Returns the number
        cancelled."""
        cancelled = 0
        for fut, _tag in self.oracle_futs.values():
            if fut.cancel():
                cancelled += 1
        self.oracle_futs.clear()
        self.oracle_deferred.clear()
        return cancelled

    def drain_oracles(self) -> None:
        """Collect concurrent oracle verdicts, then run budgeted
        searches serially (see :meth:`route_oracle`)."""
        from ..checker import linear

        for idx, (fut, engine_tag) in self.oracle_futs.items():
            r = fut.result()
            r["engine"] = engine_tag
            self.assign(idx, r)
        pure = self.spec.pure_fs if self.spec else ()
        for idx, engine_tag in self.oracle_deferred:
            if self.settled(idx):
                continue  # replayed verdicts win; skip the search
            r = linear.analysis(
                self.model_for(idx), self.histories[idx], pure_fs=pure,
                budget_s=self.oracle_budget_s,
            )
            r["engine"] = engine_tag
            self.assign(idx, r)


class PlannedBucket:
    """One stacked-and-routed bucket, ready for the execution layer:
    the :class:`~jepsen_tpu.ops.wgl.BucketPlan`, the padded 6-tuple of
    arrays, and one ``(ctx, idx)`` row token per array row."""

    __slots__ = ("key", "plan", "arrays", "rows")

    def __init__(self, key, plan, arrays, rows):
        self.key = key
        self.plan = plan
        self.arrays = arrays
        self.rows = rows


class Planner:
    """Pure per-run planning: stream host encode into per-(E, C) shape
    buckets and plan each flush's kernel route.  Holds no device
    state; safe to run on any thread (the service daemon plans on its
    request-handler threads)."""

    def __init__(
        self,
        model,
        *,
        slot_cap: int,
        frontier: int,
        spec=_UNSET,
        max_closure: Optional[int] = None,
        max_dispatch: Optional[int] = None,
        bucketed: Optional[bool] = None,
        flush_rows: Optional[int] = None,
        n_devices: int = 1,
    ):
        from ..ops import wgl
        from ..ops.step_kernels import spec_for

        self.model = model
        self.spec = spec_for(model) if spec is _UNSET else spec
        self.slot_cap = slot_cap
        self.frontier = frontier
        self.max_closure = max_closure
        self.max_dispatch = (
            wgl.DEFAULT_MAX_DISPATCH if max_dispatch is None else max_dispatch
        )
        self.bucketed = (
            default_bucketed() if bucketed is None else bool(bucketed)
        )
        # the flush threshold is a PER-DEVICE feed rate: on an n-device
        # mesh a flush fans its rows out across all n chips, so
        # flushing at the single-chip row count would hand each chip
        # 1/n of a full dispatch — the mesh scales the threshold so
        # every mid-stream flush still saturates the whole slice
        self.flush_rows = max(1, n_devices) * (
            flush_rows_default() if flush_rows is None else max(1, flush_rows)
        )
        #: distinct shape buckets seen (what the bucket-count gauge
        #: reports); flushes can exceed it when a bucket streams
        self.n_buckets = 0
        self.n_flushes = 0

    # -- encoding ---------------------------------------------------------

    def encode_one(self, ctx: RunContext, idx: int):
        """Encode one history of ``ctx``; ``None`` routes it to the
        oracle (unencodable — the caller's stage 3 starts NOW).  The
        per-history model (``ctx.model_for``) seeds the init state —
        decomposed sub-histories share this planner's spec family but
        each carry their own partition's seeded sub-model."""
        from ..ops import encode as encode_mod

        if self.spec is None:
            return None
        return encode_mod.encode_history(
            ctx.histories[idx], ctx.model_for(idx), self.slot_cap, self.spec
        )

    def bucket_key(self, e) -> Optional[tuple]:
        from ..ops import encode as encode_mod

        return (
            encode_mod.bucket_key(e, self.slot_cap) if self.bucketed else None
        )

    def _accumulate(self, ctx: RunContext, idx: int, buckets, order):
        """Encode one history into its bucket (the ONE shared
        encode/route/accumulate step — oracle routing and bucket
        keying cannot diverge between the in-process stream and the
        service's encode_buckets).  Returns the bucket key the history
        landed in (``None`` IS a valid key in unbucketed mode), or
        :data:`_ROUTED_ORACLE` when it went to the oracle instead —
        that search starts NOW, on the worker pool, overlapping all
        remaining encode and device work.

        A slot that already holds a verdict — WAL-replayed before
        encode — is skipped outright: settled rows never re-encode or
        re-dispatch, which is what makes a restarted run re-dispatch
        only its unsettled partitions."""
        if ctx.settled(idx):
            return _ROUTED_ORACLE
        e = self.encode_one(ctx, idx)
        if e is None:
            ctx.route_oracle(idx, "oracle-fallback", "unencodable")
            return _ROUTED_ORACLE
        key = self.bucket_key(e)
        acc = buckets.get(key)
        if acc is None:
            acc = buckets[key] = ([], [])
            order.append(key)
        acc[0].append(e)
        acc[1].append((ctx, idx))
        return key

    def encode_buckets(self, ctx: RunContext):
        """Encode every history of ``ctx`` into raw (unstacked) shape
        buckets: ``(buckets, order)`` with ``buckets[key] = (encs,
        tokens)``.  Unencodable histories route to the oracle
        immediately.  This is the service path: raw buckets from many
        contexts merge by key before a single stack+plan, so
        same-shape requests share compiled executables AND dispatch
        rows."""
        return self.encode_rows(ctx, range(len(ctx.histories)))

    def encode_rows(self, ctx: RunContext, idxs):
        """:meth:`encode_buckets` restricted to the given indices —
        the streaming-ingest delta path (``POST /feed``): a feed
        append encodes ONLY the rows :meth:`DecomposedRun.extend
        <jepsen_tpu.engine.decompose.DecomposedRun.extend>` just
        created, so per-partition sub-histories bucket and dispatch as
        operations complete instead of waiting for session close.
        Settled (WAL-replayed) rows skip as everywhere else."""
        buckets: Dict[Any, Tuple[list, list]] = {}
        order: List[Any] = []
        for idx in idxs:
            self._accumulate(ctx, idx, buckets, order)
        return buckets, order

    # -- planning ---------------------------------------------------------

    def plan_rows(self, key, encs: list, rows: list) -> Optional[PlannedBucket]:
        """Stack one bucket's encoded histories and plan its kernel
        route; ``rows`` are opaque ``(ctx, idx)`` tokens aligned with
        ``encs``.  Returns ``None`` for an empty bucket."""
        from ..ops import encode as encode_mod
        from ..ops import wgl

        if not encs:
            return None
        if key is not None:
            E, C = key
        else:
            # unbucketed (historical) stacking: one global padded shape
            E, C = encode_mod.global_shape(encs, self.slot_cap)
        batch = encode_mod.stack_encoded(encs, rows, E, C)
        arrays = (
            batch.init_state, batch.ev_slot, batch.cand_slot,
            batch.cand_f, batch.cand_a, batch.cand_b,
        )
        self.n_flushes += 1
        plan = wgl.plan_bucket(
            self.model, self.spec, arrays, frontier=self.frontier,
            max_closure=self.max_closure, max_dispatch=self.max_dispatch,
        )
        return PlannedBucket(key, plan, arrays, batch.row_history)

    # -- the streaming composition (in-process pipeline) ------------------

    def open_stream(self) -> "BucketStream":
        """An incremental feed/finish face over this planner — the
        seam that lets a producer interleave OTHER host work (the
        decomposition front-end's stage-0 split) between histories
        instead of handing :meth:`stream` a fully-materialized list."""
        return BucketStream(self)

    def stream(self, ctx: RunContext):
        """Generator: encode ``ctx``'s histories one at a time and
        yield a :class:`PlannedBucket` whenever a bucket fills
        (mid-stream, so the consumer's device work overlaps the
        remaining encode) or at end-of-input.  Unencodable histories
        route to the oracle pool immediately, before any yield."""
        s = self.open_stream()
        for idx in range(len(ctx.histories)):
            yield from s.feed(ctx, idx)
        yield from s.finish()


class BucketStream:
    """One in-progress streaming pass over a :class:`Planner`:
    :meth:`feed` accumulates (and mid-stream-flushes) one history at a
    time, :meth:`finish` plans the residual buckets and yields them
    **largest estimated cost first** — big buckets keep the dispatch
    window occupied while small ones fill the tail (the per-run half
    of the daemon's largest-cost-first scheduling; verdicts are
    order-independent by the engine contract, so the reorder is purely
    a throughput decision, and ties keep first-seen order so the
    sequence stays deterministic)."""

    __slots__ = ("planner", "buckets", "order", "finished")

    def __init__(self, planner: Planner):
        self.planner = planner
        self.buckets: Dict[Any, Tuple[list, list]] = {}
        self.order: List[Any] = []  # first-seen bucket order
        self.finished = False

    def feed(self, ctx: RunContext, idx: int):
        """Encode history ``idx`` of ``ctx``; yields a
        :class:`PlannedBucket` when its bucket fills mid-stream (so
        the consumer's device work overlaps the remaining encode).
        Unencodable histories route to the oracle pool immediately,
        before any yield."""
        if self.finished:
            raise RuntimeError("BucketStream already finished")
        p = self.planner
        key = p._accumulate(ctx, idx, self.buckets, self.order)
        if key is _ROUTED_ORACLE:
            return  # the oracle search is already running
        # a full bucket flushes into the dispatch window while later
        # histories are still encoding
        acc = self.buckets[key]
        if p.bucketed and len(acc[0]) >= p.flush_rows:
            pb = p.plan_rows(key, *acc)
            self.buckets[key] = ([], [])
            if pb is not None:
                yield pb

    def finish(self):
        """Plan every residual bucket, then yield biggest-cost-first."""
        if self.finished:
            raise RuntimeError("BucketStream already finished")
        p = self.planner
        planned = []
        for key in self.order:
            pb = p.plan_rows(key, *self.buckets[key])
            if pb is not None:
                planned.append(pb)
        p.n_buckets += len(self.order)
        self.finished = True
        # stable sort: equal-cost buckets keep first-seen order
        planned.sort(key=estimated_cost, reverse=True)
        yield from planned


def estimated_cost(pb: PlannedBucket) -> float:
    """Per-bucket device-cost estimate — the scheduling hook both
    compositions order dispatch by (largest first → better window
    occupancy): the checker service's cross-run coalescer and the
    per-run :meth:`BucketStream.finish` ordering.

    With a calibration artifact active (doc/tuning.md; the measured
    per-shape table ``jepsen_tpu tune`` produces — the
    arXiv:2008.01040 direction, as a direct lookup rather than a
    trained predictor) this returns the interpolated **measured
    seconds** for the bucket's (kernel, E, C, F, rows).  Untuned — or
    for a kernel the table never measured — it falls back to the
    analytic proxy: the dominant footprint term of each kernel family
    (frontier work scales with rows × F·(C+1)·ceil(E/32) state words;
    dense with rows × E, a fixed-width scan).  Oracle-routed buckets
    cost the device nothing either way.  Both forms only RANK buckets;
    absolute scale never changes a verdict."""
    plan = pb.plan
    rows = len(pb.rows)
    if plan.fn is None or plan.disp == 0:
        return 0.0
    from ..tune import artifact as _cal

    cal = _cal.active()
    if cal is not None:
        c = cal.cost(plan.kernel, plan.E, plan.C, plan.frontier, rows)
        if c is not None:
            return c
    if plan.kernel == "dense":
        return float(rows * plan.E)
    if plan.kernel == "cycles":
        # batched boolean closure (the Elle screens): per-row work is
        # the n×n matrix squaring ladder over the packed plane stack,
        # so footprint scales with E² × the plane weight (frontier
        # carries plane_weight(masks, nonadj, closure_impl) on
        # ScreenPlan, 1 on the plain has-cycle CyclePlan).  Under the
        # packed32 closure impl the frontier arrives pre-discounted by
        # W/n ≈ 1/32 — one uint32 word per 32 vertex lanes — so the
        # proxy and the measured cost table rank a word-packed bucket
        # ~32× cheaper than the same profile's uint8 lowering
        return float(rows) * plan.E * plan.E * max(1, plan.frontier)
    words = max(1, -(-plan.E // 32))
    return float(rows * plan.frontier * (plan.C + 1) * words)


def merge_buckets(runs) -> Tuple[Dict[Any, Tuple[list, list]], List[Any]]:
    """Coalesce raw per-run buckets across runs: same-key buckets from
    ``runs`` (an iterable of ``(buckets, order)`` pairs as returned by
    :meth:`Planner.encode_buckets`) concatenate in arrival order into
    one merged ``(encs, tokens)`` per key — the cross-run coalescing
    seam the checker service dispatches through."""
    merged: Dict[Any, Tuple[list, list]] = {}
    merged_order: List[Any] = []
    for buckets, order in runs:
        for key in order:
            encs, tokens = buckets[key]
            acc = merged.get(key)
            if acc is None:
                acc = merged[key] = ([], [])
                merged_order.append(key)
            acc[0].extend(encs)
            acc[1].extend(tokens)
    return merged, merged_order


def finish_run_telemetry(results: Sequence[Optional[dict]]) -> None:
    """Per-subhistory engine-outcome counters (the observable half of
    P-compositional tuning): tpu rows count under their kernel name,
    everything else under its engine tag."""
    from .. import obs
    from ..ops import wgl

    if not (obs.enabled() and results):
        return
    stats = wgl.batch_stats([r for r in results if r is not None])
    for eng, cnt in stats["engines"].items():
        if eng == "tpu":
            continue
        obs.count("jepsen_engine_rows_total", cnt, engine=eng)
    for k, cnt in stats["kernels"].items():
        obs.count("jepsen_engine_rows_total", cnt, engine=k)
