"""Pipeline smoke check: ``python -m jepsen_tpu.engine.smoke``.

Runs a small mixed-length CAS-register batch — short, long, and
high-concurrency histories (landing in different (E, C) shape
buckets), a corrupted minority (invalid verdicts), and one
slot-cap-exceeding history (concurrent oracle fallback) — through the
production ``check_batch`` path at window sizes 1 (the
serial-equivalent baseline) and 4, on both kernel routes (dense
automaton, and the generic frontier kernel via an explicit closure
cap).  Fails loudly on:

- verdict divergence between window sizes, between bucketed and the
  historical single-batch encode, or against the CPU oracle;
- missing pipeline telemetry: ``jepsen_engine_inflight_depth`` must
  exceed 1 on the window-4 run (proof the overlap actually happened —
  the acceptance gate on hosts without a chip), equal 1 on the
  window-1 run, with ``jepsen_engine_bucket_count`` ≥ 2 and recorded
  ``jepsen_engine_bubble_seconds`` observations.

Wired into ``make pipeline-smoke`` / ``make check`` so a refactor that
silently serializes the engine (or skews its verdicts) breaks CI, not
a benchmark window three rounds later.

Exit codes: 0 ok, 1 divergence or missing metrics.
"""

from __future__ import annotations

import random
import sys


def _corpus():
    """Seeded mixed-shape batch: two event buckets × two concurrency
    buckets, ~1/3 corrupted, plus one unencodable (slot-cap) history."""
    from jepsen_tpu.history import History, invoke_op
    from jepsen_tpu.synth import generate_history

    rng = random.Random(45100)
    hists = []
    for i in range(5):  # short, low concurrency → (E=64, C=4)
        hists.append(
            generate_history(
                rng, n_procs=3, n_ops=10, crash_p=0.02, corrupt=(i % 3 == 0)
            )
        )
    for i in range(5):  # long → (E=128, C=4)
        hists.append(
            generate_history(
                rng, n_procs=3, n_ops=80, crash_p=0.01, corrupt=(i % 3 == 0)
            )
        )
    for i in range(4):  # high concurrency → (E=64, C=8)
        hists.append(
            generate_history(
                rng, n_procs=8, n_ops=14, crash_p=0.02, corrupt=(i % 2 == 0)
            )
        )
    wide = History([invoke_op(p, "write", 1) for p in range(40)])
    wide.index_ops()  # 40 concurrently-open ops > slot_cap: oracle row
    hists.append(wide)
    return hists


def _bubble_count(reg) -> int:
    for d in reg.snapshot():
        if d["name"] == "jepsen_engine_bubble_seconds":
            return d.get("count", 0)
    return 0


def main(argv=None) -> int:
    from jepsen_tpu import models as m
    from jepsen_tpu import obs
    from jepsen_tpu.checker import linear
    from jepsen_tpu.ops import wgl

    hists = _corpus()
    model = m.cas_register(0)
    slot_cap = 32

    failures = []

    def check(cond, msg):
        if not cond:
            failures.append(msg)

    oracle = [
        linear.analysis(model, h, pure_fs=("read",))["valid?"]
        for h in hists
    ]
    check(False in oracle and True in oracle,
          f"corpus should mix verdicts, got {oracle}")

    # both kernel routes: default routing (dense automaton for this
    # value domain) and the generic frontier kernel (explicit closure
    # cap); max_dispatch=4 forces several chunks per bucket so the
    # window genuinely fills
    configs = {
        "dense": dict(slot_cap=slot_cap, max_dispatch=4),
        "frontier": dict(slot_cap=slot_cap, max_dispatch=4, max_closure=9),
    }
    for name, kw in configs.items():
        baseline = None
        for window, bucketed in ((1, False), (1, True), (4, True)):
            obs.enable(reset=True)
            outs = wgl.check_batch(
                model, hists, window=window, bucketed=bucketed, **kw
            )
            verdicts = [o["valid?"] for o in outs]
            check(
                verdicts == oracle,
                f"{name} w={window} bucketed={bucketed}: verdicts "
                f"{verdicts} != oracle {oracle}",
            )
            if baseline is None:
                baseline = outs
            else:
                check(
                    verdicts == [o["valid?"] for o in baseline],
                    f"{name} w={window} bucketed={bucketed} diverged "
                    "from the serial baseline",
                )
            check(
                outs[-1].get("engine") == "oracle-fallback",
                f"{name} w={window}: slot-cap history should be "
                f"oracle-fallback, got {outs[-1].get('engine')}",
            )
            reg = obs.registry()
            depth = reg.value("jepsen_engine_inflight_depth")
            if window == 1:
                check(
                    depth == 1,
                    f"{name} window=1 must be serial-equivalent "
                    f"(inflight depth {depth})",
                )
            else:
                # the acceptance gate: >1 proves host/device overlap
                # actually happened, even on the CPU backend
                check(
                    depth is not None and depth > 1,
                    f"{name} window=4: no overlap recorded "
                    f"(inflight depth {depth})",
                )
            if bucketed:
                check(
                    (reg.value("jepsen_engine_bucket_count") or 0) >= 2,
                    f"{name}: mixed-shape corpus produced "
                    f"{reg.value('jepsen_engine_bucket_count')} buckets",
                )
            check(
                _bubble_count(reg) > 0,
                f"{name} w={window}: no bubble-time observations",
            )

    if failures:
        for f_ in failures:
            print(f"pipeline-smoke: FAIL — {f_}", file=sys.stderr)
        return 1
    print(
        "pipeline-smoke: ok (windows 1/4, dense + frontier routes, "
        f"{len(hists)} mixed-shape histories)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
