"""Decomposition smoke check: ``python -m jepsen_tpu.engine.decompose_smoke``.

The P-compositionality gate (doc/checker-engines.md "Decomposition
front-end"): runs a seeded partitionable corpus — wide-keyspace
multi-register op-soup (valid + corrupted + cross-key undecomposable +
one slot-cap-exceeding oracle row), multi-mutex lock soup, and
unordered-queue traffic — through the production ``check_batch`` path
with decomposition ON vs OFF, on the dense route, the generic frontier
route (explicit closure cap), and the oracle-fallback route, and —
when ``JEPSEN_TPU_ENGINE_MESH=1`` (how ``make check`` invokes the
second pass) — sharded over the virtual-device mesh.  Fails loudly on:

- any verdict (``valid?``) divergence between the decomposed and
  pass-through paths, or any divergence in the normalized result dicts
  (everything except the decomposition-only ``partitions`` /
  ``failed-partition`` tags and the per-sub-history routing facts —
  ``engine``/``kernel``/``algorithm``/``failed-event``/witness
  payloads — which legitimately differ because sub-histories route,
  and fail, in sub-history coordinates);
- a failing decomposed history not naming its ``failed-partition``;
- missing decomposition telemetry: ``jepsen_engine_partitions_total``,
  the ``jepsen_engine_partition_fanout`` histogram, and both routes of
  ``jepsen_engine_decomposed_total`` (multi-register and multi-mutex;
  the unordered queue must instead NOT decompose engine-side — its
  direct-first routing already factors per value, and the gate
  regressing would multiply oracle tasks by the fanout);
- the perf direction inverting: the decomposed run must route FEWER
  rows to the oracle than the pass-through run on the wide-keyspace
  corpus (the whole point of the pass).

Wired into ``make decompose-smoke`` / ``make check`` so a refactor
that silently skews decomposed verdicts (or stops decomposing) breaks
CI, not a fuzz sweep rounds later.

Exit codes: 0 ok, 1 divergence or missing metrics.
"""

from __future__ import annotations

import random
import sys

#: result-dict keys the two paths must agree on bit-for-bit; routing
#: facts and failure coordinates are per-sub-history by design
_PINNED = ("valid?",)


def _normalize(r: dict) -> tuple:
    return tuple((k, r.get(k)) for k in _PINNED)


def _corpus():
    from jepsen_tpu import models as m
    from jepsen_tpu.history import History, invoke_op, ok_op
    from jepsen_tpu.synth import generate_mr_history

    rng = random.Random(45100)

    def h(*ops):
        return History(list(ops)).index_ops()

    mr_model = m.multi_register({k: 0 for k in range(16)})
    mr = [
        generate_mr_history(
            rng, n_procs=5, n_ops=60, n_keys=16, n_values=4,
            crash_p=0.02, corrupt=(i % 3 == 0),
        )
        for i in range(10)
    ]
    # cross-key txn: undecomposable, exercises the pass-through lane
    mr.append(h(
        invoke_op(0, "txn", [("w", 0, 1), ("w", 1, 2)]),
        ok_op(0, "txn", [("w", 0, 1), ("w", 1, 2)]),
        invoke_op(1, "txn", [("r", 0, None)]),
        ok_op(1, "txn", [("r", 0, 1)]),
    ))
    # slot-cap-exceeding row: oracle fallback, decomposed or not
    wide = History(
        [invoke_op(p, "txn", [("w", p % 16, 1)]) for p in range(40)]
    ).index_ops()
    mr.append(wide)

    mm_model = m.multi_mutex()
    mm = []
    for i in range(6):
        ops = []
        held = set()
        for _ in range(30):
            name = rng.choice("abcd")
            p = rng.randrange(4)
            if name in held:
                ops.append(invoke_op(p, "release", name))
                ops.append(ok_op(p, "release", name))
                held.discard(name)
            else:
                ops.append(invoke_op(p, "acquire", name))
                ops.append(ok_op(p, "acquire", name))
                held.add(name)
        if i % 3 == 0 and ops:
            # corrupt: double-acquire one held lock
            name = rng.choice("abcd")
            ops.append(invoke_op(5, "acquire", name))
            ops.append(ok_op(5, "acquire", name))
            ops.append(invoke_op(6, "acquire", name))
            ops.append(ok_op(6, "acquire", name))
        mm.append(History(ops).index_ops())

    uq_model = m.unordered_queue()
    uq = []
    for i in range(6):
        ops = []
        in_q = []
        for _ in range(24):
            if in_q and rng.random() < 0.4:
                v = in_q.pop(rng.randrange(len(in_q)))
                ops.append(invoke_op(0, "dequeue", None))
                ops.append(ok_op(0, "dequeue", v))
            else:
                v = rng.randrange(8)
                in_q.append(v)
                ops.append(invoke_op(0, "enqueue", v))
                ops.append(ok_op(0, "enqueue", v))
        if i % 3 == 0:
            ops.append(invoke_op(1, "dequeue", None))
            ops.append(ok_op(1, "dequeue", 99))  # never enqueued
        uq.append(History(ops).index_ops())

    return [(mr_model, mr), (mm_model, mm), (uq_model, uq)]


def main(argv=None) -> int:
    import os

    from jepsen_tpu import obs
    from jepsen_tpu.ops import wgl

    mesh_forced = os.environ.get("JEPSEN_TPU_ENGINE_MESH") == "1"
    if mesh_forced:
        from jepsen_tpu.platform import force_cpu_platform

        force_cpu_platform(8)

    failures = []

    def check(cond, msg):
        if not cond:
            failures.append(msg)

    # dense (default routing), frontier (explicit closure cap), and
    # oracle-fallback (tiny slot cap: most histories unencodable) —
    # the three lanes a decomposed sub-history can land in
    configs = {
        "dense": dict(slot_cap=32),
        "frontier": dict(slot_cap=32, max_closure=9),
        "oracle-fallback": dict(slot_cap=2),
    }
    for name, kw in configs.items():
        for model, hists in _corpus():
            obs.enable(reset=True)
            dec = wgl.check_batch(model, hists, decomposed=True, **kw)
            reg = obs.registry()
            n_parts = reg.value("jepsen_engine_partitions_total")
            n_dec = reg.value(
                "jepsen_engine_decomposed_total", route="decomposed"
            )
            fanout_count = next(
                (d.get("count", 0) for d in reg.snapshot()
                 if d["name"] == "jepsen_engine_partition_fanout"), 0,
            )
            dec_dense = (
                reg.value("jepsen_engine_batch_rows_total", engine="dense")
                or 0
            )
            obs.enable(reset=True)
            und = wgl.check_batch(model, hists, decomposed=False, **kw)
            und_dense = (
                reg.value("jepsen_engine_batch_rows_total", engine="dense")
                or 0
            )
            obs.enable(reset=True)
            mname = type(model).__name__
            check(
                [_normalize(a) for a in dec]
                == [_normalize(b) for b in und],
                f"{name}/{mname}: decomposed verdicts diverge from "
                f"pass-through: "
                f"{[(a.get('valid?'), b.get('valid?')) for a, b in zip(dec, und) if a.get('valid?') != b.get('valid?')]}",
            )
            check(
                all(
                    r.get("failed-partition") is not None
                    for r in dec
                    if r.get("valid?") is False and "partitions" in r
                ),
                f"{name}/{mname}: failing decomposed history missing "
                "failed-partition",
            )
            if mname == "UnorderedQueue":
                # direct-first spec: the routing gate must keep the
                # engine pass OFF (the per-value direct checker already
                # factors internally; splitting only multiplies oracle
                # tasks) — a partition here is the ~12x regression
                # coming back
                check(
                    not n_parts and not n_dec,
                    f"{name}/{mname}: direct-first model decomposed "
                    f"engine-side (partitions={n_parts} "
                    f"decomposed={n_dec})",
                )
            else:
                check(
                    (n_parts or 0) >= 2 and (n_dec or 0) >= 1
                    and fanout_count >= 1,
                    f"{name}/{mname}: missing decomposition telemetry "
                    f"(partitions={n_parts} decomposed={n_dec} "
                    f"fanout-observations={fanout_count})",
                )
            if name == "dense" and mname == "MultiRegister":
                # the envelope win the pass exists for: the 16-key
                # product state is far outside the dense automaton's
                # envelope pass-through (frontier/oracle routes), but
                # the per-key Register sub-histories land ON the dense
                # kernel — and the oracle must absorb no more
                # histories than before
                check(
                    dec_dense > und_dense,
                    f"{name}/{mname}: decomposition did not move rows "
                    f"into the dense envelope ({dec_dense} vs "
                    f"{und_dense} dense rows)",
                )
                dec_oracle = sum(
                    1 for r in dec
                    if str(r.get("engine", "")).startswith("oracle")
                    or r.get("oracle-partitions")
                )
                und_oracle = sum(
                    1 for r in und
                    if str(r.get("engine", "")).startswith("oracle")
                )
                check(
                    dec_oracle <= und_oracle,
                    f"{name}/{mname}: decomposition increased oracle-"
                    f"routed histories ({dec_oracle} vs {und_oracle})",
                )

    if failures:
        for f_ in failures:
            print(f"decompose-smoke: FAIL — {f_}", file=sys.stderr)
        return 1
    mesh_note = "8-device mesh" if mesh_forced else "single device"
    print(
        "decompose-smoke: ok (dense + frontier + oracle-fallback routes, "
        f"multi-register/multi-mutex/unordered-queue corpora, {mesh_note}, "
        "decomposed ≡ pass-through)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
