"""Web UI over the store: test table, directory browser, zip download.

(reference: jepsen/src/jepsen/web.clj — home:146, dir:235, zip:305,
files:349 with its scope check:328, serve!:385; http.server instead of
http-kit, same routes)
"""

from __future__ import annotations

import html
import io
import json
import os
import threading
import urllib.parse
import zipfile
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional

from . import store as store_mod

PAGE_STYLE = """\
body { font-family: sans-serif; margin: 2em; }
table { border-collapse: collapse; }
th, td { padding: 4px 10px; border-bottom: 1px solid #ddd; text-align: left; }
.valid-true { background: #c8f0c8; }
.valid-false { background: #f0c8c8; }
.valid-unknown { background: #f0e8c0; }
a { text-decoration: none; }
.live { color: #2a2; font-size: 0.8em; }
.first-violation { outline: 2px solid #c33; font-weight: bold; }
"""


def test_row(base: str, name: str, t: str) -> dict:
    """Cheap header info for one run: the valid? field via the partial
    map head (no full deserialize — the point of the block format)."""
    d = os.path.join(base, name, t)
    valid: Any = "unknown"
    try:
        res_path = os.path.join(d, "results.json")
        if os.path.exists(res_path):
            with open(res_path) as f:
                valid = json.load(f).get("valid?", "unknown")
        else:
            loaded = store_mod.load(
                {"name": name, "start-time": t, "store-base": base}
            )
            valid = (loaded.get("results") or {}).get("valid?", "unknown")
    except (OSError, ValueError):
        valid = "unknown"
    return {
        "name": name,
        "time": t,
        "valid": valid,
        "dir": d,
        # per-run trace artifact (jepsen_tpu.obs export): linked from
        # the home table when the run recorded one
        "trace": os.path.exists(os.path.join(d, "trace.json")),
        # per-run device-profiling capture (obs.profiling, cli test
        # --profile): a profile/ dir with the loadable manifest
        "profile": os.path.exists(
            os.path.join(d, "profile", "profile.json")),
    }


def _valid_class(v: Any) -> str:
    if v is True:
        return "valid-true"
    if v is False:
        return "valid-false"
    return "valid-unknown"


def service_section() -> str:
    """Live checker-service panel: when a resident daemon
    (jepsen_tpu.serve) answers on the local seam, the web UI is a thin
    client of it — live queue/warm-cache numbers and a link to its
    /metrics scrape; with no daemon it degrades silently to the
    store-only view this module always served."""
    try:
        from .serve import ServiceClient

        # one probe, not healthz-then-status: a failed/absent daemon
        # lands in the except either way, and home-page renders should
        # pay a single short round-trip
        client = ServiceClient(timeout=0.5)
        st = client.status()
    except Exception:  # noqa: BLE001 — store-only mode is the fallback
        return ""
    ratio = st.get("warm_hit_ratio")
    warm = f"{ratio:.0%}" if isinstance(ratio, (int, float)) else "n/a"
    murl = f"http://{client.host}:{client.port}/metrics"
    rows = [
        ("platform", st.get("platform")),
        ("uptime", f"{st.get('uptime_s', 0):.0f} s"),
        ("requests", f"{st.get('requests', 0)} "
         f"({st.get('histories', 0)} histories)"),
        ("queue", f"{st.get('queue_depth', 0)}/{st.get('max_queue_runs')}"
         + (" — draining" if st.get("stopping") else "")),
        ("coalesced", st.get("coalesced", 0)),
        ("warm-hit ratio", warm),
    ]
    live = st.get("live") or {}

    def _rate(k):
        v = live.get(k)
        return f"{v:.2f}/s" if isinstance(v, (int, float)) else "n/a"

    if live:
        qw = live.get("queue_wait_mean_s")
        busy = live.get("device_busy_ratio")
        rows.append((
            "last 60 s",
            f"req {_rate('requests_per_s')}"
            f" · hist {_rate('histories_per_s')}"
            f" · disp {_rate('dispatches_per_s')}",
        ))
        rows.append((
            "queue wait / busy",
            (f"{qw * 1e3:.1f} ms"
             if isinstance(qw, (int, float)) else "n/a")
            + " / "
            + (f"{busy:.0%}" if isinstance(busy, (int, float)) else "n/a"),
        ))
    if st.get("feed_open") or st.get("feed_sessions"):
        rows.append((
            "online feeds",
            f"{st.get('feed_open', 0)} open"
            f" ({st.get('feed_sessions', 0)} sessions,"
            f" {st.get('feed_deltas', 0)} deltas)"
            + (f" · feed {_rate('feed_deltas_per_s')}" if live else ""),
        ))
    if st.get("watch_subscribers") or st.get("watch_events"):
        rows.append((
            "watchers",
            f"{st.get('watch_subscribers', 0)}"
            f" ({st.get('watch_events', 0)} events streamed)",
        ))
    if st.get("journal_path"):
        rows.append((
            "dispatch journal",
            f"{st.get('journal_rows', 0)} rows → {st.get('journal_path')}",
        ))
    drift = st.get("drift")
    if drift:
        score = drift.get("score")
        rows.append((
            "cost-model drift",
            (f"{score:.2f}×" if isinstance(score, (int, float))
             else "n/a")
            + f" over {drift.get('shapes', 0)} shape(s)"
            + (" — RETUNE RECOMMENDED"
               if drift.get("retune_recommended") else ""),
        ))
    cells = "".join(
        f"<tr><td>{html.escape(str(k))}</td>"
        f"<td>{html.escape(str(v))}</td></tr>"
        for k, v in rows
    )
    return (
        '<h2>Checker service <span class="live">●&nbsp;live</span></h2>'
        f"<table>{cells}</table>"
        f'<p><a href="{html.escape(murl)}">live metrics</a> '
        "(Prometheus text)</p>"
        + _verdict_panel(client, st)
    )


def _verdict_panel(client, st: dict, limit: int = 10) -> str:
    """Live-verdict panel: a bounded tail of the daemon's ``/watch``
    channel (replay only the last ``limit`` WAL rows, via
    ``Last-Event-ID``).  The earliest violation in view is highlighted
    — the first thing an operator wants off an online monitor."""
    wal_rows = st.get("wal_rows") or 0
    if not wal_rows:
        return ""
    events = []
    try:
        for off, row in client.watch(last_id=max(-1, wal_rows - limit - 1),
                                     timeout=1.0):
            events.append((off, row))
            if off >= wal_rows - 1 or len(events) >= limit:
                break
    except Exception:  # noqa: BLE001 — the panel is best-effort
        return ""
    if not events:
        return ""
    first_violation = min(
        (off for off, row in events
         if (row.get("result") or {}).get("valid?") is False),
        default=None,
    )
    cells = []
    for off, row in events:
        res = row.get("result") or {}
        valid = res.get("valid?")
        cls = _valid_class(valid)
        if off == first_violation:
            cls += " first-violation"
        cells.append(
            f'<tr class="{cls}">'
            f"<td>#{off}</td>"
            f"<td>{html.escape(str(row.get('req'))[:12])}</td>"
            f"<td>{html.escape(str(row.get('stream')))}"
            f"[{html.escape(str(row.get('idx')))}]</td>"
            f"<td>{html.escape(str(valid))}</td>"
            f"<td>{html.escape(str(res.get('engine', '')))}</td></tr>"
        )
    return (
        '<h3>Settled verdicts <span class="live">●&nbsp;watch</span></h3>'
        "<table><tr><th>wal row</th><th>run</th><th>partition</th>"
        "<th>valid?</th><th>engine</th></tr>"
        + "".join(cells)
        + "</table>"
    )


def home_page(base: str) -> str:
    rows = []
    for name, runs in sorted(store_mod.tests(base).items()):
        for t in reversed(runs):
            rows.append(test_row(base, name, t))
    rows.sort(key=lambda r: r["time"], reverse=True)
    body = [
        service_section(),
        "<h1>Tests</h1>",
        "<table><tr><th>name</th><th>time</th><th>valid?</th>"
        "<th></th><th></th><th></th></tr>",
    ]
    for r in rows:
        link = urllib.parse.quote(f"/files/{r['name']}/{r['time']}/")
        zlink = urllib.parse.quote(f"/zip/{r['name']}/{r['time']}")
        tlink = urllib.parse.quote(
            f"/files/{r['name']}/{r['time']}/trace.json"
        )
        trace_cell = (
            f'<td><a href="{tlink}">trace</a></td>'
            if r.get("trace")
            else "<td></td>"
        )
        plink = urllib.parse.quote(
            f"/files/{r['name']}/{r['time']}/profile/"
        )
        profile_cell = (
            f'<td><a href="{plink}">profile</a></td>'
            if r.get("profile")
            else "<td></td>"
        )
        body.append(
            f'<tr class="{_valid_class(r["valid"])}">'
            f'<td><a href="{link}">{html.escape(r["name"])}</a></td>'
            f'<td><a href="{link}">{html.escape(r["time"])}</a></td>'
            f"<td>{html.escape(str(r['valid']))}</td>"
            f'<td><a href="{zlink}">zip</a></td>'
            f"{trace_cell}{profile_cell}</tr>"
        )
    body.append("</table>")
    return _page("Jepsen-TPU", "\n".join(body))


def dir_page(base: str, rel: str) -> str:
    d = os.path.join(base, rel) if rel else base
    entries = sorted(os.listdir(d))
    body = [f"<h1>{html.escape('/' + rel)}</h1>", "<ul>"]
    if rel:
        parent = os.path.dirname(rel.rstrip("/"))
        body.append(
            f'<li><a href="/files/{urllib.parse.quote(parent)}/">..</a></li>'
            if parent
            else '<li><a href="/files/">..</a></li>'
        )
    for e in entries:
        p = os.path.join(d, e)
        suffix = "/" if os.path.isdir(p) else ""
        link = urllib.parse.quote(f"/files/{rel}/{e}".replace("//", "/"))
        body.append(f'<li><a href="{link}{suffix}">{html.escape(e)}{suffix}</a></li>')
    body.append("</ul>")
    return _page(rel or "store", "\n".join(body))


def _page(title: str, body: str) -> str:
    return (
        f"<html><head><title>{html.escape(title)}</title>"
        f"<style>{PAGE_STYLE}</style></head><body>{body}</body></html>"
    )


def zip_bytes(d: str) -> bytes:
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
        for root, _dirs, files in os.walk(d):
            for f in files:
                full = os.path.join(root, f)
                z.write(full, os.path.relpath(full, os.path.dirname(d)))
    return buf.getvalue()


CONTENT_TYPES = {
    ".html": "text/html", ".svg": "image/svg+xml", ".json": "application/json",
    ".txt": "text/plain", ".log": "text/plain", ".jsonl": "text/plain",
    ".edn": "text/plain", ".prom": "text/plain",
}


class Handler(BaseHTTPRequestHandler):
    base = "store"

    def _ok(self, content: bytes, ctype: str = "text/html",
            extra: Optional[dict] = None):
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(content)))
        for k, v in (extra or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(content)

    def _err(self, code: int, msg: str):
        self.send_response(code)
        self.send_header("Content-Type", "text/plain")
        self.end_headers()
        self.wfile.write(msg.encode())

    def _resolve(self, rel: str) -> Optional[str]:
        """Path-traversal scope check: everything must stay under base.
        (reference: web.clj:328-347)"""
        base_abs = os.path.abspath(self.base)
        target = os.path.abspath(os.path.join(base_abs, rel))
        if target != base_abs and not target.startswith(base_abs + os.sep):
            return None
        return target

    def do_GET(self):  # noqa: N802 — http.server API
        try:
            path = urllib.parse.unquote(urllib.parse.urlparse(self.path).path)
            if path in ("/", ""):
                self._ok(home_page(self.base).encode())
                return
            if path.startswith("/files"):
                rel = path[len("/files"):].strip("/")
                target = self._resolve(rel)
                if target is None:
                    self._err(403, "out of scope")
                    return
                if os.path.isdir(target):
                    self._ok(dir_page(self.base, rel).encode())
                elif os.path.isfile(target):
                    ext = os.path.splitext(target)[1]
                    with open(target, "rb") as f:
                        self._ok(
                            f.read(),
                            CONTENT_TYPES.get(ext, "application/octet-stream"),
                        )
                else:
                    self._err(404, "not found")
                return
            if path.startswith("/zip/"):
                rel = path[len("/zip/"):].strip("/")
                target = self._resolve(rel)
                if target is None or not os.path.isdir(target):
                    self._err(404, "not found")
                    return
                name = rel.replace("/", "-") + ".zip"
                self._ok(
                    zip_bytes(target),
                    "application/zip",
                    {"Content-Disposition": f'attachment; filename="{name}"'},
                )
                return
            self._err(404, "not found")
        except BrokenPipeError:
            pass

    def log_message(self, fmt, *args):
        pass  # quiet; the store's jepsen.log is the log of record


def serve(host: str = "0.0.0.0", port: int = 8080, base: str = "store",
          block: bool = True) -> ThreadingHTTPServer:
    """Start the web UI.  (reference: web.clj:385-390)"""
    handler = type("BoundHandler", (Handler,), {"base": base})
    server = ThreadingHTTPServer((host, port), handler)
    print(f"Serving {base!r} on http://{host}:{port}/")
    if block:
        server.serve_forever()
    else:
        threading.Thread(target=server.serve_forever, daemon=True).start()
    return server
