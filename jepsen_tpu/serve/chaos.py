"""Self-chaos harness: ``python -m jepsen_tpu.serve.chaos``.

Turns the nemesis on the checker itself.  A consistency checker that
dies with its run — or worse, silently drops a violation it had
already found — is not fit to judge crash-prone systems, so the
service stack must survive the same faults it is built to detect.
Five scenarios, each asserting the acceptance gates from
doc/checker-service.md "Failure modes & recovery":

1. **kill -9 + WAL resume**: a daemon subprocess is SIGKILLed — once
   mid-request, once after settling two full batches (dense and
   frontier kernel routes) — and its verdict WAL's final line is torn
   mid-write (the crash-consistency worst case).  A restarted daemon
   replays the WAL into retried request ids: the fully-journaled
   request performs ZERO re-dispatches (``replayed == settled``), the
   torn request re-runs exactly the one lost row, and every final
   result list is byte-identical (canonical JSON) to the in-process
   engine.  The mid-request client never hangs: it fails bounded or
   completes, and its retry after restart gets identical verdicts.
2. **stalled socket + circuit breaker**: a fault-injecting TCP proxy
   on the local HTTP seam stalls responses past the client deadline.
   Every stalled call returns within the deadline budget (never
   hangs), consecutive failures trip the breaker, a tripped breaker
   fast-fails to the transparent in-process fallback (same verdicts),
   and after the cooldown a half-open ``/healthz`` probe through the
   un-stalled proxy closes the breaker again (recovery).
3. **dropped response + idempotent retry**: the proxy forwards a
   request to the daemon but drops the response.  The client's retry
   carries the same request id, the daemon serves it from the
   completed-response cache (``deduped`` + 1), and the request
   counters advance by exactly ONE — retried work is never
   double-counted.
4. **WAL auto-compaction + crash during compaction**: a daemon with a
   1-byte ``JEPSEN_TPU_WAL_COMPACT_BYTES`` threshold compacts its
   verdict WAL on the first idle turn (counted in
   ``jepsen_serve_wal_compactions_total``), keeping exactly the
   completed request's rows and leaving no ``.tmp`` behind.  A kill -9
   that leaves a half-written ``<wal>.tmp`` next to the intact WAL —
   the crash-during-compaction worst case, since ``compact()`` only
   renames after fsync — must not confuse the restart: the retried
   request id replays every settled row with zero re-dispatches and
   byte-identical results.
5. **fleet member SIGKILL + AOT rejoin**: two member daemons behind an
   in-process :class:`serve.router.Router` sharing one AOT executable
   cache.  The member owning a key is SIGKILLed mid-batch — the
   in-flight routed request spills to the sibling with byte-identical
   verdicts, the next request takes the counted connection-error
   reroute path, and one probe sweep marks the member down.  Revived
   against the same cache, the member warms ahead of ``/healthz``,
   one sweep marks it up, its key's traffic returns, and its first
   request performs ZERO cold dispatches.

Every injected fault is accounted for in metrics: client retries,
breaker trips and probes, router reroutes (this process's registry),
WAL replays and request dedups (the daemon's ``/metrics``).

Wired into ``make chaos-smoke`` / ``make check``.  Exit codes: 0 ok,
1 any gate failed.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time


def _canon(results) -> str:
    from jepsen_tpu.serve import protocol

    return json.dumps(protocol.sanitize_results(results), sort_keys=True)


def _metric_value(text: str, name: str):
    total = None
    for line in text.splitlines():
        if line.startswith(name) and not line.startswith("#"):
            head = line.split(" ", 1)[0]
            if head == name or head.startswith(name + "{"):
                try:
                    total = (total or 0.0) + float(line.rsplit(" ", 1)[1])
                except ValueError:
                    return None
    return total


# -- daemon-subprocess lifecycle ---------------------------------------------


def _spawn_daemon(port: int, tmp: str, extra_env: dict = None):
    """Start a real daemon subprocess (the kill -9 target must be a
    separate process) with its journal + verdict WAL in ``tmp``.
    ``extra_env`` overlays the child environment (scenario 5 points
    fleet members at one shared AOT executable cache this way)."""
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.update(extra_env or {})
    env["JEPSEN_TPU_JOURNAL"] = os.path.join(tmp, "journal.jsonl")
    env["JEPSEN_TPU_WAL"] = os.path.join(tmp, "verdict-wal.jsonl")
    # cwd is ``tmp`` (isolation), so the child can't rely on an
    # importable package in its working directory — point it at ours
    root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    prior = env.get("PYTHONPATH")
    env["PYTHONPATH"] = root + (os.pathsep + prior if prior else "")
    log = open(os.path.join(tmp, "daemon.log"), "ab")
    try:
        return subprocess.Popen(
            [sys.executable, "-m", "jepsen_tpu.serve",
             "--port", str(port)],
            cwd=tmp, env=env, stdout=log, stderr=log,
        )
    finally:
        log.close()


def _wait_healthy(client, proc, wait_s: float = 90.0) -> bool:
    deadline = time.monotonic() + wait_s
    while time.monotonic() < deadline:
        if client.healthy(timeout=0.5):
            return True
        if proc.poll() is not None:
            return False
        time.sleep(0.2)
    return False


def _sigkill(proc) -> None:
    try:
        os.kill(proc.pid, signal.SIGKILL)
    except ProcessLookupError:
        pass  # already dead — the harness assertions will say why
    proc.wait(timeout=30)


def _tear_tail(path: str) -> None:
    """Simulate a crash mid-append: drop any already-torn tail, then
    cut the last COMPLETE line in half (no trailing newline) — the
    read-back must skip it without losing prior rows."""
    with open(path, "rb") as f:
        data = f.read()
    complete, _, _ = data.rpartition(b"\n")
    head, _, last = complete.rpartition(b"\n")
    torn = last[: max(1, len(last) // 2)]
    with open(path, "wb") as f:
        if head:
            f.write(head + b"\n")
        f.write(torn)


def _post_check(client, model, histories, opts, rid):
    """POST /check with a CALLER-CHOSEN request id (the crash-retry
    scenarios must replay the same id across daemon lives, which
    ``ServiceClient.check_batch``'s per-call ids cannot do)."""
    from jepsen_tpu.serve import protocol

    body = protocol.check_request(model, histories, opts, req=rid)
    code, resp = client._resilient_post("/check", body)
    return code, protocol.decode_body(resp)


# -- the fault-injecting proxy (the local HTTP seam) --------------------------


def _recv_http(conn) -> bytes:
    """Read one Content-Length-framed HTTP message off a socket."""
    buf = b""
    while b"\r\n\r\n" not in buf:
        chunk = conn.recv(65536)
        if not chunk:
            return buf
        buf += chunk
    head, _, rest = buf.partition(b"\r\n\r\n")
    n = 0
    for line in head.split(b"\r\n"):
        if line.lower().startswith(b"content-length:"):
            n = int(line.split(b":", 1)[1].strip())
    while len(rest) < n:
        chunk = conn.recv(65536)
        if not chunk:
            break
        rest += chunk
    return head + b"\r\n\r\n" + rest


class _FaultProxy:
    """TCP proxy between client and daemon with three modes:
    ``forward`` (pass-through), ``stall`` (accept, never answer —
    the frozen-daemon fault), ``drop_response`` (forward the request
    upstream, swallow the response — the lost-reply fault that forces
    an idempotent retry)."""

    def __init__(self, upstream_port: int):
        self.upstream = upstream_port
        self.mode = "forward"
        self.drop_remaining = 0
        self.stalled = 0
        self.dropped = 0
        self._release = threading.Event()
        self._stop = False
        self._srv = socket.socket()
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(("127.0.0.1", 0))
        self._srv.listen(16)
        self.port = self._srv.getsockname()[1]
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self) -> None:  # jt: thread-entry
        self._srv.settimeout(0.2)
        while not self._stop:
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(
                target=self._handle, args=(conn,), daemon=True,
            ).start()

    def _handle(self, conn) -> None:  # jt: thread-entry
        up = None
        try:
            conn.settimeout(30)
            mode = self.mode
            if mode == "stall":
                self.stalled += 1
                # hold the client's socket open, answer nothing: its
                # deadline budget — not this proxy — must end the wait
                self._release.wait(timeout=30)
                return
            data = _recv_http(conn)
            if not data:
                return
            up = socket.create_connection(
                ("127.0.0.1", self.upstream), timeout=10)
            up.settimeout(60)
            up.sendall(data)
            resp = _recv_http(up)
            if mode == "drop_response" and self.drop_remaining > 0:
                self.drop_remaining -= 1
                self.dropped += 1
                return  # the daemon DID the work; the client sees EOF
            conn.sendall(resp)
        except OSError:
            pass
        finally:
            for s in (conn, up):
                if s is not None:
                    try:
                        s.close()
                    except OSError:
                        pass

    def close(self) -> None:
        self._stop = True  # jt: allow[concurrency-unguarded-shared] — monotonic shutdown flag; the accept loop re-reads it every 0.2s tick
        self._release.set()
        try:
            self._srv.close()
        except OSError:
            pass


# -- the harness --------------------------------------------------------------


def main(argv=None) -> int:
    from jepsen_tpu import models as m
    from jepsen_tpu import obs
    from jepsen_tpu.engine.smoke import _corpus
    from jepsen_tpu.ops import wgl
    from jepsen_tpu.serve import ServiceClient, client as client_mod
    from jepsen_tpu.serve.smoke import _corpus_b
    from jepsen_tpu.util import free_port

    failures = []

    def check(cond, msg):
        if not cond:
            failures.append(msg)

    obs.enable(reset=True)
    model = m.cas_register(0)
    batch = _corpus()
    batch_v = _corpus_b()
    configs = {
        "dense": dict(slot_cap=32, max_dispatch=4),
        "frontier": dict(slot_cap=32, max_dispatch=4, max_closure=9),
    }
    expected = {route: _canon(wgl.check_batch(model, batch, **kw))
                for route, kw in configs.items()}
    expected_v = _canon(wgl.check_batch(model, batch_v, **configs["dense"]))

    tmp = tempfile.mkdtemp(prefix="jepsen-chaos-")
    wal_path = os.path.join(tmp, "verdict-wal.jsonl")
    port = free_port()
    client_mod.reset_breakers()

    # == scenario 1: kill -9 mid-request, then after settled batches ==
    proc = _spawn_daemon(port, tmp)
    client = ServiceClient(port=port)
    check(_wait_healthy(client, proc), "daemon A did not come up")
    rid_v = "chaos-victim"
    victim = {}

    def post_victim():
        try:
            victim["out"] = _post_check(
                client, model, batch_v, configs["dense"], rid_v)
        except Exception as e:  # noqa: BLE001 — the assertion target
            victim["err"] = e

    t0 = time.monotonic()
    t = threading.Thread(target=post_victim)
    t.start()
    time.sleep(0.05)
    _sigkill(proc)  # the nemesis: kill -9 mid-request
    t.join(timeout=60)
    check(not t.is_alive(),
          "client hung after daemon was SIGKILLed mid-request")
    check(time.monotonic() - t0 < 60,
          "mid-request kill was not bounded by the client deadline")
    # the victim's retries against the dead daemon are consecutive
    # connection failures, so they legitimately trip the breaker —
    # scenario 2 pins that behaviour; here it would mask the WAL path
    client_mod.reset_breakers()

    # a fresh daemon life settles both kernel routes completely
    proc = _spawn_daemon(port, tmp)
    check(_wait_healthy(client, proc), "daemon A2 did not come up")
    settled = {}
    for route, kw in configs.items():
        code, payload = _post_check(
            client, model, batch, kw, f"chaos-{route}")
        check(code == 200, f"{route}: first pass returned {code}")
        check(_canon(payload.get("results") or []) == expected[route],
              f"{route}: pre-crash verdicts diverged from in-process")
        diag = payload.get("diag") or {}
        settled[route] = diag.get("settled", 0)
        check(settled[route] > 0, f"{route}: no settled count in diag")
        check(diag.get("replayed") == 0,
              f"{route}: fresh request claims WAL replays ({diag})")
    _sigkill(proc)  # kill -9 again — now with a fully-written WAL
    check(os.path.exists(wal_path), "verdict WAL was never written")
    _tear_tail(wal_path)  # corrupt the journal mid-write

    # restart: retried ids replay the WAL, re-dispatching only what
    # the torn line lost
    proc = _spawn_daemon(port, tmp)
    check(_wait_healthy(client, proc), "daemon B did not come up")
    for route, kw in configs.items():
        code, payload = _post_check(
            client, model, batch, kw, f"chaos-{route}")
        check(code == 200, f"{route}: replay pass returned {code}")
        check(_canon(payload.get("results") or []) == expected[route],
              f"{route}: post-crash verdicts diverged from in-process")
        diag = payload.get("diag") or {}
        want = settled[route] - (1 if route == "frontier" else 0)
        check(diag.get("replayed") == want,
              f"{route}: replayed {diag.get('replayed')} of "
              f"{settled[route]} settled rows, wanted {want}")
        if route == "dense":
            # fully journaled ⇒ zero re-dispatches: the crash cost
            # nothing but the replay read
            check(diag.get("cold_dispatches", 0) == 0
                  and diag.get("warm_dispatches", 0) == 0,
                  f"{route}: fully-replayed request re-dispatched "
                  f"({diag})")
    # the mid-request victim retries its id against the restarted
    # daemon: identical verdicts, whatever the crash interrupted
    code, payload = _post_check(
        client, model, batch_v, configs["dense"], rid_v)
    check(code == 200 and _canon(payload.get("results") or [])
          == expected_v,
          "victim retry after kill -9 diverged from in-process")
    st = client.status()
    mtext = client.metrics_text()
    want_replayed = (settled["dense"] + settled["frontier"] - 1
                     + (payload.get("diag") or {}).get("replayed", 0))
    check(st.get("replayed") == want_replayed,
          f"/status replayed {st.get('replayed')} != {want_replayed}")
    check(_metric_value(mtext, "jepsen_serve_wal_replayed_total")
          == want_replayed,
          "jepsen_serve_wal_replayed_total does not account the replays")
    check(st.get("wal_path") == wal_path and st.get("wal_rows", 0) > 0,
          f"/status does not advertise the WAL ({st.get('wal_path')}, "
          f"{st.get('wal_rows')})")

    # == scenario 2: stalled socket → deadline, breaker, fallback ==
    os.environ["JEPSEN_TPU_CLIENT_DEADLINE"] = "2.0"
    os.environ["JEPSEN_TPU_CLIENT_BACKOFF"] = "0.05"
    os.environ["JEPSEN_TPU_BREAKER_FAILURES"] = "3"
    os.environ["JEPSEN_TPU_BREAKER_COOLDOWN"] = "1.0"
    client_mod.reset_breakers()
    proxy = _FaultProxy(port)
    proxy.mode = "stall"
    stalled = ServiceClient(port=proxy.port)
    br = client_mod.breaker_for(stalled.host, stalled.port)
    for i in range(3):
        t0 = time.monotonic()
        try:
            _post_check(stalled, model, batch_v, configs["dense"],
                        f"chaos-stall-{i}")
            check(False, f"stalled call {i} unexpectedly succeeded")
        except client_mod.ServiceError:
            pass
        wall = time.monotonic() - t0
        check(wall <= 3.5,
              f"stalled call {i} took {wall:.1f}s — past the 2.0s "
              "deadline budget")
    check(br.state() == "open",
          f"breaker did not trip after 3 stalled calls ({br.state()})")
    # a tripped breaker fast-fails the transparent seam to in-process
    t0 = time.monotonic()
    res = client_mod.check_batch(model, batch_v, client=stalled,
                                 **configs["dense"])
    wall = time.monotonic() - t0
    check(_canon(res) == expected_v,
          "open-breaker fallback verdicts diverged from in-process")
    check(wall <= 0.75,
          f"open breaker did not fast-fail ({wall:.2f}s)")
    # recovery: un-stall the seam, wait out the cooldown, and the
    # half-open /healthz probe closes the breaker again
    proxy.mode = "forward"
    proxy._release.set()
    time.sleep(1.1)
    req0 = client.status().get("requests", 0)
    res = client_mod.check_batch(model, batch_v, client=stalled,
                                 **configs["dense"])
    check(_canon(res) == expected_v,
          "post-recovery verdicts diverged from in-process")
    check(br.state() == "closed",
          f"breaker did not close after half-open probe ({br.state()})")
    check(br.probes >= 1, "recovery path never probed /healthz")
    check(client.status().get("requests", 0) > req0,
          "post-recovery request did not reach the daemon")

    # == scenario 3: dropped response → idempotent retry, no double count ==
    st0 = client.status()
    proxy.mode = "drop_response"
    proxy.drop_remaining = 1
    code, payload = _post_check(stalled, model, batch_v,
                                configs["dense"], "chaos-dedup")
    check(code == 200 and _canon(payload.get("results") or [])
          == expected_v,
          "retried-after-drop verdicts diverged from in-process")
    st1 = client.status()
    check(proxy.dropped == 1, "proxy never dropped a response")
    check(st1.get("requests", 0) - st0.get("requests", 0) == 1,
          f"duplicate request double-counted "
          f"({st0.get('requests')} → {st1.get('requests')})")
    check(st1.get("deduped", 0) - st0.get("deduped", 0) == 1,
          f"daemon did not dedupe the retried id "
          f"({st0.get('deduped')} → {st1.get('deduped')})")
    check((_metric_value(client.metrics_text(),
                         "jepsen_serve_request_dedup_total") or 0) >= 1,
          "jepsen_serve_request_dedup_total does not account the dedup")

    # == scenario 4: WAL auto-compaction + crash during compaction ==
    from jepsen_tpu.obs import journal as obs_journal

    os.environ["JEPSEN_TPU_WAL_COMPACT_BYTES"] = "1"
    tmp2 = tempfile.mkdtemp(prefix="jepsen-chaos-compact-")
    wal2 = os.path.join(tmp2, "verdict-wal.jsonl")
    port2 = free_port()
    client_mod.reset_breakers()
    proc2 = _spawn_daemon(port2, tmp2)
    client2 = ServiceClient(port=port2)
    check(_wait_healthy(client2, proc2), "daemon C did not come up")
    code, payload = _post_check(
        client2, model, batch, configs["dense"], "chaos-compact")
    check(code == 200, f"compaction-prep check returned {code}")
    settled_c = (payload.get("diag") or {}).get("settled", 0)
    check(settled_c > 0, "compaction prep settled nothing")
    # the device thread compacts on its next idle turn (~1 s quiet)
    st = {}
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        st = client2.status()
        if st.get("wal_compactions", 0) >= 1:
            break
        time.sleep(0.2)
    check(st.get("wal_compactions", 0) >= 1,
          "idle daemon never compacted a WAL past the 1-byte threshold")
    check((_metric_value(client2.metrics_text(),
                         "jepsen_serve_wal_compactions_total") or 0) >= 1,
          "jepsen_serve_wal_compactions_total does not account it")
    check(not os.path.exists(wal2 + ".tmp"),
          "compaction left its .tmp behind")
    kept = list(obs_journal.read_verdict_rows(wal2))
    check(len(kept) == settled_c
          and all(r.get("req") == "chaos-compact" for r in kept),
          f"compacted WAL diverged ({len(kept)} rows, "
          f"wanted {settled_c} × chaos-compact)")
    # crash "mid-compaction": kill -9, then plant the half-written
    # .tmp a real crash would leave beside the intact (renamed-over or
    # original) WAL — the restart must ignore it and replay cleanly
    _sigkill(proc2)
    with open(wal2 + ".tmp", "w") as f:
        f.write('{"v": 1, "req": "chaos-compact", "stream": "ma')
    proc2 = _spawn_daemon(port2, tmp2)
    check(_wait_healthy(client2, proc2),
          "daemon C2 did not come up beside a stale compaction .tmp")
    code, payload = _post_check(
        client2, model, batch, configs["dense"], "chaos-compact")
    diag = payload.get("diag") or {}
    check(code == 200 and _canon(payload.get("results") or [])
          == expected["dense"],
          "post-compaction replay diverged from in-process")
    check(diag.get("replayed") == settled_c,
          f"compacted WAL replayed {diag.get('replayed')} of "
          f"{settled_c} settled rows")
    check(diag.get("cold_dispatches", 0) == 0
          and diag.get("warm_dispatches", 0) == 0,
          "fully-compacted-and-replayed request re-dispatched")
    os.environ.pop("JEPSEN_TPU_WAL_COMPACT_BYTES", None)
    try:
        client2.shutdown()
        proc2.wait(timeout=30)
    except Exception:  # noqa: BLE001 — fall back to the hard kill
        _sigkill(proc2)

    # == scenario 5: fleet member SIGKILL → router spillover + AOT rejoin ==
    # the nemesis turns on the fleet tier: kill one member mid-batch
    # and the ROUTER (not the client) must absorb it — rerouting the
    # in-flight request to the sibling with byte-identical verdicts —
    # and the revived member, warm from the shared AOT executable
    # cache, must rejoin with zero cold dispatches on its first request
    from jepsen_tpu.serve import router as router_mod

    for name in ("JEPSEN_TPU_CLIENT_DEADLINE", "JEPSEN_TPU_CLIENT_BACKOFF",
                 "JEPSEN_TPU_BREAKER_FAILURES",
                 "JEPSEN_TPU_BREAKER_COOLDOWN"):
        os.environ.pop(name, None)
    client_mod.reset_breakers()
    tmp3 = tempfile.mkdtemp(prefix="jepsen-chaos-fleet-")
    aot_dir = os.path.join(tmp3, "aot")
    fleet_ports = [free_port(), free_port()]
    fleet_procs, fleet_clients = [], []
    for i, p in enumerate(fleet_ports):
        mdir = os.path.join(tmp3, f"m{i}")
        os.makedirs(mdir, exist_ok=True)
        fleet_procs.append(_spawn_daemon(
            p, mdir, {"JEPSEN_TPU_SERVE_AOT_CACHE": aot_dir}))
        fleet_clients.append(ServiceClient(port=p, timeout=60.0))
    for i, (c, pr) in enumerate(zip(fleet_clients, fleet_procs)):
        check(_wait_healthy(c, pr), f"fleet member {i} did not come up")
    # a long probe interval parks the background prober: every
    # membership transition below is the harness's own deterministic
    # probe_once() sweep, so the reroute path (connection error on a
    # member still marked up) is exercised on purpose, not by luck
    rt = router_mod.Router(
        [f"127.0.0.1:{p}" for p in fleet_ports],
        port=0, probe_interval_s=30.0)
    rt.start(block=False)
    check(rt.probe_once() == 2, "router prober missed a live member")
    rclient = ServiceClient(port=rt.port)
    req0 = [c.status().get("requests", 0) for c in fleet_clients]
    res = rclient.check_batch(model, batch_v, **configs["dense"])
    check(_canon(res) == expected_v,
          "routed fleet verdicts diverged from in-process")
    deltas = [c.status().get("requests", 0) - r0
              for c, r0 in zip(fleet_clients, req0)]
    owner = max(range(2), key=lambda i: deltas[i])
    sibling = 1 - owner

    # kill -9 the key's owner mid-batch; the in-flight routed request
    # must spill to the sibling and lose nothing
    spill = {}

    def post_spill():
        try:
            c = ServiceClient(port=rt.port)
            spill["res"] = c.check_batch(model, batch_v,
                                         **configs["dense"])
        except Exception as e:  # noqa: BLE001 — the assertion target
            spill["err"] = e

    reroutes0 = _metric_value(obs.render_prom(),
                              "jepsen_route_reroutes_total") or 0
    t5 = threading.Thread(target=post_spill)
    t5.start()
    time.sleep(0.05)
    _sigkill(fleet_procs[owner])
    t5.join(timeout=120)
    check(not t5.is_alive(), "routed request hung after member kill -9")
    check(_canon(spill.get("res") or []) == expected_v,
          f"mid-batch member kill lost verdicts "
          f"({spill.get('err') or 'diverged'})")
    # the router still thinks the owner is up (prober parked): the next
    # request MUST take the connection-error reroute path to the sibling
    res = rclient.check_batch(model, batch_v, **configs["dense"])
    check(_canon(res) == expected_v,
          "rerouted verdicts diverged from in-process")
    check((_metric_value(obs.render_prom(),
                         "jepsen_route_reroutes_total") or 0)
          > reroutes0,
          "router never counted a reroute for the killed member")
    check(rt.probe_once() == 1,
          "probe sweep still counts the killed member as up")

    # revival: same port, same shared AOT cache — the member comes
    # back warm and its first request performs ZERO cold dispatches
    fleet_procs[owner] = _spawn_daemon(
        fleet_ports[owner], os.path.join(tmp3, f"m{owner}"),
        {"JEPSEN_TPU_SERVE_AOT_CACHE": aot_dir})
    check(_wait_healthy(fleet_clients[owner], fleet_procs[owner]),
          "killed fleet member did not revive")
    st_aot = (fleet_clients[owner].status().get("aot") or {})
    check((st_aot.get("warmed") or 0) > 0,
          f"revived member warmed nothing from the AOT cache ({st_aot})")
    check(rt.probe_once() == 2,
          "probe sweep did not mark the revived member up")
    own0 = fleet_clients[owner].status().get("requests", 0)
    res = rclient.check_batch(model, batch_v, **configs["dense"])
    rdiag = dict(rclient.last_diag)
    check(_canon(res) == expected_v,
          "post-revival routed verdicts diverged from in-process")
    check(fleet_clients[owner].status().get("requests", 0) > own0,
          "traffic did not return to the revived key owner")
    check(rdiag.get("cold_dispatches", 0) == 0,
          f"revived member paid a cold start on rejoin (diag {rdiag})")
    rt.stop()
    for i, (c, pr) in enumerate(zip(fleet_clients, fleet_procs)):
        try:
            c.shutdown()
            pr.wait(timeout=30)
        except Exception:  # noqa: BLE001 — fall back to the hard kill
            _sigkill(pr)

    # == fault accounting, client side (this process's registry) ==
    mine = obs.render_prom()
    for name in ("jepsen_client_retries_total",
                 "jepsen_client_breaker_trips_total",
                 "jepsen_client_breaker_probes_total",
                 "jepsen_route_requests_total",
                 "jepsen_route_reroutes_total"):
        check((_metric_value(mine, name) or 0) >= 1,
              f"client metrics missing {name}")

    # teardown
    proxy.close()
    try:
        client.shutdown()
        proc.wait(timeout=30)
    except Exception:  # noqa: BLE001 — fall back to the hard kill
        _sigkill(proc)

    if failures:
        for f_ in failures:
            print(f"chaos-smoke: FAIL — {f_}", file=sys.stderr)
        return 1
    print(
        "chaos-smoke: ok (kill -9 + torn-WAL replay byte-identical on "
        "both kernel routes; stalled-socket calls bounded by the "
        "deadline, breaker tripped to in-process and recovered "
        "half-open; dropped response deduped by request id; idle WAL "
        "compaction kept only live rows and survived a simulated "
        "crash mid-compaction; fleet member kill -9 spilled to the "
        "sibling losing no verdicts and rejoined warm from the AOT "
        "cache with zero cold dispatches; all faults accounted in "
        "metrics)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
