"""Checker-service smoke check: ``python -m jepsen_tpu.serve.smoke``.

Brings a resident checker daemon up in-process (ephemeral port, a
bounded coalesce-gather window so concurrency is deterministic) and
proves the service acceptance gates on both kernel routes (dense
automaton, and the generic frontier kernel via an explicit closure
cap):

- **verdict byte-equality**: the service path returns results
  byte-identical (canonical JSON) to the in-process
  ``engine.pipeline.run`` path for the same mixed-shape batches —
  including the oracle-fallback row;
- **cross-run coalescing with per-client routing**: two concurrent
  clients posting DIFFERENT batches coalesce into one shared device
  batch (``jepsen_serve_coalesced_requests_total`` > 0) and each gets
  exactly its own verdicts back;
- **the warm path**: a repeat run against the warm daemon performs
  zero compile-phase dispatches (``warm-hit`` metric > 0, measured
  re-jit time ≈ 0) — the amortization the daemon exists for;
- **footprint safety under coalesced load**: in-flight dispatch depth
  never exceeds the window and the frontier dispatch-budget ratio
  stays ≤ 1 — the shared executor inherits the crash-calibrated
  single-dispatch HBM caps;
- **live observability**: ``/metrics`` passes the same Prometheus
  validator as the at-exit ``metrics.prom`` dump (one formatter:
  ``obs.render_prom``), and ``/healthz`` answers;
- **clean shutdown**: a request in flight when ``/shutdown`` lands
  still completes (drain), then the daemon stops answering.

Wired into ``make serve-smoke`` / ``make check``.  Exit codes: 0 ok,
1 any gate failed.
"""

from __future__ import annotations

import json
import random
import sys
import threading
import time


def _corpus_b():
    """A second batch, distinct from engine.smoke's corpus, so
    per-client result routing errors are detectable."""
    from jepsen_tpu.synth import generate_history

    rng = random.Random(977)
    hists = []
    for i in range(6):
        hists.append(
            generate_history(
                rng, n_procs=3, n_ops=12, crash_p=0.02, corrupt=(i % 2 == 0)
            )
        )
    for i in range(4):
        hists.append(
            generate_history(
                rng, n_procs=6, n_ops=60, crash_p=0.01, corrupt=(i == 1)
            )
        )
    return hists


def _canon(results) -> str:
    """Canonical JSON of a result list — the byte-equality the
    acceptance gate names."""
    from jepsen_tpu.serve import protocol

    return json.dumps(protocol.sanitize_results(results), sort_keys=True)


def _metric_value(text: str, name: str):
    for line in text.splitlines():
        if line.startswith(name) and not line.startswith("#"):
            head = line.split(" ", 1)[0]
            if head == name or head.startswith(name + "{"):
                try:
                    return float(line.rsplit(" ", 1)[1])
                except ValueError:
                    return None
    return None


def main(argv=None) -> int:
    from jepsen_tpu import models as m
    from jepsen_tpu import obs
    from jepsen_tpu.engine.smoke import _corpus
    from jepsen_tpu.obs import export as obs_export
    from jepsen_tpu.ops import wgl
    from jepsen_tpu.serve import CheckerDaemon, ServiceClient

    failures = []

    def check(cond, msg):
        if not cond:
            failures.append(msg)

    obs.enable(reset=True)
    model = m.cas_register(0)
    batch_a = _corpus()
    batch_b = _corpus_b()
    configs = {
        "dense": dict(slot_cap=32, max_dispatch=4),
        "frontier": dict(slot_cap=32, max_dispatch=4, max_closure=9),
    }

    daemon = CheckerDaemon(port=0, coalesce_wait_s=0.75)
    daemon.start(block=False)
    try:
        client = ServiceClient(port=daemon.port)
        check(client.healthy(), "daemon did not come up healthy")

        for route, kw in configs.items():
            # -- cold pass: this route's shapes compile exactly once,
            # in the daemon, for the daemon's whole life
            t0 = time.perf_counter()
            cold = client.check_batch(model, batch_a, **kw)
            cold_s = time.perf_counter() - t0
            cold_diag = dict(client.last_diag)
            check(
                cold_diag.get("cold_dispatches", 0) > 0,
                f"{route}: first service run should compile "
                f"(diag {cold_diag})",
            )

            # -- two concurrent clients, DIFFERENT batches: coalesce
            # into one device batch, each routed its own verdicts
            coalesced0 = daemon.status()["coalesced"]
            out = {}
            barrier = threading.Barrier(2)

            def post(tag, hists, kw=kw):
                c = ServiceClient(port=daemon.port)
                barrier.wait()  # jt: allow[net-timeout] — in-process barrier; both parties are this test
                out[tag] = (c.check_batch(model, hists, **kw),
                            dict(c.last_diag))

            threads = [
                threading.Thread(target=post, args=("a", batch_a)),
                threading.Thread(target=post, args=("b", batch_b)),
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            check(
                daemon.status()["coalesced"] - coalesced0 >= 2,
                f"{route}: concurrent clients did not coalesce "
                f"(status {daemon.status()})",
            )

            # -- warm pass: zero compiles, measured re-jit time ≈ 0
            t0 = time.perf_counter()
            warm = client.check_batch(model, batch_a, **kw)
            warm_s = time.perf_counter() - t0
            warm_diag = dict(client.last_diag)
            check(
                warm_diag.get("cold_dispatches", 0) == 0
                and warm_diag.get("warm_dispatches", 0) > 0,
                f"{route}: warm run re-jitted (diag {warm_diag})",
            )
            check(
                warm_s < cold_s,
                f"{route}: warm run ({warm_s:.3f}s) not faster than "
                f"cold ({cold_s:.3f}s)",
            )

            # -- byte-equality vs the in-process engine path, every
            # result of every run above
            exp_a = wgl.check_batch(model, batch_a, **kw)
            exp_b = wgl.check_batch(model, batch_b, **kw)
            for tag, got in (
                ("cold", cold), ("warm", warm),
                ("client-a", out["a"][0]), ("client-b", out["b"][0]),
            ):
                want = exp_b if tag == "client-b" else exp_a
                check(
                    _canon(got) == _canon(want),
                    f"{route}/{tag}: service verdicts diverged from "
                    "the in-process engine",
                )
            check(
                cold[-1].get("engine") == "oracle-fallback",
                f"{route}: slot-cap history should ride the oracle "
                f"through the service, got {cold[-1].get('engine')}",
            )

        # -- live observability: one formatter for scrape + dump
        mtext = client.metrics_text()
        reason = obs_export.validate_prometheus_text(mtext)
        check(reason is None, f"/metrics failed validation: {reason}")
        for name in ("jepsen_serve_requests_total",
                     "jepsen_serve_coalesced_requests_total",
                     "jepsen_serve_warm_hits_total"):
            check(
                (_metric_value(mtext, name) or 0) > 0,
                f"/metrics missing live {name}",
            )
        # footprint safety under coalesced load: depth bounded by the
        # window, frontier budget ratio within the calibrated 1.0
        depth = _metric_value(mtext, "jepsen_engine_inflight_depth")
        window = daemon.status()["window"]
        check(
            depth is not None and depth <= window,
            f"in-flight depth {depth} exceeded window {window}",
        )
        ratio = _metric_value(
            mtext, "jepsen_frontier_dispatch_budget_used_ratio")
        check(
            ratio is None or ratio <= 1.0,
            f"frontier dispatch budget overshot under coalesced load "
            f"({ratio})",
        )

        # -- clean shutdown drains in-flight work
        drain_out = {}

        def late_post():
            c = ServiceClient(port=daemon.port)
            drain_out["res"] = c.check_batch(
                model, batch_b, **configs["dense"])

        t = threading.Thread(target=late_post)
        t.start()
        time.sleep(0.2)  # admitted, sitting in the coalesce window
        client.shutdown()
        t.join(timeout=30)
        check(
            _canon(drain_out.get("res") or [])
            == _canon(wgl.check_batch(model, batch_b,
                                      **configs["dense"])),
            "in-flight request was not drained correctly on shutdown",
        )
        deadline = time.monotonic() + 10
        while client.healthy(timeout=0.3) and time.monotonic() < deadline:
            time.sleep(0.1)
        check(not client.healthy(timeout=0.3),
              "daemon still answering after shutdown")
    finally:
        daemon.stop()

    if failures:
        for f_ in failures:
            print(f"serve-smoke: FAIL — {f_}", file=sys.stderr)
        return 1
    print(
        "serve-smoke: ok (dense + frontier routes; coalesced concurrent "
        "clients, warm-path zero-rejit, live /metrics valid, drained "
        "shutdown)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
