"""Fleet-tier smoke check: ``python -m jepsen_tpu.serve.fleet_smoke``.

Brings up TWO real member daemons (separate processes — the kill
drill needs a real SIGKILL target) sharing one AOT executable cache
directory, fronts them with an in-process :class:`serve.router.Router`,
and proves the fleet acceptance gates on both kernel routes:

- **routed byte-equality**: verdicts through router → member are
  byte-identical (canonical JSON) to the in-process engine for the
  same batches, dense AND frontier — the router forwards raw bytes,
  so this holds by construction, and the smoke pins it;
- **shape coalescing across clients**: concurrent same-shape requests
  from different clients rendezvous onto ONE member (exactly one
  member's request counter moves), so the fleet preserves the
  single-daemon coalescing win instead of spraying shapes;
- **kill/spill drill**: SIGKILL the member that owns a key mid-batch —
  the router records the connection failure and reroutes the SAME
  request to the sibling; the client still gets every verdict,
  byte-identical, and the prober marks the member down;
- **warm restart, zero re-jit**: the killed member restarts against
  the same shared AOT cache and answers its FIRST request with zero
  cold dispatches (``diag.cold_dispatches == 0``), proven twice —
  request diag, and the restarted life's journal containing no
  ``cache=miss`` rows besides the ``trace_id=aot-warm`` warmup rows.

Wired into ``make fleet-smoke`` / ``make check``.  Exit codes: 0 ok,
1 any gate failed.
"""

from __future__ import annotations

import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn_member(port: int, tmp: str, idx: int, aot_dir: str, life: int):
    """One fleet member subprocess: per-member journal/WAL (like
    ``fleet_member_env``), the SHARED AOT cache dir, journal split per
    life so the restart assertion scans only the new life's rows."""
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["JEPSEN_TPU_JOURNAL"] = os.path.join(
        tmp, f"journal-{idx}-life{life}.jsonl")
    env["JEPSEN_TPU_WAL"] = os.path.join(tmp, f"verdict-wal-{idx}.jsonl")
    env["JEPSEN_TPU_SERVE_AOT_CACHE"] = aot_dir
    root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    prior = env.get("PYTHONPATH")
    env["PYTHONPATH"] = root + (os.pathsep + prior if prior else "")
    log = open(os.path.join(tmp, f"member-{idx}.log"), "ab")
    try:
        return subprocess.Popen(
            [sys.executable, "-m", "jepsen_tpu.serve",
             "--port", str(port)],
            cwd=tmp, env=env, stdout=log, stderr=log,
        )
    finally:
        log.close()


def _wait_healthy(client, proc, wait_s: float = 120.0) -> bool:
    deadline = time.monotonic() + wait_s
    while time.monotonic() < deadline:
        if client.healthy(timeout=0.5):
            return True
        if proc.poll() is not None:
            return False
        time.sleep(0.2)
    return False


def _sigkill(proc) -> None:
    try:
        os.kill(proc.pid, signal.SIGKILL)
    except ProcessLookupError:
        pass
    proc.wait(timeout=30)


def _journal_rows(path: str) -> list:
    rows = []
    try:
        with open(path) as f:
            for line in f:
                try:
                    rows.append(json.loads(line))
                except ValueError:
                    continue
    except OSError:
        pass
    return rows


def main(argv=None) -> int:
    from jepsen_tpu import models as m
    from jepsen_tpu import obs
    from jepsen_tpu.engine.smoke import _corpus
    from jepsen_tpu.ops import wgl
    from jepsen_tpu.serve import ServiceClient
    from jepsen_tpu.serve import router as router_mod
    from jepsen_tpu.serve.client import reset_breakers
    from jepsen_tpu.serve.smoke import _canon, _corpus_b, _metric_value

    failures = []

    def check(cond, msg):
        if not cond:
            failures.append(msg)

    obs.enable(reset=True)
    reset_breakers()
    model = m.cas_register(0)
    batch_a = _corpus()
    batch_b = _corpus_b()
    configs = {
        "dense": dict(slot_cap=32, max_dispatch=4),
        "frontier": dict(slot_cap=32, max_dispatch=4, max_closure=9),
    }

    tmp = tempfile.mkdtemp(prefix="jepsen-fleet-smoke-")
    aot_dir = os.path.join(tmp, "aot")
    ports = [_free_port(), _free_port()]
    members = [f"127.0.0.1:{p}" for p in ports]
    procs = [_spawn_member(p, tmp, i, aot_dir, life=1)
             for i, p in enumerate(ports)]
    member_clients = [ServiceClient(port=p, timeout=60.0) for p in ports]
    rt = None
    try:
        for i, (c, proc) in enumerate(zip(member_clients, procs)):
            if not _wait_healthy(c, proc):
                print(f"fleet-smoke: member {i} never became healthy "
                      f"(see {tmp}/member-{i}.log)", file=sys.stderr)
                return 1

        rt = router_mod.Router(members, port=0, probe_interval_s=0.25)
        rt.start(block=False)
        check(rt.probe_once() == 2, "prober did not see both members up")
        client = ServiceClient(port=rt.port)
        check(client.healthy(), "router /healthz did not answer ok")

        def member_requests():
            return [c.status().get("requests", 0) for c in member_clients]

        # -- routed byte-equality + same-shape coalescing, both routes
        for route, kw in configs.items():
            req0 = member_requests()
            out = {}
            barrier = threading.Barrier(2)

            def post(tag, kw=kw):
                c = ServiceClient(port=rt.port)
                barrier.wait()  # jt: allow[net-timeout] — in-process barrier; both parties are this test
                out[tag] = (c.check_batch(model, batch_a, **kw),
                            dict(c.last_diag))

            threads = [threading.Thread(target=post, args=(t,))
                       for t in ("a", "b")]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            deltas = [b - a for a, b in zip(req0, member_requests())]
            check(
                sum(1 for d in deltas if d) == 1,
                f"{route}: same-shape requests did not coalesce on one "
                f"member (per-member request deltas {deltas})",
            )
            want = _canon(wgl.check_batch(model, batch_a, **kw))
            for tag in ("a", "b"):
                check(
                    _canon(out[tag][0]) == want,
                    f"{route}/client-{tag}: routed verdicts diverged "
                    "from the in-process engine",
                )
        mtext = obs.render_prom()
        check(
            (_metric_value(mtext, "jepsen_route_requests_total") or 0) > 0,
            "router did not count jepsen_route_requests_total",
        )

        # -- find the member that owns batch_b's dense key by posting
        # once and watching the counters (observed, not predicted: the
        # same property real traffic relies on)
        kw = configs["dense"]
        req0 = member_requests()
        first = client.check_batch(model, batch_b, **kw)
        want_b = _canon(wgl.check_batch(model, batch_b, **kw))
        check(_canon(first) == want_b,
              "dense/batch-b: routed verdicts diverged")
        deltas = [b - a for a, b in zip(req0, member_requests())]
        victim = max(range(2), key=lambda i: deltas[i])
        sibling = 1 - victim

        # -- kill/spill drill: SIGKILL the owner mid-batch; the router
        # reroutes the same request to the sibling and no verdict is
        # lost (idempotent ids make the replay safe)
        drill = {}

        def drill_post():
            c = ServiceClient(port=rt.port)
            drill["res"] = c.check_batch(model, batch_b, **kw)

        t = threading.Thread(target=drill_post)
        t.start()
        time.sleep(0.05)
        _sigkill(procs[victim])
        t.join(timeout=120)
        check(not t.is_alive(), "kill-drill request never completed")
        check(
            _canon(drill.get("res") or []) == want_b,
            "kill drill lost or corrupted verdicts (spillover must "
            "recompute the full batch on the sibling)",
        )
        check(rt.probe_once() == 1,
              "prober still counts the killed member as up")
        sib0 = member_clients[sibling].status().get("requests", 0)
        again = client.check_batch(model, batch_b, **kw)
        check(_canon(again) == want_b,
              "post-kill traffic diverged on the sibling")
        check(
            member_clients[sibling].status().get("requests", 0) > sib0,
            "post-kill traffic did not re-route to the sibling",
        )

        # -- warm restart: same shared AOT cache, fresh journal; the
        # revived member answers its FIRST request with zero cold
        # dispatches
        procs[victim] = _spawn_member(
            ports[victim], tmp, victim, aot_dir, life=2)
        if not _wait_healthy(member_clients[victim], procs[victim]):
            print(f"fleet-smoke: member {victim} never revived "
                  f"(see {tmp}/member-{victim}.log)", file=sys.stderr)
            return 1
        st = member_clients[victim].status()
        aot = st.get("aot") or {}
        check(
            (aot.get("warmed") or 0) > 0,
            f"revived member warmed nothing from the AOT cache "
            f"(aot {aot})",
        )
        check(rt.probe_once() == 2,
              "prober did not mark the revived member up")
        # first request straight at the revived member: the
        # request-visible cold start must be gone
        direct = member_clients[victim]
        got = direct.check_batch(model, batch_b, **kw)
        diag = dict(direct.last_diag)
        check(_canon(got) == want_b,
              "revived member's verdicts diverged")
        check(
            diag.get("cold_dispatches", 0) == 0
            and diag.get("warm_dispatches", 0) > 0,
            f"revived member paid a cold start on its first request "
            f"(diag {diag})",
        )
        rows = _journal_rows(
            os.path.join(tmp, f"journal-{victim}-life2.jsonl"))
        cold_rows = [r for r in rows if r.get("cache") == "miss"
                     and r.get("trace_id") != "aot-warm"]
        check(rows, "revived member's journal is empty")
        check(
            not cold_rows,
            f"revived member's journal shows {len(cold_rows)} real "
            "cache=miss row(s) — the AOT warm pass missed shapes",
        )
        check(
            any(r.get("cache") == "miss"
                and r.get("trace_id") == "aot-warm" for r in rows),
            "revived member's journal has no aot-warm warmup rows",
        )
    finally:
        if rt is not None:
            rt.stop()
        for proc in procs:
            if proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    proc.kill()
        shutil.rmtree(tmp, ignore_errors=True)

    if failures:
        for f_ in failures:
            print(f"fleet-smoke: FAIL — {f_}", file=sys.stderr)
        return 1
    print(
        "fleet-smoke: ok (routed byte-equality dense + frontier, "
        "same-shape coalescing on one member, kill/spill drill lost "
        "no verdicts, revived member warm from the AOT cache with "
        "zero cold dispatches)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
