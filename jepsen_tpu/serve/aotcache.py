"""Shared on-disk AOT executable cache for the serve fleet tier.

PR 6 measured the problem: a fresh daemon pays a ~31× cold/warm gap
on its first batch because every ``(kernel, E, C, F, mesh)`` shape
jits from nothing.  The persistent XLA compilation cache
(``JEPSEN_TPU_SERVE_JIT_CACHE``) already makes the *second* compile of
a shape a disk hit, but the restarted daemon still pays trace +
cache-lookup + executable load lazily, on the first *request* — the
request-visible cold start survives.  This module grows that seam
into a real ahead-of-time warm path (the TVM AOT shape,
arXiv:1802.04799):

- **record** — the resident executor's :attr:`on_cold_compile` hook
  appends one manifest row per cold dispatch: the tune fingerprint
  (:func:`jepsen_tpu.tune.artifact.aot_fingerprint`), the shape key
  ``(kernel, E, C, F, mesh)``, and everything needed to rebuild and
  re-dispatch the executable (spec name, closure cap, value domain,
  the padded array shapes/dtypes and their neutral pad fills).
- **warm** — a fresh or supervisor-restarted daemon replays the
  manifest ON the device thread, before ``/healthz`` goes ready:
  each matching entry rebuilds its jitted fn and dispatches one
  all-padding (neutral) batch at the recorded shape, claiming the
  ``(fn, shape)`` pair in the compile/execute phase accounting.  The
  XLA bits load from the persistent compilation cache under the same
  directory, so the warmup is a disk read, not a re-jit — and the
  first real request then runs with ZERO cold dispatches (journal
  rows all ``cache=hit``; warmup rows carry ``trace_id=aot-warm``).

The directory is shared fleet-wide: every member records into and
warms from one manifest, so a shape compiled anywhere warms
everywhere.  Append-only JSONL with single-``write`` O_APPEND lines
keeps concurrent members safe; damaged or foreign-fingerprint lines
are skipped, never fatal.  Layout::

    <dir>/manifest.jsonl   # one row per recorded executable
    <dir>/xla/             # jax persistent compilation cache

Metrics: ``jepsen_route_aot_hits_total`` (manifest entries warmed at
startup), ``jepsen_route_aot_misses_total`` (cold compiles the cache
could not prevent, now recorded for the next life).
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .. import obs

MANIFEST_VERSION = 1
MANIFEST_NAME = "manifest.jsonl"

#: kernels the warm path can rebuild (history kernels only — the Elle
#: screen plans carry self-settling custom lowerings whose executables
#: rebuild lazily through their own cache)
_WARMABLE_KERNELS = ("dense", "frontier")


def manifest_path(cache_dir: str) -> str:
    return os.path.join(cache_dir, MANIFEST_NAME)


def xla_cache_dir(cache_dir: str) -> str:
    """The persistent XLA compilation cache living under the AOT dir —
    what makes the warm pass a disk load instead of a re-jit."""
    return os.path.join(cache_dir, "xla")


def _jsonable(x):
    """Coerce numpy scalars/containers to plain JSON types (the value
    domain can be an ``np.int64`` or a tuple of them)."""
    if isinstance(x, (tuple, list)):
        return [_jsonable(v) for v in x]
    if isinstance(x, np.generic):
        return x.item()
    return x


def _untuple(x):
    """Invert :func:`_jsonable` for the value-domain key: JSON lists
    come back as the tuples ``kernel_choice``/``make_dense_fn`` key on."""
    if isinstance(x, list):
        return tuple(_untuple(v) for v in x)
    return x


def _entry_key(row: Dict[str, Any]) -> Tuple:
    return (
        row.get("fp"), row.get("kernel"), row.get("spec"),
        row.get("E"), row.get("C"), row.get("F"), row.get("mc"),
        json.dumps(row.get("n_values")), json.dumps(row.get("mesh")),
        json.dumps(row.get("shapes")),
    )


def read_manifest(cache_dir: str) -> List[Dict[str, Any]]:
    """Every well-formed manifest row (damaged lines skipped — a torn
    concurrent append must not poison the whole cache)."""
    rows = []
    try:
        with open(manifest_path(cache_dir), "r") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except ValueError:
                    continue
                if isinstance(row, dict) and row.get("v") == MANIFEST_VERSION:
                    rows.append(row)
    except OSError:
        pass
    return rows


def _eligible(plan) -> bool:
    """Only history-kernel bucket plans rebuild deterministically from
    a manifest row; anything carrying a custom lowering stays out."""
    return (
        getattr(plan, "fn", None) is not None
        and getattr(plan, "kernel", None) in _WARMABLE_KERNELS
        and getattr(plan, "run_rows", None) is None
        and getattr(plan, "settle_rows", None) is None
        and getattr(getattr(plan, "spec", None), "name", None) is not None
    )


class Recorder:
    """The :attr:`Executor.on_cold_compile` hook: append one manifest
    row per distinct cold-compiled executable.  Single-writer per
    process (the device thread), O_APPEND single-line writes across
    processes; dedup is in-memory against the manifest read at build
    time plus everything this life recorded."""

    def __init__(self, cache_dir: str, mesh_shape: List[int]):
        from ..ops import wgl
        from ..tune import artifact as _cal

        self.cache_dir = cache_dir
        self.mesh_shape = list(mesh_shape)
        self.fp = _cal.aot_fingerprint()
        self._pad_fills = wgl._PAD_FILLS
        self._lock = threading.Lock()
        self._seen = {_entry_key(r) for r in read_manifest(cache_dir)}  # jt: guarded-by(_lock)
        self.recorded = 0  # jt: guarded-by(_lock)

    def __call__(self, plan, arrays, disp_shape) -> None:
        if not _eligible(plan):
            return
        fills = getattr(plan, "pad_fills", self._pad_fills)
        row = {
            "v": MANIFEST_VERSION,
            "fp": self.fp,
            "kernel": plan.kernel,
            "spec": plan.spec.name,
            "E": int(plan.E),
            "C": int(plan.C),
            "F": int(plan.frontier),
            "mc": int(plan.mc),
            "n_values": _jsonable(plan.n_values),
            "disp": int(plan.disp),
            "mesh": self.mesh_shape,
            "shapes": [list(np.asarray(a).shape) for a in arrays],
            "dtypes": [str(np.asarray(a).dtype) for a in arrays],
            "fills": [_jsonable(np.asarray(f).item()
                               if isinstance(f, np.generic) else f)
                      for f in fills],
        }
        key = _entry_key(row)
        with self._lock:
            if key in self._seen:
                return
            self._seen.add(key)
            self.recorded += 1
        obs.count("jepsen_route_aot_misses_total", kernel=plan.kernel)
        try:
            os.makedirs(self.cache_dir, exist_ok=True)
            line = (json.dumps(row, sort_keys=True) + "\n").encode()
            fd = os.open(manifest_path(self.cache_dir),
                         os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
            try:
                os.write(fd, line)
            finally:
                os.close(fd)
        except OSError:
            pass  # the cache is an optimization, never a failure


def _rebuild_fn(row: Dict[str, Any]):
    """Reproduce the exact lru-cached jitted fn a ``plan_bucket`` of
    the same bucket would hand the executor (same cache entry, so the
    warm claim is the claim real traffic hits)."""
    from ..ops import wgl

    if row["kernel"] == "dense":
        return wgl.make_best_check_fn(
            row["spec"], row["E"], row["C"], row["F"], row["mc"],
            _untuple(row["n_values"]),
        )
    return wgl.make_check_fn(
        row["spec"], row["E"], row["C"], row["F"], row["mc"])


def warm(executor, cache_dir: str) -> Tuple[int, int]:
    """Pre-claim every manifest entry matching the current fingerprint
    and mesh by dispatching one neutral all-padding batch per entry
    through ``executor`` (MUST run on the executor's owner thread,
    before the daemon goes ready).  Returns ``(warmed, matched)`` —
    entries actually dispatched vs entries that matched the key."""
    from ..ops import wgl
    from ..tune import artifact as _cal

    fp = _cal.aot_fingerprint()
    mesh_shape = (list(executor.mesh.devices.shape)
                  if executor.mesh is not None else [1])
    n_dev = executor.n_devices
    warmed = matched = 0
    seen = set()
    prev_ctx = executor.journal_context
    executor.journal_context = {"coalesced": 1, "trace_id": "aot-warm"}
    try:
        for row in read_manifest(cache_dir):
            if row.get("fp") != fp or row.get("mesh") != mesh_shape:
                continue
            key = _entry_key(row)
            if key in seen:
                continue
            seen.add(key)
            matched += 1
            try:
                fn = _rebuild_fn(row)
                if fn is None:
                    continue
                shapes, dtypes = row["shapes"], row["dtypes"]
                B = int(shapes[0][0])
                if n_dev > 1 and B % n_dev:
                    continue  # recorded under a different shard layout
                disp_shape = B if n_dev == 1 else (B // n_dev, n_dev)
                if wgl._shape_dispatched(fn, disp_shape):
                    continue  # an earlier entry already claimed it
                arrays = tuple(
                    np.full(tuple(s), fill, dtype=np.dtype(dt))
                    for s, dt, fill in zip(shapes, dtypes, row["fills"])
                )
                plan = wgl.BucketPlan()
                plan.spec = None  # warm rows carry no escalation path
                plan.kernel = row["kernel"]
                plan.fn = fn
                plan.E = int(row["E"])
                plan.C = int(row["C"])
                plan.mc = int(row["mc"])
                plan.n_values = _untuple(row["n_values"])
                plan.frontier = int(row["F"])
                plan.disp = int(row.get("disp") or 0) or B
                # zero live rows: the dispatch claims the (fn, shape)
                # pair and loads the executable; settle slices [:0], so
                # no verdict state is touched and nothing can escalate
                executor._dispatch_chunk(plan, arrays, [])
                executor.drain()
                warmed += 1
                obs.count("jepsen_route_aot_hits_total",
                          kernel=row["kernel"])
            except Exception:  # noqa: BLE001 — a bad entry must not
                # keep the daemon from coming up; it just stays cold
                continue
    finally:
        executor.journal_context = prev_ctx
    return warmed, matched
